from .api_boundary import EXCLUDED_REFERENCE_UTILS
from .dataclasses import (
    AORecipeKwargs,
    AutocastConfig,
    AutocastKwargs,
    CheckpointConfig,
    ComputeEnvironment,
    CustomDtype,
    DDPCommunicationHookType,
    DataLoaderConfiguration,
    DeepSpeedPlugin,
    DeepSpeedSequenceParallelConfig,
    DistributedDataParallelKwargs,
    DistributedType,
    DummyOptim,
    DummyScheduler,
    DynamoBackend,
    FP8RecipeKwargs,
    FullyShardedDataParallelPlugin,
    GradScalerConfig,
    GradScalerKwargs,
    GradientAccumulationPlugin,
    HfDeepSpeedConfig,
    InitProcessGroupKwargs,
    JitConfig,
    KwargsHandler,
    LoggerType,
    MSAMPRecipeKwargs,
    MegatronLMPlugin,
    MixedPrecisionPolicy,
    PrecisionType,
    ProfileConfig,
    ProfileKwargs,
    ProjectConfiguration,
    RNGType,
    SageMakerDistributedType,
    SaveFormat,
    TERecipeKwargs,
    TorchContextParallelConfig,
    TorchDynamoPlugin,
    TorchTensorParallelConfig,
    TorchTensorParallelPlugin,
    WatchdogConfig,
    add_model_config_to_megatron_parser,
    deepspeed_required,
    disable_fsdp_ram_efficient_loading,
    enable_fsdp_ram_efficient_loading,
    get_active_deepspeed_plugin,
)
from .versions import compare_versions, is_jax_version, is_torch_version
from .environment import (
    are_libraries_initialized,
    clear_environment,
    convert_dict_to_env_variables,
    get_cpu_distributed_information,
    get_current_device_type,
    get_int_from_env,
    parse_choice_from_env,
    parse_flag_from_env,
    patch_environment,
    purge_accelerate_environment,
    set_numa_affinity,
    str_to_bool,
)
# Collectives and RNG helpers are re-exported LAZILY (module __getattr__
# below): operations/random import ..state, which imports this package —
# eager imports here would cycle. Reference users' `from accelerate.utils
# import gather, set_seed, ...` spellings resolve the same either way.
_OPERATIONS = {
    "CannotPadNestedTensorWarning",
    "DistributedOperationException",
    "TensorInformation",
    "avg_losses_across_data_parallel_group",
    "broadcast",
    "broadcast_object_list",
    "concatenate",
    "find_batch_size",
    "gather",
    "gather_across_data_parallel_groups",
    "gather_object",
    "get_data_structure",
    "ignorant_find_batch_size",
    "initialize_tensors",
    "is_tensor_information",
    "pad_across_processes",
    "pad_input_tensors",
    "recursively_apply",
    "reduce",
    "send_to_device",
    "slice_tensors",
    "stack_batches",
    "verify_operation",
}
_RANDOM = {
    "capture_rng_states",
    "restore_rng_states",
    "set_seed",
    "synchronize_rng_state",
    "synchronize_rng_states",
}
# Reference `from accelerate.utils import …` spellings, routed to their
# TPU-native homes (reference utils/__init__.py re-exports ~260 names; these
# are the ones with native counterparts here).
_MODELING = {
    "abstract_params",
    "align_module_device",
    "calculate_maximum_sizes",
    "check_device_map",
    "check_tied_parameters_in_config",
    "check_tied_parameters_on_same_device",
    "clean_device_map",
    "compute_module_sizes",
    "compute_parameter_sizes",
    "convert_file_size_to_int",
    "copy_tensor_to_devices",
    "dtype_byte_size",
    "ensure_weights_retied",
    "extract_submodules_state_dict",
    "filter_first_and_last_linear_layers",
    "find_tied_parameters",
    "get_balanced_memory",
    "get_fsdp2_grad_scaler",
    "get_grad_scaler",
    "get_max_layer_size",
    "get_max_memory",
    "get_mixed_precision_context_manager",
    "get_module_children_bottom_up",
    "has_4bit_bnb_layers",
    "has_ao_layers",
    "has_offloaded_params",
    "has_transformer_engine_layers",
    "id_tensor_storage",
    "load_offloaded_weights",
    "named_module_tensors",
    "set_module_tensor_to_device",
    "infer_auto_device_map",
    "load_checkpoint_in_params",
    "load_state_dict",
    "named_parameters",
    "retie_parameters",
    "total_byte_size",
    "unflatten_parameters",
}
_LAUNCH = {"prepare_multi_gpu_env", "prepare_simple_launcher_cmd_env", "prepare_tpu"}
_OFFLOAD = {
    "OffloadedWeightsLoader",
    "PrefixedDataset",
    "load_offload_index",
    "load_offloaded_weight",
    "offload_state_dict",
    "offload_weight",
    "save_offload_index",
}
_MEMORY = {"clear_device_cache", "find_executable_batch_size", "release_memory", "should_reduce_batch_size"}
_QUANT = {"QuantizationConfig", "QuantizedArray", "load_and_quantize_model", "quantize_params", "dequantize_params"}
_PACKING = {"pack_sequences", "unpack_logits"}
_OTHER = {
    "check_os_kernel",
    "compile_regions",
    "has_compiled_regions",
    "is_compiled_module",
    "is_torch_tensor",
    "clean_state_dict_for_safetensors",
    "convert_bytes",
    "convert_outputs_to_fp32",
    "convert_to_fp32",
    "extract_model_from_parallel",
    "find_device",
    "get_pretty_name",
    "honor_type",
    "is_namedtuple",
    "is_port_in_use",
    "listify",
    "load",
    "merge_dicts",
    "recursive_getattr",
    "save",
}
# checkpoint-layout constants (reference utils/constants.py:20-33)
_CONSTANTS = {
    "MODEL_NAME", "OPTIMIZER_NAME", "SCHEDULER_NAME", "SAMPLER_NAME", "RNG_NAME",
    "SAFE_MODEL_NAME", "SAFE_WEIGHTS_NAME", "SAFE_WEIGHTS_INDEX_NAME",
    "SAFE_WEIGHTS_PATTERN_NAME", "WEIGHTS_NAME", "WEIGHTS_INDEX_NAME",
    "WEIGHTS_PATTERN_NAME", "RNG_STATE_NAME", "SCALER_NAME", "PROFILE_PATTERN_NAME",
}
# sharded save/load reference spellings (utils/fsdp_utils.py)
_FSDP_CKPT = {"save_fsdp_model", "load_fsdp_model", "save_fsdp_optimizer", "load_fsdp_optimizer"}


def __getattr__(name):
    if name in _OPERATIONS:
        from . import operations

        return getattr(operations, name)
    if name in _LAUNCH:
        from . import launch

        return getattr(launch, name)
    if name in _RANDOM:
        from . import random

        return getattr(random, name)
    if name in _MODELING:
        from . import modeling

        return getattr(modeling, name)
    if name in _OFFLOAD:
        from . import offload

        return getattr(offload, name)
    if name in _MEMORY:
        from . import memory

        return getattr(memory, name)
    if name in _QUANT:
        from . import quantization

        return getattr(quantization, name)
    if name in _OTHER:
        from . import other

        return getattr(other, name)
    if name in _PACKING:
        from . import packing

        return getattr(packing, name)
    if name in _CONSTANTS:
        from .. import checkpointing

        return getattr(checkpointing, name)
    if name in _FSDP_CKPT:
        from .. import sharded_checkpoint

        return getattr(sharded_checkpoint, name)
    if name == "ParallelismConfig":  # reference re-exports it from utils too
        from ..parallelism_config import ParallelismConfig

        return ParallelismConfig
    if name == "PrepareForLaunch":
        from ..launchers import PrepareForLaunch

        return PrepareForLaunch
    if name == "load_checkpoint_in_model":
        from ..checkpointing import load_checkpoint_in_model

        return load_checkpoint_in_model
    if name == "BnbQuantizationConfig":  # reference name for the quant config
        from .quantization import QuantizationConfig

        return QuantizationConfig
    if name == "wait_for_everyone":
        # deferred: constructing PartialState initializes the backend — that
        # must happen at call time, not attribute-lookup time
        def wait_for_everyone():
            from ..state import PartialState

            return PartialState().wait_for_everyone()

        return wait_for_everyone
    if name == "merge_fsdp_weights":  # reference utils/fsdp_utils.py:360
        from ..sharded_checkpoint import merge_sharded_checkpoint

        return merge_sharded_checkpoint
    if name == "tqdm":
        from .tqdm import tqdm

        return tqdm
    if name == "write_basic_config":  # reference: accelerate.utils re-export
        from ..commands.config import write_basic_config

        return write_basic_config
    raise AttributeError(f"module 'accelerate_tpu.utils' has no attribute {name!r}")


from .imports import (
    is_4bit_bnb_available,
    is_8bit_bnb_available,
    is_aim_available,
    is_bf16_available,
    is_bitsandbytes_multi_backend_available,
    is_bnb_available,
    is_boto3_available,
    is_habana_gaudi1,
    is_hpu_available,
    is_mlu_available,
    is_msamp_available,
    is_musa_available,
    is_npu_available,
    is_peft_model,
    is_sdaa_available,
    is_torchao_available,
    is_transformer_engine_available,
    is_transformer_engine_mxfp8_available,
    is_xpu_available,
    model_has_dtensor,
    torchao_required,
    is_chex_available,
    is_clearml_available,
    is_comet_ml_available,
    is_cpu_only,
    is_cuda_available,
    is_datasets_available,
    is_deepspeed_available,
    is_dvclive_available,
    is_flax_available,
    is_fp8_available,
    is_fp16_available,
    is_gpu_available,
    is_import_timer_available,
    is_lomo_available,
    is_matplotlib_available,
    is_megatron_lm_available,
    is_mlflow_available,
    is_mps_available,
    is_multihost,
    is_optax_available,
    is_orbax_available,
    is_pallas_available,
    is_pandas_available,
    is_peft_available,
    is_pippy_available,
    is_pynvml_available,
    is_pytest_available,
    is_rich_available,
    is_safetensors_available,
    is_sagemaker_available,
    is_schedulefree_available,
    is_swanlab_available,
    is_tensorboard_available,
    is_timm_available,
    is_torch_available,
    is_torch_xla_available,
    is_torchdata_available,
    is_torchdata_stateful_dataloader_available,
    is_torchvision_available,
    is_tpu_available,
    is_tqdm_available,
    is_trackio_available,
    is_transformers_available,
    is_triton_available,
    is_wandb_available,
    is_weights_only_available,
    is_xccl_available,
)

# __all__ spans the eager imports above AND the lazy names (star-import
# resolves the lazy ones through module __getattr__, PEP 562); __dir__ keeps
# tab-completion/introspection seeing the lazy names too.
_LAZY_EXTRA = {
    "write_basic_config",
    "BnbQuantizationConfig",
    "wait_for_everyone",
    "merge_fsdp_weights",
    "tqdm",
    "ParallelismConfig",
    "PrepareForLaunch",
    "load_checkpoint_in_model",
}
_ALL_LAZY = (
    _OPERATIONS | _RANDOM | _MODELING | _OFFLOAD | _MEMORY | _QUANT | _OTHER | _PACKING
    | _CONSTANTS | _FSDP_CKPT | _LAUNCH | _LAZY_EXTRA
)

__all__ = sorted(
    {n for n in globals() if not n.startswith("_") and n != "annotations"} | _ALL_LAZY
)


def __dir__():
    return sorted(set(globals()) | _ALL_LAZY)
