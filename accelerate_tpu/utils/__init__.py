from .dataclasses import (
    AutocastConfig,
    AutocastKwargs,
    DDPCommunicationHookType,
    DataLoaderConfiguration,
    DeepSpeedPlugin,
    DistributedDataParallelKwargs,
    DistributedType,
    FullyShardedDataParallelPlugin,
    GradScalerConfig,
    GradScalerKwargs,
    GradientAccumulationPlugin,
    InitProcessGroupKwargs,
    JitConfig,
    KwargsHandler,
    LoggerType,
    MixedPrecisionPolicy,
    PrecisionType,
    ProfileConfig,
    ProfileKwargs,
    ProjectConfiguration,
    RNGType,
    SaveFormat,
)
from .versions import compare_versions, is_jax_version
from .environment import (
    are_libraries_initialized,
    get_int_from_env,
    parse_choice_from_env,
    parse_flag_from_env,
    patch_environment,
    str_to_bool,
)
# Collectives and RNG helpers are re-exported LAZILY (module __getattr__
# below): operations/random import ..state, which imports this package —
# eager imports here would cycle. Reference users' `from accelerate.utils
# import gather, set_seed, ...` spellings resolve the same either way.
_OPERATIONS = {
    "DistributedOperationException",
    "broadcast",
    "broadcast_object_list",
    "concatenate",
    "find_batch_size",
    "gather",
    "gather_object",
    "get_data_structure",
    "initialize_tensors",
    "pad_across_processes",
    "pad_input_tensors",
    "recursively_apply",
    "reduce",
    "send_to_device",
    "slice_tensors",
    "stack_batches",
    "verify_operation",
}
_RANDOM = {
    "capture_rng_states",
    "restore_rng_states",
    "set_seed",
    "synchronize_rng_state",
    "synchronize_rng_states",
}


def __getattr__(name):
    if name in _OPERATIONS:
        from . import operations

        return getattr(operations, name)
    if name in _RANDOM:
        from . import random

        return getattr(random, name)
    if name == "write_basic_config":  # reference: accelerate.utils re-export
        from ..commands.config import write_basic_config

        return write_basic_config
    raise AttributeError(f"module 'accelerate_tpu.utils' has no attribute {name!r}")


from .imports import (
    is_chex_available,
    is_cpu_only,
    is_datasets_available,
    is_flax_available,
    is_gpu_available,
    is_mlflow_available,
    is_multihost,
    is_optax_available,
    is_orbax_available,
    is_pallas_available,
    is_rich_available,
    is_safetensors_available,
    is_tensorboard_available,
    is_torch_available,
    is_tpu_available,
    is_tqdm_available,
    is_transformers_available,
    is_wandb_available,
)

# __all__ spans the eager imports above AND the lazy collectives/RNG names
# (star-import resolves the lazy ones through module __getattr__, PEP 562);
# __dir__ keeps tab-completion/introspection seeing the lazy names too.
_LAZY_EXTRA = {"write_basic_config"}

__all__ = sorted(
    {n for n in globals() if not n.startswith("_") and n != "annotations"}
    | _OPERATIONS
    | _RANDOM
    | _LAZY_EXTRA
)


def __dir__():
    return sorted(set(globals()) | _OPERATIONS | _RANDOM | _LAZY_EXTRA)
