"""Launcher env-protocol spellings (reference ``utils/launch.py:98-420``).

The real assembly lives in ``commands/launch.py`` (``build_launch_env``); these
are the reference's public utils spellings over it, so scripts that build
launch environments programmatically (`prepare_simple_launcher_cmd_env`,
`prepare_multi_gpu_env`, `prepare_tpu`) port without edits. Imports of
``commands`` happen lazily to keep ``utils`` import-light and cycle-free.
"""

from __future__ import annotations

import os
import sys
from typing import Any


def _cluster_config_from_args(args) -> "Any":
    """Duck-typed argparse-namespace/ClusterConfig → ClusterConfig."""
    from ..commands.config import ClusterConfig

    if isinstance(args, ClusterConfig):
        return args
    cfg = ClusterConfig()
    for f in cfg.__dataclass_fields__:
        if getattr(args, f, None) is not None:
            setattr(cfg, f, getattr(args, f))
    return cfg


def prepare_simple_launcher_cmd_env(args) -> "tuple[list[str], dict[str, str]]":
    """``(cmd, env)`` for a single-host launch (reference
    ``utils/launch.py:98`` ``prepare_simple_launcher_cmd_env``): the python
    command line for the training script plus the ``ACCELERATE_*`` /
    ``PARALLELISM_CONFIG_*`` env channel."""
    from ..commands.launch import build_launch_env

    cfg = _cluster_config_from_args(args)
    cmd = [sys.executable]
    if getattr(args, "module", False):
        cmd.append("-m")
    script = getattr(args, "training_script", None) or getattr(args, "script", None)
    if script:
        cmd.append(script)
    cmd.extend(getattr(args, "training_script_args", []) or [])
    env = {**os.environ, **build_launch_env(cfg)}
    return cmd, env


def prepare_multi_gpu_env(args) -> dict[str, str]:
    """Env channel for a multi-process launch (reference
    ``utils/launch.py:197`` ``prepare_multi_gpu_env`` builds torchrun env).
    Here every host runs ONE process over all its chips (SPMD), so this is the
    coordinator/rank channel consumed by ``PartialState``."""
    from ..commands.launch import build_launch_env

    return build_launch_env(_cluster_config_from_args(args))


def prepare_tpu(args, current_env: "dict[str, str] | None" = None, pod: bool = False
                ) -> "tuple[Any, dict[str, str]]":
    """TPU-specific env preparation (reference ``utils/launch.py``
    ``prepare_tpu`` sets ``XLA_USE_BF16``-era torch_xla flags). Native JAX
    needs none of those; what remains meaningful is downcast intent →
    ``ACCELERATE_MIXED_PRECISION`` and, for pods, the coordinator channel."""
    env = dict(current_env or {})
    mp = getattr(args, "mixed_precision", None) or getattr(args, "downcast_bf16", None)
    if mp:
        env["ACCELERATE_MIXED_PRECISION"] = "bf16" if mp in (True, "bf16") else str(mp)
    if pod:
        env.update(prepare_multi_gpu_env(args))
    return args, env
