"""Generic helpers (reference ``utils/other.py`` — ``save:354``, ``load``,
``clean_state_dict_for_safetensors:319``, ``convert_bytes``, ``merge_dicts``,
``is_port_in_use``, ``check_os_kernel:501``, ``get_pretty_name``; and
``utils/operations.py`` — ``honor_type``, ``listify``, ``find_device``,
``convert_to_fp32``). TPU-native versions: trees of jax/numpy arrays instead of
torch tensors; "saving" means npz or safetensors of host arrays.
"""

from __future__ import annotations

import os
import platform
import socket
import warnings
from typing import Any, Mapping

import numpy as np


# ------------------------------------------------------------- tree helpers --


def is_namedtuple(data) -> bool:
    """True for namedtuple instances (not plain tuples)."""
    return isinstance(data, tuple) and hasattr(data, "_asdict") and hasattr(data, "_fields")


def honor_type(obj, generator):
    """Rebuild ``obj``'s sequence type from ``generator`` (namedtuples need
    positional-splat construction)."""
    if is_namedtuple(obj):
        return type(obj)(*list(generator))
    return type(obj)(generator)


def listify(data):
    """Nested structure of arrays/scalars → plain python lists/numbers (the
    form trackers and json can take)."""
    if isinstance(data, (int, float, str, bool)) or data is None:
        return data
    if isinstance(data, Mapping):
        return {k: listify(v) for k, v in data.items()}
    if isinstance(data, (list, tuple)):
        return honor_type(data, (listify(v) for v in data))
    if hasattr(data, "tolist"):
        return np.asarray(data).tolist()
    return data


def find_device(data):
    """First jax array's device in a nested structure (None if none found)."""
    import jax

    for leaf in jax.tree_util.tree_leaves(data):
        if isinstance(leaf, jax.Array):
            return next(iter(leaf.devices()))
    return None


def convert_to_fp32(tree):
    """Cast every floating leaf to float32 (reference ``convert_to_fp32:819`` —
    used on eval outputs computed under a low-precision policy)."""
    import jax
    import jax.numpy as jnp

    def _cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(jnp.float32)
        return x

    return jax.tree_util.tree_map(_cast, tree)


# alias matching the reference's decorator-flavored name (ours is a pure fn)
convert_outputs_to_fp32 = convert_to_fp32


def get_pretty_name(obj) -> str:
    """Best human name for an object (reference ``get_pretty_name``)."""
    for attr in ("__qualname__", "__name__"):
        name = getattr(obj, attr, None)
        if name:
            return name
    name = getattr(type(obj), "__qualname__", None) or getattr(type(obj), "__name__", "")
    return name or str(obj)


def merge_dicts(source: dict, destination: dict) -> dict:
    """Recursively merge ``source`` into (a copy of) ``destination``."""
    out = dict(destination)
    for key, value in source.items():
        if isinstance(value, dict) and isinstance(out.get(key), dict):
            out[key] = merge_dicts(value, out[key])
        else:
            out[key] = value
    return out


def recursive_getattr(obj, attr: str):
    """``recursive_getattr(m, "a.b.c")`` → ``m.a.b.c``."""
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def extract_model_from_parallel(model, keep_fp32_wrapper: bool = True):
    """Identity — params are never wrapped here (reference unwraps DDP/FSDP/
    compiled modules, ``extract_model_from_parallel``)."""
    return model


# ------------------------------------------------------------------- system --


def is_port_in_use(port: int | None = None) -> bool:
    """True when localhost:``port`` already has a listener (the launcher's
    coordinator-port probe)."""
    if port is None:
        port = 29500
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        return s.connect_ex(("localhost", int(port))) == 0


def check_os_kernel(release: str | None = None) -> None:
    """Warn on Linux kernels older than 5.5 (reference ``check_os_kernel:501``:
    MKL/threading stalls observed there affect host-side input pipelines).

    ``release`` overrides the detected kernel release string (tests pin it so
    the assertion does not depend on the host the suite happens to run on).
    """
    info = platform.uname()
    if info.system != "Linux":
        return
    if release is None:
        release = info.release
    try:
        version = tuple(int(p) for p in release.split(".")[:2])
    except ValueError:  # pragma: no cover - exotic kernel strings
        return
    if version < (5, 5):
        warnings.warn(
            f"Detected Linux kernel {release} (< 5.5); host-side data "
            "pipelines may stall on older kernels. Consider upgrading.",
            UserWarning,
        )


def convert_bytes(size: float) -> str:
    """Human-readable byte count: ``convert_bytes(1024**2) == '1.0 MB'``."""
    for unit in ("bytes", "KB", "MB", "GB", "TB"):
        if abs(size) < 1024.0 or unit == "TB":
            return f"{size:.1f} {unit}" if unit != "bytes" else f"{int(size)} {unit}"
        size /= 1024.0
    return f"{size:.1f} TB"  # pragma: no cover - unreachable


# -------------------------------------------------------------- persistence --


def clean_state_dict_for_safetensors(state_dict: Mapping[str, Any]) -> dict:
    """Drop duplicate entries that share storage (tied weights) and commit to
    host numpy — safetensors refuses aliased tensors (reference
    ``clean_state_dict_for_safetensors:319``)."""
    seen: dict[int, str] = {}
    out: dict[str, Any] = {}
    for key in sorted(state_dict):
        value = state_dict[key]
        ident = id(value)
        if ident in seen:
            continue
        seen[ident] = key
        out[key] = np.asarray(value)
    return out


def save(obj, f: str, save_on_each_node: bool = False, safe_serialization: bool = False) -> None:
    """Save a pytree/state-dict from the main process (reference ``save:354``).
    ``safe_serialization`` writes safetensors (flat arrays only); otherwise npz.
    """
    from ..state import PartialState

    state = PartialState()
    if not (state.is_main_process or save_on_each_node):
        return
    from .modeling import named_parameters

    flat = {k: np.asarray(v) for k, v in named_parameters(obj).items() if v is not None}
    if safe_serialization:
        from safetensors.numpy import save_file

        save_file(clean_state_dict_for_safetensors(flat), f)
    else:
        # np.savez on a path silently appends ".npz" when the extension is
        # missing (save(obj, "model.bin") would write "model.bin.npz" and a
        # later load("model.bin") would fail); writing through an open file
        # handle preserves the exact path the caller asked for.
        with open(f, "wb") as fh:
            np.savez(fh, **flat)


def is_compiled_module(module) -> bool:
    """reference ``is_compiled_module``: True for a torch.compile-wrapped
    module. Bridged modules are always XLA-compiled, so this only reports the
    torch-side wrapper."""
    import sys

    torch = sys.modules.get("torch")
    if torch is None:
        return False
    dynamo = getattr(torch, "_dynamo", None)
    opt = getattr(getattr(dynamo, "eval_frame", None), "OptimizedModule", None)
    return opt is not None and isinstance(module, opt)


def is_torch_tensor(x) -> bool:
    """reference ``operations.py is_torch_tensor`` — without importing torch
    when it isn't already loaded."""
    import sys

    torch = sys.modules.get("torch")
    return torch is not None and isinstance(x, torch.Tensor)


def load(f: str):
    """Load a flat state-dict saved by :func:`save` (npz or safetensors)."""
    if str(f).endswith(".safetensors"):
        from safetensors.numpy import load_file

        return load_file(f)
    with np.load(f, allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def compile_regions(fn_or_config, **jit_kwargs):
    """Regional compilation, the native way (reference ``utils/other.py:102``
    ``compile_regions`` compiles each repeated block once with
    ``torch.compile``; its benchmark claims 5-9x faster cold compile).

    Under XLA the structural equivalent is scan-over-stacked-layers: one layer
    body is traced and compiled once regardless of depth. Accepts either

    - a model **config** with an ``unroll_layers`` field (``LlamaConfig``,
      ``BertConfig``): returns a copy with ``unroll_layers=False`` — every
      forward built from it compiles regionally;
    - a **callable**: returns ``jax.jit(fn, **jit_kwargs)`` tagged so
      :func:`has_compiled_regions` can recognize it.

    Measured on this repo's bench (``compile_time_llama1b`` config): scan
    compile vs fully-unrolled compile of a Llama-1B-class forward.
    """
    import dataclasses as _dc

    import jax

    if _dc.is_dataclass(fn_or_config) and hasattr(fn_or_config, "unroll_layers"):
        return _dc.replace(fn_or_config, unroll_layers=False)
    if callable(fn_or_config):
        compiled = jax.jit(fn_or_config, **jit_kwargs)
        try:
            compiled._accelerate_compiled_regions = True
        except AttributeError:  # jit wrappers allow attrs today; guard anyway
            pass
        return compiled
    raise TypeError(
        f"compile_regions expects a model config with unroll_layers or a "
        f"callable, got {type(fn_or_config).__name__}"
    )


def has_compiled_regions(obj) -> bool:
    """True for objects produced by :func:`compile_regions` (reference
    ``utils/other.py`` spelling): a tagged jitted callable or a config whose
    layers scan (compile regionally)."""
    if getattr(obj, "_accelerate_compiled_regions", False):
        return True
    return getattr(obj, "unroll_layers", None) is False
