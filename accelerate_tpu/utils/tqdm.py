"""Rank-aware progress bars (reference ``utils/tqdm.py`` — main-process-only
``tqdm`` so N hosts don't print N bars)."""

from __future__ import annotations

from .imports import is_tqdm_available


def tqdm(*args, main_process_only: bool = True, **kwargs):
    """``tqdm.auto.tqdm`` that renders only on the main process (reference
    ``utils/tqdm.py:43``)."""
    if not is_tqdm_available():
        raise ImportError("tqdm is not installed; pip install tqdm")
    from tqdm.auto import tqdm as _tqdm

    from ..state import PartialState

    if main_process_only:
        kwargs.setdefault("disable", not PartialState().is_main_process)
    return _tqdm(*args, **kwargs)
