"""Sequence packing: variable-length documents → fixed-shape [N, S] batches.

Static shapes are the TPU contract (SURVEY §7 hard parts: "static shapes force
even_batches-style wraparound"); padding every document to S wastes MXU work
proportional to the length variance. Packing lays several documents in one row
with per-token ``segment_ids`` — the model (``llama_forward(segment_ids=...)``)
masks cross-document attention, restarts rope positions per document, and
excludes boundary/padding targets from the LM loss. The reference's
counterpart pressure point is ``examples/by_feature/
gradient_accumulation_for_autoregressive_models.py`` (token-weighted batching);
packing is the TPU-native resolution.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

__all__ = ["pack_sequences", "unpack_logits"]


def pack_sequences(
    sequences: Iterable[Sequence[int]],
    seq_len: int,
    pad_id: int = 0,
    split_long: bool = True,
):
    """Greedily pack token sequences into rows of exactly ``seq_len``.

    Returns ``(input_ids, segment_ids)`` int32 arrays of shape [N, seq_len]:
    ``segment_ids`` numbers each document 1..k within its row, 0 = padding.
    Documents longer than ``seq_len`` are chunked (``split_long=True``, one
    OUTPUT SEGMENT PER CHUNK — a long doc maps to several consecutive
    segments) or rejected. Empty documents are rejected (a silent skip would
    misalign per-document bookkeeping). Packing is SHELF (append to the open
    row, open a new one when full) — deterministic, O(n), and
    ORDER-PRESERVING: row-major segment order equals input order, so with
    no over-length docs :func:`unpack_logits` maps 1:1 back to the input
    list. (First-fit packs a few percent tighter but reorders documents;
    shuffle the corpus if utilization matters more than order.)
    """
    chunks: list[list[int]] = []
    for i, seq in enumerate(sequences):
        seq = list(seq)
        if not seq:
            raise ValueError(
                f"sequence {i} is empty — filter empties out first (a silent "
                "skip would misalign unpack_logits with the input list)"
            )
        if len(seq) > seq_len:
            if not split_long:
                raise ValueError(f"sequence of {len(seq)} tokens exceeds seq_len={seq_len}")
            for i in range(0, len(seq), seq_len):
                piece = seq[i : i + seq_len]
                if piece:
                    chunks.append(piece)
        else:
            chunks.append(seq)

    rows: list[list[list[int]]] = []
    used = seq_len  # force a new row for the first chunk
    for chunk in chunks:
        if used + len(chunk) > seq_len:  # shelf: only the open row is a target
            rows.append([])
            used = 0
        rows[-1].append(chunk)
        used += len(chunk)

    n = len(rows)
    input_ids = np.full((n, seq_len), pad_id, dtype=np.int32)
    segment_ids = np.zeros((n, seq_len), dtype=np.int32)
    for r, row in enumerate(rows):
        pos = 0
        for s, chunk in enumerate(row, start=1):
            input_ids[r, pos : pos + len(chunk)] = chunk
            segment_ids[r, pos : pos + len(chunk)] = s
            pos += len(chunk)
    return input_ids, segment_ids


def unpack_logits(logits, segment_ids):
    """Split packed per-token outputs back into per-document arrays.

    ``logits``: [N, S, ...]; returns a list of [len_i, ...] arrays in
    row-major segment order — which :func:`pack_sequences`'s shelf packing
    guarantees IS the original input order (per-document eval bookkeeping
    stays aligned; docs that were CHUNKED by ``split_long`` appear as their
    consecutive chunks)."""
    logits = np.asarray(logits)
    segment_ids = np.asarray(segment_ids)
    docs = []
    for r in range(segment_ids.shape[0]):
        for s in range(1, int(segment_ids[r].max(initial=0)) + 1):
            sel = segment_ids[r] == s
            if sel.any():
                docs.append(logits[r][sel])
    return docs
