"""High-level quantized model loading — twin of ``utils/bnb.py``
(``load_and_quantize_model:44``), built on :mod:`accelerate_tpu.ops.quantization`.

The reference flow is: empty-init → replace nn.Linear with bnb layers → load
checkpoint shard-by-shard → move to device. Ours: stream the checkpoint into
the abstract param tree (``load_checkpoint_in_params``), quantize matching
leaves as they land, leave skip-listed leaves (lm_head/embeddings) dense.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional

from ..ops.quantization import (
    QuantizationConfig,
    QuantizedArray,
    dequantize_params,
    quantize_params,
    quantized_byte_size,
)

__all__ = [
    "QuantizationConfig",
    "QuantizedArray",
    "load_and_quantize_model",
    "quantize_params",
    "dequantize_params",
    "quantized_byte_size",
]


def load_and_quantize_model(
    params_or_template,
    quantization_config: QuantizationConfig,
    checkpoint: Optional[str] = None,
    device_map: Optional[Mapping[str, Any]] = None,
    offload_folder: Optional[str] = None,
):
    """Load (optionally) then quantize a param tree.

    - ``params_or_template``: concrete params, or an abstract tree
      (``jax.eval_shape`` output) when ``checkpoint`` is given.
    - ALWAYS returns ``(quantized_params, offload_index)``; the index is ``{}``
      unless a ``device_map`` spilled leaves to disk (those leaves are ``None``
      in the tree and resolvable through the index, mirroring
      ``load_checkpoint_in_params``).
    """
    if checkpoint is not None:
        from .modeling import load_checkpoint_in_params

        params, offload_index = load_checkpoint_in_params(
            params_or_template, checkpoint, device_map=device_map,
            offload_folder=offload_folder,
        )
    else:
        params, offload_index = params_or_template, {}
    quantized = quantize_params(params, quantization_config)
    return quantized, offload_index or {}
