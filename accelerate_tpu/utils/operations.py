"""Backend-polymorphic pytree collectives and tensor utilities.

TPU-native counterpart of the reference's ``utils/operations.py``
(``/root/reference/src/accelerate/utils/operations.py`` — ``recursively_apply:85``,
``send_to_device:136``, ``gather:419``, ``gather_object:445``, ``broadcast:539``,
``broadcast_object_list:560``, ``pad_across_processes:632``, ``reduce:728``,
``verify_operation:364``).

Two regimes exist on TPU:

1. **Inside jit** — collectives are either compiler-inserted (GSPMD, from shardings)
   or explicit ``jax.lax.psum/all_gather/ppermute``; nothing here is needed.
2. **Host level** (metrics, logging, object exchange) — these helpers. With a single
   process and a global ``jax.Array`` input, gathering is just resharding to
   replicated; across processes we use ``jax.experimental.multihost_utils``.

There is no ``mark_step`` anywhere: the reference's XLA graph-cut discipline
(``operations.py:301-313, 748-756``) is an artifact of lazy-tensor mode and
disappears under jit.
"""

from __future__ import annotations

import pickle
from typing import Any, Callable, Optional

import numpy as np

from ..resilience.chaos import maybe_inject as _chaos_inject
from ..state import PartialState
from ..telemetry import events as _telemetry
from ..telemetry import flight_recorder as _flight
from .environment import parse_flag_from_env


class DistributedOperationException(Exception):
    """Raised when an operation cannot proceed consistently across processes
    (reference ``utils/operations.py:37``)."""


# ---------------------------------------------------------------------------
# Comms counters (telemetry): op type, payload bytes, call count for the
# host-level collectives, so sharding regressions show up as traffic, not
# vibes. Counting happens ONLY while telemetry is enabled — the disabled path
# is one flag check per op call.

_COMM_COUNTS: "dict[str, list]" = {}  # op -> [calls, bytes]


def _tree_nbytes(tree) -> int:
    total = 0

    def _add(x):
        nonlocal total
        nbytes = getattr(x, "nbytes", None)
        total += int(nbytes) if nbytes is not None else int(np.asarray(x).nbytes)
        return x

    recursively_apply(_add, tree)
    return total


def _record_comm(op: str, tree=None, nbytes: Optional[int] = None) -> None:
    if not _telemetry.is_enabled():
        return
    try:
        n = int(nbytes) if nbytes is not None else _tree_nbytes(tree)
    except Exception:
        n = 0
    rec = _COMM_COUNTS.setdefault(op, [0, 0])
    rec[0] += 1
    rec[1] += n
    # wire=False marks a single-process (loopback) call: the logical payload
    # is counted — the regression signal the counters exist for — but no bytes
    # crossed a host boundary
    _telemetry.emit("comm", op=op, bytes=n, wire=PartialState().num_processes > 1)


def record_compiled_collective(op: str, nbytes: int) -> None:
    """Count a collective COMPILED INTO a jitted step (fused ZeRO-1's
    reduce-scatter/all-gather, ``parallel/weight_update.py``): the host never
    dispatches it, so its payload is accounted from the static bucket plan,
    once per step. Namespaced ``compiled:`` so the report's comms table
    separates device-fabric traffic from host-level collectives. The disabled
    path is one flag check."""
    if not _telemetry.is_enabled():
        return
    rec = _COMM_COUNTS.setdefault(f"compiled:{op}", [0, 0])
    rec[0] += 1
    rec[1] += int(nbytes)
    # wire=True: these bytes really cross the device fabric (ICI/DCN) even in
    # a single-process multi-device run
    _telemetry.emit("comm", op=f"compiled:{op}", bytes=int(nbytes), wire=True)


def _collective_signature(tree) -> str:
    """Compact (shape, dtype) description of a collective payload, folded
    into the flight recorder's per-rank schedule fingerprint — the runtime
    cross-check for jaxlint R4: two ranks whose fingerprints diverge took
    different collective schedules, and a ``--by-rank`` report can name the
    first differing call post-mortem. Single-process runs record the op with
    a ``local`` placeholder instead — divergence needs two ranks to exist,
    so the payload walk would be pure hot-path overhead there."""
    if PartialState().num_processes == 1:
        return "local"
    parts: "list[str]" = []

    def _walk(x):
        # read-only traversal — this runs on every collective call, so it
        # must not pay recursively_apply's container reconstruction
        if isinstance(x, (list, tuple)):
            for item in x:
                _walk(item)
        elif isinstance(x, dict):
            for value in x.values():
                _walk(value)
        elif _is_tensorlike(x) or _is_foreign_tensor(x):
            shape = getattr(x, "shape", None)
            parts.append(
                f"{tuple(shape) if shape is not None else ()}/{getattr(x, 'dtype', '?')}"
            )

    try:
        _walk(tree)
    except Exception:
        return "?"
    return ",".join(parts) if parts else "-"


def get_comm_counters() -> "dict[str, dict]":
    """Live per-op traffic counters: ``{op: {"calls": n, "bytes": b}}``."""
    return {op: {"calls": rec[0], "bytes": rec[1]} for op, rec in _COMM_COUNTS.items()}


def reset_comm_counters() -> None:
    _COMM_COUNTS.clear()


def _is_jax_array(x) -> bool:
    import jax

    return isinstance(x, jax.Array)


def _is_tensorlike(x) -> bool:
    return _is_jax_array(x) or isinstance(x, np.ndarray)


def _is_foreign_tensor(x) -> bool:
    """torch tensors / bridge _TensorViews — accepted at op boundaries so
    torch-interop scripts can call gather_for_metrics etc. unmodified."""
    if type(x).__name__ == "_TensorView" and hasattr(x, "array"):
        return True
    try:
        import sys

        torch = sys.modules.get("torch")
        return torch is not None and isinstance(x, torch.Tensor)
    except Exception:
        return False


def _normalize_foreign(tree):
    """Convert foreign leaves (torch / _TensorView) to jax/numpy arrays."""

    def _conv(x):
        if type(x).__name__ == "_TensorView":
            return x.array
        return x.detach().cpu().numpy()

    return recursively_apply(_conv, tree, test_type=_is_foreign_tensor)


def recursively_apply(
    func: Callable,
    data: Any,
    *args,
    test_type: Callable = _is_tensorlike,
    error_on_other_type: bool = False,
    **kwargs,
):
    """Apply ``func`` to all leaves of ``data`` that pass ``test_type``
    (reference ``operations.py:85``). Containers (list/tuple/dict/namedtuple) are
    rebuilt with their original type."""
    if isinstance(data, (list, tuple)):
        cls = type(data)
        mapped = [
            recursively_apply(
                func, o, *args, test_type=test_type, error_on_other_type=error_on_other_type, **kwargs
            )
            for o in data
        ]
        if hasattr(data, "_fields"):  # namedtuple
            return cls(*mapped)
        return cls(mapped)
    if isinstance(data, dict):
        return type(data)(
            {
                k: recursively_apply(
                    func, v, *args, test_type=test_type, error_on_other_type=error_on_other_type, **kwargs
                )
                for k, v in data.items()
            }
        )
    if test_type(data):
        return func(data, *args, **kwargs)
    if error_on_other_type:
        raise TypeError(
            f"Unsupported type {type(data)} passed to a collective op — only nested "
            "list/tuple/dict of arrays are supported."
        )
    return data


def send_to_device(tree, device=None, non_blocking: bool = True, skip_keys=None):
    """Place all array leaves on ``device`` — a ``jax.Device``, ``Sharding`` or
    ``None`` for the default device (reference ``operations.py:136``)."""
    import jax

    if skip_keys and isinstance(tree, dict):
        if isinstance(skip_keys, str):
            skip_keys = [skip_keys]
        return type(tree)(
            {
                k: (v if k in skip_keys else send_to_device(v, device, non_blocking))
                for k, v in tree.items()
            }
        )

    def _put(x):
        arr = np.asarray(x) if not hasattr(x, "dtype") else x
        if getattr(arr, "dtype", None) is not None and arr.dtype.kind in "USO":
            return x  # strings/objects have no device representation
        return jax.device_put(x, device)

    return recursively_apply(_put, tree)


def _replicate_global_array(x):
    """Reshard a (possibly sharded) global jax.Array to fully-replicated — the SPMD
    meaning of "gather": every device/host ends up with the full value."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = x.sharding
    if getattr(sharding, "mesh", None) is not None:
        target = NamedSharding(sharding.mesh, PartitionSpec())
        return jax.device_put(x, target)
    return x


def gather(tree):
    """Gather array leaves so every process holds the full value
    (reference ``gather:419`` — there: concat along dim0 across ranks).

    - global sharded ``jax.Array`` → resharded to fully-replicated (ICI allgather)
    - host-local numpy (multi-process) → ``process_allgather`` concat along dim 0
    """
    tree = _normalize_foreign(tree)
    _record_comm("gather", tree)
    state = PartialState()

    def _gather(x):
        if _is_jax_array(x):
            x = _replicate_global_array(x)
            if not x.is_fully_addressable:  # pragma: no cover - multihost only
                from .jax_compat import process_allgather

                return process_allgather(x, tiled=True)
            return x
        if state.num_processes > 1:  # pragma: no cover - multihost only
            from .jax_compat import process_allgather

            return process_allgather(x, tiled=True)
        return x

    # flight-recorder annotation: a rank that hangs here is "blocked in
    # collective:gather" in the watchdog's stall dump, not just "stuck"
    _flight.record_collective("gather", _collective_signature(tree))
    _chaos_inject("collective")
    with _flight.phase("collective:gather"):
        return recursively_apply(_gather, tree)


def gather_object(obj: Any) -> list[Any]:
    """Gather arbitrary picklable objects from all processes into a list
    (reference ``gather_object:445``)."""
    state = PartialState()
    # object payloads legitimately differ per rank (each contributes its
    # own), so the fingerprint carries the op only — never the size
    _flight.record_collective("gather_object", "obj")
    if state.num_processes == 1:
        if _telemetry.is_enabled():
            _record_comm("gather_object", nbytes=len(pickle.dumps(obj)))
        return [obj]
    # pragma: no cover - multihost only
    from .jax_compat import process_allgather

    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    _record_comm("gather_object", nbytes=payload.size)
    with _flight.phase("collective:gather_object", nbytes=int(payload.size)):
        sizes = process_allgather(np.array([payload.size]), tiled=False).reshape(-1)
        max_size = int(sizes.max())
        padded = np.zeros(max_size, dtype=np.uint8)
        padded[: payload.size] = payload
        gathered = process_allgather(padded, tiled=False)
    return [
        pickle.loads(gathered[i, : int(sizes[i])].tobytes()) for i in range(state.num_processes)
    ]


def broadcast(tree, from_process: int = 0):
    """Broadcast array leaves from ``from_process`` to all processes
    (reference ``broadcast:539``). Single-process: identity."""
    _record_comm("broadcast", tree)
    _flight.record_collective("broadcast", _collective_signature(tree))
    _chaos_inject("collective")
    state = PartialState()
    if state.num_processes == 1:
        return tree
    # pragma: no cover - multihost only
    from .jax_compat import broadcast_one_to_all

    def _bcast(x):
        return broadcast_one_to_all(x, is_source=state.process_index == from_process)

    with _flight.phase("collective:broadcast", from_process=from_process):
        return recursively_apply(_bcast, tree)


def broadcast_object_list(object_list: list, from_process: int = 0) -> list:
    """Broadcast a list of picklable objects (reference ``broadcast_object_list:560``)."""
    state = PartialState()
    _flight.record_collective("broadcast_object_list", "obj")
    if state.num_processes == 1:
        if _telemetry.is_enabled():
            _record_comm("broadcast_object_list", nbytes=len(pickle.dumps(object_list)))
        return object_list
    # pragma: no cover - multihost only
    from .jax_compat import broadcast_one_to_all

    is_source = state.process_index == from_process
    payload = np.frombuffer(pickle.dumps(object_list), dtype=np.uint8)
    _record_comm("broadcast_object_list", nbytes=payload.size)
    with _flight.phase("collective:broadcast_object_list", from_process=from_process):
        size = broadcast_one_to_all(np.array([payload.size]), is_source=is_source)
        buf = np.zeros(int(size[0]), dtype=np.uint8)
        if is_source:
            buf[:] = payload
        buf = broadcast_one_to_all(buf, is_source=is_source)
    result = pickle.loads(buf.tobytes())
    object_list[:] = result
    return object_list


def reduce(tree, reduction: str = "mean", scale: float = 1.0):
    """Elementwise sum/mean of the per-PROCESS values of each leaf (reference
    ``reduce:728`` — ``dist.all_reduce`` then divide by world size for mean).

    Two leaf regimes:

    - **host-local** (numpy, or a fully-addressable ``jax.Array`` — the only
      kind whose value can differ per process): every process contributes its
      own value; they are allgathered and summed/averaged across the process
      axis. This is the path multi-host ``LocalSGD`` relies on to actually
      average divergent replicas.
    - **global** ``jax.Array`` spanning hosts (not fully addressable): GSPMD
      guarantees one consistent logical value, so "mean" of the identical
      per-process copies is the value itself and "sum" is ``num_processes ×``
      it — exactly what the reference's all_reduce computes on identical
      replicas.

    Inside jit use ``jax.lax.psum/pmean`` directly.
    """
    import jax.numpy as jnp

    state = PartialState()

    def _reduce(x):
        was_jax = _is_jax_array(x)
        if was_jax and not x.is_fully_addressable:  # pragma: no cover - multihost only
            if reduction == "sum":
                return x * (scale * state.num_processes)
            return x * scale
        if state.num_processes > 1:  # pragma: no cover - multihost only
            import jax

            from .jax_compat import process_allgather

            host_value = np.asarray(jax.device_get(x) if was_jax else x)
            stacked = process_allgather(host_value, tiled=False)
            if reduction == "mean":
                out = stacked.mean(axis=0) * scale
            elif reduction == "sum":
                out = stacked.sum(axis=0) * scale
            else:
                out = host_value * scale
            return jnp.asarray(out) if was_jax else out
        return jnp.asarray(x) * scale if was_jax else np.asarray(x) * scale

    if reduction not in ("mean", "sum", "none"):
        raise ValueError(f"reduction must be mean/sum/none, got {reduction}")
    tree = _normalize_foreign(tree)
    _record_comm("reduce", tree)
    _flight.record_collective(f"reduce:{reduction}", _collective_signature(tree))
    _chaos_inject("collective")
    with _flight.phase("collective:reduce", reduction=reduction):
        return recursively_apply(_reduce, tree)


def pad_across_processes(tree, dim: int = 0, pad_index: int = 0, pad_first: bool = False):
    """Pad array leaves to the max size along ``dim`` across processes
    (reference ``pad_across_processes:632``). Needed before ``gather`` when
    per-process batch sizes differ."""
    tree = _normalize_foreign(tree)
    state = PartialState()
    # op-only signature: padding exists precisely because per-rank shapes
    # DIFFER here — folding them in would poison the fingerprint on every
    # healthy ragged batch (same contract as the object collectives)
    _flight.record_collective("pad_across_processes", "ragged")

    def _pad(x):
        arr = np.asarray(x)
        if arr.dtype == object:
            # ragged/object leaf: not paddable as one array (reference warns
            # the same way for torch nested tensors) — passes through as-is
            import warnings

            warnings.warn(
                f"cannot pad a ragged/object leaf of type {type(x).__name__}; "
                "passing it through unpadded",
                CannotPadNestedTensorWarning,
                stacklevel=2,
            )
            return x
        if dim >= arr.ndim:
            return x
        if state.num_processes == 1:
            return x
        # pragma: no cover - multihost only
        from .jax_compat import process_allgather

        sizes = process_allgather(np.array([arr.shape[dim]]), tiled=False).reshape(-1)
        max_size = int(sizes.max())
        if max_size == arr.shape[dim]:
            return x
        pad_width = [(0, 0)] * arr.ndim
        pad_width[dim] = (max_size - arr.shape[dim], 0) if pad_first else (0, max_size - arr.shape[dim])
        return np.pad(arr, pad_width, constant_values=pad_index)

    return recursively_apply(_pad, tree)


def pad_input_tensors(tree, batch_size: int, num_processes: int, dim: int = 0):
    """Pad a batch so it divides evenly across processes by repeating final rows
    (reference ``pad_input_tensors:687``)."""

    def _pad(x):
        arr = np.asarray(x) if not _is_jax_array(x) else x
        size = arr.shape[dim]
        if size % num_processes == 0:
            return x
        target = ((size // num_processes) + 1) * num_processes
        extra = target - size
        idx = [slice(None)] * arr.ndim
        idx[dim] = slice(size - 1, size)
        tail = arr[tuple(idx)]
        reps = [1] * arr.ndim
        reps[dim] = extra
        if _is_jax_array(arr):
            import jax.numpy as jnp

            return jnp.concatenate([arr, jnp.tile(tail, reps)], axis=dim)
        return np.concatenate([arr, np.tile(tail, reps)], axis=dim)

    return recursively_apply(_pad, tree)


def slice_tensors(data, tensor_slice, process_index: Optional[int] = None, num_processes: Optional[int] = None):
    """Slice all array leaves (reference ``slice_tensors:581``)."""

    def _slice(x):
        return x[tensor_slice]

    return recursively_apply(_slice, data)


def concatenate(data: list, dim: int = 0):
    """Concatenate the leaves of a list of same-structure pytrees
    (reference ``concatenate:601``)."""
    import jax.numpy as jnp

    first = data[0]
    if isinstance(first, (list, tuple)):
        return type(first)(concatenate([d[i] for d in data], dim=dim) for i in range(len(first)))
    if isinstance(first, dict):
        return type(first)({k: concatenate([d[k] for d in data], dim=dim) for k in first})
    if _is_jax_array(first):
        return jnp.concatenate(data, axis=dim)
    return np.concatenate(data, axis=dim)


def stack_batches(batches: list):
    """Stack a list of same-structure batch pytrees along a new leading step
    axis ``[K, ...]`` — the input shape for
    :meth:`Accelerator.prepare_train_loop` (K scanned steps per dispatch).
    Any registered pytree container works (dict/list/tuple/namedtuple/...).
    No reference counterpart: the reference's hot loop is per-batch Python."""
    import jax

    def _stack(*leaves):
        if _is_jax_array(leaves[0]):
            import jax.numpy as jnp

            return jnp.stack(leaves)
        return np.stack(leaves)

    return jax.tree_util.tree_map(_stack, *batches)


def find_batch_size(data) -> Optional[int]:
    """First dimension of the first array leaf (reference ``find_batch_size:238``)."""
    if isinstance(data, (list, tuple)):
        for o in data:
            result = find_batch_size(o)
            if result is not None:
                return result
        return None
    if isinstance(data, dict):
        for v in data.values():
            result = find_batch_size(v)
            if result is not None:
                return result
        return None
    if _is_tensorlike(data) and getattr(data, "ndim", 0) >= 1:
        return int(data.shape[0])
    return None


def gather_across_data_parallel_groups(tree):
    """reference ``utils/deepspeed.py gather_across_data_parallel_groups``:
    gather each leaf across the data-parallel replicas. Under SPMD the dp axes
    are the only cross-process batch axes, so this is :func:`gather`."""
    return gather(tree)


def avg_losses_across_data_parallel_group(losses):
    """reference ``avg_losses_across_data_parallel_group``: elementwise mean of
    the per-replica loss values across the data-parallel group."""
    if isinstance(losses, (list, tuple)):
        losses = np.stack([np.asarray(v) for v in losses])
    return reduce(losses, "mean")


def ignorant_find_batch_size(data) -> Optional[int]:
    """reference ``ignorant_find_batch_size:262``: like :func:`find_batch_size`
    but never raises — any structure without an array leaf yields None."""
    try:
        return find_batch_size(data)
    except Exception:
        return None


# reference spelling for the shape/dtype skeleton leaves: TensorInformation is
# the metadata record the dispatcher's sideband exchanges; here jax's
# ShapeDtypeStruct IS that record
def TensorInformation(shape, dtype):  # noqa: N802 - reference class name
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


def is_tensor_information(x) -> bool:
    import jax

    return isinstance(x, jax.ShapeDtypeStruct)


def get_data_structure(data):
    """Shape/dtype skeleton of a pytree, for dispatch-mode metadata exchange
    (reference ``get_data_structure:188``)."""
    import jax

    def _describe(x):
        return jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype if not _is_jax_array(x) else x.dtype)

    return recursively_apply(_describe, data)


def initialize_tensors(structure):
    """Materialize zeros matching a skeleton from :func:`get_data_structure`
    (reference ``initialize_tensors:224``)."""

    def _init(x):
        return np.zeros(x.shape, dtype=x.dtype)

    import jax

    return recursively_apply(_init, structure, test_type=lambda x: isinstance(x, jax.ShapeDtypeStruct))


def verify_operation(function: Callable) -> Callable:
    """Debug-mode wrapper: before a collective, check shapes match across processes
    and raise :class:`DistributedOperationException` on mismatch (reference
    ``verify_operation:364``, enabled by ``ACCELERATE_DEBUG_MODE``)."""

    def wrapper(tree, *args, **kwargs):
        state = PartialState()
        if state.num_processes > 1 and (
            getattr(state, "debug", False) or parse_flag_from_env("ACCELERATE_DEBUG_MODE")
        ):  # pragma: no cover - multihost only
            shapes = recursively_apply(lambda x: tuple(np.shape(x)), tree, test_type=_is_tensorlike)
            all_shapes = gather_object(shapes)
            if any(s != all_shapes[0] for s in all_shapes[1:]):
                raise DistributedOperationException(
                    f"Shapes mismatch across processes in {function.__name__}: {all_shapes}"
                )
        return function(tree, *args, **kwargs)

    wrapper.__name__ = function.__name__
    return wrapper


gather = verify_operation(gather)
broadcast = verify_operation(broadcast)
reduce_ = reduce  # alias to avoid shadowing builtins at import sites


class CannotPadNestedTensorWarning(UserWarning):
    """Raised-as-warning when ``pad_across_processes`` meets a leaf it cannot
    pad (reference ``utils/operations.py`` spelling for torch nested tensors;
    here: object leaves with no shape). The leaf passes through unpadded."""
