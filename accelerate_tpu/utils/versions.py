"""Version comparison helpers (reference ``utils/versions.py`` —
``compare_versions``, ``is_torch_version``). Ours compares against jax, the
engine the framework actually rides on, with a generic probe for anything else.
"""

from __future__ import annotations

import importlib.metadata
import operator

_OPS = {
    "<": operator.lt,
    "<=": operator.le,
    "==": operator.eq,
    "!=": operator.ne,
    ">=": operator.ge,
    ">": operator.gt,
}


def _parse(v: str) -> tuple:
    """Minimal PEP-440-ish parse: numeric dotted prefix, suffixes compare as 0."""
    parts = []
    for piece in v.split(".")[:4]:
        digits = ""
        for ch in piece:
            if ch.isdigit():
                digits += ch
            else:
                break
        parts.append(int(digits) if digits else 0)
    return tuple(parts)


def compare_versions(library_or_version, op: str, requirement_version: str) -> bool:
    """``compare_versions("jax", ">=", "0.4.30")`` — reference
    ``utils/versions.py`` semantics. First arg may be a library name (its
    installed version is looked up) or a version string."""
    if op not in _OPS:
        raise ValueError(f"op must be one of {sorted(_OPS)}, got {op!r}")
    version = str(library_or_version)
    if not version[:1].isdigit():
        version = importlib.metadata.version(version)
    a, b = _parse(version), _parse(requirement_version)
    # pad to equal length so "0.7.0" == "0.7" (PEP 440 semantics)
    width = max(len(a), len(b))
    a += (0,) * (width - len(a))
    b += (0,) * (width - len(b))
    return _OPS[op](a, b)


def is_jax_version(op: str, version: str) -> bool:
    """True when the installed jax satisfies ``op version``."""
    import jax

    return compare_versions(jax.__version__, op, version)
