"""Version comparison helpers (reference ``utils/versions.py`` —
``compare_versions``, ``is_torch_version``). Ours compares against jax, the
engine the framework actually rides on, with a generic probe for anything else.
"""

from __future__ import annotations

import importlib.metadata
import operator
import re

_OPS = {
    "<": operator.lt,
    "<=": operator.le,
    "==": operator.eq,
    "!=": operator.ne,
    ">=": operator.ge,
    ">": operator.gt,
}


_PRE_RANK = {
    # PEP 440 ordering among pre-release kinds: dev < alpha < beta < rc < final
    "dev": -4,
    "alpha": -3,
    "a": -3,
    "beta": -2,
    "b": -2,
    "rc": -1,
    "c": -1,
    "preview": -1,
    "pre": -1,
    "post": 1,  # post-releases sort ABOVE the bare release
}
# longest-first alternation so "preview" isn't eaten by "pre"; anchored at the
# start of the (separator-stripped) suffix, and single-letter markers require a
# following digit/end so platform tags like "-arm64" aren't read as alpha
_PRE_RE = re.compile(r"^(preview|alpha|beta|post|dev|pre|rc|[abc](?=\d|$))[._\-]?(\d*)")


def _parse(v: str) -> "tuple[tuple, tuple]":
    """Minimal fallback parse when ``packaging`` is unavailable: returns the
    numeric dotted release tuple plus a (pre-release kind rank, pre-release
    number) pair so ``0.5.0.dev0 < 0.5.0`` and ``1.0rc1 < 1.0rc2``."""
    s = v.lower().strip()
    m = re.match(r"\d+(?:\.\d+)*", s)
    release = tuple(int(x) for x in m.group(0).split(".")) if m else (0,)
    rest = s[m.end() :] if m else s
    rest = rest.split("+", 1)[0]  # local segment ("+cuda12") never lowers rank
    pm = _PRE_RE.match(rest.lstrip("._-"))
    if pm:
        return release, (_PRE_RANK[pm.group(1)], int(pm.group(2) or 0))
    return release, (0, 0)


def _fallback_compare(version: str, op: str, requirement_version: str) -> bool:
    """Compare without ``packaging``: releases are padded to a COMMON width
    ("0.7" == "0.7.0") before the pre-release pair breaks ties."""
    a_rel, a_pre = _parse(version)
    b_rel, b_pre = _parse(requirement_version)
    width = max(len(a_rel), len(b_rel))
    a = a_rel + (0,) * (width - len(a_rel)) + a_pre
    b = b_rel + (0,) * (width - len(b_rel)) + b_pre
    return _OPS[op](a, b)


def compare_versions(library_or_version, op: str, requirement_version: str) -> bool:
    """``compare_versions("jax", ">=", "0.4.30")`` — reference
    ``utils/versions.py`` semantics. First arg may be a library name (its
    installed version is looked up) or a version string. Uses
    ``packaging.version`` (true PEP 440) when available."""
    if op not in _OPS:
        raise ValueError(f"op must be one of {sorted(_OPS)}, got {op!r}")
    version = str(library_or_version)
    if not version[:1].isdigit():
        version = importlib.metadata.version(version)
    try:
        from packaging.version import InvalidVersion, Version

        try:
            return _OPS[op](Version(version), Version(requirement_version))
        except InvalidVersion:
            pass
    except ImportError:
        pass
    return _fallback_compare(version, op, requirement_version)


def is_jax_version(op: str, version: str) -> bool:
    """True when the installed jax satisfies ``op version``."""
    import jax

    return compare_versions(jax.__version__, op, version)


def is_torch_version(op: str, version: str) -> bool:
    """reference ``is_torch_version`` — torch matters here for the interop
    bridge (torch.export) and DLPack paths."""
    import torch

    return compare_versions(torch.__version__, op, version)
