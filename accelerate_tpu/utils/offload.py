"""Disk-offload storage: numpy-memmap spill format + ``index.json``.

TPU-native counterpart of the reference's ``utils/offload.py``
(``/root/reference/src/accelerate/utils/offload.py`` — ``offload_weight:25``,
``load_offloaded_weight:46``, ``save_offload_index``, ``OffloadedWeightsLoader:127``,
``PrefixedDataset:104``). The format is identical in spirit (one ``.dat`` raw
memmap per tensor + a json index of shape/dtype) so offloaded checkpoints are
inspectable with plain numpy; loading returns zero-copy memmaps that
``jax.device_put`` streams to HBM without an intermediate copy.
"""

from __future__ import annotations

import json
import os
from collections.abc import Mapping
from typing import Optional

import numpy as np


def offload_weight(weight, weight_name: str, offload_folder: str, index: Optional[dict] = None) -> dict:
    """Spill one array to ``<offload_folder>/<weight_name>.dat`` (raw memmap)
    and record shape/dtype in ``index`` (reference ``offload_weight:25``)."""
    array = np.asarray(weight)
    dtype = None
    if array.dtype == np.dtype("bfloat16") or str(array.dtype) == "bfloat16":
        # bfloat16 has no portable numpy memmap dtype: store the raw bits as
        # int16 and remember the logical dtype (reference stores bf16 as int16
        # too, utils/offload.py:29-34).
        dtype = "bfloat16"
        array = array.view(np.int16) if array.dtype != np.int16 else array
    if index is None:
        index = {}
    tensor_file = os.path.join(offload_folder, f"{weight_name}.dat")
    # param paths are '/'-joined — keep the hierarchy on disk
    os.makedirs(os.path.dirname(tensor_file), exist_ok=True)
    index[weight_name] = {"dtype": dtype or str(array.dtype), "shape": list(array.shape)}
    if array.ndim == 0:
        array = array[None]
    file_array = np.memmap(tensor_file, dtype=array.dtype, mode="w+", shape=array.shape)
    file_array[:] = array[:]
    file_array.flush()
    return index


def load_offloaded_weight(weight_file: str, weight_info: dict) -> np.ndarray:
    """Memmap one spilled array back (reference ``load_offloaded_weight:46``)."""
    shape = tuple(weight_info["shape"])
    if shape == ():
        shape = (1,)
    dtype = weight_info["dtype"]
    logical_bf16 = dtype == "bfloat16"
    if logical_bf16:
        dtype = "int16"
    weight = np.memmap(weight_file, dtype=dtype, shape=shape, mode="r")
    if tuple(weight_info["shape"]) == ():
        weight = weight[0]
    if logical_bf16:
        import ml_dtypes

        weight = weight.view(ml_dtypes.bfloat16)
    return weight


def save_offload_index(index: dict, offload_folder: str) -> None:
    if index is None or len(index) == 0:
        return
    offload_index_file = os.path.join(offload_folder, "index.json")
    current_index = {}
    if os.path.isfile(offload_index_file):
        with open(offload_index_file, encoding="utf-8") as f:
            current_index = json.load(f)
    current_index.update(index)
    with open(offload_index_file, "w", encoding="utf-8") as f:
        json.dump(current_index, f, indent=2)


def load_offload_index(offload_folder: str) -> dict:
    offload_index_file = os.path.join(offload_folder, "index.json")
    if not os.path.isfile(offload_index_file):
        return {}
    with open(offload_index_file, encoding="utf-8") as f:
        return json.load(f)


def offload_state_dict(save_dir: str, state_dict: Mapping) -> None:
    """Spill a flat ``{name: array}`` dict (reference ``offload_state_dict:76``)."""
    os.makedirs(save_dir, exist_ok=True)
    index = {}
    for name, parameter in state_dict.items():
        index = offload_weight(parameter, name, save_dir, index=index)
    save_offload_index(index, save_dir)


class PrefixedDataset(Mapping):
    """View of a mapping keyed under a prefix (reference ``PrefixedDataset:104``)."""

    def __init__(self, dataset: Mapping, prefix: str):
        self.dataset = dataset
        self.prefix = prefix

    def __getitem__(self, key):
        return self.dataset[f"{self.prefix}{key}"]

    def __iter__(self):
        return iter([key for key in self.dataset if key.startswith(self.prefix)])

    def __len__(self):
        return len([key for key in self.dataset if key.startswith(self.prefix)])


class OffloadedWeightsLoader(Mapping):
    """Unified lazy mapping over in-memory arrays + a disk-offload folder
    (reference ``OffloadedWeightsLoader:127``). Values come back as numpy
    (mem)maps ready for ``jax.device_put``."""

    def __init__(
        self,
        state_dict: Optional[Mapping] = None,
        save_folder: Optional[str] = None,
        index: Optional[Mapping] = None,
    ):
        if state_dict is None and save_folder is None and index is None:
            raise ValueError("need either a state_dict, a save_folder or an index")
        self.state_dict = dict(state_dict) if state_dict is not None else {}
        if index is None and save_folder is not None:
            index = load_offload_index(save_folder)
        self.index = dict(index) if index is not None else {}
        self.save_folder = save_folder
        self.all_keys = list(self.state_dict.keys())
        self.all_keys.extend([key for key in self.index if key not in self.all_keys])

    def __getitem__(self, key: str):
        if key in self.state_dict:
            return self.state_dict[key]
        weight_info = self.index[key]
        if weight_info.get("safetensors_file") is not None:
            # weight lives inside a safetensors shard; lazy-slice just this one
            from safetensors import safe_open

            with safe_open(weight_info["safetensors_file"], framework="numpy") as f:
                return f.get_tensor(weight_info.get("weight_name", key))
        weight_file = os.path.join(self.save_folder, f"{key}.dat")
        return load_offloaded_weight(weight_file, weight_info)

    def __iter__(self):
        return iter(self.all_keys)

    def __len__(self):
        return len(self.all_keys)
