"""LocalSGD: k-step local updates, then cross-replica parameter averaging.

TPU-native counterpart of the reference's ``local_sgd.py``
(``/root/reference/src/accelerate/local_sgd.py`` — ``LocalSGD:19``,
``_sync_and_avg_model_params:97-106`` which calls ``reduce(params, "mean")``).
Communication drops from every-step gradient allreduce to a parameter average
every ``local_sgd_steps`` — useful when dp replicas sit across DCN.

Two surfaces:

- :class:`LocalSGD` — imperative context manager with the reference's API
  (``with LocalSGD(...) as ls: ... ls.step()``).
- :func:`make_local_sgd_train_step` — the compiled path: each ``dp`` group keeps
  its OWN param copy (leaves carry a leading ``dp`` axis, sharded over the mesh
  so HBM cost equals the replicated baseline), updates locally with zero
  cross-replica traffic, and a traced ``lax.cond`` averages params only on
  boundary steps. The reference cannot express this (DDP syncs in backward);
  under ``shard_map`` it is one scan-friendly jitted function.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import numpy as np

from .utils import operations as ops


class LocalSGD:
    """Imperative parity surface (reference ``LocalSGD:19``).

    ``step()`` counts micro-steps; every ``local_sgd_steps`` the registered
    params are averaged across replicas via ``reduce(..., "mean")`` exactly like
    the reference's ``_sync_and_avg_model_params``.
    """

    def __init__(self, accelerator, model=None, local_sgd_steps: int = 8, enabled: bool = True):
        if accelerator.parallelism_config is not None and accelerator.parallelism_config.tp_enabled:
            raise NotImplementedError("LocalSGD is not supported with tensor parallelism")
        self.enabled = enabled and accelerator.use_distributed
        self.accelerator = accelerator
        self.local_sgd_steps = local_sgd_steps
        self.num_steps = 0
        self._params = model

    def __enter__(self):
        if self.enabled:
            # local phase: suppress grad sync bookkeeping (reference __enter__
            # enters model.no_sync())
            self.accelerator.gradient_state._set_sync_gradients(False)
        return self

    def __exit__(self, *exc):
        if self.enabled:
            self._sync_and_avg()
            self.accelerator.gradient_state._set_sync_gradients(True)

    def step(self, params=None):
        """Call after every optimizer step; averages on the k-step boundary."""
        if params is not None:
            self._params = params
        self.num_steps += 1
        if not self.enabled:
            return self._params
        if self.num_steps % self.local_sgd_steps == 0:
            self._params = self._sync_and_avg()
        return self._params

    def _sync_and_avg(self):
        if self._params is not None:
            self._params = ops.reduce_(self._params, reduction="mean")
        return self._params


def make_local_sgd_train_step(
    loss_fn: Callable,
    optimizer,
    mesh,
    local_sgd_steps: int = 8,
    dp_axis: str = "dp_shard",
    jit: bool = True,
) -> Callable:
    """Compiled local-SGD: ``step(params_stack, opt_state_stack, batch, step_idx)``.

    ``params_stack`` leaves have a leading axis of size ``mesh.shape[dp_axis]``,
    sharded over ``dp_axis`` — each dp group trains its own replica. Gradients
    never cross replicas; on steps where ``(step_idx+1) % local_sgd_steps == 0``
    a ``lax.pmean`` over ``dp_axis`` averages params (and resets nothing else).

    Build the stack with :func:`replicate_for_local_sgd`.
    """
    import jax
    import jax.numpy as jnp
    import optax
    from .utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    n_rep = int(mesh.shape[dp_axis])

    def _local_step(params, opt_state, batch, step_idx):
        # params leaves arrive as [1, ...] local slices inside shard_map
        squeeze = lambda t: jax.tree_util.tree_map(lambda x: x[0], t)
        unsqueeze = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        p, s = squeeze(params), squeeze(opt_state)
        loss, grads = jax.value_and_grad(loss_fn)(p, batch)
        updates, new_s = optimizer.update(grads, s, p)
        new_p = optax.apply_updates(p, updates)
        do_avg = (step_idx + 1) % local_sgd_steps == 0
        new_p = jax.lax.cond(
            do_avg,
            lambda t: jax.tree_util.tree_map(lambda x: jax.lax.pmean(x, dp_axis), t),
            lambda t: t,
            new_p,
        )
        # loss averaged for reporting only
        loss = jax.lax.pmean(loss, dp_axis)
        return unsqueeze(new_p), unsqueeze(new_s), loss

    def _specs_like(tree, leading):
        return jax.tree_util.tree_map(lambda _: P(*leading), tree, is_leaf=lambda x: x is None)

    def step(params_stack, opt_state_stack, batch, step_idx):
        stack_spec = jax.tree_util.tree_map(lambda _: P(dp_axis), params_stack)
        opt_spec = jax.tree_util.tree_map(lambda _: P(dp_axis), opt_state_stack)
        batch_spec = jax.tree_util.tree_map(lambda _: P(dp_axis), batch)
        fn = shard_map(
            _local_step,
            mesh=mesh,
            in_specs=(stack_spec, opt_spec, batch_spec, P()),
            out_specs=(stack_spec, opt_spec, P()),
            check_vma=False,
        )
        return fn(params_stack, opt_state_stack, batch, step_idx)

    return jax.jit(step) if jit else step


def replicate_for_local_sgd(tree, mesh, dp_axis: str = "dp_shard"):
    """Stack a param/opt-state tree ``n_rep`` times along a new leading axis and
    shard it over ``dp_axis`` (each dp group gets one resident copy)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_rep = int(mesh.shape[dp_axis])

    def _stack(x):
        stacked = jnp.stack([jnp.asarray(x)] * n_rep, axis=0)
        return jax.device_put(stacked, NamedSharding(mesh, P(dp_axis)))

    return jax.tree_util.tree_map(_stack, tree)


def unstack_local_sgd(tree_stack, index: int = 0):
    """Take one replica back out of the stack (they are equal right after an
    averaging boundary)."""
    import jax

    return jax.tree_util.tree_map(lambda x: x[index], tree_stack)
