"""LR scheduler wrapper.

TPU-native counterpart of the reference's ``scheduler.py``
(``/root/reference/src/accelerate/scheduler.py`` — ``AcceleratedScheduler:25``,
``step:54-83``): steps only when the optimizer really stepped (gradient-
accumulation boundaries; fp16 overflow skips), and — matching reference
semantics when ``split_batches=False`` — advances ``num_processes``× per call so
schedules written for single-device step counts stay correct at the same
*sample* budget.

Two underlying kinds are supported:

- an **optax schedule** (pure ``step -> lr`` fn): the compiled train-step path
  evaluates it internally, so this wrapper only tracks ``get_last_lr`` and the
  checkpointable step counter;
- a **torch-style scheduler object** (has ``.step()``; e.g. the lr_scheduler a
  torch-interop script built over its torch optimizer): we advance it so the
  bridged optimizer observes the updated ``param_groups[...]["lr"]``.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

from .state import GradientState


class AcceleratedScheduler:
    def __init__(
        self,
        schedule_fn: Union[Callable[[int], float], object],
        optimizer=None,
        step_with_optimizer: bool = True,
        split_batches: bool = False,
        num_processes: Optional[int] = None,
    ):
        # a torch-style scheduler is an object with .step(); an optax schedule
        # is a plain callable step->lr
        self.scheduler = schedule_fn if hasattr(schedule_fn, "step") else None
        self.schedule_fn = None if self.scheduler is not None else schedule_fn
        self.optimizer = optimizer
        self.step_with_optimizer = step_with_optimizer
        self.split_batches = split_batches
        self.gradient_state = GradientState()
        self._step_count = 0
        if num_processes is None:
            from .state import AcceleratorState

            # scale by the data-parallel world size (dp_replicate x dp_shard), not
            # the total device count — tp/cp/sp/ep devices see the same samples
            state = AcceleratorState()
            pc = state.parallelism_config
            num_processes = pc.dp_replicate_size * pc.infer_dp_shard(state.num_devices)
        self.num_processes = num_processes

    def _advance(self, times: int) -> None:
        self._step_count += times
        if self.scheduler is not None:
            for _ in range(times):
                self.scheduler.step()

    def step(self) -> None:
        if not self.step_with_optimizer:
            self._advance(1)
            return
        # never advance on non-boundary accumulation micro-steps (reference :62-65)
        if not self.gradient_state.sync_gradients:
            return
        self._advance(1 if self.split_batches else self.num_processes)

    @property
    def last_lr(self) -> float:
        if self.scheduler is not None:
            return float(self.scheduler.get_last_lr()[0])
        return float(self.schedule_fn(self._step_count))

    def get_last_lr(self) -> list[float]:
        if self.scheduler is not None:
            return list(self.scheduler.get_last_lr())
        return [self.last_lr]

    def state_dict(self) -> dict:
        state = {"step_count": self._step_count}
        if self.scheduler is not None and hasattr(self.scheduler, "state_dict"):
            inner = self.scheduler.state_dict()
            # keep it JSON-serializable for checkpointing.py
            state["scheduler"] = {
                k: v for k, v in inner.items() if isinstance(v, (int, float, str, bool, list, type(None)))
            }
        return state

    def load_state_dict(self, state: dict) -> None:
        self._step_count = state["step_count"]
        if self.scheduler is not None and "scheduler" in state and hasattr(self.scheduler, "load_state_dict"):
            try:
                self.scheduler.load_state_dict(state["scheduler"])
            except Exception:  # partial snapshot (non-JSON fields dropped at save)
                pass
