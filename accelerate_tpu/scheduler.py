"""LR scheduler wrapper.

TPU-native counterpart of the reference's ``scheduler.py``
(``/root/reference/src/accelerate/scheduler.py`` — ``AcceleratedScheduler:25``,
``step:54-83``): steps only when the optimizer really stepped (gradient-
accumulation boundaries; fp16 overflow skips), and — matching reference
semantics when ``split_batches=False`` — advances ``num_processes``× per call so
schedules written for single-device step counts stay correct at the same
*sample* budget.

In optax, a schedule is a pure ``step -> lr`` function that the optimizer chain
evaluates on its internal count, so the compiled train-step path needs no
scheduler object at all. This wrapper exists for the imperative/parity API:
tracking ``get_last_lr`` and checkpointing the step counter.
"""

from __future__ import annotations

from typing import Callable, Optional

from .state import GradientState


class AcceleratedScheduler:
    def __init__(
        self,
        schedule_fn: Callable[[int], float],  # optax schedule
        optimizer=None,
        step_with_optimizer: bool = True,
        split_batches: bool = False,
        num_processes: Optional[int] = None,
    ):
        self.schedule_fn = schedule_fn
        self.optimizer = optimizer
        self.step_with_optimizer = step_with_optimizer
        self.split_batches = split_batches
        self.gradient_state = GradientState()
        self._step_count = 0
        if num_processes is None:
            from .state import AcceleratorState

            # scale by the data-parallel world size (dp_replicate x dp_shard), not
            # the total device count — tp/cp/sp/ep devices see the same samples
            state = AcceleratorState()
            pc = state.parallelism_config
            num_processes = pc.dp_replicate_size * pc.infer_dp_shard(state.num_devices)
        self.num_processes = num_processes

    def step(self) -> None:
        if not self.step_with_optimizer:
            self._step_count += 1
            return
        # never advance on non-boundary accumulation micro-steps (reference :62-65)
        if not self.gradient_state.sync_gradients:
            return
        if self.split_batches:
            self._step_count += 1
        else:
            self._step_count += self.num_processes

    @property
    def last_lr(self) -> float:
        return float(self.schedule_fn(self._step_count))

    def get_last_lr(self) -> list[float]:
        return [self.last_lr]

    def state_dict(self) -> dict:
        return {"step_count": self._step_count}

    def load_state_dict(self, state: dict) -> None:
        self._step_count = state["step_count"]
