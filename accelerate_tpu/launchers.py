"""Notebook/debug launchers.

TPU-native counterpart of the reference's ``launchers.py``
(``/root/reference/src/accelerate/launchers.py`` — ``notebook_launcher:41``,
``debug_launcher:276``). The reference must fork ``num_processes`` python
processes (Colab TPU via ``xmp.spawn``, one per core); under SPMD **one process
drives every local chip**, so launching from a notebook is simply calling the
function — with env setup for multi-host when a coordinator is given.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional

from .utils.environment import patch_environment


def notebook_launcher(
    function: Callable,
    args: tuple = (),
    num_processes: Optional[int] = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    master_addr: Optional[str] = None,
    node_rank: int = 0,
    num_nodes: int = 1,
    **kwargs: Any,
):
    """Run ``function(*args)`` with the accelerate env configured
    (reference ``notebook_launcher launchers.py:41``).

    Single host: direct call — jit already uses every local chip; no forking
    (the reference's per-core ``xmp.spawn`` is an artifact of non-SPMD torch-xla).
    Multi-host notebooks: pass ``master_addr``/``num_nodes``/``node_rank`` and the
    coordinator env is set before the call.
    """
    env: dict[str, Any] = {"ACCELERATE_MIXED_PRECISION": mixed_precision}
    if num_nodes > 1:
        if master_addr is None:
            raise ValueError("multi-node notebook launch needs master_addr")
        env.update(
            ACCELERATE_COORDINATOR_ADDRESS=f"{master_addr}:{use_port}",
            ACCELERATE_NUM_PROCESSES=num_nodes,
            ACCELERATE_PROCESS_ID=node_rank,
        )
    with patch_environment(**env):
        return function(*args)


class PrepareForLaunch:
    """reference ``PrepareForLaunch utils/launch.py``: a picklable wrapper that
    sets the per-process env protocol before calling ``function`` — used when
    a launcher spawns worker processes for multi-host rendezvous."""

    def __init__(self, launcher: Callable, distributed_type: str = "NO", debug: bool = False):
        self.launcher = launcher
        self.distributed_type = str(distributed_type)
        self.debug = debug

    def __call__(self, index: int, *args):
        env: dict[str, Any] = {"ACCELERATE_PROCESS_ID": index}
        if self.debug:
            env["ACCELERATE_DEBUG_MODE"] = "true"
        with patch_environment(**env):
            return self.launcher(*args)


def debug_launcher(function: Callable, args: tuple = (), num_processes: int = 2):
    """Run ``function`` on a virtual ``num_processes``-device CPU mesh
    (reference ``debug_launcher:276`` forks CPU processes; here XLA fakes the
    devices in-process, which exercises real sharding semantics).

    Must be called before JAX initializes its backends.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={num_processes}"
        ).strip()
    import jax

    if not getattr(jax._src.xla_bridge, "_backends", None):
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    with patch_environment(ACCELERATE_USE_CPU="yes"):
        return function(*args)
