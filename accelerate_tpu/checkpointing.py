"""Checkpoint save/load: model, optimizer, scheduler, dataloader, RNG, custom objects.

TPU-native counterpart of the reference's ``checkpointing.py``
(``/root/reference/src/accelerate/checkpointing.py`` — ``save_accelerator_state:62``
with RNG capture ``:153-176``, ``load_accelerator_state:180`` with RNG restore
``:287-309``, ``save_custom_state:314``) and the Accelerator glue
(``accelerator.py:3529`` rotation/naming ``:3567-3593``, ``save_model:3386``
safetensors shard-splitting).

Format: each pytree is flattened to '/'-joined paths and stored as one
``.npz`` (or safetensors for model export). Sharded ``jax.Array`` leaves are
gathered to host — the ZeRO-3/FSDP "16-bit gather on save" (reference
``get_state_dict accelerator.py:3947``) collapses to a reshard-to-replicated.
Loading re-places leaves with the live tree's shardings preserved.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, Optional

import numpy as np

from .logging import get_logger

logger = get_logger(__name__)

MODEL_NAME = "model"
OPTIMIZER_NAME = "optimizer"
SCHEDULER_NAME = "scheduler"
SAMPLER_NAME = "dataloader"
RNG_NAME = "random_states"
CUSTOM_NAME = "custom_checkpoint"

# reference utils/constants.py:20-33 spellings, reflecting THIS framework's
# file layout (safetensors for interop, npz for the dependency-free path)
SAFE_MODEL_NAME = MODEL_NAME
SAFE_WEIGHTS_NAME = "model.safetensors"
SAFE_WEIGHTS_INDEX_NAME = "model.safetensors.index.json"
SAFE_WEIGHTS_PATTERN_NAME = "model{suffix}.safetensors"
WEIGHTS_NAME = "model.npz"
WEIGHTS_INDEX_NAME = "model.npz.index.json"
WEIGHTS_PATTERN_NAME = "model{suffix}.npz"
RNG_STATE_NAME = RNG_NAME
SCALER_NAME = "scaler"  # fp16 scale state lives inside the optimizer state
PROFILE_PATTERN_NAME = "profile_{suffix}.json"


# ---------------------------------------------------------------------------
# pytree <-> flat dict


def flatten_pytree(tree) -> dict[str, np.ndarray]:
    import jax

    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(
            str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))) for p in path
        )
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            # multi-host sharded leaf: reshard to replicated first (the ZeRO-3
            # gather-on-save, reference accelerator.py:3947)
            from .utils.operations import _replicate_global_array

            leaf = _replicate_global_array(leaf)
        flat[key or "_root"] = np.asarray(leaf)
    return flat


def unflatten_into(template, flat: dict[str, np.ndarray]):
    """Restore values from ``flat`` into the structure of ``template``, preserving
    each live leaf's sharding/dtype placement."""
    import jax

    def _restore(path, leaf):
        key = "/".join(
            str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))) for p in path
        )
        key = key or "_root"
        if key not in flat:
            raise KeyError(f"checkpoint missing key {key!r}")
        value = flat[key]
        if isinstance(leaf, jax.Array):
            return jax.device_put(value.astype(leaf.dtype), leaf.sharding)
        return np.asarray(value, dtype=getattr(leaf, "dtype", None))

    return jax.tree_util.tree_map_with_path(_restore, template)


def save_pytree(tree, path: str) -> None:
    np.savez(path, **flatten_pytree(tree))


def load_flat(path: str) -> dict[str, np.ndarray]:
    with np.load(path, allow_pickle=False) as data:
        return {k: data[k] for k in data.files}


# ---------------------------------------------------------------------------
# accelerator state


def _checkpoint_dir(accelerator, output_dir: Optional[str]) -> str:
    pc = accelerator.project_configuration
    if output_dir is None:
        if pc.automatic_checkpoint_naming:
            output_dir = os.path.join(accelerator.project_dir or ".", "checkpoints")
        else:
            raise ValueError("pass output_dir or enable automatic_checkpoint_naming")
    if pc.automatic_checkpoint_naming:
        folder = os.path.join(output_dir, f"checkpoint_{pc.iteration}")
        # every process checks (raising only on main would leave the others hung
        # at the save barrier); the iteration counter is process-consistent
        if os.path.isdir(folder):
            raise FileExistsError(
                f"Checkpoint {folder} already exists — iteration was not advanced"
            )
        if accelerator.is_main_process:
            # rotation (reference accelerator.py:3567-3593)
            if pc.total_limit is not None and os.path.isdir(output_dir):
                existing = sorted(
                    (d for d in os.listdir(output_dir) if re.fullmatch(r"checkpoint_\d+", d)),
                    key=lambda d: int(d.split("_")[1]),
                )
                while len(existing) + 1 > pc.total_limit:
                    victim = existing.pop(0)
                    shutil.rmtree(os.path.join(output_dir, victim), ignore_errors=True)
        output_dir = folder
    return output_dir


def _should_shard(trees) -> bool:
    """Auto-detect: shard the save when any leaf is not fully addressable
    (multi-host sharded state — gathering it to one host is exactly the
    host-RAM-OOM failure mode the reference avoids with DCP sharded writers)."""
    import jax

    for tree in trees:
        if tree is None:
            continue
        for leaf in jax.tree_util.tree_leaves(tree):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                return True
    return False


def _remove_stale_model_files(output_dir: str) -> None:
    """Remove previous model/optimizer artifacts (both formats) from a reused
    checkpoint dir so a fresh save never mixes with leftovers."""
    pattern = re.compile(
        rf"({MODEL_NAME}|{OPTIMIZER_NAME})(_\d+)?"
        r"(\.npz|-shard-\d{5}\.(npz|bin|index\.json))"
    )
    for name in os.listdir(output_dir):
        if pattern.fullmatch(name):
            try:
                os.remove(os.path.join(output_dir, name))
            except OSError:  # pragma: no cover - concurrent cleanup
                pass


def save_accelerator_state(
    accelerator,
    output_dir: Optional[str] = None,
    params=None,
    opt_state=None,
    save_on_each_node: bool = False,
    sharded: Optional[bool] = None,
) -> str:
    """Save everything needed to resume (reference ``save_accelerator_state:62``
    driven by ``accelerator.save_state:3529``).

    ``params``/``opt_state`` let functional training loops pass their live
    threaded values explicitly; without them the values written back by the
    prepared train step (``Accelerator.prepare_train_step``) are used.

    ``sharded=True`` (auto-on when any leaf spans hosts) writes model/optimizer
    state as per-process shard files — no host ever materializes the full
    state (reference ``save_fsdp_model utils/fsdp_utils.py:103`` via
    ``torch.distributed.checkpoint`` sharded writers).
    """
    from .utils.random import capture_rng_states

    output_dir = _checkpoint_dir(accelerator, output_dir)
    is_writer = accelerator.is_main_process or save_on_each_node
    if is_writer:
        os.makedirs(output_dir, exist_ok=True)

    models = [params] if params is not None else accelerator._models
    opt_states = (
        [opt_state] if opt_state is not None else [o.opt_state for o in accelerator._optimizers]
    )
    # user pre-hooks see the RESOLVED directory (post automatic naming), like
    # the reference's register_save_state_pre_hook contract (accelerator.py:3497)
    for hook in getattr(accelerator, "_save_state_pre_hooks", {}).values():
        hook(models, output_dir)
    if sharded is None:
        sharded = _should_shard(list(models) + list(opt_states))
    # a reused output_dir may hold the OTHER format (or shard files from a
    # different process count) — load prefers npz and merges every index file,
    # so stale leftovers would silently restore old state; scrub first. Every
    # writer scrubs: with save_on_each_node on a node-local FS the main
    # process cannot reach the other nodes' dirs
    if is_writer and os.path.isdir(output_dir):
        _remove_stale_model_files(output_dir)
    # barrier taken by EVERY process (a branch-local one would deadlock when
    # only rank 0 writes): no process starts writing until every writer's
    # stale-file scrub is done — with save_on_each_node on a shared fs all
    # processes write into the same dir
    accelerator.wait_for_everyone()
    if sharded:
        from .sharded_checkpoint import save_sharded_pytree

        os.makedirs(output_dir, exist_ok=True)  # every proc makes its own
        for i, model in enumerate(models):
            suffix = "" if i == 0 else f"_{i}"
            save_sharded_pytree(model, output_dir, prefix=f"{MODEL_NAME}{suffix}")
        for i, state in enumerate(opt_states):
            if state is not None:
                suffix = "" if i == 0 else f"_{i}"
                save_sharded_pytree(state, output_dir, prefix=f"{OPTIMIZER_NAME}{suffix}")
    elif is_writer:
        for i, model in enumerate(models):
            suffix = "" if i == 0 else f"_{i}"
            save_pytree(model, os.path.join(output_dir, f"{MODEL_NAME}{suffix}.npz"))
        for i, state in enumerate(opt_states):
            if state is not None:
                suffix = "" if i == 0 else f"_{i}"
                save_pytree(state, os.path.join(output_dir, f"{OPTIMIZER_NAME}{suffix}.npz"))
    if is_writer:
        for i, sched in enumerate(accelerator._schedulers):
            suffix = "" if i == 0 else f"_{i}"
            with open(os.path.join(output_dir, f"{SCHEDULER_NAME}{suffix}.json"), "w") as f:
                json.dump(sched.state_dict(), f)
        for i, dl in enumerate(accelerator._dataloaders):
            suffix = "" if i == 0 else f"_{i}"
            base = os.path.join(output_dir, f"{SAMPLER_NAME}{suffix}")
            state = dl.state_dict()
            # a stateful INNER loader's (torchdata) state is OPAQUE: always
            # pickle it — json "succeeding" can still be lossy (int dict keys
            # coerce to strings, mangling worker-state maps), and tensors/bytes
            # fail outright. Native wrapper states are plain and stay json.
            payload = None
            if not getattr(dl, "_stateful_inner", False):
                try:
                    payload = json.dumps(state)
                    if json.loads(payload) != state:
                        # dumps can "succeed" lossily (int dict keys coerce to
                        # strings, tuples to lists) — only a clean round-trip
                        # may use the json spelling
                        payload = None
                except (TypeError, ValueError):
                    payload = None  # e.g. a custom sampler with tensor state
            if payload is None:
                import pickle as _pickle

                with open(base + ".pkl", "wb") as f:
                    _pickle.dump(state, f)
                if os.path.exists(base + ".json"):  # overwritten checkpoint dir
                    os.remove(base + ".json")
            else:
                with open(base + ".json", "w") as f:
                    f.write(payload)
                if os.path.exists(base + ".pkl"):
                    os.remove(base + ".pkl")
        for i, obj in enumerate(accelerator._custom_objects):
            _save_custom(obj, os.path.join(output_dir, f"{CUSTOM_NAME}_{i}.npz"))

    # RNG is per-process (reference :153-176)
    rng_states = capture_rng_states()
    rng_file = os.path.join(output_dir, f"{RNG_NAME}_{accelerator.process_index}.pkl")
    accelerator.wait_for_everyone()
    import pickle

    os.makedirs(output_dir, exist_ok=True)
    with open(rng_file, "wb") as f:
        pickle.dump(rng_states, f)

    accelerator.project_configuration.iteration += 1
    logger.info(f"saved state to {output_dir}")
    return output_dir


def load_accelerator_state(
    accelerator,
    input_dir: Optional[str] = None,
    params=None,
    opt_state=None,
    load_kwargs: Optional[dict] = None,
):
    """Mirror of :func:`save_accelerator_state` (reference
    ``load_accelerator_state:180``). Returns restored params (pytree or list);
    with ``opt_state`` given as a live template, returns
    ``(params, opt_state)`` so functional loops can rethread both."""
    from .utils.random import restore_rng_states

    if input_dir is None:
        base = os.path.join(accelerator.project_dir or ".", "checkpoints")
        candidates = sorted(
            (d for d in os.listdir(base) if re.fullmatch(r"checkpoint_\d+", d)),
            key=lambda d: int(d.split("_")[1]),
        )
        if not candidates:
            raise FileNotFoundError(f"no checkpoints under {base}")
        input_dir = os.path.join(base, candidates[-1])

    # user pre-hooks see the RESOLVED directory (after latest-checkpoint
    # discovery), reference register_load_state_pre_hook contract (:3664)
    for hook in getattr(accelerator, "_load_state_pre_hooks", {}).values():
        hook([params] if params is not None else accelerator._models, input_dir)

    from .sharded_checkpoint import is_sharded_checkpoint, load_sharded_pytree

    def _load_tree(prefix: str, template):
        """Dispatch npz vs sharded format; returns None if neither exists."""
        npz_path = os.path.join(input_dir, f"{prefix}.npz")
        if os.path.exists(npz_path):
            return unflatten_into(template, load_flat(npz_path))
        if is_sharded_checkpoint(input_dir, prefix):
            return load_sharded_pytree(template, input_dir, prefix)
        return None

    models = [params] if params is not None else accelerator._models
    restored = []
    for i, model in enumerate(models):
        suffix = "" if i == 0 else f"_{i}"
        value = _load_tree(f"{MODEL_NAME}{suffix}", model)
        if value is None:
            raise FileNotFoundError(f"no {MODEL_NAME}{suffix} checkpoint in {input_dir}")
        restored.append(value)
    restored_opt_state = None
    if opt_state is not None:
        restored_opt_state = _load_tree(OPTIMIZER_NAME, opt_state)
        if restored_opt_state is not None and accelerator._optimizers:
            accelerator._optimizers[0].opt_state = restored_opt_state
    else:
        for i, opt in enumerate(accelerator._optimizers):
            suffix = "" if i == 0 else f"_{i}"
            if opt.opt_state is not None:
                value = _load_tree(f"{OPTIMIZER_NAME}{suffix}", opt.opt_state)
                if value is not None:
                    opt.opt_state = value
    for i, sched in enumerate(accelerator._schedulers):
        suffix = "" if i == 0 else f"_{i}"
        path = os.path.join(input_dir, f"{SCHEDULER_NAME}{suffix}.json")
        if os.path.exists(path):
            with open(path) as f:
                sched.load_state_dict(json.load(f))
    for i, dl in enumerate(accelerator._dataloaders):
        suffix = "" if i == 0 else f"_{i}"
        base = os.path.join(input_dir, f"{SAMPLER_NAME}{suffix}")
        if os.path.exists(base + ".json"):
            with open(base + ".json") as f:
                dl.load_state_dict(json.load(f))
        elif os.path.exists(base + ".pkl"):  # tensorful stateful-inner state
            import pickle as _pickle

            with open(base + ".pkl", "rb") as f:
                dl.load_state_dict(_pickle.load(f))
    for i, obj in enumerate(accelerator._custom_objects):
        _load_custom(obj, os.path.join(input_dir, f"{CUSTOM_NAME}_{i}.npz"))

    # restore the automatic-naming iteration counter so the next save does not
    # collide with an existing checkpoint_<i> after a process restart
    folder = os.path.basename(os.path.normpath(input_dir))
    match = re.fullmatch(r"checkpoint_(\d+)", folder)
    if match:
        accelerator.project_configuration.iteration = int(match.group(1)) + 1

    rng_file = os.path.join(input_dir, f"{RNG_NAME}_{accelerator.process_index}.pkl")
    if os.path.exists(rng_file):
        import pickle

        with open(rng_file, "rb") as f:
            try:
                restore_rng_states(pickle.load(f))
            except Exception as e:  # version drift in host RNG formats is non-fatal
                logger.warning(f"could not restore RNG states: {e}")

    logger.info(f"loaded state from {input_dir}")
    if params is not None:
        return (restored[0], restored_opt_state) if opt_state is not None else restored[0]
    accelerator._models = restored
    return (restored, restored_opt_state) if opt_state is not None else restored


def _save_custom(obj, path: str) -> None:
    state = obj.state_dict()
    flat = flatten_pytree(state)
    np.savez(path, **flat)
    with open(path + ".meta.json", "w") as f:
        json.dump({"keys": sorted(flat)}, f)


def _load_custom(obj, path: str) -> None:
    state = obj.state_dict()
    flat = load_flat(path)
    obj.load_state_dict(unflatten_into(state, flat))


# ---------------------------------------------------------------------------
# model export (safetensors interop)


def _parse_size(size: str) -> int:
    match = re.fullmatch(r"(\d+)\s*([KMGT]?B)", size.strip(), re.IGNORECASE)
    if not match:
        raise ValueError(f"cannot parse size {size!r}")
    mult = {"B": 1, "KB": 2**10, "MB": 2**20, "GB": 2**30, "TB": 2**40}
    return int(match.group(1)) * mult[match.group(2).upper()]


def save_model(
    params,
    save_directory: str,
    max_shard_size: str = "10GB",
    safe_serialization: bool = True,
) -> list[str]:
    """Export params as (sharded) safetensors with an index.json — interop format
    (reference ``save_model accelerator.py:3386``; file layout mirrors
    ``model.safetensors.index.json`` conventions)."""
    os.makedirs(save_directory, exist_ok=True)
    flat = flatten_pytree(params)
    limit = _parse_size(max_shard_size)

    shards: list[dict[str, np.ndarray]] = [{}]
    sizes = [0]
    for key in sorted(flat):
        arr = flat[key]
        nbytes = arr.nbytes
        if sizes[-1] + nbytes > limit and shards[-1]:
            shards.append({})
            sizes.append(0)
        shards[-1][key] = arr
        sizes[-1] += nbytes

    written = []
    if safe_serialization:
        from safetensors.numpy import save_file

        if len(shards) == 1:
            path = os.path.join(save_directory, SAFE_WEIGHTS_NAME)
            save_file(_safetensors_compat(shards[0]), path)
            written.append(path)
        else:
            index = {"metadata": {"total_size": sum(sizes)}, "weight_map": {}}
            for i, shard in enumerate(shards):
                name = f"model-{i + 1:05d}-of-{len(shards):05d}.safetensors"
                save_file(_safetensors_compat(shard), os.path.join(save_directory, name))
                written.append(os.path.join(save_directory, name))
                for key in shard:
                    index["weight_map"][key] = name
            with open(os.path.join(save_directory, SAFE_WEIGHTS_INDEX_NAME), "w") as f:
                json.dump(index, f, indent=2)
    else:
        path = os.path.join(save_directory, WEIGHTS_NAME)
        np.savez(path, **flat)
        written.append(path)
    return written


def _safetensors_compat(shard: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """safetensors-numpy rejects some dtypes (e.g. ml_dtypes bfloat16 views vary by
    version); upcast unsupported dtypes to float32."""
    out = {}
    for k, v in shard.items():
        if v.dtype.kind not in "fiub" or str(v.dtype) == "bfloat16":
            v = v.astype(np.float32)
        out[k] = v
    return out


def load_checkpoint_in_model(params_template, checkpoint_path: str):
    """Load a safetensors/npz checkpoint into a params pytree template
    (reference ``load_checkpoint_in_model utils/modeling.py:1788``)."""
    if os.path.isdir(checkpoint_path):
        index_file = os.path.join(checkpoint_path, SAFE_WEIGHTS_INDEX_NAME)
        single = os.path.join(checkpoint_path, SAFE_WEIGHTS_NAME)
        npz = os.path.join(checkpoint_path, WEIGHTS_NAME)
        if os.path.exists(index_file):
            from safetensors.numpy import load_file

            with open(index_file) as f:
                index = json.load(f)
            flat = {}
            for name in sorted(set(index["weight_map"].values())):
                flat.update(load_file(os.path.join(checkpoint_path, name)))
        elif os.path.exists(single):
            from safetensors.numpy import load_file

            flat = load_file(single)
        elif os.path.exists(npz):
            flat = load_flat(npz)
        else:
            raise FileNotFoundError(f"no model checkpoint in {checkpoint_path}")
    elif checkpoint_path.endswith(".safetensors"):
        from safetensors.numpy import load_file

        flat = load_file(checkpoint_path)
    else:
        flat = load_flat(checkpoint_path)
    return unflatten_into(params_template, flat)
