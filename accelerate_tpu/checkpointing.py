"""Checkpoint save/load: model, optimizer, scheduler, dataloader, RNG, custom objects.

TPU-native counterpart of the reference's ``checkpointing.py``
(``/root/reference/src/accelerate/checkpointing.py`` — ``save_accelerator_state:62``
with RNG capture ``:153-176``, ``load_accelerator_state:180`` with RNG restore
``:287-309``, ``save_custom_state:314``) and the Accelerator glue
(``accelerator.py:3529`` rotation/naming ``:3567-3593``, ``save_model:3386``
safetensors shard-splitting).

Format: each pytree is flattened to '/'-joined paths and stored as one
``.npz`` (or safetensors for model export). Sharded ``jax.Array`` leaves are
gathered to host — the ZeRO-3/FSDP "16-bit gather on save" (reference
``get_state_dict accelerator.py:3947``) collapses to a reshard-to-replicated.
Loading re-places leaves with the live tree's shardings preserved.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import signal
import time
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Optional

import numpy as np

from .logging import get_logger
from .sharded_checkpoint import (  # noqa: F401  (public re-exports)
    CheckpointCorruptError,
    CheckpointTopologyError,
    resize_padded_bucket,
)

logger = get_logger(__name__)

MODEL_NAME = "model"
OPTIMIZER_NAME = "optimizer"
SCHEDULER_NAME = "scheduler"
SAMPLER_NAME = "dataloader"
RNG_NAME = "random_states"
CUSTOM_NAME = "custom_checkpoint"

# reference utils/constants.py:20-33 spellings, reflecting THIS framework's
# file layout (safetensors for interop, npz for the dependency-free path)
SAFE_MODEL_NAME = MODEL_NAME
SAFE_WEIGHTS_NAME = "model.safetensors"
SAFE_WEIGHTS_INDEX_NAME = "model.safetensors.index.json"
SAFE_WEIGHTS_PATTERN_NAME = "model{suffix}.safetensors"
WEIGHTS_NAME = "model.npz"
WEIGHTS_INDEX_NAME = "model.npz.index.json"
WEIGHTS_PATTERN_NAME = "model{suffix}.npz"
RNG_STATE_NAME = RNG_NAME
SCALER_NAME = "scaler"  # fp16 scale state lives inside the optimizer state
PROFILE_PATTERN_NAME = "profile_{suffix}.json"

# crash-consistent commit protocol (see docs/checkpointing.md "Async saves and
# crash consistency"): every save serializes into `<dir>.tmp`, fsyncs, writes
# the COMMITTED_MARKER manifest last, then atomically `os.replace`s onto the
# final name. A directory without the marker was torn mid-write and is never
# loaded; a `.tmp` directory WITH the marker crashed between marker and rename
# and is repaired (the rename is finished) on the next load/save.
COMMITTED_MARKER = "_COMMITTED"
STAGING_SUFFIX = ".tmp"
_TRASH_SUFFIX = ".trash"
_DONE_RE = re.compile(r"_DONE\.rank(\d{5})\.json")
_AUTO_DIR_RE = re.compile(r"checkpoint_(\d+)")


def _maybe_crash(point: str) -> None:
    """Deterministic fault injection for crash-consistency tests: SIGKILL this
    process when ``ACCELERATE_CKPT_CRASH_POINT`` names the current point. A
    no-op (one env lookup) outside tests."""
    if os.environ.get("ACCELERATE_CKPT_CRASH_POINT") == point:
        os.kill(os.getpid(), signal.SIGKILL)


def _fsync_path(path: str) -> None:
    """fsync a file or directory by path (Linux allows fsync on O_RDONLY fds —
    directory fsync is how a rename/create is made durable)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                return crc & 0xFFFFFFFF
            crc = zlib.crc32(block, crc)


def is_committed_checkpoint(directory: str) -> bool:
    """True iff ``directory`` finished its save protocol (marker present)."""
    return os.path.isfile(os.path.join(directory, COMMITTED_MARKER))


# ---------------------------------------------------------------------------
# pytree <-> flat dict


def flatten_pytree(tree, copy: bool = False) -> dict[str, np.ndarray]:
    """Flatten to '/'-joined paths → numpy. ``copy=True`` forces owned host
    buffers (on the CPU backend ``np.asarray`` can alias the device buffer,
    which a donating train step will mutate under an async writer)."""
    import jax

    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(
            str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))) for p in path
        )
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            # multi-host sharded leaf: reshard to replicated first (the ZeRO-3
            # gather-on-save, reference accelerator.py:3947)
            from .utils.operations import _replicate_global_array

            leaf = _replicate_global_array(leaf)
        arr = np.asarray(leaf)
        flat[key or "_root"] = np.array(arr, copy=True) if copy else arr
    return flat


def unflatten_into(template, flat: dict[str, np.ndarray], elastic: bool = False):
    """Restore values from ``flat`` into the structure of ``template``, preserving
    each live leaf's sharding/dtype placement.

    ``elastic=True`` re-pads 1-D leaves whose saved length differs from the
    template's — the fused-ZeRO-1 bucket case, whose padded length depends on
    the replicate width (:func:`resize_padded_bucket`); any other mismatch
    still fails in the placement below."""
    import jax

    def _restore(path, leaf):
        key = "/".join(
            str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))) for p in path
        )
        key = key or "_root"
        if key not in flat:
            raise KeyError(f"checkpoint missing key {key!r}")
        value = flat[key]
        if (
            elastic
            and getattr(value, "ndim", None) == 1
            and getattr(leaf, "ndim", None) == 1
            and value.shape[0] != leaf.shape[0]
        ):
            value = resize_padded_bucket(np.asarray(value), int(leaf.shape[0]), key)
        if isinstance(leaf, jax.Array):
            return jax.device_put(value.astype(leaf.dtype), leaf.sharding)
        return np.asarray(value, dtype=getattr(leaf, "dtype", None))

    return jax.tree_util.tree_map_with_path(_restore, template)


def save_pytree(tree, path: str) -> None:
    np.savez(path, **flatten_pytree(tree))


def load_flat(path: str) -> dict[str, np.ndarray]:
    try:
        with np.load(path, allow_pickle=False) as data:
            return {k: data[k] for k in data.files}
    except FileNotFoundError:
        raise
    except Exception as e:  # torn zip container, truncated header, ...
        raise CheckpointCorruptError(
            f"corrupt checkpoint file {path}: {e} (torn write? resume from an "
            "older committed checkpoint)",
            path=path,
        ) from e


# ---------------------------------------------------------------------------
# accelerator state


def _checkpoint_dir(accelerator, output_dir: Optional[str]) -> str:
    """Resolve the final checkpoint directory. Rotation does NOT happen here:
    deleting old checkpoints before the new save commits would leave zero
    usable checkpoints after a mid-save crash — rotation runs post-commit
    (:func:`rotate_checkpoints`)."""
    pc = accelerator.project_configuration
    if output_dir is None:
        if pc.automatic_checkpoint_naming:
            output_dir = os.path.join(accelerator.project_dir or ".", "checkpoints")
        else:
            raise ValueError("pass output_dir or enable automatic_checkpoint_naming")
    if pc.automatic_checkpoint_naming:
        folder = os.path.join(output_dir, f"checkpoint_{pc.iteration}")
        # every process checks (raising only on main would leave the others hung
        # at the save barrier); the iteration counter is process-consistent
        if os.path.isdir(folder):
            raise FileExistsError(
                f"Checkpoint {folder} already exists — iteration was not advanced"
            )
        output_dir = folder
    return output_dir


def repair_interrupted_commit(final_dir: str) -> bool:
    """Finish a commit that crashed between marker write and rename: a
    ``<final>.tmp`` holding the COMMITTED_MARKER is fully durable — complete
    the swap. Returns True when a repair happened."""
    tmp = final_dir + STAGING_SUFFIX
    if not (os.path.isdir(tmp) and is_committed_checkpoint(tmp)):
        return False
    trash = final_dir + _TRASH_SUFFIX
    shutil.rmtree(trash, ignore_errors=True)
    if os.path.isdir(final_dir):
        os.replace(final_dir, trash)
    os.replace(tmp, final_dir)
    shutil.rmtree(trash, ignore_errors=True)
    parent = os.path.dirname(os.path.abspath(final_dir))
    if os.path.isdir(parent):
        _fsync_path(parent)
    logger.warning(f"repaired interrupted checkpoint commit: {tmp} -> {final_dir}")
    return True


def clean_stale_staging(final_dir: str, active: Optional["set[str]"] = None) -> None:
    """Remove partial ``.tmp``/``.trash`` staging left by a crashed save
    (repairing committed-but-unrenamed ones first). Sweeps the sibling
    ``checkpoint_*`` staging dirs too under automatic naming. ``active`` names
    staging dirs owned by in-flight async saves — never touched."""
    active = active or set()
    candidates = {final_dir}
    parent = os.path.dirname(os.path.abspath(final_dir))
    if _AUTO_DIR_RE.fullmatch(os.path.basename(final_dir)) and os.path.isdir(parent):
        for name in os.listdir(parent):
            if _AUTO_DIR_RE.fullmatch(name.removesuffix(STAGING_SUFFIX)):
                candidates.add(os.path.join(parent, name.removesuffix(STAGING_SUFFIX)))
    for final in sorted(candidates):
        tmp = final + STAGING_SUFFIX
        if tmp in active:
            continue
        if repair_interrupted_commit(final):
            continue
        if os.path.isdir(tmp):
            logger.warning(f"removing partial checkpoint staging dir {tmp}")
            shutil.rmtree(tmp, ignore_errors=True)
        shutil.rmtree(final + _TRASH_SUFFIX, ignore_errors=True)


def rotate_checkpoints(root: str, total_limit: int, just_committed: str) -> None:
    """Post-commit rotation (reference accelerator.py:3567-3593 deletes BEFORE
    saving — here deletion only ever happens after the replacement landed).
    Keeps the ``total_limit`` newest ``checkpoint_<i>`` dirs; staging/trash
    dirs never match the pattern; the just-committed dir and the newest
    committed dir are never victims even if the limit says otherwise."""
    if total_limit is None or not os.path.isdir(root):
        return
    existing = sorted(
        (d for d in os.listdir(root) if _AUTO_DIR_RE.fullmatch(d)),
        key=lambda d: int(d.split("_")[1]),
    )
    committed = [d for d in existing if is_committed_checkpoint(os.path.join(root, d))]
    protect = {os.path.basename(os.path.normpath(just_committed))}
    if committed:
        protect.add(committed[-1])
    victims = existing[: max(0, len(existing) - max(1, int(total_limit)))]
    for victim in victims:
        if victim in protect:
            continue
        shutil.rmtree(os.path.join(root, victim), ignore_errors=True)


def _should_shard(trees) -> bool:
    """Auto-detect: shard the save when any leaf is not fully addressable
    (multi-host sharded state — gathering it to one host is exactly the
    host-RAM-OOM failure mode the reference avoids with DCP sharded writers)."""
    import jax

    for tree in trees:
        if tree is None:
            continue
        for leaf in jax.tree_util.tree_leaves(tree):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                return True
    return False


@dataclass
class _Artifact:
    """One file-to-be of a checkpoint: ``kind`` selects the serializer.

    ``npz``: payload is a flat ``{key: np.ndarray}`` dict; ``sharded``:
    payload is a ``ShardedTreeSnapshot`` and ``name`` is the shard prefix;
    ``text``/``bytes``: pre-encoded small state (json/pickle)."""

    kind: str
    name: str
    payload: Any


@dataclass
class CheckpointSnapshot:
    """Everything a checkpoint save needs, detached from live training state.

    Produced by :func:`snapshot_accelerator_state` in the **fast** phase (the
    only part the train loop waits for): device→host copies of the replica-0
    array regions plus encoded small states. Consumed by
    :func:`write_and_commit` — on the caller thread (blocking save) or a
    background writer (``save_state(blocking=False)``)."""

    final_dir: str
    artifacts: "list[_Artifact]"
    process_index: int
    num_processes: int
    is_main: bool
    sharded: bool
    save_on_each_node: bool = False
    is_local_main: bool = False
    rotation: Optional["tuple[str, int]"] = None  # (root, total_limit), post-commit
    iteration: Optional[int] = None
    nbytes: int = 0
    blocking: bool = True  # telemetry: writer time is hidden when False
    snapshot_s: float = 0.0
    mesh_shape: Optional[dict] = None  # writing mesh axis→size (topology guard)

    @property
    def staging_dir(self) -> str:
        return self.final_dir + STAGING_SUFFIX

    @property
    def is_committer(self) -> bool:
        """Who runs the marker rendezvous + atomic rename. Under
        ``save_on_each_node`` every node's dir needs its own commit, so each
        local main commits (peer committers racing on a shared fs are handled
        at the ``os.replace``)."""
        return self.is_main or (self.save_on_each_node and self.is_local_main)


def _encode_small_states(accelerator) -> "list[_Artifact]":
    """Scheduler/dataloader/custom-object/RNG states: small, host-resident,
    encoded at snapshot time so the writer never touches live objects."""
    import pickle

    from .utils.random import capture_rng_states

    artifacts: "list[_Artifact]" = []
    for i, sched in enumerate(accelerator._schedulers):
        suffix = "" if i == 0 else f"_{i}"
        artifacts.append(
            _Artifact("text", f"{SCHEDULER_NAME}{suffix}.json", json.dumps(sched.state_dict()))
        )
    for i, dl in enumerate(accelerator._dataloaders):
        suffix = "" if i == 0 else f"_{i}"
        base = f"{SAMPLER_NAME}{suffix}"
        state = dl.state_dict()
        # a stateful INNER loader's (torchdata) state is OPAQUE: always
        # pickle it — json "succeeding" can still be lossy (int dict keys
        # coerce to strings, mangling worker-state maps), and tensors/bytes
        # fail outright. Native wrapper states are plain and stay json.
        payload = None
        if not getattr(dl, "_stateful_inner", False):
            try:
                payload = json.dumps(state)
                if json.loads(payload) != state:
                    # dumps can "succeed" lossily (int dict keys coerce to
                    # strings, tuples to lists) — only a clean round-trip
                    # may use the json spelling
                    payload = None
            except (TypeError, ValueError):
                payload = None  # e.g. a custom sampler with tensor state
        if payload is None:
            artifacts.append(_Artifact("bytes", base + ".pkl", pickle.dumps(state)))
        else:
            artifacts.append(_Artifact("text", base + ".json", payload))
    for i, obj in enumerate(accelerator._custom_objects):
        flat = flatten_pytree(obj.state_dict(), copy=True)
        name = f"{CUSTOM_NAME}_{i}.npz"
        artifacts.append(_Artifact("npz", name, flat))
        artifacts.append(
            _Artifact("text", name + ".meta.json", json.dumps({"keys": sorted(flat)}))
        )
    return artifacts


def snapshot_accelerator_state(
    accelerator,
    output_dir: Optional[str] = None,
    params=None,
    opt_state=None,
    save_on_each_node: bool = False,
    sharded: Optional[bool] = None,
    blocking: bool = True,
    active_staging: Optional["set[str]"] = None,
) -> CheckpointSnapshot:
    """The fast phase of a save: resolve the directory, copy this process's
    replica-0 array regions device→host, encode small states, advance the
    iteration counter — and return in milliseconds-to-subseconds, never
    touching the filesystem beyond stale-staging cleanup. The returned
    snapshot owns every byte it references; live params/opt-state may be
    donated/mutated immediately after."""
    from .sharded_checkpoint import snapshot_sharded_pytree
    from .telemetry import events as _tel

    t0 = time.monotonic()
    output_dir = _checkpoint_dir(accelerator, output_dir)
    pc = accelerator.project_configuration
    is_writer = accelerator.is_main_process or save_on_each_node

    models = [params] if params is not None else accelerator._models
    opt_states = (
        [opt_state] if opt_state is not None else [o.opt_state for o in accelerator._optimizers]
    )
    # user pre-hooks see the RESOLVED directory (post automatic naming), like
    # the reference's register_save_state_pre_hook contract (accelerator.py:3497)
    for hook in getattr(accelerator, "_save_state_pre_hooks", {}).values():
        hook(models, output_dir)
    if sharded is None:
        sharded = _should_shard(list(models) + list(opt_states))

    # a previous crashed save may have left partial staging next to (or at)
    # this save's target — repair committed ones, drop torn ones. Main only
    # (plus each node's local main under save_on_each_node, whose dir may be
    # node-local): racing rmtrees across writers on a shared fs helps nobody.
    if accelerator.is_main_process or (save_on_each_node and accelerator.is_local_main_process):
        clean_stale_staging(output_dir, active=active_staging)

    artifacts: "list[_Artifact]" = []
    if sharded:
        # every process snapshots exactly the chunks it will write (the same
        # replica-0 selection save_sharded_pytree always computed)
        for i, model in enumerate(models):
            suffix = "" if i == 0 else f"_{i}"
            artifacts.append(
                _Artifact("sharded", f"{MODEL_NAME}{suffix}", snapshot_sharded_pytree(model))
            )
        for i, state in enumerate(opt_states):
            if state is not None:
                suffix = "" if i == 0 else f"_{i}"
                artifacts.append(
                    _Artifact(
                        "sharded", f"{OPTIMIZER_NAME}{suffix}", snapshot_sharded_pytree(state)
                    )
                )
    elif is_writer:
        for i, model in enumerate(models):
            suffix = "" if i == 0 else f"_{i}"
            artifacts.append(
                _Artifact("npz", f"{MODEL_NAME}{suffix}.npz", flatten_pytree(model, copy=True))
            )
        for i, state in enumerate(opt_states):
            if state is not None:
                suffix = "" if i == 0 else f"_{i}"
                artifacts.append(
                    _Artifact(
                        "npz", f"{OPTIMIZER_NAME}{suffix}.npz", flatten_pytree(state, copy=True)
                    )
                )
    if is_writer:
        artifacts.extend(_encode_small_states(accelerator))

    # RNG is per-process (reference :153-176)
    import pickle

    from .utils.random import capture_rng_states

    artifacts.append(
        _Artifact(
            "bytes",
            f"{RNG_NAME}_{accelerator.process_index}.pkl",
            pickle.dumps(capture_rng_states()),
        )
    )

    # barrier taken by EVERY process: after it, every rank's device→host
    # copies are done, so callers may mutate/donate live state — and the
    # process-consistent iteration counter can advance
    accelerator.wait_for_everyone()
    iteration = pc.iteration if pc.automatic_checkpoint_naming else None
    rotation = None
    if pc.automatic_checkpoint_naming:
        if pc.total_limit is not None:
            rotation = (os.path.dirname(output_dir), int(pc.total_limit))
        pc.iteration += 1

    nbytes = 0
    for art in artifacts:
        if art.kind == "sharded":
            nbytes += art.payload.nbytes
        elif art.kind == "npz":
            nbytes += sum(a.nbytes for a in art.payload.values())
        else:
            nbytes += len(art.payload)
    mesh_shape = None
    try:
        from .resilience.reshard import mesh_shape_dict

        mesh_shape = mesh_shape_dict(getattr(accelerator, "mesh", None))
    except Exception:
        pass  # meshless accelerators (tests with bare state) still save
    snap = CheckpointSnapshot(
        final_dir=output_dir,
        artifacts=artifacts,
        process_index=accelerator.process_index,
        num_processes=accelerator.num_processes,
        is_main=accelerator.is_main_process,
        sharded=bool(sharded),
        save_on_each_node=save_on_each_node,
        is_local_main=accelerator.is_local_main_process,
        rotation=rotation,
        iteration=iteration,
        nbytes=nbytes,
        blocking=blocking,
        snapshot_s=time.monotonic() - t0,
        mesh_shape=mesh_shape,
    )
    _tel.emit(
        "checkpoint",
        phase="snapshot",
        dur_s=round(snap.snapshot_s, 6),
        bytes=nbytes,
        dir=output_dir,
        hidden=False,
        blocking=blocking,
        sharded=snap.sharded,
    )
    from .telemetry import goodput as _goodput

    _goodput.note("checkpoint_stall", snap.snapshot_s)
    return snap


def write_snapshot(
    snap: CheckpointSnapshot,
    directory: Optional[str] = None,
    heartbeat: Optional[Callable[..., None]] = None,
) -> "tuple[dict[str, dict], dict[str, float]]":
    """Serialize every artifact of ``snap`` into ``directory`` (default: the
    snapshot's staging dir), fsync each file and the directory. Pure IO —
    safe on a background thread. Returns ``(files, timings)``: per-file
    bytes/CRC32 for the commit manifest and serialize/write second splits."""
    from .sharded_checkpoint import write_sharded_snapshot

    directory = directory or snap.staging_dir
    os.makedirs(directory, exist_ok=True)
    files: "dict[str, dict]" = {}
    serialize_s = 0.0
    write_s = 0.0
    first_written = False
    for art in snap.artifacts:
        if heartbeat is not None:
            heartbeat(file=art.name)
        if art.kind == "sharded":
            t0 = time.monotonic()
            files.update(
                write_sharded_snapshot(art.payload, directory, prefix=art.name, heartbeat=heartbeat)
            )
            write_s += time.monotonic() - t0
        else:
            path = os.path.join(directory, art.name)
            if art.kind == "npz":
                # savez streams straight into the open file: no BytesIO
                # doubling of the (model-sized) host copy the snapshot holds
                t0 = time.monotonic()
                with open(path, "wb") as f:
                    np.savez(f, **art.payload)
                    f.flush()
                    os.fsync(f.fileno())
                write_s += time.monotonic() - t0
                files[art.name] = {
                    "bytes": os.path.getsize(path),
                    "crc32": _file_crc32(path),
                }
            else:
                t0 = time.monotonic()
                data = art.payload.encode("utf-8") if art.kind == "text" else art.payload
                serialize_s += time.monotonic() - t0
                t0 = time.monotonic()
                with open(path, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                write_s += time.monotonic() - t0
                files[art.name] = {
                    "bytes": len(data),
                    "crc32": zlib.crc32(data) & 0xFFFFFFFF,
                }
        if not first_written:
            first_written = True
            _maybe_crash("mid_write")
    _fsync_path(directory)
    return files, {"serialize_s": serialize_s, "write_s": write_s}


def _commit_timeout() -> float:
    try:
        return float(os.environ.get("ACCELERATE_CKPT_COMMIT_TIMEOUT", "600"))
    except ValueError:
        return 600.0


def commit_snapshot(
    snap: CheckpointSnapshot,
    files: "dict[str, dict]",
    heartbeat: Optional[Callable[..., None]] = None,
) -> str:
    """Make the staged save durable and visible, atomically.

    Every process drops a fsynced ``_DONE.rank<k>.json`` (its file manifest)
    into staging. The main process waits for all ranks' markers (shared-fs
    rendezvous — the same assumption the sharded loader already makes), merges
    them into the ``_COMMITTED`` manifest written last, fsyncs, and
    ``os.replace``s staging onto the final name. A crash at ANY point leaves
    either the old committed checkpoint untouched or a repairable
    marker-carrying staging dir — never a half-written directory under the
    final name."""
    staging = snap.staging_dir
    done_name = f"_DONE.rank{snap.process_index:05d}.json"
    done_payload = {
        "process_index": snap.process_index,
        "files": files,
        "bytes": snap.nbytes,
    }
    done_path = os.path.join(staging, done_name)
    # write-then-rename: the committer's poll matches done_name the instant it
    # appears in listdir, so the marker must never be visible half-written
    with open(done_path + ".tmp", "w") as f:
        json.dump(done_payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(done_path + ".tmp", done_path)
    _fsync_path(staging)
    if not snap.is_committer:
        return snap.final_dir

    merged_files = dict(files)
    if snap.num_processes > 1:
        deadline = time.monotonic() + _commit_timeout()
        want = snap.num_processes
        if snap.save_on_each_node:
            # per-node dirs: only this node's ranks drop markers here. The
            # launcher declares the node size; without a declaration assume a
            # shared fs (every rank's marker lands in this staging dir).
            local = os.environ.get("LOCAL_WORLD_SIZE", "")
            if local.strip().isdigit():
                want = max(1, min(want, int(local)))
        while True:
            done = [n for n in os.listdir(staging) if _DONE_RE.fullmatch(n)]
            if heartbeat is not None:
                heartbeat(waiting_ranks=want - len(done))
            if len(done) >= want:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"checkpoint commit timed out waiting for rank done-markers in "
                    f"{staging} ({len(done)}/{want} present). On a node-local "
                    "filesystem use save_on_each_node (and declare LOCAL_WORLD_SIZE "
                    "so each node's commit waits only for its own ranks); raise "
                    "ACCELERATE_CKPT_COMMIT_TIMEOUT for slow filesystems."
                )
            time.sleep(0.05)
        for name in done:
            with open(os.path.join(staging, name)) as f:
                merged_files.update(json.load(f).get("files", {}))

    manifest = {
        "schema": 1,
        "iteration": snap.iteration,
        "num_processes": snap.num_processes,
        "sharded": snap.sharded,
        "mesh": snap.mesh_shape,
        "total_bytes": snap.nbytes,
        "committed_at_unix": round(time.time(), 3),
        "files": merged_files,
    }
    marker = os.path.join(staging, COMMITTED_MARKER)
    with open(marker, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    _fsync_path(staging)
    _maybe_crash("before_replace")
    final = snap.final_dir
    trash = final + _TRASH_SUFFIX
    try:
        if os.path.isdir(final):
            shutil.rmtree(trash, ignore_errors=True)
            os.replace(final, trash)
        os.replace(staging, final)
    except FileNotFoundError:
        # a peer committer (save_on_each_node on a shared fs) won the race;
        # the checkpoint is in place either way
        if not os.path.isdir(final):
            raise
    shutil.rmtree(trash, ignore_errors=True)
    parent = os.path.dirname(os.path.abspath(final))
    if os.path.isdir(parent):
        _fsync_path(parent)
    return final


def write_and_commit(
    snap: CheckpointSnapshot, heartbeat: Optional[Callable[..., None]] = None
) -> str:
    """Writer-side pipeline: serialize → fsync → commit → rotate. Runs on the
    caller thread for blocking saves and on the background writer for async
    ones; telemetry marks the time hidden when async."""
    from .telemetry import events as _tel

    hidden = not snap.blocking
    files, timings = write_snapshot(snap, heartbeat=heartbeat)
    _tel.emit(
        "checkpoint",
        phase="serialize",
        dur_s=round(timings["serialize_s"], 6),
        dir=snap.final_dir,
        hidden=hidden,
    )
    _tel.emit(
        "checkpoint",
        phase="write",
        dur_s=round(timings["write_s"], 6),
        bytes=sum(int(rec.get("bytes", 0)) for rec in files.values()),
        dir=snap.final_dir,
        hidden=hidden,
    )
    t0 = time.monotonic()
    final = commit_snapshot(snap, files, heartbeat=heartbeat)
    commit_s = time.monotonic() - t0
    _tel.emit(
        "checkpoint",
        phase="commit",
        dur_s=round(commit_s, 6),
        dir=final,
        hidden=hidden,
        committed=snap.is_committer,
    )
    if snap.is_committer and snap.rotation is not None:
        rotate_checkpoints(snap.rotation[0], snap.rotation[1], final)
    if not hidden:
        # blocking saves stall the training loop for the full pipeline; async
        # writer time is hidden and only surfaces via backpressure/drain
        from .telemetry import goodput as _goodput

        _goodput.note(
            "checkpoint_stall",
            timings["serialize_s"] + timings["write_s"] + commit_s,
        )
    logger.info(f"saved state to {final}")
    return final


def save_accelerator_state(
    accelerator,
    output_dir: Optional[str] = None,
    params=None,
    opt_state=None,
    save_on_each_node: bool = False,
    sharded: Optional[bool] = None,
) -> str:
    """Save everything needed to resume (reference ``save_accelerator_state:62``
    driven by ``accelerator.save_state:3529``) — the blocking path:
    snapshot + write + commit back-to-back on the caller thread, with the same
    staging/fsync/marker crash-consistency the async writer uses.

    ``params``/``opt_state`` let functional training loops pass their live
    threaded values explicitly; without them the values written back by the
    prepared train step (``Accelerator.prepare_train_step``) are used.

    ``sharded=True`` (auto-on when any leaf spans hosts) writes model/optimizer
    state as per-process shard files — no host ever materializes the full
    state (reference ``save_fsdp_model utils/fsdp_utils.py:103`` via
    ``torch.distributed.checkpoint`` sharded writers).
    """
    snap = snapshot_accelerator_state(
        accelerator,
        output_dir=output_dir,
        params=params,
        opt_state=opt_state,
        save_on_each_node=save_on_each_node,
        sharded=sharded,
        blocking=True,
    )
    final = write_and_commit(snap)
    # no process reads a checkpoint its peers have not finished committing
    accelerator.wait_for_everyone()
    return final


def find_latest_checkpoint(base: str) -> str:
    """Newest *committed* ``checkpoint_<i>`` under ``base``: staging dirs are
    invisible, interrupted commits are repaired first, and an uncommitted
    (torn) newer dir is skipped in favor of the newest committed one — a
    kill -9 mid-save can therefore never leave resume pointing at garbage.
    Dirs predating the commit protocol (no marker at all) remain loadable as
    a fallback when no committed dir exists."""
    if not os.path.isdir(base):
        raise FileNotFoundError(f"no checkpoints under {base}")
    for name in sorted(os.listdir(base)):
        stem = name.removesuffix(STAGING_SUFFIX)
        if name.endswith(STAGING_SUFFIX) and _AUTO_DIR_RE.fullmatch(stem):
            repair_interrupted_commit(os.path.join(base, stem))
    candidates = sorted(
        (d for d in os.listdir(base) if _AUTO_DIR_RE.fullmatch(d)),
        key=lambda d: int(d.split("_")[1]),
    )
    if not candidates:
        raise FileNotFoundError(f"no checkpoints under {base}")
    committed = [d for d in candidates if is_committed_checkpoint(os.path.join(base, d))]
    if committed:
        skipped = [d for d in candidates if int(d.split("_")[1]) > int(committed[-1].split("_")[1])]
        if skipped:
            logger.warning(
                f"ignoring uncommitted checkpoint dir(s) {skipped} (torn save?); "
                f"resuming from {committed[-1]}"
            )
        return os.path.join(base, committed[-1])
    logger.warning(
        f"no committed checkpoints under {base}; falling back to newest dir "
        f"{candidates[-1]} (pre-async-checkpoint layout)"
    )
    return os.path.join(base, candidates[-1])


def _validate_manifest(input_dir: str) -> None:
    """Check the committed manifest against the directory: every listed file
    must be present with the recorded size (and, with
    ``ACCELERATE_CKPT_VERIFY=crc``, the recorded whole-file CRC32). Catches
    post-commit tampering/truncation before any bytes are deserialized; chunk
    CRCs in the sharded format are additionally verified on every read."""
    marker = os.path.join(input_dir, COMMITTED_MARKER)
    if not os.path.isfile(marker):
        return
    try:
        with open(marker) as f:
            manifest = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CheckpointCorruptError(
            f"unparseable commit manifest {marker}: {e}", path=marker
        ) from e
    check_crc = os.environ.get("ACCELERATE_CKPT_VERIFY", "size").strip().lower() == "crc"
    for name, rec in (manifest.get("files") or {}).items():
        path = os.path.join(input_dir, name)
        if not os.path.isfile(path):
            # per-process files (RNG) legitimately live only on their node
            # under save_on_each_node; missing SHARED artifacts are corruption
            if name.startswith(RNG_NAME):
                continue
            raise CheckpointCorruptError(
                f"checkpoint {input_dir} is missing {name} listed in its commit "
                "manifest",
                path=path,
            )
        size = os.path.getsize(path)
        if rec.get("bytes") is not None and size != int(rec["bytes"]):
            raise CheckpointCorruptError(
                f"checkpoint file {path} has {size} bytes, manifest says "
                f"{rec['bytes']} (torn/tampered write)",
                path=path,
            )
        if check_crc and rec.get("crc32") is not None:
            crc = _file_crc32(path)
            if crc != int(rec["crc32"]):
                raise CheckpointCorruptError(
                    f"checkpoint file {path} fails manifest CRC32 "
                    f"({crc:#010x} != {int(rec['crc32']):#010x})",
                    path=path,
                )


def load_accelerator_state(
    accelerator,
    input_dir: Optional[str] = None,
    params=None,
    opt_state=None,
    load_kwargs: Optional[dict] = None,
    elastic: Optional[bool] = None,
):
    """Mirror of :func:`save_accelerator_state` (reference
    ``load_accelerator_state:180``). Returns restored params (pytree or list);
    with ``opt_state`` given as a live template, returns
    ``(params, opt_state)`` so functional loops can rethread both.

    Topology guard: the saved mesh shape (``_COMMITTED`` manifest / shard
    indices) is compared against the live mesh. A mismatch raises
    :class:`CheckpointTopologyError` naming both shapes — unless ``elastic``
    is truthy (default: the ``ACCELERATE_ELASTIC_RESUME`` env flag, set by
    the elastic supervisor), in which case the load re-shards: coordinates
    re-chunk for free and fused-ZeRO-1 buckets are re-padded for the new
    replicate width (see ``resilience/reshard.py``)."""
    from .utils.random import restore_rng_states

    if elastic is None:
        from .utils.environment import parse_flag_from_env

        elastic = parse_flag_from_env("ACCELERATE_ELASTIC_RESUME")
    if input_dir is None:
        base = os.path.join(accelerator.project_dir or ".", "checkpoints")
        input_dir = find_latest_checkpoint(base)
    else:
        # a crash between marker and rename leaves the checkpoint under
        # `<dir>.tmp` with the marker inside — finish the rename and load it
        if not os.path.isdir(input_dir):
            repair_interrupted_commit(input_dir)
        if os.path.isdir(input_dir) and not is_committed_checkpoint(input_dir):
            logger.warning(
                f"loading {input_dir} without a {COMMITTED_MARKER} manifest "
                "(pre-async-checkpoint save, or a save torn mid-write)"
            )
    _validate_manifest(input_dir)

    # topology guard: a dp=N checkpoint loaded onto a dp=M mesh either
    # re-shards (elastic) or fails HERE with both shapes named — not deep in
    # jax with a bare shape error
    from .resilience.reshard import check_topology, mesh_shape_dict, saved_topology

    saved_mesh = saved_topology(input_dir)
    current_mesh = mesh_shape_dict(getattr(accelerator, "mesh", None))
    resharding = check_topology(saved_mesh, current_mesh, elastic=bool(elastic))
    if resharding:
        logger.warning(
            f"elastic resume: re-sharding checkpoint {input_dir} "
            f"({saved_mesh} -> {current_mesh})"
        )
        from .telemetry import events as _tel

        _tel.emit("elastic", phase="reshard", dir=input_dir,
                  saved_mesh=saved_mesh, current_mesh=current_mesh)

    # user pre-hooks see the RESOLVED directory (after latest-checkpoint
    # discovery), reference register_load_state_pre_hook contract (:3664)
    for hook in getattr(accelerator, "_load_state_pre_hooks", {}).values():
        hook([params] if params is not None else accelerator._models, input_dir)

    from .sharded_checkpoint import is_sharded_checkpoint, load_sharded_pytree

    def _load_tree(prefix: str, template):
        """Dispatch npz vs sharded format; returns None if neither exists."""
        npz_path = os.path.join(input_dir, f"{prefix}.npz")
        if os.path.exists(npz_path):
            return unflatten_into(template, load_flat(npz_path), elastic=resharding)
        if is_sharded_checkpoint(input_dir, prefix):
            return load_sharded_pytree(template, input_dir, prefix, elastic=resharding)
        return None

    models = [params] if params is not None else accelerator._models
    restored = []
    for i, model in enumerate(models):
        suffix = "" if i == 0 else f"_{i}"
        value = _load_tree(f"{MODEL_NAME}{suffix}", model)
        if value is None:
            raise FileNotFoundError(f"no {MODEL_NAME}{suffix} checkpoint in {input_dir}")
        restored.append(value)
    restored_opt_state = None
    if opt_state is not None:
        restored_opt_state = _load_tree(OPTIMIZER_NAME, opt_state)
        if restored_opt_state is not None and accelerator._optimizers:
            accelerator._optimizers[0].opt_state = restored_opt_state
    else:
        for i, opt in enumerate(accelerator._optimizers):
            suffix = "" if i == 0 else f"_{i}"
            if opt.opt_state is not None:
                value = _load_tree(f"{OPTIMIZER_NAME}{suffix}", opt.opt_state)
                if value is not None:
                    opt.opt_state = value
    for i, sched in enumerate(accelerator._schedulers):
        suffix = "" if i == 0 else f"_{i}"
        path = os.path.join(input_dir, f"{SCHEDULER_NAME}{suffix}.json")
        if os.path.exists(path):
            with open(path) as f:
                sched.load_state_dict(json.load(f))
    for i, dl in enumerate(accelerator._dataloaders):
        suffix = "" if i == 0 else f"_{i}"
        base = os.path.join(input_dir, f"{SAMPLER_NAME}{suffix}")
        if os.path.exists(base + ".json"):
            with open(base + ".json") as f:
                dl.load_state_dict(json.load(f))
        elif os.path.exists(base + ".pkl"):  # tensorful stateful-inner state
            import pickle as _pickle

            with open(base + ".pkl", "rb") as f:
                dl.load_state_dict(_pickle.load(f))
    for i, obj in enumerate(accelerator._custom_objects):
        _load_custom(obj, os.path.join(input_dir, f"{CUSTOM_NAME}_{i}.npz"))

    # restore the automatic-naming iteration counter so the next save does not
    # collide with an existing checkpoint_<i> after a process restart
    folder = os.path.basename(os.path.normpath(input_dir))
    match = re.fullmatch(r"checkpoint_(\d+)", folder)
    if match:
        accelerator.project_configuration.iteration = int(match.group(1)) + 1

    rng_file = os.path.join(input_dir, f"{RNG_NAME}_{accelerator.process_index}.pkl")
    if os.path.exists(rng_file):
        import pickle

        with open(rng_file, "rb") as f:
            try:
                restore_rng_states(pickle.load(f))
            except Exception as e:  # version drift in host RNG formats is non-fatal
                logger.warning(f"could not restore RNG states: {e}")

    logger.info(f"loaded state from {input_dir}")
    if params is not None:
        return (restored[0], restored_opt_state) if opt_state is not None else restored[0]
    accelerator._models = restored
    return (restored, restored_opt_state) if opt_state is not None else restored


def _load_custom(obj, path: str) -> None:
    state = obj.state_dict()
    flat = load_flat(path)
    obj.load_state_dict(unflatten_into(state, flat))


# ---------------------------------------------------------------------------
# model export (safetensors interop)


def _parse_size(size: str) -> int:
    match = re.fullmatch(r"(\d+)\s*([KMGT]?B)", size.strip(), re.IGNORECASE)
    if not match:
        raise ValueError(f"cannot parse size {size!r}")
    mult = {"B": 1, "KB": 2**10, "MB": 2**20, "GB": 2**30, "TB": 2**40}
    return int(match.group(1)) * mult[match.group(2).upper()]


def save_model(
    params,
    save_directory: str,
    max_shard_size: str = "10GB",
    safe_serialization: bool = True,
) -> list[str]:
    """Export params as (sharded) safetensors with an index.json — interop format
    (reference ``save_model accelerator.py:3386``; file layout mirrors
    ``model.safetensors.index.json`` conventions)."""
    os.makedirs(save_directory, exist_ok=True)
    flat = flatten_pytree(params)
    limit = _parse_size(max_shard_size)

    shards: list[dict[str, np.ndarray]] = [{}]
    sizes = [0]
    for key in sorted(flat):
        arr = flat[key]
        nbytes = arr.nbytes
        if sizes[-1] + nbytes > limit and shards[-1]:
            shards.append({})
            sizes.append(0)
        shards[-1][key] = arr
        sizes[-1] += nbytes

    written = []
    if safe_serialization:
        from safetensors.numpy import save_file

        if len(shards) == 1:
            path = os.path.join(save_directory, SAFE_WEIGHTS_NAME)
            save_file(_safetensors_compat(shards[0]), path)
            written.append(path)
        else:
            index = {"metadata": {"total_size": sum(sizes)}, "weight_map": {}}
            for i, shard in enumerate(shards):
                name = f"model-{i + 1:05d}-of-{len(shards):05d}.safetensors"
                save_file(_safetensors_compat(shard), os.path.join(save_directory, name))
                written.append(os.path.join(save_directory, name))
                for key in shard:
                    index["weight_map"][key] = name
            with open(os.path.join(save_directory, SAFE_WEIGHTS_INDEX_NAME), "w") as f:
                json.dump(index, f, indent=2)
    else:
        path = os.path.join(save_directory, WEIGHTS_NAME)
        np.savez(path, **flat)
        written.append(path)
    return written


def _safetensors_compat(shard: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """safetensors-numpy rejects some dtypes (e.g. ml_dtypes bfloat16 views vary by
    version); upcast unsupported dtypes to float32."""
    out = {}
    for k, v in shard.items():
        if v.dtype.kind not in "fiub" or str(v.dtype) == "bfloat16":
            v = v.astype(np.float32)
        out[k] = v
    return out


def load_checkpoint_in_model(params_template, checkpoint_path: str):
    """Load a safetensors/npz checkpoint into a params pytree template
    (reference ``load_checkpoint_in_model utils/modeling.py:1788``)."""
    if os.path.isdir(checkpoint_path):
        index_file = os.path.join(checkpoint_path, SAFE_WEIGHTS_INDEX_NAME)
        single = os.path.join(checkpoint_path, SAFE_WEIGHTS_NAME)
        npz = os.path.join(checkpoint_path, WEIGHTS_NAME)
        if os.path.exists(index_file):
            from safetensors.numpy import load_file

            with open(index_file) as f:
                index = json.load(f)
            flat = {}
            for name in sorted(set(index["weight_map"].values())):
                flat.update(load_file(os.path.join(checkpoint_path, name)))
        elif os.path.exists(single):
            from safetensors.numpy import load_file

            flat = load_file(single)
        elif os.path.exists(npz):
            flat = load_flat(npz)
        else:
            raise FileNotFoundError(f"no model checkpoint in {checkpoint_path}")
    elif checkpoint_path.endswith(".safetensors"):
        from safetensors.numpy import load_file

        flat = load_file(checkpoint_path)
    else:
        flat = load_flat(checkpoint_path)
    return unflatten_into(params_template, flat)
