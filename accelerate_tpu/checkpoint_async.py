"""Background checkpoint writer: the output-side mirror of the input
prefetch pipeline (``data_loader.py``'s producer thread — PR 3 proved the
overlap pattern on the input side; this applies it to ``save_state``).

``Accelerator.save_state(blocking=False)`` splits a save into the fast
**snapshot** (``checkpointing.snapshot_accelerator_state`` — device→host
copies of exactly the replica-0 chunks this process owns, returning control
to the train loop in milliseconds) and the **write+commit** pipeline
(``checkpointing.write_and_commit`` — serialize into ``<dir>.tmp``, fsync,
``_COMMITTED`` manifest last, atomic ``os.replace``), which this module runs
on a single daemon thread so checkpoint cadence stops taxing step time.

Back-pressure: at most ``CheckpointConfig.max_in_flight`` snapshots may be
queued or writing (default 1 — one extra host copy of the state, the same
bound the reference's blocking save has). An additional ``save_state`` blocks
in :meth:`CheckpointManager.submit` until a slot frees; that wait is exposed
stall and is reported as such (telemetry ``checkpoint``/``backpressure``).

Forensics: while writing, the worker is a registered watchdog heartbeat
source (``checkpoint_writer``) that beats once per file, and every write/
commit runs inside a flight-recorder phase — a hung filesystem write is
named in stall dumps instead of reading as a silent training hang
(see ``telemetry/watchdog.py``, PR 4).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Optional

from .logging import get_logger
from .telemetry import events as _tel
from .telemetry import flight_recorder as _flight
from .telemetry import watchdog as _watchdog

logger = get_logger(__name__)

_WD_SOURCE = "checkpoint_writer"


class _Job:
    __slots__ = ("snapshot", "done", "result", "error")

    def __init__(self, snapshot):
        self.snapshot = snapshot
        self.done = threading.Event()
        self.result: Optional[str] = None
        self.error: Optional[BaseException] = None


class CheckpointManager:
    """Owns the writer thread and the in-flight accounting for one
    :class:`~accelerate_tpu.accelerator.Accelerator`.

    Lifecycle: lazily started on the first ``submit``; ``drain()`` blocks
    until every queued save has committed (surfacing the first writer error);
    ``shutdown()`` drains and stops the thread. The thread is a daemon, but
    ``Accelerator.end_training``/``__del__`` drain explicitly — relying on
    daemon teardown would tear a write mid-commit on clean exits.
    """

    def __init__(self, max_in_flight: int = 1):
        self.max_in_flight = max(1, int(max_in_flight))
        self._queue: "collections.deque[_Job]" = collections.deque()
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._slots = threading.BoundedSemaphore(self.max_in_flight)
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._jobs: "list[_Job]" = []  # completed, pending error harvest
        self._active_staging: "set[str]" = set()

    # ------------------------------------------------------------- interface --
    def active_staging(self) -> "set[str]":
        """Staging dirs owned by queued/writing saves — stale-staging cleanup
        must never touch these."""
        with self._lock:
            return set(self._active_staging)

    def reserve_slot(self) -> float:
        """Back-pressure gate, taken BEFORE the snapshot is built (bounding
        host RAM at ``max_in_flight`` extra state copies). Returns seconds
        blocked, which is exposed stall by definition."""
        t0 = time.monotonic()
        if not self._slots.acquire(blocking=False):
            with _flight.phase("checkpoint_backpressure"):
                self._slots.acquire()
            waited = time.monotonic() - t0
            _tel.emit(
                "checkpoint", phase="backpressure", dur_s=round(waited, 6), hidden=False
            )
            from .telemetry import goodput as _goodput

            _goodput.note("checkpoint_stall", waited)
            return waited
        return 0.0

    def release_slot(self) -> None:
        """Give back a slot reserved with :meth:`reserve_slot` when the save
        it was for never got submitted (snapshot raised)."""
        self._slots.release()

    def submit(self, snapshot) -> str:
        """Enqueue a snapshot for background write+commit; returns the final
        directory the save will land in. The caller must hold a slot from
        :meth:`reserve_slot`."""
        self.check_error()
        job = _Job(snapshot)
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop = False
                self._thread = threading.Thread(
                    target=self._run, name="checkpoint-writer", daemon=True
                )
                self._thread.start()
            self._queue.append(job)
            self._jobs.append(job)
            self._active_staging.add(snapshot.staging_dir)
            self._wake.notify_all()
        return snapshot.final_dir

    def pending(self) -> int:
        """Jobs not yet committed (queued or writing)."""
        with self._lock:
            return sum(1 for j in self._jobs if not j.done.is_set())

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until every submitted save has committed; re-raise the first
        writer error. ``timeout`` (seconds) raises TimeoutError on expiry."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                jobs = list(self._jobs)
            if not jobs:
                break
            for job in jobs:
                remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
                if not job.done.wait(remaining):
                    raise TimeoutError(
                        f"checkpoint writer did not finish within {timeout}s "
                        f"(writing {job.snapshot.final_dir})"
                    )
            with self._lock:
                # only harvest jobs everyone waited on; new submits stay
                self._jobs = [j for j in self._jobs if j not in jobs]
            for job in jobs:
                if job.error is not None:
                    raise RuntimeError(
                        f"background checkpoint save to {job.snapshot.final_dir} failed"
                    ) from job.error
        self.check_error()

    def check_error(self) -> None:
        """Raise the first unharvested writer error (without waiting)."""
        with self._lock:
            failed = next((j for j in self._jobs if j.done.is_set() and j.error), None)
            if failed is not None:
                self._jobs.remove(failed)
        if failed is not None:
            raise RuntimeError(
                f"background checkpoint save to {failed.snapshot.final_dir} failed"
            ) from failed.error

    def shutdown(self, drain: bool = True) -> None:
        thread = self._thread
        if thread is None:
            return
        try:
            if drain:
                self.drain()
        finally:
            with self._lock:
                self._stop = True
                self._wake.notify_all()
            thread.join(timeout=30.0)
            self._thread = None

    # ---------------------------------------------------------------- worker --
    def _run(self) -> None:
        from . import checkpointing  # late: tests monkeypatch write_and_commit

        while True:
            with self._lock:
                while not self._queue and not self._stop:
                    self._wake.wait()
                if self._stop and not self._queue:
                    return
                job = self._queue.popleft()
            snap = job.snapshot
            try:
                _watchdog.register(_WD_SOURCE, dir=snap.final_dir)

                def heartbeat(**info: Any) -> None:
                    _watchdog.beat(_WD_SOURCE, **info)

                with _flight.phase("checkpoint_write", dir=snap.final_dir):
                    job.result = checkpointing.write_and_commit(snap, heartbeat=heartbeat)
            except BaseException as e:  # surfaced on drain/next submit
                job.error = e
                logger.error(f"background checkpoint save to {snap.final_dir} failed: {e}")
            finally:
                _watchdog.unregister(_WD_SOURCE)
                with self._lock:
                    self._active_staging.discard(snap.staging_dir)
                self._slots.release()
                job.done.set()
