"""Experiment-tracker abstraction + integrations.

TPU-native counterpart of the reference's ``tracking.py``
(``/root/reference/src/accelerate/tracking.py`` — ``GeneralTracker:101`` with API
``start/store_init_configuration/log/finish:143-181``, ``on_main_process:77``,
TensorBoard ``:182``, WandB ``:297``, MLflow ``:696``, ``filter_trackers:1262``).

Always-available baseline: :class:`JSONLTracker` writes one JSON line per log call
— dependency-free and trivially parseable (the reference's tests use log-file
parsing for exactly this reason, ``tests/test_tracking.py``).
"""

from __future__ import annotations

import json
import os
import time
from functools import wraps
from typing import Any, Optional, Union

from .logging import get_logger
from .state import PartialState
from .utils.dataclasses import LoggerType
from .utils.imports import (
    is_aim_available,
    is_clearml_available,
    is_comet_ml_available,
    is_dvclive_available,
    is_mlflow_available,
    is_swanlab_available,
    is_tensorboard_available,
    is_trackio_available,
    is_wandb_available,
)

logger = get_logger(__name__)


def on_main_process(function):
    """Run only on the main process (reference ``tracking.py:77``)."""

    @wraps(function)
    def execute_on_main_process(self, *args, **kwargs):
        if PartialState().is_main_process:
            return function(self, *args, **kwargs)

    return execute_on_main_process


class GeneralTracker:
    """Base tracker API (reference ``GeneralTracker tracking.py:101``)."""

    main_process_only = True

    name: str = "general"
    requires_logging_directory: bool = False

    def __init__(self, run_name: str, **kwargs):
        self.run_name = run_name

    @property
    def tracker(self):
        raise NotImplementedError

    def store_init_configuration(self, values: dict) -> None:
        pass

    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        pass

    def finish(self) -> None:
        pass


class JSONLTracker(GeneralTracker):
    """Dependency-free tracker: one JSON object per line in ``<dir>/<run>.jsonl``."""

    name = "jsonl"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__(run_name)
        os.makedirs(logging_dir, exist_ok=True)
        self.path = os.path.join(logging_dir, f"{run_name}.jsonl")
        self._file = open(self.path, "a")

    @property
    def tracker(self):
        return self._file

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self._write({"_type": "config", **_jsonable(values)})

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        entry = {"_type": "log", "_time": time.time(), **_jsonable(values)}
        if step is not None:
            entry["step"] = step
        self._write(entry)

    def _write(self, obj: dict) -> None:
        self._file.write(json.dumps(obj) + "\n")
        self._file.flush()

    @on_main_process
    def finish(self) -> None:
        self._file.close()


class TensorBoardTracker(GeneralTracker):
    """reference ``tracking.py:182``."""

    name = "tensorboard"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__(run_name)
        try:
            from torch.utils import tensorboard

            self.writer = tensorboard.SummaryWriter(os.path.join(logging_dir, run_name), **kwargs)
        except ImportError:
            from tensorboardX import SummaryWriter

            self.writer = SummaryWriter(os.path.join(logging_dir, run_name), **kwargs)

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.writer.add_hparams(_flatten_scalars(values), metric_dict={})
        self.writer.flush()

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        for k, v in _flatten_scalars(values).items():
            if isinstance(v, str):
                self.writer.add_text(k, v, global_step=step)
            else:
                self.writer.add_scalar(k, v, global_step=step, **kwargs)
        self.writer.flush()

    @on_main_process
    def finish(self) -> None:
        self.writer.close()


class WandBTracker(GeneralTracker):
    """reference ``tracking.py:297``."""

    name = "wandb"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__(run_name)
        import wandb

        self.run = wandb.init(project=run_name, **kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        import wandb

        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        self.run.log(values, step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.run.finish()


class MLflowTracker(GeneralTracker):
    """reference ``tracking.py:696``."""

    name = "mlflow"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, logging_dir: Optional[str] = None, **kwargs):
        super().__init__(run_name)
        import mlflow

        mlflow.set_experiment(run_name)
        self.run = mlflow.start_run(**kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        import mlflow

        for k, v in _flatten_scalars(values).items():
            mlflow.log_param(k, v)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        import mlflow

        mlflow.log_metrics(
            {k: v for k, v in _flatten_scalars(values).items() if not isinstance(v, str)}, step=step
        )

    @on_main_process
    def finish(self) -> None:
        import mlflow

        mlflow.end_run()


class CometMLTracker(GeneralTracker):
    """reference ``tracking.py:499``."""

    name = "comet_ml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__(run_name)
        from comet_ml import start

        self.experiment = start(project_name=run_name, **kwargs)

    @property
    def tracker(self):
        return self.experiment

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.experiment.log_parameters(_jsonable(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        if step is not None:
            self.experiment.set_step(step)
        for k, v in _flatten_scalars(values).items():
            if isinstance(v, str):
                self.experiment.log_other(k, v)
            else:
                self.experiment.log_metric(k, v, step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.experiment.end()


class AimTracker(GeneralTracker):
    """reference ``tracking.py:593``."""

    name = "aim"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__(run_name)
        from aim import Run

        self.writer = Run(repo=logging_dir, **kwargs)
        self.writer.name = run_name

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.writer["hparams"] = _jsonable(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        for k, v in _flatten_scalars(values).items():
            self.writer.track(v, name=k, step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.writer.close()


class ClearMLTracker(GeneralTracker):
    """reference ``tracking.py:903``."""

    name = "clearml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__(run_name)
        from clearml import Task

        self.task = Task.init(project_name=run_name, **kwargs)

    @property
    def tracker(self):
        return self.task

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.task.connect_configuration(_jsonable(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        clearml_logger = self.task.get_logger()
        for k, v in _flatten_scalars(values).items():
            if isinstance(v, str):
                clearml_logger.report_text(f"{k}: {v}")
            elif step is None:
                clearml_logger.report_single_value(name=k, value=v, **kwargs)
            else:
                title, _, series = k.rpartition("/")
                clearml_logger.report_scalar(
                    title=title or k, series=series or k, value=v, iteration=step, **kwargs
                )

    @on_main_process
    def finish(self) -> None:
        self.task.close()


class DVCLiveTracker(GeneralTracker):
    """reference ``tracking.py:1061``."""

    name = "dvclive"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, live=None, **kwargs):
        super().__init__(run_name)
        from dvclive import Live

        self.live = live if live is not None else Live(**kwargs)

    @property
    def tracker(self):
        return self.live

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.live.log_params(_flatten_scalars(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        if step is not None:
            self.live.step = step
        for k, v in _flatten_scalars(values).items():
            self.live.log_metric(k, v, **kwargs)
        self.live.next_step()

    @on_main_process
    def finish(self) -> None:
        self.live.end()


class SwanLabTracker(GeneralTracker):
    """reference ``tracking.py:1149``."""

    name = "swanlab"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__(run_name)
        import swanlab

        self.run = swanlab.init(project=run_name, **kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        import swanlab

        swanlab.config.update(_jsonable(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        self.run.log(
            {k: v for k, v in _flatten_scalars(values).items() if not isinstance(v, str)},
            step=step,
        )

    @on_main_process
    def finish(self) -> None:
        import swanlab

        swanlab.finish()


class TrackioTracker(GeneralTracker):
    """reference ``tracking.py:422``."""

    name = "trackio"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__(run_name)
        import trackio

        self.run = trackio.init(project=run_name, **kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.run.config.update(_jsonable(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        # trackio's run.log has no step parameter (auto-incremented internally)
        # — the reference drops it too (tracking.py:487)
        self.run.log(
            {k: v for k, v in _flatten_scalars(values).items() if not isinstance(v, str)},
            **kwargs,
        )

    @on_main_process
    def finish(self) -> None:
        self.run.finish()


LOGGER_TYPE_TO_CLASS = {
    "jsonl": JSONLTracker,
    "tensorboard": TensorBoardTracker,
    "wandb": WandBTracker,
    "mlflow": MLflowTracker,
    "comet_ml": CometMLTracker,
    "aim": AimTracker,
    "clearml": ClearMLTracker,
    "dvclive": DVCLiveTracker,
    "swanlab": SwanLabTracker,
    "trackio": TrackioTracker,
}

_AVAILABILITY = {
    "jsonl": lambda: True,
    "tensorboard": is_tensorboard_available,
    "wandb": is_wandb_available,
    "mlflow": is_mlflow_available,
    "comet_ml": is_comet_ml_available,
    "aim": is_aim_available,
    "clearml": is_clearml_available,
    "dvclive": is_dvclive_available,
    "swanlab": is_swanlab_available,
    "trackio": is_trackio_available,
}


def filter_trackers(
    log_with,
    project_name: str,
    logging_dir: Optional[str] = None,
    config: Optional[dict] = None,
    init_kwargs: Optional[dict] = None,
) -> list[GeneralTracker]:
    """Resolve requested trackers to available instances (reference
    ``filter_trackers:1262``)."""
    if log_with is None:
        return []
    if not isinstance(log_with, (list, tuple)):
        log_with = [log_with]
    names: list[str] = []
    instances: list[GeneralTracker] = []
    for entry in log_with:
        if isinstance(entry, GeneralTracker):
            instances.append(entry)
            continue
        value = str(entry)
        if value == str(LoggerType.ALL):
            names.extend(n for n in LOGGER_TYPE_TO_CLASS if _AVAILABILITY[n]())
        else:
            names.append(value)
    for name in dict.fromkeys(names):
        if name not in LOGGER_TYPE_TO_CLASS:
            raise ValueError(f"unknown tracker {name!r}; options: {sorted(LOGGER_TYPE_TO_CLASS)}")
        if not _AVAILABILITY[name]():
            logger.warning(f"tracker {name!r} requested but its library is unavailable; skipping")
            continue
        cls = LOGGER_TYPE_TO_CLASS[name]
        kwargs = dict((init_kwargs or {}).get(name, {}))
        if cls.requires_logging_directory:
            kwargs.setdefault("logging_dir", logging_dir or ".")
        tracker = cls(project_name, **kwargs)
        if config:
            tracker.store_init_configuration(config)
        instances.append(tracker)
    return instances


def _jsonable(values: dict) -> dict:
    import numpy as np

    def conv(v):
        if hasattr(v, "item") and getattr(v, "ndim", 1) == 0:
            return v.item()
        if isinstance(v, (np.floating, np.integer)):
            return v.item()
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [conv(x) for x in v]
        if isinstance(v, (str, int, float, bool)) or v is None:
            return v
        return str(v)

    return {k: conv(v) for k, v in values.items()}


def _flatten_scalars(values: dict, prefix: str = "") -> dict:
    flat = {}
    for k, v in values.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten_scalars(v, prefix=f"{key}/"))
        else:
            v = v.item() if hasattr(v, "item") and getattr(v, "ndim", 1) == 0 else v
            if isinstance(v, (int, float, str, bool)):
                flat[key] = v
    return flat
