"""Experiment-tracker abstraction + integrations.

TPU-native counterpart of the reference's ``tracking.py``
(``/root/reference/src/accelerate/tracking.py`` — ``GeneralTracker:101`` with API
``start/store_init_configuration/log/finish:143-181``, ``on_main_process:77``,
TensorBoard ``:182``, WandB ``:297``, MLflow ``:696``, ``filter_trackers:1262``).

Always-available baseline: :class:`JSONLTracker` writes one JSON line per log call
— dependency-free and trivially parseable (the reference's tests use log-file
parsing for exactly this reason, ``tests/test_tracking.py``).
"""

from __future__ import annotations

import json
import os
import time
from functools import wraps
from typing import Any, Optional, Union

from .logging import get_logger
from .state import PartialState
from .utils.dataclasses import LoggerType
from .utils.imports import (
    is_aim_available,
    is_clearml_available,
    is_comet_ml_available,
    is_dvclive_available,
    is_mlflow_available,
    is_swanlab_available,
    is_tensorboard_available,
    is_trackio_available,
    is_wandb_available,
)

logger = get_logger(__name__)


def on_main_process(function):
    """Run only on the main process (reference ``tracking.py:77``)."""

    @wraps(function)
    def execute_on_main_process(self, *args, **kwargs):
        if PartialState().is_main_process:
            return function(self, *args, **kwargs)

    return execute_on_main_process


class GeneralTracker:
    """Base tracker API (reference ``GeneralTracker tracking.py:101``).

    Two-phase lifecycle (reference ``start:142``): ``__init__`` only records
    configuration; :meth:`start` performs the SDK/run initialization. The
    ``Accelerator`` calls ``start()`` from ``init_trackers``; direct users may
    skip it — every logging method lazily starts on first use."""

    main_process_only = True

    name: str = "general"
    requires_logging_directory: bool = False

    def __init__(self, run_name: str, **kwargs):
        self.run_name = run_name
        self._started = False

    def start(self) -> None:
        """Deferred (idempotent) initialization — the heavy SDK setup lives in
        ``_do_start`` so constructing a tracker stays side-effect free."""
        if getattr(self, "_started", False):
            return
        self._started = True
        if PartialState().is_main_process:
            self._do_start()

    def _do_start(self) -> None:
        pass

    def _ensure_started(self) -> None:
        self.start()

    @property
    def tracker(self):
        raise NotImplementedError

    def store_init_configuration(self, values: dict) -> None:
        pass

    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        pass

    def log_telemetry(self, summary: dict, step: Optional[int] = None, **kwargs) -> None:
        """Receive a flattened telemetry summary (``telemetry/...`` scalar
        metrics from :mod:`accelerate_tpu.telemetry.tracker_bridge`). The
        default routes through :meth:`log`, so every integration gets
        step-time percentiles / recompile counts / comms bytes wherever its
        metrics already go; trackers with a native concept of summaries may
        override."""
        self.log(summary, step=step, **kwargs)

    def log_images(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        """Log named images/image-lists (reference e.g. ``tracking.py:272``).
        Trackers without image support warn and skip."""
        logger.warning(f"tracker {self.name!r} does not support log_images; skipping")

    def log_table(
        self,
        table_name: str,
        columns: Optional[list] = None,
        data: Optional[list] = None,
        dataframe: Any = None,
        step: Optional[int] = None,
        **kwargs,
    ) -> None:
        """Log a table by columns+data or dataframe (reference
        ``tracking.py:383``). Trackers without table support warn and skip."""
        logger.warning(f"tracker {self.name!r} does not support log_table; skipping")

    def finish(self) -> None:
        pass


def _table_rows(columns, data, dataframe):
    """Normalize (columns, data) | dataframe to (columns, rows-of-lists)."""
    if dataframe is not None:
        cols = [str(c) for c in dataframe.columns]
        return cols, dataframe.values.tolist()
    return list(columns or []), [list(r) for r in (data or [])]


class JSONLTracker(GeneralTracker):
    """Dependency-free tracker: one JSON object per line in ``<dir>/<run>.jsonl``."""

    name = "jsonl"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__(run_name)
        self._logging_dir = logging_dir

    def _do_start(self) -> None:
        os.makedirs(self._logging_dir, exist_ok=True)
        self.path = os.path.join(self._logging_dir, f"{self.run_name}.jsonl")
        self._file = open(self.path, "a")

    @property
    def tracker(self):
        self._ensure_started()
        return self._file

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self._ensure_started()
        self._write({"_type": "config", **_jsonable(values)})

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        self._ensure_started()
        entry = {"_type": "log", "_time": time.time(), **_jsonable(values)}
        if step is not None:
            entry["step"] = step
        self._write(entry)

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        """Images go to ``<dir>/<run>_media/*.npy`` sidecars; the jsonl records
        their paths and shapes (dependency-free — no image codec needed)."""
        import numpy as np

        self._ensure_started()
        media_dir = os.path.join(self._logging_dir, f"{self.run_name}_media")
        os.makedirs(media_dir, exist_ok=True)
        entry = {"_type": "images", "_time": time.time()}
        if step is not None:
            entry["step"] = step
        for k, imgs in values.items():
            paths = []
            for i, img in enumerate(imgs):
                arr = np.asarray(img)
                fname = f"{k.replace('/', '_')}_{step if step is not None else 'x'}_{i}.npy"
                np.save(os.path.join(media_dir, fname), arr)
                paths.append({"path": os.path.join(media_dir, fname), "shape": list(arr.shape)})
            entry[k] = paths
        self._write(entry)

    @on_main_process
    def log_table(self, table_name, columns=None, data=None, dataframe=None,
                  step: Optional[int] = None, **kwargs) -> None:
        self._ensure_started()
        cols, rows = _table_rows(columns, data, dataframe)
        entry = {"_type": "table", "name": table_name,
                 "columns": cols, "rows": _jsonable({"r": rows})["r"]}
        if step is not None:
            entry["step"] = step
        self._write(entry)

    def _write(self, obj: dict) -> None:
        self._file.write(json.dumps(obj) + "\n")
        self._file.flush()

    @on_main_process
    def finish(self) -> None:
        if getattr(self, "_started", False) and getattr(self, "_file", None):
            self._file.close()


class TensorBoardTracker(GeneralTracker):
    """reference ``tracking.py:182``."""

    name = "tensorboard"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__(run_name)
        self._logging_dir = logging_dir
        self._init_kwargs = kwargs

    def _do_start(self) -> None:
        try:
            from torch.utils import tensorboard

            self.writer = tensorboard.SummaryWriter(
                os.path.join(self._logging_dir, self.run_name), **self._init_kwargs
            )
        except ImportError:
            from tensorboardX import SummaryWriter

            self.writer = SummaryWriter(
                os.path.join(self._logging_dir, self.run_name), **self._init_kwargs
            )

    @property
    def tracker(self):
        self._ensure_started()
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self._ensure_started()
        self.writer.add_hparams(_flatten_scalars(values), metric_dict={})
        self.writer.flush()

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        self._ensure_started()
        for k, v in _flatten_scalars(values).items():
            if isinstance(v, str):
                self.writer.add_text(k, v, global_step=step)
            else:
                self.writer.add_scalar(k, v, global_step=step, **kwargs)
        self.writer.flush()

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        """reference ``tracking.py:272`` — ``SummaryWriter.add_images``;
        NHWC is detected and passed as ``dataformats`` unless given."""
        import numpy as np

        self._ensure_started()
        for k, v in values.items():
            arr = np.asarray(v)
            kw = dict(kwargs)
            if "dataformats" not in kw and arr.ndim == 4 and arr.shape[-1] in (1, 3, 4):
                kw["dataformats"] = "NHWC"
            self.writer.add_images(k, arr, global_step=step, **kw)
        self.writer.flush()

    @on_main_process
    def finish(self) -> None:
        if getattr(self, "_started", False) and getattr(self, "writer", None):
            self.writer.close()


class WandBTracker(GeneralTracker):
    """reference ``tracking.py:297``."""

    name = "wandb"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__(run_name)
        self._init_kwargs = kwargs

    def _do_start(self) -> None:
        import wandb

        self.run = wandb.init(project=self.run_name, **self._init_kwargs)

    @property
    def tracker(self):
        self._ensure_started()
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        import wandb

        self._ensure_started()
        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        self._ensure_started()
        self.run.log(values, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        """reference ``tracking.py:364`` — each value list becomes wandb.Image s."""
        import wandb

        self._ensure_started()
        for k, v in values.items():
            self.run.log({k: [wandb.Image(img) for img in v]}, step=step, **kwargs)

    @on_main_process
    def log_table(self, table_name, columns=None, data=None, dataframe=None,
                  step: Optional[int] = None, **kwargs) -> None:
        """reference ``tracking.py:383`` — wandb.Table by columns+data or df."""
        import wandb

        self._ensure_started()
        table = wandb.Table(columns=columns, data=data, dataframe=dataframe)
        self.run.log({table_name: table}, step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        if getattr(self, "_started", False) and getattr(self, "run", None):
            self.run.finish()


class MLflowTracker(GeneralTracker):
    """reference ``tracking.py:696``."""

    name = "mlflow"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, logging_dir: Optional[str] = None, **kwargs):
        super().__init__(run_name)
        self._init_kwargs = kwargs

    def _do_start(self) -> None:
        import mlflow

        mlflow.set_experiment(self.run_name)
        self.run = mlflow.start_run(**self._init_kwargs)

    @property
    def tracker(self):
        self._ensure_started()
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        import mlflow

        self._ensure_started()
        for k, v in _flatten_scalars(values).items():
            mlflow.log_param(k, v)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        import mlflow

        self._ensure_started()
        mlflow.log_metrics(
            {k: v for k, v in _flatten_scalars(values).items() if not isinstance(v, str)}, step=step
        )

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        """``mlflow.log_image`` per image, named ``<key>_<step>_<i>.png``."""
        import mlflow
        import numpy as np

        self._ensure_started()
        for k, v in values.items():
            for i, img in enumerate(v):
                fname = f"{k.replace('/', '_')}_{step if step is not None else 'x'}_{i}.png"
                mlflow.log_image(np.asarray(img), fname)

    @on_main_process
    def log_table(self, table_name, columns=None, data=None, dataframe=None,
                  step: Optional[int] = None, **kwargs) -> None:
        """``mlflow.log_table`` from a dict or dataframe."""
        import mlflow

        self._ensure_started()
        if dataframe is not None:
            mlflow.log_table(dataframe, artifact_file=f"{table_name}.json")
        else:
            cols, rows = _table_rows(columns, data, None)
            payload = {c: [r[i] for r in rows] for i, c in enumerate(cols)}
            mlflow.log_table(payload, artifact_file=f"{table_name}.json")

    @on_main_process
    def finish(self) -> None:
        if getattr(self, "_started", False):
            import mlflow

            mlflow.end_run()


class CometMLTracker(GeneralTracker):
    """reference ``tracking.py:499``."""

    name = "comet_ml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__(run_name)
        self._init_kwargs = kwargs

    def _do_start(self) -> None:
        from comet_ml import start

        self.experiment = start(project_name=self.run_name, **self._init_kwargs)

    @property
    def tracker(self):
        self._ensure_started()
        return self.experiment

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self._ensure_started()
        self.experiment.log_parameters(_jsonable(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        self._ensure_started()
        if step is not None:
            self.experiment.set_step(step)
        for k, v in _flatten_scalars(values).items():
            if isinstance(v, str):
                self.experiment.log_other(k, v)
            else:
                self.experiment.log_metric(k, v, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        self._ensure_started()
        for k, v in values.items():
            for i, img in enumerate(v):
                self.experiment.log_image(img, name=f"{k}_{i}", step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        if getattr(self, "_started", False) and getattr(self, "experiment", None):
            self.experiment.end()


class AimTracker(GeneralTracker):
    """reference ``tracking.py:593``."""

    name = "aim"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__(run_name)
        self._logging_dir = logging_dir
        self._init_kwargs = kwargs

    def _do_start(self) -> None:
        from aim import Run

        self.writer = Run(repo=self._logging_dir, **self._init_kwargs)
        self.writer.name = self.run_name

    @property
    def tracker(self):
        self._ensure_started()
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self._ensure_started()
        self.writer["hparams"] = _jsonable(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        self._ensure_started()
        for k, v in _flatten_scalars(values).items():
            self.writer.track(v, name=k, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        """reference ``tracking.py:657`` — aim.Image per value. Optional
        ``aim_image``/``track`` sub-dicts route kwargs to the Image ctor and
        ``Run.track`` respectively (same split the reference exposes)."""
        import aim

        self._ensure_started()
        aim_image_kw = kwargs.pop("aim_image", {})
        track_kw = kwargs.pop("track", {})
        for k, v in values.items():
            self.writer.track(aim.Image(v, **aim_image_kw), name=k, step=step, **track_kw)

    @on_main_process
    def finish(self) -> None:
        if getattr(self, "_started", False) and getattr(self, "writer", None):
            self.writer.close()


class ClearMLTracker(GeneralTracker):
    """reference ``tracking.py:903``."""

    name = "clearml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__(run_name)
        self._init_kwargs = kwargs

    def _do_start(self) -> None:
        from clearml import Task

        self.task = Task.init(project_name=self.run_name, **self._init_kwargs)

    @property
    def tracker(self):
        self._ensure_started()
        return self.task

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self._ensure_started()
        self.task.connect_configuration(_jsonable(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        self._ensure_started()
        clearml_logger = self.task.get_logger()
        for k, v in _flatten_scalars(values).items():
            if isinstance(v, str):
                clearml_logger.report_text(f"{k}: {v}")
            elif step is None:
                clearml_logger.report_single_value(name=k, value=v, **kwargs)
            else:
                title, _, series = k.rpartition("/")
                clearml_logger.report_scalar(
                    title=title or k, series=series or k, value=v, iteration=step, **kwargs
                )

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        """reference ``tracking.py:989`` — ``Logger.report_image``."""
        self._ensure_started()
        clearml_logger = self.task.get_logger()
        for k, v in values.items():
            title, _, series = k.rpartition("/")
            for i, img in enumerate(v):
                clearml_logger.report_image(
                    title=title or k, series=f"{series or k}_{i}",
                    iteration=step, image=img, **kwargs
                )

    @on_main_process
    def log_table(self, table_name, columns=None, data=None, dataframe=None,
                  step: Optional[int] = None, **kwargs) -> None:
        """reference ``tracking.py:1007`` — ``Logger.report_table``."""
        self._ensure_started()
        clearml_logger = self.task.get_logger()
        if dataframe is not None:
            payload = dataframe
        else:
            cols, rows = _table_rows(columns, data, None)
            payload = [cols] + rows  # first row = header, clearml convention
        title, _, series = table_name.rpartition("/")
        clearml_logger.report_table(
            title=title or table_name, series=series or table_name,
            iteration=step, table_plot=payload, **kwargs,
        )

    @on_main_process
    def finish(self) -> None:
        if getattr(self, "_started", False) and getattr(self, "task", None):
            self.task.close()


class DVCLiveTracker(GeneralTracker):
    """reference ``tracking.py:1061``."""

    name = "dvclive"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, live=None, **kwargs):
        super().__init__(run_name)
        self._live_arg = live
        self._init_kwargs = kwargs

    def _do_start(self) -> None:
        from dvclive import Live

        self.live = self._live_arg if self._live_arg is not None else Live(**self._init_kwargs)

    @property
    def tracker(self):
        self._ensure_started()
        return self.live

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self._ensure_started()
        self.live.log_params(_flatten_scalars(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        self._ensure_started()
        if step is not None:
            self.live.step = step
        for k, v in _flatten_scalars(values).items():
            self.live.log_metric(k, v, **kwargs)
        self.live.next_step()

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        self._ensure_started()
        if step is not None:
            self.live.step = step
        for k, v in values.items():
            for i, img in enumerate(v):
                self.live.log_image(f"{k}_{i}.png", img, **kwargs)

    @on_main_process
    def finish(self) -> None:
        if getattr(self, "_started", False) and getattr(self, "live", None):
            self.live.end()


class SwanLabTracker(GeneralTracker):
    """reference ``tracking.py:1149``."""

    name = "swanlab"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__(run_name)
        self._init_kwargs = kwargs

    def _do_start(self) -> None:
        import swanlab

        self.run = swanlab.init(project=self.run_name, **self._init_kwargs)

    @property
    def tracker(self):
        self._ensure_started()
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        import swanlab

        self._ensure_started()
        swanlab.config.update(_jsonable(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        self._ensure_started()
        self.run.log(
            {k: v for k, v in _flatten_scalars(values).items() if not isinstance(v, str)},
            step=step,
        )

    @on_main_process
    def log_images(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        """reference ``tracking.py:1220`` — swanlab.Image per value."""
        import swanlab

        self._ensure_started()
        for k, v in values.items():
            self.run.log({k: [swanlab.Image(img) for img in v]}, step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        if getattr(self, "_started", False):
            import swanlab

            swanlab.finish()


class TrackioTracker(GeneralTracker):
    """reference ``tracking.py:422``."""

    name = "trackio"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__(run_name)
        self._init_kwargs = kwargs

    def _do_start(self) -> None:
        import trackio

        self.run = trackio.init(project=self.run_name, **self._init_kwargs)

    @property
    def tracker(self):
        self._ensure_started()
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self._ensure_started()
        self.run.config.update(_jsonable(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        # trackio's run.log has no step parameter (auto-incremented internally)
        # — the reference drops it too (tracking.py:487)
        self._ensure_started()
        self.run.log(
            {k: v for k, v in _flatten_scalars(values).items() if not isinstance(v, str)},
            **kwargs,
        )

    @on_main_process
    def finish(self) -> None:
        if getattr(self, "_started", False) and getattr(self, "run", None):
            self.run.finish()


LOGGER_TYPE_TO_CLASS = {
    "jsonl": JSONLTracker,
    "tensorboard": TensorBoardTracker,
    "wandb": WandBTracker,
    "mlflow": MLflowTracker,
    "comet_ml": CometMLTracker,
    "aim": AimTracker,
    "clearml": ClearMLTracker,
    "dvclive": DVCLiveTracker,
    "swanlab": SwanLabTracker,
    "trackio": TrackioTracker,
}

_AVAILABILITY = {
    "jsonl": lambda: True,
    "tensorboard": is_tensorboard_available,
    "wandb": is_wandb_available,
    "mlflow": is_mlflow_available,
    "comet_ml": is_comet_ml_available,
    "aim": is_aim_available,
    "clearml": is_clearml_available,
    "dvclive": is_dvclive_available,
    "swanlab": is_swanlab_available,
    "trackio": is_trackio_available,
}


def filter_trackers(
    log_with,
    project_name: str,
    logging_dir: Optional[str] = None,
    config: Optional[dict] = None,
    init_kwargs: Optional[dict] = None,
) -> list[GeneralTracker]:
    """Resolve requested trackers to available instances (reference
    ``filter_trackers:1262``)."""
    if log_with is None:
        return []
    if not isinstance(log_with, (list, tuple)):
        log_with = [log_with]
    names: list[str] = []
    instances: list[GeneralTracker] = []
    for entry in log_with:
        if isinstance(entry, GeneralTracker):
            entry.start()  # two-phase init; idempotent for pre-started ones
            instances.append(entry)
            continue
        value = str(entry)
        if value == str(LoggerType.ALL):
            names.extend(get_available_trackers())
        else:
            names.append(value)
    for name in dict.fromkeys(names):
        if name not in LOGGER_TYPE_TO_CLASS:
            raise ValueError(f"unknown tracker {name!r}; options: {sorted(LOGGER_TYPE_TO_CLASS)}")
        if not _AVAILABILITY[name]():
            logger.warning(f"tracker {name!r} requested but its library is unavailable; skipping")
            continue
        cls = LOGGER_TYPE_TO_CLASS[name]
        kwargs = dict((init_kwargs or {}).get(name, {}))
        if cls.requires_logging_directory:
            kwargs.setdefault("logging_dir", logging_dir or ".")
        tracker = cls(project_name, **kwargs)
        tracker.start()  # two-phase init (reference Accelerator calls start())
        if config:
            tracker.store_init_configuration(config)
        instances.append(tracker)
    return instances


def _jsonable(values: dict) -> dict:
    import numpy as np

    def conv(v):
        if hasattr(v, "item") and getattr(v, "ndim", 1) == 0:
            return v.item()
        if isinstance(v, (np.floating, np.integer)):
            return v.item()
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [conv(x) for x in v]
        if isinstance(v, (str, int, float, bool)) or v is None:
            return v
        return str(v)

    return {k: conv(v) for k, v in values.items()}


def _flatten_scalars(values: dict, prefix: str = "") -> dict:
    flat = {}
    for k, v in values.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten_scalars(v, prefix=f"{key}/"))
        else:
            v = v.item() if hasattr(v, "item") and getattr(v, "ndim", 1) == 0 else v
            if isinstance(v, (int, float, str, bool)):
                flat[key] = v
    return flat


def get_available_trackers() -> list[str]:
    """Names of tracker integrations whose libraries are importable
    (reference ``get_available_trackers``)."""
    return [name for name in LOGGER_TYPE_TO_CLASS if _AVAILABILITY[name]()]
