"""Experiment-tracker abstraction + integrations.

TPU-native counterpart of the reference's ``tracking.py``
(``/root/reference/src/accelerate/tracking.py`` — ``GeneralTracker:101`` with API
``start/store_init_configuration/log/finish:143-181``, ``on_main_process:77``,
TensorBoard ``:182``, WandB ``:297``, MLflow ``:696``, ``filter_trackers:1262``).

Always-available baseline: :class:`JSONLTracker` writes one JSON line per log call
— dependency-free and trivially parseable (the reference's tests use log-file
parsing for exactly this reason, ``tests/test_tracking.py``).
"""

from __future__ import annotations

import json
import os
import time
from functools import wraps
from typing import Any, Optional, Union

from .logging import get_logger
from .state import PartialState
from .utils.dataclasses import LoggerType
from .utils.imports import is_mlflow_available, is_tensorboard_available, is_wandb_available

logger = get_logger(__name__)


def on_main_process(function):
    """Run only on the main process (reference ``tracking.py:77``)."""

    @wraps(function)
    def execute_on_main_process(self, *args, **kwargs):
        if PartialState().is_main_process:
            return function(self, *args, **kwargs)

    return execute_on_main_process


class GeneralTracker:
    """Base tracker API (reference ``GeneralTracker tracking.py:101``)."""

    main_process_only = True

    name: str = "general"
    requires_logging_directory: bool = False

    def __init__(self, run_name: str, **kwargs):
        self.run_name = run_name

    @property
    def tracker(self):
        raise NotImplementedError

    def store_init_configuration(self, values: dict) -> None:
        pass

    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        pass

    def finish(self) -> None:
        pass


class JSONLTracker(GeneralTracker):
    """Dependency-free tracker: one JSON object per line in ``<dir>/<run>.jsonl``."""

    name = "jsonl"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__(run_name)
        os.makedirs(logging_dir, exist_ok=True)
        self.path = os.path.join(logging_dir, f"{run_name}.jsonl")
        self._file = open(self.path, "a")

    @property
    def tracker(self):
        return self._file

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self._write({"_type": "config", **_jsonable(values)})

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        entry = {"_type": "log", "_time": time.time(), **_jsonable(values)}
        if step is not None:
            entry["step"] = step
        self._write(entry)

    def _write(self, obj: dict) -> None:
        self._file.write(json.dumps(obj) + "\n")
        self._file.flush()

    @on_main_process
    def finish(self) -> None:
        self._file.close()


class TensorBoardTracker(GeneralTracker):
    """reference ``tracking.py:182``."""

    name = "tensorboard"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__(run_name)
        try:
            from torch.utils import tensorboard

            self.writer = tensorboard.SummaryWriter(os.path.join(logging_dir, run_name), **kwargs)
        except ImportError:
            from tensorboardX import SummaryWriter

            self.writer = SummaryWriter(os.path.join(logging_dir, run_name), **kwargs)

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.writer.add_hparams(_flatten_scalars(values), metric_dict={})
        self.writer.flush()

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        for k, v in _flatten_scalars(values).items():
            if isinstance(v, str):
                self.writer.add_text(k, v, global_step=step)
            else:
                self.writer.add_scalar(k, v, global_step=step, **kwargs)
        self.writer.flush()

    @on_main_process
    def finish(self) -> None:
        self.writer.close()


class WandBTracker(GeneralTracker):
    """reference ``tracking.py:297``."""

    name = "wandb"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__(run_name)
        import wandb

        self.run = wandb.init(project=run_name, **kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        import wandb

        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        self.run.log(values, step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.run.finish()


class MLflowTracker(GeneralTracker):
    """reference ``tracking.py:696``."""

    name = "mlflow"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, logging_dir: Optional[str] = None, **kwargs):
        super().__init__(run_name)
        import mlflow

        mlflow.set_experiment(run_name)
        self.run = mlflow.start_run(**kwargs)

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        import mlflow

        for k, v in _flatten_scalars(values).items():
            mlflow.log_param(k, v)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs) -> None:
        import mlflow

        mlflow.log_metrics(
            {k: v for k, v in _flatten_scalars(values).items() if not isinstance(v, str)}, step=step
        )

    @on_main_process
    def finish(self) -> None:
        import mlflow

        mlflow.end_run()


LOGGER_TYPE_TO_CLASS = {
    "jsonl": JSONLTracker,
    "tensorboard": TensorBoardTracker,
    "wandb": WandBTracker,
    "mlflow": MLflowTracker,
}

_AVAILABILITY = {
    "jsonl": lambda: True,
    "tensorboard": is_tensorboard_available,
    "wandb": is_wandb_available,
    "mlflow": is_mlflow_available,
}


def filter_trackers(
    log_with,
    project_name: str,
    logging_dir: Optional[str] = None,
    config: Optional[dict] = None,
    init_kwargs: Optional[dict] = None,
) -> list[GeneralTracker]:
    """Resolve requested trackers to available instances (reference
    ``filter_trackers:1262``)."""
    if log_with is None:
        return []
    if not isinstance(log_with, (list, tuple)):
        log_with = [log_with]
    names: list[str] = []
    instances: list[GeneralTracker] = []
    for entry in log_with:
        if isinstance(entry, GeneralTracker):
            instances.append(entry)
            continue
        value = str(entry)
        if value == str(LoggerType.ALL):
            names.extend(n for n in LOGGER_TYPE_TO_CLASS if _AVAILABILITY[n]())
        else:
            names.append(value)
    for name in dict.fromkeys(names):
        if name not in LOGGER_TYPE_TO_CLASS:
            raise ValueError(f"unknown tracker {name!r}; options: {sorted(LOGGER_TYPE_TO_CLASS)}")
        if not _AVAILABILITY[name]():
            logger.warning(f"tracker {name!r} requested but its library is unavailable; skipping")
            continue
        cls = LOGGER_TYPE_TO_CLASS[name]
        kwargs = dict((init_kwargs or {}).get(name, {}))
        if cls.requires_logging_directory:
            kwargs.setdefault("logging_dir", logging_dir or ".")
        tracker = cls(project_name, **kwargs)
        if config:
            tracker.store_init_configuration(config)
        instances.append(tracker)
    return instances


def _jsonable(values: dict) -> dict:
    import numpy as np

    def conv(v):
        if hasattr(v, "item") and getattr(v, "ndim", 1) == 0:
            return v.item()
        if isinstance(v, (np.floating, np.integer)):
            return v.item()
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [conv(x) for x in v]
        if isinstance(v, (str, int, float, bool)) or v is None:
            return v
        return str(v)

    return {k: conv(v) for k, v in values.items()}


def _flatten_scalars(values: dict, prefix: str = "") -> dict:
    flat = {}
    for k, v in values.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten_scalars(v, prefix=f"{key}/"))
        else:
            v = v.item() if hasattr(v, "item") and getattr(v, "ndim", 1) == 0 else v
            if isinstance(v, (int, float, str, bool)):
                flat[key] = v
    return flat
