"""Rank-aware logging.

TPU-native counterpart of the reference's ``logging.py``
(``/root/reference/src/accelerate/logging.py`` — ``MultiProcessAdapter:23``,
``get_logger:87``): log lines carry rank info, fire on the main process only by
default, optionally on all processes (``main_process_only=False``) or strictly
``in_order`` across hosts.
"""

from __future__ import annotations

import functools
import logging
import os


class MultiProcessAdapter(logging.LoggerAdapter):
    @staticmethod
    def _should_log(main_process_only: bool) -> bool:
        from .state import PartialState

        state = PartialState()
        return not main_process_only or state.is_main_process

    def log(self, level, msg, *args, main_process_only: bool = True, in_order: bool = False, **kwargs):
        if self.isEnabledFor(level):
            from .state import PartialState

            state = PartialState()
            kwargs.setdefault("stacklevel", 2)
            if in_order and state.num_processes > 1:
                for i in range(state.num_processes):
                    if i == state.process_index:
                        msg, kw = self.process(msg, kwargs)
                        self.logger.log(level, msg, *args, **kw)
                    state.wait_for_everyone(f"log_in_order_{i}")
                return
            if self._should_log(main_process_only):
                msg, kwargs = self.process(msg, kwargs)
                self.logger.log(level, msg, *args, **kwargs)

    @functools.lru_cache(None)
    def warning_once(self, *args, **kwargs):
        """Emit a warning exactly once per unique message (reference ``:78``)."""
        self.warning(*args, **kwargs)


def get_logger(name: str, log_level: str | None = None) -> MultiProcessAdapter:
    """Rank-aware logger (reference ``get_logger:87``). Level from arg or
    ``ACCELERATE_LOG_LEVEL``."""
    logger = logging.getLogger(name)
    level = log_level or os.environ.get("ACCELERATE_LOG_LEVEL", None)
    if level is not None:
        logger.setLevel(level.upper())
        logger.root.setLevel(level.upper())
    return MultiProcessAdapter(logger, {})
