"""Sharded, reproducible, resumable data loading that yields global device arrays.

TPU-native counterpart of the reference's ``data_loader.py``
(``/root/reference/src/accelerate/data_loader.py`` — ``SeedableRandomSampler:73``,
``BatchSamplerShard:110``, ``IterableDatasetShard:266``, ``DataLoaderShard:500``,
``DataLoaderDispatcher:704``, ``prepare_data_loader:996``, ``SkipBatchSampler:1312``,
``SkipDataLoader:1335``, ``skip_first_batches:1375``).

Design shift vs the reference: instead of each rank holding a *local* torch tensor,
the loader yields **one global ``jax.Array`` per field**, sharded over the mesh's
batch axes (``dp_replicate × dp_shard`` on dim 0; ``cp``/``sp`` on the sequence dim).
Each host reads only the sample rows its addressable devices own, then the global
array is assembled with ``jax.make_array_from_single_device_arrays`` — the SPMD twin
of the reference's mesh-aware rank remap (``data_loader.py:1109-1145``). Inside a
jitted train step, XLA sees one logical batch and inserts any needed collectives.

Static-shape discipline: ``even_batches=True`` (wraparound, reference
``data_loader.py:236-262``) is the default so every step has identical shapes and
never recompiles; ``GradientState.remainder`` records the duplicate count so
``gather_for_metrics`` can trim (reference ``accelerator.py:3020-3092``).

Asynchronous prefetch: a bounded background producer pulls up to
``prefetch_depth`` batches ahead (default 2), runs host-side processing and
issues the sharded host→device transfer, so the transfer for batch N+1
overlaps the jitted step for batch N and the consumer only pays a queue-pop
("stall") when the producer cannot keep up. ``prefetch_depth=0`` restores the
fully synchronous iteration byte-for-byte. See ``docs/data_pipeline.md``.
"""

from __future__ import annotations

import math
import queue as _queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np

from .parallelism_config import ParallelismConfig
from .resilience.chaos import maybe_inject as _chaos_inject
from .state import GradientState, PartialState
from .telemetry import events as _tel
from .telemetry import flight_recorder as _flight
from .telemetry import watchdog as _watchdog
from .telemetry.step_profiler import record_data_wait
from .utils.dataclasses import DataLoaderConfiguration
from .utils.operations import find_batch_size, recursively_apply, send_to_device

_NO_BATCH = object()


def _pop_next(q: "_queue.Queue", thread: threading.Thread):
    """Block for the producer's next event, detecting a dead producer.
    Annotated by the caller as the ``prefetch_wait`` flight phase, so a
    consumer starved by a wedged (but alive) producer is diagnosable too."""
    while True:
        try:
            return q.get(timeout=1.0)
        except _queue.Empty:
            if not thread.is_alive():
                # the producer may have enqueued its final event in the
                # instant after our timeout — drain before declaring it dead
                try:
                    return q.get_nowait()
                except _queue.Empty:
                    raise RuntimeError(
                        "prefetch producer thread died without a final event"
                    ) from None


# ---------------------------------------------------------------------------
# Samplers (pure index math — carries over from the reference nearly verbatim
# in *behavior*, reimplemented for numpy)


class SeedableRandomSampler:
    """Deterministic shuffling sampler: permutation = f(seed, epoch)
    (reference ``data_loader.py:73``). ``set_epoch`` reshuffles per epoch."""

    def __init__(self, data_source_len: int, seed: int = 0, epoch: int = 0):
        self.data_source_len = data_source_len
        self.seed = seed
        self.epoch = epoch

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self.data_source_len

    def __iter__(self) -> Iterator[int]:
        rng = np.random.default_rng(self.seed + self.epoch)
        yield from rng.permutation(self.data_source_len).tolist()

    def state_dict(self) -> dict:
        return {"seed": self.seed, "epoch": self.epoch}

    def load_state_dict(self, state: dict) -> None:
        self.seed = state["seed"]
        self.epoch = state["epoch"]


class SequentialSampler:
    def __init__(self, data_source_len: int):
        self.data_source_len = data_source_len

    def set_epoch(self, epoch: int) -> None:
        pass

    def __len__(self) -> int:
        return self.data_source_len

    def __iter__(self) -> Iterator[int]:
        yield from range(self.data_source_len)


class BatchSampler:
    """Group sample indices into batches (torch-equivalent semantics)."""

    def __init__(self, sampler, batch_size: int, drop_last: bool = False):
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.sampler, "set_epoch"):
            self.sampler.set_epoch(epoch)

    def __len__(self) -> int:
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return math.ceil(n / self.batch_size)

    def __iter__(self) -> Iterator[list[int]]:
        batch: list[int] = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch


class BatchSamplerShard:
    """Yield only the batches (or batch slices) for one shard out of ``num_shards``
    (reference ``BatchSamplerShard data_loader.py:110``).

    ``split_batches=False`` (reference ``_iter_with_no_split:218``): shard *i* gets
    batches ``i, i+n, i+2n, …``; with ``even_batches`` the tail wraps around to the
    beginning so all shards see the same number of equal-size batches
    (reference ``:236-262``).
    ``split_batches=True`` (reference ``_iter_with_split:196``): every shard slices
    ``1/n`` of each batch.
    """

    def __init__(
        self,
        batch_sampler,
        num_shards: int,
        shard_index: int,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        if split_batches and getattr(batch_sampler, "batch_size", None) is not None:
            if batch_sampler.batch_size % num_shards != 0:
                raise ValueError(
                    f"split_batches=True requires batch_size ({batch_sampler.batch_size}) "
                    f"divisible by num_shards ({num_shards})"
                )
        self.batch_sampler = batch_sampler
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.split_batches = split_batches
        self.even_batches = even_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        self.drop_last = getattr(batch_sampler, "drop_last", False)

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(epoch)

    def _tail_size(self) -> Optional[int]:
        """Size of the epoch's short tail batch (0 if none), or None when the
        underlying sampler is not introspectable."""
        sampler = getattr(self.batch_sampler, "sampler", None)
        if sampler is None or self.batch_size is None:
            return None
        try:
            n = len(sampler)
        except TypeError:
            return None
        return n % self.batch_size

    def __len__(self) -> int:
        """Exact yield count for every mode — unlike the reference, whose
        split-mode ``__len__`` is nominal (``len(batch_sampler)``) and over-
        counts shards whose slice of the tail batch is empty when
        ``even_batches=False`` (reference ``data_loader.py:175-196``)."""
        length = len(self.batch_sampler)
        if self.split_batches:
            if self.even_batches or self.drop_last:
                return length
            tail = self._tail_size()
            if tail is None or tail == 0 or self.batch_size is None:
                return length  # nominal fallback (un-introspectable sampler)
            # the tail batch only reaches shards whose slice starts before it ends
            size = self.batch_size // self.num_shards
            return length - 1 + int(tail > size * self.shard_index)
        if self.drop_last:
            return length // self.num_shards
        if self.even_batches:
            return math.ceil(length / self.num_shards)
        return length // self.num_shards + int(self.shard_index < length % self.num_shards)

    def __iter__(self) -> Iterator[list[int]]:
        if self.split_batches:
            yield from self._iter_with_split()
        else:
            yield from self._iter_with_no_split()

    def _iter_with_split(self) -> Iterator[list[int]]:
        first_batch = None
        size = None
        for batch in self.batch_sampler:
            if first_batch is None:
                first_batch = batch
                # per-shard slice of the NOMINAL batch size (reference
                # ``batch_length`` :198) — a short first batch (dataset smaller
                # than batch_size) must not shrink every shard's slice
                size = (
                    self.batch_size // self.num_shards
                    if self.batch_size
                    else len(batch) // self.num_shards
                )
            chunk = batch[self.shard_index * size : (self.shard_index + 1) * size]
            if len(chunk) < size:
                if not self.even_batches:
                    if chunk:
                        yield chunk
                    continue
                # wraparound pad from the first batch (reference :206-216);
                # loop because the first batch itself may be shorter than size
                while len(chunk) < size and first_batch:
                    chunk = (chunk + first_batch)[:size]
            if chunk:
                yield chunk

    def _iter_with_no_split(self) -> Iterator[list[int]]:
        initial_batches: list[list[int]] = []  # epoch-start batches for wraparound
        window: list[list[int]] = []
        full_size: Optional[int] = None
        for batch in self.batch_sampler:
            if full_size is None:
                full_size = len(batch)
            if len(initial_batches) < self.num_shards:
                initial_batches.append(batch)
            if len(batch) < full_size:
                # a short batch can only be the epoch tail
                if self.drop_last:
                    break
                if self.even_batches:
                    # top up with samples from the epoch start (reference :236-262)
                    pool = [i for b in initial_batches for i in b]
                    batch = (batch + pool * math.ceil(full_size / len(pool)))[:full_size]
            window.append(batch)
            if len(window) == self.num_shards:
                yield window[self.shard_index]
                window = []
        if not window or self.drop_last:
            return
        if not self.even_batches:
            if self.shard_index < len(window):
                yield window[self.shard_index]
            return
        # complete the final round by recycling epoch-start batches (reference :236-262);
        # a recycled batch can itself be the short tail (L < num_shards) — top it up
        pool = [i for b in initial_batches for i in b]
        i = 0
        while len(window) < self.num_shards:
            recycled = initial_batches[i % len(initial_batches)]
            if full_size and len(recycled) < full_size and pool:
                recycled = (recycled + pool * math.ceil(full_size / len(pool)))[:full_size]
            window.append(recycled[:full_size] if full_size else recycled)
            i += 1
        yield window[self.shard_index]


class IterableDatasetShard:
    """Round-robin shard an iterable dataset across shards (reference
    ``IterableDatasetShard data_loader.py:266``): collect ``batch_size*num_shards``
    items, give each shard its slice; tail handling per drop_last/even_batches."""

    def __init__(
        self,
        dataset: Iterable,
        batch_size: int,
        num_shards: int,
        shard_index: int,
        drop_last: bool = False,
        even_batches: bool = True,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.drop_last = drop_last
        self.even_batches = even_batches

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __iter__(self):
        real_batch_size = self.batch_size * self.num_shards
        first_window: Optional[list] = None
        window: list = []
        for item in self.dataset:
            window.append(item)
            if len(window) == real_batch_size:
                if first_window is None:
                    first_window = list(window)
                start = self.shard_index * self.batch_size
                yield from window[start : start + self.batch_size]
                window = []
        if not window or self.drop_last:
            return
        if first_window is None:
            first_window = list(window)
        if self.even_batches:
            while len(window) < real_batch_size:
                window += first_window[: real_batch_size - len(window)]
            start = self.shard_index * self.batch_size
            yield from window[start : start + self.batch_size]
        else:
            start = self.shard_index * self.batch_size
            yield from window[start : start + self.batch_size]


# ---------------------------------------------------------------------------
# Native minimal DataLoader (map-style datasets → numpy batches)


def default_collate(samples: list[Any]):
    """Stack a list of samples (dicts/tuples/arrays/scalars) into a batch.

    Large fixed-shape leaves go through the native C++ memcpy team
    (``native.parallel_collate`` — the torch-C++-collate equivalent); small or
    ragged ones use ``np.stack``.
    """
    first = samples[0]
    if isinstance(first, dict):
        return type(first)({k: default_collate([s[k] for s in samples]) for k in first})
    if isinstance(first, (list, tuple)) and not isinstance(first, str):
        return type(first)(default_collate([s[i] for s in samples]) for i in range(len(first)))
    arr0 = np.asarray(first)
    if arr0.nbytes * len(samples) >= (1 << 20):
        from .native import is_native_ready, parallel_collate

        # only if the library is already loaded — never compile on the hot path
        # (DataLoader.__init__ warms the build in the background)
        if is_native_ready():
            return parallel_collate(samples)
    return np.stack([np.asarray(s) for s in samples])


class DataLoader:
    """Minimal map-style loader: dataset[i] → sample; batches collated to numpy.

    The native replacement for ``torch.utils.data.DataLoader`` in the common case.
    ``dataset`` needs ``__len__`` and ``__getitem__``.
    """

    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = False,
        collate_fn: Optional[Callable] = None,
        batch_sampler=None,
        sampler=None,
    ):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate
        # start the (cached after first time) native-library build off-thread
        # so the first big collate finds it ready instead of compiling inline
        from .native import warm_build

        warm_build()
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", None)
        else:
            if sampler is None:
                sampler = (
                    SeedableRandomSampler(len(dataset), seed=seed)
                    if shuffle
                    else SequentialSampler(len(dataset))
                )
            self.batch_sampler = BatchSampler(sampler, batch_size, drop_last)
            self.batch_size = batch_size

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.batch_sampler)

    def __iter__(self):
        for batch_indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in batch_indices])


# ---------------------------------------------------------------------------
# Global-array assembly


class GlobalBatchAssembler:
    """Turn per-host batch blocks into one global sharded ``jax.Array`` per field.

    The moral twin of the reference's device placement + mesh-aware rank remap
    (``data_loader.py:577, 1109-1145``): dim 0 is sharded over ``(dp_replicate,
    dp_shard)``, the sequence dim over ``cp``/``sp`` when enabled, and data is
    replicated over ``tp``/``ep``. Assembly uses
    ``jax.make_array_from_single_device_arrays`` so it works identically for
    single-process (all devices addressable) and multi-host.
    """

    def __init__(self, mesh, parallelism_config: Optional[ParallelismConfig] = None, seq_dim: int = 1):
        self.mesh = mesh
        self.pc = parallelism_config
        self.seq_dim = seq_dim
        self._dp_size = mesh.shape.get("dp_replicate", 1) * mesh.shape.get("dp_shard", 1)
        self._seq_axis = None
        if parallelism_config is not None:
            if parallelism_config.cp_enabled:
                self._seq_axis = "cp"
            elif parallelism_config.sp_enabled:
                self._seq_axis = "sp"
        else:
            if mesh.shape.get("cp", 1) > 1:
                self._seq_axis = "cp"
            elif mesh.shape.get("sp", 1) > 1:
                self._seq_axis = "sp"
        # per-device coordinates in the mesh
        axis_names = mesh.axis_names
        self._coords = {}
        for coord, dev in zip(np.ndindex(*mesh.devices.shape), mesh.devices.flat):
            self._coords[dev] = dict(zip(axis_names, coord))
        # every mesh device addressable ⇒ the per-host block IS the global
        # batch and one committed sharded device_put replaces the per-device
        # shard loop (XLA splits + dispatches the transfer asynchronously)
        self._fully_addressable = set(mesh.devices.flat) == set(mesh.local_devices)

    @property
    def dp_size(self) -> int:
        return self._dp_size

    def _dp_row(self, coords: dict) -> int:
        return coords.get("dp_replicate", 0) * self.mesh.shape.get("dp_shard", 1) + coords.get(
            "dp_shard", 0
        )

    def local_dp_rows(self) -> list[int]:
        """Sorted distinct dp-rows owned by this process's addressable devices —
        exactly which slices of the global batch this host must read."""
        rows = sorted({self._dp_row(self._coords[d]) for d in self.mesh.local_devices})
        return rows

    def batch_spec(self, ndim: int):
        from jax.sharding import PartitionSpec

        dims: list = [("dp_replicate", "dp_shard")]
        if self._seq_axis is not None and ndim > self.seq_dim:
            while len(dims) < self.seq_dim:
                dims.append(None)
            dims.append(self._seq_axis)
        return PartitionSpec(*dims)

    def to_global(self, local_block):
        """``local_block``: pytree whose dim-0 contains rows for
        ``local_dp_rows()`` in sorted order (per-host batch block). Returns the
        pytree with each leaf a global sharded ``jax.Array``."""
        import jax
        from jax.sharding import NamedSharding

        rows = self.local_dp_rows()
        row_pos = {r: i for i, r in enumerate(rows)}
        seq_size = self.mesh.shape.get(self._seq_axis, 1) if self._seq_axis else 1

        def _build(x):
            import jax as _jax
            from jax.sharding import NamedSharding as _NS, PartitionSpec as _P

            x = np.asarray(x)
            if x.ndim == 0:
                # scalar leaves are replicated, not batch-sharded
                return _jax.device_put(x, _NS(self.mesh, _P()))
            local_rows = x.shape[0]
            if local_rows % len(rows) != 0:
                raise ValueError(
                    f"per-host batch ({local_rows}) must divide evenly across its "
                    f"{len(rows)} dp-rows"
                )
            per_row = local_rows // len(rows)
            global_shape = (per_row * self._dp_size,) + x.shape[1:]
            if self._seq_axis is not None and x.ndim > self.seq_dim and seq_size > 1:
                seq_len = x.shape[self.seq_dim]
                if seq_len % seq_size != 0:
                    raise ValueError(
                        f"sequence dim ({seq_len}) not divisible by {self._seq_axis} "
                        f"size {seq_size}"
                    )
            sharding = NamedSharding(self.mesh, self.batch_spec(x.ndim))
            if self._fully_addressable:
                # single committed sharded transfer: XLA splits the host array
                # across the mesh and dispatches every per-device copy in one
                # asynchronous call — no per-device Python loop on the hot path
                return jax.device_put(x, sharding)
            # multi-host: each process contributes only its addressable shards
            shards = []
            for dev in self.mesh.local_devices:  # pragma: no cover - multihost only
                coords = self._coords[dev]
                r = row_pos[self._dp_row(coords)]
                shard = x[r * per_row : (r + 1) * per_row]
                if self._seq_axis is not None and x.ndim > self.seq_dim and seq_size > 1:
                    s = coords[self._seq_axis]
                    chunk = x.shape[self.seq_dim] // seq_size
                    idx = [slice(None)] * x.ndim
                    idx[self.seq_dim] = slice(s * chunk, (s + 1) * chunk)
                    shard = shard[tuple(idx)]
                shards.append(jax.device_put(shard, dev))
            return jax.make_array_from_single_device_arrays(global_shape, sharding, shards)

        return recursively_apply(
            _build, local_block, test_type=lambda x: isinstance(x, (np.ndarray, np.generic))
            or (hasattr(x, "__array__") and not isinstance(x, (str, bytes)))
        )


def _to_numpy_batch(batch):
    """Convert torch tensors / lists in a batch to numpy (interop boundary)."""

    def _conv(x):
        if hasattr(x, "detach") and hasattr(x, "numpy"):  # torch tensor
            return x.detach().cpu().numpy()
        return x

    return recursively_apply(_conv, batch, test_type=lambda x: hasattr(x, "detach") or isinstance(x, np.ndarray))


# ---------------------------------------------------------------------------
# Prepared loaders


class DataLoaderShard:
    """Per-host sharded loader yielding global device arrays (reference
    ``DataLoaderShard data_loader.py:500``).

    Iteration protocol (reference ``__iter__:558-592``): fetch one batch ahead so
    ``GradientState.end_of_dataloader`` flips *on* the last batch (grad-accum must
    force a sync step there); sync host RNG across processes at epoch start.

    With ``prefetch_depth > 0`` (default 2) the fetch + host-processing +
    sharded transfer runs on a bounded background producer thread, so device
    compute for batch N overlaps the input pipeline for batches N+1..N+depth.
    Stateful snapshots, skip/resume, ``end_of_dataloader``/``remainder``
    flagging and exception propagation are preserved exactly: every queue item
    carries the snapshot taken right after ITS fetch, and flags are applied at
    yield time on the consumer thread. ``prefetch_depth=0`` is the synchronous
    path, byte-identical to the pre-prefetch behavior.
    """

    def __init__(
        self,
        base_dataloader,
        assembler: Optional[GlobalBatchAssembler] = None,
        rng_types: Optional[Sequence[str]] = None,
        synchronized_generator=None,
        skip_batches: int = 0,
        total_expected_batches: Optional[int] = None,
        total_dataset_length: Optional[int] = None,
        prefetch_depth: int = 2,
        _drop_last: bool = False,
        _non_blocking: bool = True,
    ):
        self.base_dataloader = base_dataloader
        self.assembler = assembler
        self.rng_types = rng_types
        self.synchronized_generator = synchronized_generator
        self.skip_batches = skip_batches
        self.gradient_state = GradientState()
        self.prefetch_depth = max(0, int(prefetch_depth))
        self.end_of_dataloader = False
        self.remainder = -1
        self.iteration = 0  # epoch counter
        self.total_dataset_length = total_dataset_length
        self._batches_seen = 0
        # stateful-inner protocol (reference DataLoaderAdapter:408-497 wrapping
        # torchdata StatefulDataLoader): when the WRAPPED loader carries its own
        # state machinery, preserve it — state_dict() serves a snapshot taken at
        # the correct yield boundary and load_state_dict() delegates inward.
        self._stateful_inner = hasattr(base_dataloader, "state_dict") and hasattr(
            base_dataloader, "load_state_dict"
        )
        self._inner_snapshot: Optional[dict] = None
        self._inner_finished = False

    @property
    def batch_size(self):
        return getattr(self.base_dataloader, "batch_size", None)

    @property
    def dataset(self):
        return getattr(self.base_dataloader, "dataset", None)

    def set_epoch(self, epoch: int) -> None:
        self.iteration = epoch
        if hasattr(self.base_dataloader, "set_epoch"):
            self.base_dataloader.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.base_dataloader) - self.skip_batches

    def _find_stateful_sampler(self):
        """Walk the sampler chain (possibly _InterleavedBatchSampler →
        BatchSamplerShard → BatchSampler → SeedableRandomSampler) to the innermost
        object exposing ``state_dict``."""
        seen = set()
        node = getattr(self.base_dataloader, "batch_sampler", None)
        while node is not None and id(node) not in seen:
            seen.add(id(node))
            if hasattr(node, "state_dict"):
                return node
            for attr in ("sampler", "batch_sampler"):
                child = getattr(node, attr, None)
                if child is not None:
                    node = child
                    break
            else:
                shards = getattr(node, "shards", None)
                node = shards[0] if shards else None
        return None

    def state_dict(self) -> dict:
        """Resume info (reference ``DataLoaderAdapter`` state_dict ``:463-497``).

        With a stateful inner loader (torchdata ``StatefulDataLoader`` or any
        loader exposing ``state_dict``/``load_state_dict``), ITS state dict is
        served — from a snapshot captured before the one-ahead prefetch pulled
        the next batch, so the recorded position matches what the user actually
        consumed (the reference corrects the same off-by-one arithmetically in
        ``adjust_state_dict_for_prefetch``); ``_iterator_finished`` is tagged
        on top, as in the reference."""
        if self._stateful_inner and self._snapshots_inner():
            snap = self._inner_snapshot
            if snap is None:  # not iterated yet: the inner's fresh state
                snap = self.base_dataloader.state_dict()
            state = dict(snap)
            state["_iterator_finished"] = self._inner_finished or self.end_of_dataloader
            return state
        state = {"batches_seen": self._batches_seen, "iteration": self.iteration}
        sampler = self._find_stateful_sampler()
        if sampler is not None:
            state["sampler"] = sampler.state_dict()
        return state

    def load_state_dict(self, state: dict) -> None:
        if self._stateful_inner and self._snapshots_inner():
            self._inner_finished = bool(state.get("_iterator_finished", False))
            # the loaded state replaces the wrapper's epoch bookkeeping too: a
            # mid-epoch state loaded after a completed epoch must not inherit
            # the stale end_of_dataloader and be re-tagged finished
            self.end_of_dataloader = False
            # hand the state through VERBATIM (reference :448-449):
            # _iterator_finished is torchdata's own field — a real
            # StatefulDataLoader uses it to start the next epoch fresh with
            # correctly-advanced sampler RNG. Popping it (or withholding the
            # state) would replay epoch-0 shuffle order after a boundary
            # resume. A custom stateful loader must honor the same contract.
            self.base_dataloader.load_state_dict(dict(state))
            # the loaded state IS the current position until iteration moves:
            # a state_dict() before the next batch must echo it, not a stale
            # pre-load snapshot
            snap = dict(state)
            snap.pop("_iterator_finished", None)  # re-tagged at serve time
            self._inner_snapshot = snap
            return
        self.skip_batches = state.get("batches_seen", 0)
        self.iteration = state.get("iteration", 0)
        sampler = self._find_stateful_sampler()
        if sampler is not None and "sampler" in state:
            sampler.load_state_dict(state["sampler"])

    def _sync_rng(self):
        if self.rng_types:
            from .utils.random import synchronize_rng_states

            synchronize_rng_states(self.rng_types, self.synchronized_generator)

    # -- iteration hooks (overridden by DataLoaderDispatcher) -----------------
    def _iter_base(self):
        """Which processes iterate the base loader (dispatcher: main only)."""
        return iter(self.base_dataloader)

    def _fetch_batch(self, base_iter):
        """Next per-host batch or ``_NO_BATCH`` (dispatcher: rank-0 broadcast)."""
        return next(base_iter, _NO_BATCH)

    def _global_batch_size(self, batch) -> int:
        """Global rows per yielded batch, for the gather_for_metrics remainder
        (dispatcher batches are global already)."""
        bs = find_batch_size(batch) or 0
        if self.assembler is None:
            return bs
        return bs * self.assembler.dp_size // len(self.assembler.local_dp_rows())

    def _snapshots_inner(self) -> bool:
        """Whether THIS process may touch the inner loader's state machinery
        (the dispatcher's non-main ranks never iterate the base loader and
        must not poke it — its source may be rank-0-only)."""
        return self._stateful_inner

    def _effective_prefetch_depth(self) -> int:
        """How far the producer may run ahead this epoch (0 = synchronous)."""
        return self.prefetch_depth

    def _final_remainder(self, batch) -> Optional[int]:
        """Real-row remainder of the epoch's final global batch, or None when
        it cannot (or need not) be derived."""
        if self.total_dataset_length is not None:
            global_bs = self._global_batch_size(batch)
            if global_bs:
                return self.total_dataset_length % global_bs
            return None
        # unknown length (iterable source): the dispatcher header carried the
        # final batch's REAL row count
        real = getattr(self, "_last_data_real_bs", None)
        full = getattr(self, "_last_data_global_bs", None)
        if real is not None and full and real < full:
            return real
        return None

    # -- telemetry: data-wait accounting (step_profiler drains it per step) ----
    # ``critical=True`` (synchronous path) charges the duration to the step's
    # ``data_wait_s``; the async producer emits the same phases off the
    # critical path and only the consumer's queue-pop stall is charged.
    def _timed_fetch(self, base_iter, critical: bool = True, totals: Optional[dict] = None):
        if not _tel.is_enabled():
            # flight-phase annotation survives the telemetry kill switch: a
            # hang inside the dataset shows as "blocked in data_fetch" in a
            # watchdog/crash dump even when no JSONL stream is being written
            with _flight.phase("data_fetch"):
                return self._fetch_batch(base_iter)
        t0 = time.monotonic()
        with _flight.phase("data_fetch"):
            batch = self._fetch_batch(base_iter)
        dt = time.monotonic() - t0
        if critical:
            record_data_wait(dt)
        if totals is not None:
            totals["fetch_s"] += dt
        _tel.emit("data_wait", dur_s=round(dt, 6), phase="fetch", critical=critical)
        return batch

    def _timed_process(self, batch, critical: bool = True, totals: Optional[dict] = None):
        if not _tel.is_enabled():
            with _flight.phase("data_transfer"):
                return self._process(batch)
        t0 = time.monotonic()
        with _flight.phase("data_transfer"):
            out = self._process(batch)
        dt = time.monotonic() - t0
        if critical:
            record_data_wait(dt)
        if totals is not None:
            totals["transfer_s"] += dt
        _tel.emit("data_wait", dur_s=round(dt, 6), phase="transfer", critical=critical)
        return out

    def __iter__(self):
        self._sync_rng()
        self.gradient_state._add_dataloader(self)
        self.end_of_dataloader = False
        self.remainder = -1
        self._inner_finished = False  # a fresh epoch is not finished
        try:
            if self._effective_prefetch_depth() > 0:
                yield from self._iter_async()
            else:
                yield from self._iter_sync()
        finally:
            self.gradient_state._remove_dataloader(self)
            self.iteration += 1
            # resume-skip applies to the first (resumed) epoch only (reference
            # skip_first_batches returns a one-shot skipping loader, :1375)
            self.skip_batches = 0
            if self.end_of_dataloader:
                # a checkpoint taken after a COMPLETED epoch must resume at the
                # next epoch's first batch, not skip a full epoch's worth
                self._batches_seen = 0

    def _iter_sync(self):
        base_iter = self._iter_base()
        snapshots = self._snapshots_inner()
        # prefetch-one-ahead so the last batch is flagged (reference :558-592)
        current = self._timed_fetch(base_iter)
        n = 0
        while current is not _NO_BATCH:
            if snapshots:
                # snapshot NOW — after `current` was pulled, before the
                # prefetch pulls `nxt` — so a resume from this snapshot
                # replays from the first un-consumed batch. Per-batch
                # snapshotting matches the reference adapter
                # (_update_state_dict per yield, data_loader.py:463-497).
                self._inner_snapshot = self.base_dataloader.state_dict()
            nxt = self._timed_fetch(base_iter)
            if n >= self.skip_batches:
                if nxt is _NO_BATCH:
                    self.end_of_dataloader = True
                    rem = self._final_remainder(current)
                    if rem is not None:
                        self.remainder = rem
                self._batches_seen = n + 1
                yield self._timed_process(current)
            current = nxt
            n += 1

    def _iter_async(self):
        """Bounded producer/consumer pipeline: the producer fetches, snapshots,
        host-processes and issues the sharded device transfer for up to
        ``prefetch_depth`` batches ahead; the consumer pops finished batches
        and applies per-batch bookkeeping (snapshot served, end-of-epoch flags)
        exactly where the synchronous path would."""
        depth = self._effective_prefetch_depth()
        q: _queue.Queue = _queue.Queue(maxsize=depth)
        stop = threading.Event()
        skip = self.skip_batches
        snapshots = self._snapshots_inner()
        tel_on = _tel.is_enabled()
        totals = {"fetch_s": 0.0, "transfer_s": 0.0}

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    if tel_on:
                        _tel.gauge("prefetch_queue", q.qsize(), capacity=depth)
                    return True
                except _queue.Full:
                    # blocked on a full queue means the producer is *ahead* of
                    # the consumer, not stalled — keep the heartbeat fresh so a
                    # slow train step can't read as a producer stall
                    _watchdog.beat(wd_source, queue_full=True)
                    continue
            return False

        def _snap():
            return self.base_dataloader.state_dict() if snapshots else None

        # watchdog registration: the producer beats once per produced batch,
        # under its own name — so a hang report distinguishes "the input
        # pipeline stopped producing" from "a rank is blocked in a collective"
        wd_source = f"prefetch_producer@{id(self):x}"
        _watchdog.register(wd_source, depth=depth)

        def _produce():
            try:
                base_iter = self._iter_base()
                current = self._timed_fetch(base_iter, critical=False, totals=totals)
                snap = _snap() if current is not _NO_BATCH else None
                n = 0
                while current is not _NO_BATCH and not stop.is_set():
                    _watchdog.beat(wd_source, batch=n)
                    _chaos_inject("prefetch")
                    nxt = self._timed_fetch(base_iter, critical=False, totals=totals)
                    nxt_snap = _snap() if nxt is not _NO_BATCH else None
                    if n >= skip:
                        is_last = nxt is _NO_BATCH
                        rem = self._final_remainder(current) if is_last else None
                        processed = self._timed_process(current, critical=False, totals=totals)
                        if not _put(("batch", (n, processed, snap, is_last, rem))):
                            return
                    current, snap = nxt, nxt_snap
                    n += 1
                if not stop.is_set():
                    _put(("end", None))
            except BaseException as exc:  # propagate into the consumer
                _put(("exc", exc))
            finally:
                # the consumer may spend several step-times draining the queue
                # after the final put; unregister from the producer's own exit
                # so that healthy drain window cannot read as a producer stall
                _watchdog.unregister(wd_source)

        thread = threading.Thread(
            target=_produce, name="accelerate-tpu-prefetch", daemon=True
        )
        thread.start()
        stall_s = 0.0
        yielded = 0
        try:
            while True:
                t0 = time.monotonic()
                with _flight.phase("prefetch_wait"):
                    kind, payload = _pop_next(q, thread)
                if _tel.is_enabled():
                    dt = time.monotonic() - t0
                    stall_s += dt
                    record_data_wait(dt)
                    _tel.emit(
                        "data_wait", dur_s=round(dt, 6), phase="stall",
                        critical=True, queued=q.qsize(),
                    )
                if kind == "end":
                    return
                if kind == "exc":
                    raise payload
                n, processed, snap, is_last, rem = payload
                if snapshots and snap is not None:
                    self._inner_snapshot = snap
                if is_last:
                    self.end_of_dataloader = True
                    if rem is not None:
                        self.remainder = rem
                self._batches_seen = n + 1
                yielded += 1
                yield processed
        finally:
            stop.set()
            _watchdog.unregister(wd_source)  # clean shutdown is not a stall
            while True:  # unblock a producer waiting on a full queue
                try:
                    q.get_nowait()
                except _queue.Empty:
                    break
            thread.join(timeout=5.0)
            if _tel.is_enabled():
                busy = totals["fetch_s"] + totals["transfer_s"]
                summary = dict(
                    batches=yielded,
                    depth=depth,
                    fetch_s=round(totals["fetch_s"], 6),
                    transfer_s=round(totals["transfer_s"], 6),
                    stall_s=round(stall_s, 6),
                )
                if busy > 0:
                    summary["overlap_ratio"] = round(
                        max(0.0, min(1.0, 1.0 - stall_s / busy)), 6
                    )
                _tel.emit("prefetch_summary", **summary)

    def _process(self, batch):
        batch = _to_numpy_batch(batch)
        if self.assembler is not None:
            return self.assembler.to_global(batch)
        return send_to_device(batch)


class DataLoaderDispatcher(DataLoaderShard):
    """ONLY process 0 reads the base loader; the rest receive batches over the
    wire (reference ``DataLoaderDispatcher data_loader.py:704`` —
    ``_fetch_batches:786`` rank-0 ``next()`` + tensor ``broadcast:876``).

    This is the documented contract for sources only rank 0 can read (a local
    file, a DB cursor): non-main processes never touch ``base_dataloader`` —
    neither its dataset nor its sampler — and readable sources pay 1× IO
    instead of N×. Under a single process this degenerates to
    :class:`DataLoaderShard`.

    Wire protocol (the tensor fast-path — no per-batch pickling): the FIRST
    batch of each distinct structure goes over the object channel and every
    rank derives a *signature* (treedef + shapes + dtypes + batch size) from
    it; subsequent batches ship as a 3-int header broadcast plus ONE raw-bytes
    array broadcast of known size. An uneven final batch is padded up to the
    signature's batch size by repeating final rows (reference
    ``pad_input_tensors utils/operations.py:687``) so broadcast shapes stay
    static and the global batch still divides across dp rows; the header
    carries the REAL size so ``remainder``/``gather_for_metrics`` drop the
    padded duplicates.
    """

    _H_END, _H_DATA, _H_NEW_SIG, _H_OBJECT = 0, 1, 2, 3

    def _iter_base(self):
        # non-main processes NEVER iterate the base loader
        state = PartialState()
        self._fetched_rows = 0  # per-epoch: finality proof for ragged padding
        return iter(self.base_dataloader) if state.is_main_process else iter(())

    def _snapshots_inner(self) -> bool:
        # the contract above extends to state machinery: a non-main rank must
        # not call state_dict() on a base loader it never iterates (stale
        # position AND a possibly rank-0-only source); checkpoints are written
        # by the main process, which holds the real position
        return self._stateful_inner and PartialState().is_main_process

    def _effective_prefetch_depth(self) -> int:
        depth = super()._effective_prefetch_depth()
        if depth and PartialState().num_processes > 1:  # pragma: no cover - multihost only
            # the dispatcher's per-batch rank-0 broadcast is a HOST collective:
            # issuing it from a producer thread while user code (gather_for_
            # metrics, broadcasts) runs collectives on the main thread would
            # interleave differently per rank and deadlock. Broadcasts stay on
            # the consumer thread, in iteration order — synchronous.
            if not getattr(self, "_prefetch_downgrade_emitted", False):
                self._prefetch_downgrade_emitted = True
                _tel.emit(
                    "prefetch_mode", mode="sync", requested_depth=depth,
                    reason="dispatcher_multiprocess_collective_ordering",
                )
            return 0
        return depth

    # -- signature registry (identical on every rank by construction) ---------
    def _ensure_sig_state(self):
        if not hasattr(self, "_sigs"):
            self._sigs = []  # sig_id -> dict(treedef, leaves, bs, nbytes)
            self._sig_keys = {}  # rank-0 only: structure key -> sig_id
            self._last_data_real_bs = None
            self._last_data_global_bs = None

    @staticmethod
    def _leaf_meta(leaf, bs):
        batched = leaf.ndim > 0 and leaf.shape[:1] == (bs,)
        return (leaf.shape[1:] if batched else leaf.shape, leaf.dtype.str, batched)

    def _register_sig(self, batch):
        """Derive + store the signature from a full batch; every rank does this
        on the same (object-channel) batch, so sig ids agree everywhere."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(batch)
        leaves = [np.asarray(x) for x in leaves]
        bs = find_batch_size(batch) or 0
        metas = [self._leaf_meta(x, bs) for x in leaves]
        shapes = [((bs,) + m[0] if m[2] else m[0]) for m in metas]
        dtypes = [np.dtype(m[1]) for m in metas]
        sizes = [int(np.prod(s, dtype=np.int64)) * d.itemsize for s, d in zip(shapes, dtypes)]
        offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
        sig = {
            "treedef": treedef,
            "shapes": shapes,
            "dtypes": dtypes,
            "offsets": offsets,
            "nbytes": int(offsets[-1]),
            "bs": bs,
        }
        self._sigs.append(sig)
        sig_id = len(self._sigs) - 1
        self._sig_keys[(treedef, tuple(metas))] = sig_id
        return sig_id

    @staticmethod
    def _pad_rows(leaf, real_bs: int, target_bs: int):
        if leaf.ndim == 0 or leaf.shape[0] != real_bs or real_bs == target_bs:
            return leaf
        reps = np.repeat(leaf[-1:], target_bs - real_bs, axis=0)
        return np.concatenate([leaf, reps], axis=0)

    def _fetch_batch(self, base_iter):
        """Main process ``next()``s the base loader; every process returns the
        same global batch, or ``_NO_BATCH`` when exhausted."""
        state = PartialState()
        if state.num_processes == 1:
            batch = next(base_iter, _NO_BATCH)
            return batch if batch is _NO_BATCH else _to_numpy_batch(batch)
        # pragma: no cover start - multihost only (exercised by the real
        # multi-process suite, tests/test_multiprocess.py)
        import jax

        from .utils.jax_compat import broadcast_one_to_all
        from .utils.operations import broadcast_object_list

        self._ensure_sig_state()
        is_main = state.is_main_process

        def bcast_header(vals):
            arr = np.asarray(vals, np.int64)
            return broadcast_one_to_all(arr, is_source=is_main)

        if is_main:
            batch = next(base_iter, _NO_BATCH)
            if batch is _NO_BATCH:
                bcast_header([self._H_END, 0, 0])
                return _NO_BATCH
            batch = _to_numpy_batch(batch)
            leaves, treedef = jax.tree_util.tree_flatten(batch)
            leaves = [np.asarray(x) for x in leaves]
            real_bs = find_batch_size(batch) or 0
            if any(x.dtype.hasobject for x in leaves):
                # object-dtype leaves (strings, ragged lists) have no raw-bytes
                # form: keep the whole structure on the object channel
                bcast_header([self._H_OBJECT, 0, real_bs])
                broadcast_object_list([batch])
                self._last_data_real_bs = real_bs
                self._last_data_global_bs = real_bs
                return batch
            key = (treedef, tuple(self._leaf_meta(x, real_bs) for x in leaves))
            sig_id = self._sig_keys.get(key)
            rows_before = getattr(self, "_fetched_rows", 0)
            self._fetched_rows = rows_before + real_bs
            is_final = (
                self.total_dataset_length is not None
                and rows_before + real_bs >= self.total_dataset_length
            )
            if sig_id is not None and real_bs < self._sigs[sig_id]["bs"] and not is_final:
                # an undersized batch we cannot PROVE is the epoch's last (a
                # custom sampler's mid-epoch size change, or unknown length):
                # padding it would silently duplicate rows that no trimming
                # step ever removes — ship the real rows on the object channel
                bcast_header([self._H_OBJECT, 0, real_bs])
                broadcast_object_list([batch])
                self._last_data_real_bs = real_bs
                self._last_data_global_bs = real_bs
                return batch
            if sig_id is None or real_bs > self._sigs[sig_id]["bs"]:
                # first sighting of this structure: object channel, then every
                # rank derives the signature from the same batch
                bcast_header([self._H_NEW_SIG, 0, real_bs])
                broadcast_object_list([batch])
                self._register_sig(batch)
                self._last_data_real_bs = real_bs
                self._last_data_global_bs = real_bs
                return batch
            sig = self._sigs[sig_id]
            if real_bs < sig["bs"]:  # PROVABLY-final ragged batch: pad rows
                leaves = [self._pad_rows(x, real_bs, sig["bs"]) for x in leaves]
            bcast_header([self._H_DATA, sig_id, real_bs])
            buf = np.frombuffer(
                b"".join(np.ascontiguousarray(x).tobytes() for x in leaves), np.uint8
            )
            broadcast_one_to_all(buf, is_source=True)
            self._last_data_real_bs = real_bs
            self._last_data_global_bs = sig["bs"]
            return jax.tree_util.tree_unflatten(treedef, leaves)

        kind, sig_id, real_bs = (int(v) for v in bcast_header([0, 0, 0]))
        if kind == self._H_END:
            return _NO_BATCH
        if kind in (self._H_NEW_SIG, self._H_OBJECT):
            batch = broadcast_object_list([None])[0]
            if kind == self._H_NEW_SIG:
                self._register_sig(batch)
            self._last_data_real_bs = real_bs
            self._last_data_global_bs = find_batch_size(batch) or 0
            return batch
        sig = self._sigs[sig_id]
        buf = broadcast_one_to_all(np.zeros(sig["nbytes"], np.uint8), is_source=False)
        # ONE host copy of the payload; per-leaf views via frombuffer offsets
        payload = np.asarray(buf).tobytes()
        leaves = [
            np.frombuffer(
                payload,
                dtype=sig["dtypes"][i],
                count=int(np.prod(sig["shapes"][i], dtype=np.int64)),
                offset=int(sig["offsets"][i]),
            ).reshape(sig["shapes"][i])
            for i in range(len(sig["shapes"]))
        ]
        self._last_data_real_bs = real_bs
        self._last_data_global_bs = sig["bs"]
        return jax.tree_util.tree_unflatten(sig["treedef"], leaves)
        # pragma: no cover end

    def _global_batch_size(self, batch) -> int:
        return find_batch_size(batch) or 0  # dispatch batches are global already

    def _process(self, batch):
        state = PartialState()
        if state.num_processes > 1 and self.assembler is not None:  # pragma: no cover - multihost only
            # keep only this host's dp-rows of the global batch
            rows = self.assembler.local_dp_rows()
            per_row = (find_batch_size(batch) or 0) // self.assembler.dp_size

            def _slice(x):
                x = np.asarray(x)
                return np.concatenate([x[r * per_row : (r + 1) * per_row] for r in rows], axis=0)

            batch = recursively_apply(_slice, batch)
        if self.assembler is not None:
            return self.assembler.to_global(batch)
        return send_to_device(batch)


# ---------------------------------------------------------------------------
# Skip/resume helpers


class SkipBatchSampler:
    """Skip the first ``skip_batches`` batches (reference ``:1312``)."""

    def __init__(self, batch_sampler, skip_batches: int = 0):
        self.batch_sampler = batch_sampler
        self.skip_batches = skip_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.batch_sampler) - self.skip_batches

    def __iter__(self):
        for i, batch in enumerate(self.batch_sampler):
            if i >= self.skip_batches:
                yield batch


def skip_first_batches(dataloader, num_batches: int = 0):
    """Return a loader resuming ``num_batches`` in (reference ``:1375``)."""
    if isinstance(dataloader, DataLoaderShard):
        dataloader.skip_batches = num_batches
        if isinstance(dataloader, SkipDataLoader):
            # flag the one-shot resume so __iter__ honors it over (max'd
            # with) the loader's persistent every-epoch skip
            dataloader._resume_pending = True
        return dataloader
    return DataLoaderShard(dataloader, skip_batches=num_batches)


class SkipDataLoader(DataLoaderShard):
    """reference ``SkipDataLoader:1335``: skips its first ``skip_batches``
    batches on EVERY iteration (unlike :func:`skip_first_batches`' prepared
    loaders, whose skip is one-shot for resume). A checkpoint resume
    (``load_state_dict``) takes precedence for its one epoch, then the
    persistent skip resumes."""

    def __init__(self, dataloader, skip_batches: int = 0, **kwargs):
        super().__init__(dataloader, skip_batches=skip_batches, **kwargs)
        self._persistent_skip = skip_batches
        self._resume_pending = False

    def load_state_dict(self, state: dict) -> None:
        super().load_state_dict(state)
        self._resume_pending = True

    def _effective_skip(self) -> int:
        if self._resume_pending:
            # an epoch-boundary checkpoint records batches_seen=0; the
            # persistent skip still applies (it applies EVERY epoch)
            return max(self.skip_batches, self._persistent_skip)
        return self._persistent_skip

    def __len__(self) -> int:
        # the base finally-block zeroes skip_batches after an epoch; length
        # must keep reporting the EVERY-epoch skip
        return len(self.base_dataloader) - self._effective_skip()

    def __iter__(self):
        self.skip_batches = self._effective_skip()
        self._resume_pending = False
        yield from super().__iter__()


def _stateful_dataloader_cls():
    """torchdata's ``StatefulDataLoader`` when importable at >=0.8.0, else
    None — the single probe both the rebuild and its error reporting use."""
    try:
        import torchdata
        from torchdata.stateful_dataloader import StatefulDataLoader
    except ImportError:
        return None
    from .utils.versions import compare_versions

    try:
        if not compare_versions(getattr(torchdata, "__version__", "0"), ">=", "0.8.0"):
            return None
    except Exception:
        return None
    return StatefulDataLoader


def stateful_dataloader_available() -> bool:
    return _stateful_dataloader_cls() is not None


def as_stateful_dataloader(dataloader):
    """Rebuild a plain ``torch.utils.data.DataLoader`` as a torchdata
    ``StatefulDataLoader`` over the same dataset/sampler/collate (reference
    ``DataLoaderAdapter:414-431`` does this whenever
    ``use_stateful_dataloader=True`` and torchdata is installed).

    Returns ``None`` when torchdata>=0.8.0 is not importable or the input is
    not a torch DataLoader — the caller decides whether that is an
    ImportError (it is, for ``use_stateful_dataloader=True``).
    """
    StatefulDataLoader = _stateful_dataloader_cls()
    if StatefulDataLoader is None:
        return None
    try:
        import torch.utils.data as tud
    except ImportError:
        return None
    if not isinstance(dataloader, tud.DataLoader):
        return None
    if type(dataloader) is not tud.DataLoader:
        import warnings

        warnings.warn(
            f"rebuilding {type(dataloader).__name__} as a StatefulDataLoader "
            "keeps its dataset/sampler/collate but DROPS any overridden "
            "loader behavior (custom __iter__ etc.)",
            stacklevel=3,
        )
    common = dict(
        num_workers=dataloader.num_workers,
        collate_fn=dataloader.collate_fn,
        pin_memory=dataloader.pin_memory,
        timeout=dataloader.timeout,
        worker_init_fn=dataloader.worker_init_fn,
        generator=getattr(dataloader, "generator", None),
        persistent_workers=getattr(dataloader, "persistent_workers", False),
        multiprocessing_context=getattr(dataloader, "multiprocessing_context", None),
    )
    if dataloader.num_workers > 0 and getattr(dataloader, "prefetch_factor", None) is not None:
        common["prefetch_factor"] = dataloader.prefetch_factor
    pin_device = getattr(dataloader, "pin_memory_device", "")
    if pin_device:
        common["pin_memory_device"] = pin_device
    if dataloader.batch_size is None and dataloader.batch_sampler is not None:
        # user-supplied batch_sampler (torch zeroes batch_size for these)
        return StatefulDataLoader(dataloader.dataset, batch_sampler=dataloader.batch_sampler, **common)
    if isinstance(dataloader.dataset, tud.IterableDataset):
        # iterable sources forbid any sampler argument
        return StatefulDataLoader(
            dataloader.dataset,
            batch_size=dataloader.batch_size,
            drop_last=dataloader.drop_last if dataloader.batch_size is not None else False,
            **common,
        )
    if dataloader.batch_size is None:
        # automatic batching disabled (batch_size=None, no batch_sampler):
        # keep it disabled — drop_last is mutually exclusive with this mode
        return StatefulDataLoader(
            dataloader.dataset, batch_size=None, sampler=dataloader.sampler, **common
        )
    return StatefulDataLoader(
        dataloader.dataset,
        batch_size=dataloader.batch_size,
        sampler=dataloader.sampler,
        drop_last=dataloader.drop_last,
        **common,
    )


# reference base-class spellings (data_loader.py:365/:408): user code does
# `isinstance(dl, DataLoaderStateMixin)` / subclass checks — here every
# prepared loader is a DataLoaderShard carrying the same surface
# (end_of_dataloader/remainder/state_dict), so both names resolve to it
DataLoaderStateMixin = DataLoaderShard
DataLoaderAdapter = DataLoaderShard


def get_sampler(dataloader):
    """reference ``get_sampler``: the innermost stateful sampler behind a
    prepared or native loader, for seed/state introspection."""
    if isinstance(dataloader, DataLoaderShard):
        inner = dataloader._find_stateful_sampler()
        if inner is not None:
            return inner
    base = getattr(dataloader, "base_dataloader", dataloader)
    sampler = getattr(base, "batch_sampler", None)
    if sampler is None:
        sampler = getattr(base, "sampler", None)
    # walk to the innermost sampler (BatchSampler -> RandomSampler etc.)
    seen = set()
    while sampler is not None and id(sampler) not in seen:
        seen.add(id(sampler))
        child = getattr(sampler, "sampler", None)
        if child is None:
            break
        sampler = child
    return sampler


# ---------------------------------------------------------------------------
# prepare entry point


def prepare_data_loader(
    dataloader,
    state=None,
    mesh=None,
    parallelism_config: Optional[ParallelismConfig] = None,
    device_placement: bool = True,
    split_batches: bool = False,
    even_batches: bool = True,
    dispatch_batches: Optional[bool] = None,
    rng_types: Optional[Sequence[str]] = None,
    data_seed: Optional[int] = None,
    use_seedable_sampler: bool = True,
    seq_dim: int = 1,
    prefetch_depth: int = 2,
) -> DataLoaderShard:
    """Wrap a loader for the current mesh (reference ``prepare_data_loader:996``).

    Accepts our native :class:`DataLoader`, a ``torch.utils.data.DataLoader``
    (rebuilt around its dataset with a sharded batch sampler when map-style), or any
    iterable of batches (wrapped as-is; assumed already per-host sharded).
    """
    from .state import AcceleratorState

    if state is None:
        state = AcceleratorState()
    if mesh is None:
        mesh = state.mesh
    if parallelism_config is None:
        parallelism_config = state.parallelism_config

    assembler = GlobalBatchAssembler(mesh, parallelism_config, seq_dim=seq_dim) if device_placement else None
    dp_size = assembler.dp_size if assembler else 1
    local_rows = assembler.local_dp_rows() if assembler else [0]

    total_len = None
    cls = DataLoaderDispatcher if dispatch_batches else DataLoaderShard

    # native loader: reshard its batch sampler so this host reads only its dp-rows
    if isinstance(dataloader, DataLoader):
        dataset = dataloader.dataset
        total_len = len(dataset) if hasattr(dataset, "__len__") else None
        inner = dataloader.batch_sampler
        if dp_size > 1 and not dispatch_batches:
            # one BatchSamplerShard per local dp-row; interleave their batches so
            # the per-host block has rows for local_dp_rows in sorted order
            shards = [
                BatchSamplerShard(inner, dp_size, row, split_batches=split_batches, even_batches=even_batches)
                for row in local_rows
            ]
            merged = _InterleavedBatchSampler(shards)
            new_dl = DataLoader(dataset, batch_sampler=merged, collate_fn=dataloader.collate_fn)
            _tel.emit(
                "dataloader_reshard",
                decision="native_sampler_sharded",
                dp_size=dp_size,
                local_rows=len(local_rows),
                split_batches=split_batches,
                prefetch_depth=prefetch_depth,
            )
        else:
            new_dl = dataloader
            _tel.emit(
                "dataloader_reshard",
                decision="dispatcher" if dispatch_batches else "no_reshard_needed",
                dp_size=dp_size,
                dispatch_batches=bool(dispatch_batches),
                prefetch_depth=prefetch_depth,
            )
        return cls(
            new_dl,
            assembler=assembler,
            rng_types=rng_types,
            total_dataset_length=total_len,
            prefetch_depth=prefetch_depth,
        )

    # torch DataLoader interop: rebuild a native loader over the same dataset when
    # map-style; otherwise iterate as-is
    try:
        import torch.utils.data as tud

        if isinstance(dataloader, tud.DataLoader):
            if hasattr(dataloader, "state_dict") and hasattr(dataloader, "load_state_dict"):
                # torchdata StatefulDataLoader (or subclass carrying its own
                # state machinery): PRESERVE that machinery instead of
                # rebuilding — the wrapper serves prefetch-corrected snapshots
                # of the inner state (reference DataLoaderAdapter:408-497).
                # Resharding a stateful loader would orphan its state. Under
                # data parallelism it is ROUTED TO THE DISPATCHER (rank 0
                # reads, the rest receive): iterating it on every rank would
                # silently duplicate data across dp replicas.
                if dp_size > 1 and not dispatch_batches:
                    if dispatch_batches is False:
                        raise ValueError(
                            "a stateful torch DataLoader cannot be resharded "
                            "(its state machinery would be orphaned) and "
                            "iterating it on every rank would silently "
                            "duplicate data across dp replicas. Drop "
                            "dispatch_batches=False (the dispatcher route is "
                            "the default for stateful loaders) or use the "
                            "native DataLoader."
                        )
                    import warnings

                    warnings.warn(
                        "stateful torch DataLoader under data parallelism: "
                        "routing through DataLoaderDispatcher (process 0 reads "
                        "and broadcasts) so ranks do not duplicate data; each "
                        "yielded batch is treated as the GLOBAL batch",
                        stacklevel=2,
                    )
                    cls = DataLoaderDispatcher
                    _tel.emit(
                        "dataloader_reshard",
                        decision="stateful_to_dispatcher",
                        dp_size=dp_size,
                        dispatch_batches=True,
                    )
                else:
                    _tel.emit(
                        "dataloader_reshard",
                        # dispatch_batches=True means rank 0 reads and
                        # broadcasts; only without it is the loader truly
                        # iterated per-host
                        decision="stateful_dispatcher" if dispatch_batches else "stateful_preserved",
                        dp_size=dp_size,
                        dispatch_batches=bool(dispatch_batches),
                    )
                return cls(
                    dataloader, assembler=assembler, rng_types=rng_types,
                    prefetch_depth=prefetch_depth,
                )
            dataset = dataloader.dataset
            custom_batch_sampler = (
                dataloader.batch_size is None  # torch sets None iff batch_sampler given
            )
            sampler = getattr(dataloader, "sampler", None)
            custom_sampler = sampler is not None and not isinstance(
                sampler, (tud.RandomSampler, tud.SequentialSampler)
            )
            if custom_batch_sampler or custom_sampler or not (
                hasattr(dataset, "__len__") and hasattr(dataset, "__getitem__")
            ):
                # custom sampling we cannot faithfully reshard: iterate the torch
                # loader as-is (each batch = one per-dp-row block is NOT implied;
                # fall back to dispatch-style semantics) and warn loudly
                import warnings

                warnings.warn(
                    "torch DataLoader with a custom sampler/batch_sampler or "
                    "iterable dataset cannot be resharded; iterating it as-is. "
                    "Each yielded batch is treated as the per-host block.",
                    stacklevel=2,
                )
                _tel.emit(
                    "dataloader_reshard", decision="torch_as_is", dp_size=dp_size,
                    dispatch_batches=bool(dispatch_batches),
                )
                return cls(
                    dataloader, assembler=assembler, rng_types=rng_types,
                    prefetch_depth=prefetch_depth,
                )
            shuffle = isinstance(sampler, tud.RandomSampler)
            native = DataLoader(
                dataset,
                batch_size=dataloader.batch_size,
                shuffle=shuffle,
                seed=data_seed or 0,
                drop_last=getattr(dataloader, "drop_last", False),
                collate_fn=_torch_collate_to_numpy(dataloader.collate_fn),
            )
            return prepare_data_loader(
                native,
                state=state,
                mesh=mesh,
                parallelism_config=parallelism_config,
                device_placement=device_placement,
                split_batches=split_batches,
                even_batches=even_batches,
                dispatch_batches=dispatch_batches,
                rng_types=rng_types,
                seq_dim=seq_dim,
                prefetch_depth=prefetch_depth,
            )
    except ImportError:
        pass

    # generic iterable of batches
    return cls(
        dataloader, assembler=assembler, rng_types=rng_types,
        total_dataset_length=total_len, prefetch_depth=prefetch_depth,
    )


class _InterleavedBatchSampler:
    """Round-robin over several shard samplers so a host covering multiple dp-rows
    reads one batch per row per step, concatenated in row order."""

    def __init__(self, shards: list):
        self.shards = shards
        self.batch_size = getattr(shards[0], "batch_size", None)

    def set_epoch(self, epoch: int) -> None:
        for s in self.shards:
            s.set_epoch(epoch)

    def __len__(self) -> int:
        return min(len(s) for s in self.shards)

    def __iter__(self):
        iters = [iter(s) for s in self.shards]
        while True:
            batches = []
            for it in iters:
                try:
                    batches.append(next(it))
                except StopIteration:
                    return
            yield [i for b in batches for i in b]


def _torch_collate_to_numpy(collate_fn):
    if collate_fn is None:
        return None

    def _fn(samples):
        return _to_numpy_batch(collate_fn(samples))

    return _fn
