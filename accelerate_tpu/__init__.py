"""accelerate-tpu: TPU-native training orchestration (JAX/XLA/pjit/pallas-first).

A brand-new framework with the capabilities of HuggingFace Accelerate
(reference: yao-matrix/accelerate), designed for TPU from the start: parallelism is
expressed as shardings over a named device mesh, collectives are compiler-inserted
or explicit ``jax.lax`` primitives, and the hot path is one jitted train step.
"""

__version__ = "0.1.0"

from .parallelism_config import ParallelismConfig
from .state import AcceleratorState, GradientState, PartialState
from .utils import (
    AutocastKwargs,
    CheckpointConfig,
    DDPCommunicationHookType,
    DataLoaderConfiguration,
    DeepSpeedPlugin,
    DistributedDataParallelKwargs,
    DistributedType,
    FullyShardedDataParallelPlugin,
    GradScalerKwargs,
    GradientAccumulationPlugin,
    InitProcessGroupKwargs,
    MixedPrecisionPolicy,
    PrecisionType,
    ProfileKwargs,
    ProjectConfiguration,
)

__all__ = [
    "Accelerator",
    "AutocastKwargs",
    "CheckpointConfig",
    "CheckpointCorruptError",
    "CheckpointTopologyError",
    "DDPCommunicationHookType",
    "DeepSpeedPlugin",
    "DispatchedParams",
    "DistributedDataParallelKwargs",
    "FullyShardedDataParallelPlugin",
    "GradScalerKwargs",
    "InitProcessGroupKwargs",
    "ProfileKwargs",
    "debug_launcher",
    "notebook_launcher",
    "skip_first_batches",
    "cpu_offload",
    "cpu_offload_with_hook",
    "disk_offload",
    "dispatch_model",
    "dispatch_params",
    "infer_auto_device_map",
    "init_empty_weights",
    "is_rich_available",
    "load_checkpoint_and_dispatch",
    "load_checkpoint_in_model",
    "prepare_pipeline",
    "prepare_pippy",
    "rich",
    "synchronize_rng_states",
    "LocalSGD",
    "find_executable_batch_size",
    "release_memory",
    "AcceleratedOptimizer",
    "AcceleratedScheduler",
    "AcceleratorState",
    "DataLoader",
    "DataLoaderConfiguration",
    "DistributedType",
    "GradientAccumulationPlugin",
    "GradientState",
    "MixedPrecisionPolicy",
    "ParallelismConfig",
    "PartialState",
    "PrecisionType",
    "ProjectConfiguration",
    "QuantizationConfig",
    "QuantizedArray",
    "load_and_quantize_model",
    "quantize_params",
    "dequantize_params",
]


def __getattr__(name):
    # Lazy to keep `import accelerate_tpu` light and avoid import cycles.
    if name == "Accelerator":
        from .accelerator import Accelerator

        return Accelerator
    if name == "AcceleratedOptimizer":
        from .optimizer import AcceleratedOptimizer

        return AcceleratedOptimizer
    if name == "AcceleratedScheduler":
        from .scheduler import AcceleratedScheduler

        return AcceleratedScheduler
    if name == "DataLoader":
        from .data_loader import DataLoader

        return DataLoader
    if name == "notebook_launcher":
        from .launchers import notebook_launcher

        return notebook_launcher
    if name == "debug_launcher":
        from .launchers import debug_launcher

        return debug_launcher
    if name == "skip_first_batches":
        from .data_loader import skip_first_batches

        return skip_first_batches
    if name == "LocalSGD":
        from .local_sgd import LocalSGD

        return LocalSGD
    if name in ("find_executable_batch_size", "release_memory", "clear_device_cache"):
        from .utils import memory

        return getattr(memory, name)
    if name == "tqdm":
        from .utils.tqdm import tqdm

        return tqdm
    if name in ("rich_print", "get_console"):
        from .utils import rich

        return getattr(rich, name)
    if name == "load_checkpoint_in_model":
        from .checkpointing import load_checkpoint_in_model

        return load_checkpoint_in_model
    if name == "CheckpointCorruptError":
        from .checkpointing import CheckpointCorruptError

        return CheckpointCorruptError
    if name == "CheckpointTopologyError":
        from .checkpointing import CheckpointTopologyError

        return CheckpointTopologyError
    if name == "synchronize_rng_states":
        from .utils.random import synchronize_rng_states

        return synchronize_rng_states
    if name == "is_rich_available":
        from .utils.imports import is_rich_available

        return is_rich_available
    if name in ("prepare_pipeline", "prepare_pippy"):
        # reference spelling `accelerate.prepare_pippy` (inference.py:126)
        # resolves to the native pipeline prep
        from .parallel.pipeline import prepare_pipeline

        return prepare_pipeline
    if name == "rich":
        # reference exports the rich helper module at top level
        from .utils import rich

        return rich
    if name in _BIG_MODELING:
        from . import big_modeling

        return getattr(big_modeling, name)
    if name in _MODELING_UTILS:
        from .utils import modeling

        return getattr(modeling, name)
    if name in _QUANTIZATION:
        from .utils import quantization

        return getattr(quantization, name)
    raise AttributeError(f"module 'accelerate_tpu' has no attribute {name!r}")


# lazy names served by __getattr__ that are not in __all__ — keep in sync
# when adding a new branch there, or dir() will hide the new export
_LAZY_EXTRAS = {"tqdm", "rich_print", "get_console", "clear_device_cache"}


def __dir__():
    # make the lazy names introspectable: dir(accelerate_tpu) must show the
    # full public surface, not just what's been imported eagerly
    return sorted(
        set(globals())
        | set(__all__)
        | _LAZY_EXTRAS
        | _BIG_MODELING
        | _MODELING_UTILS
        | _QUANTIZATION
    )


_BIG_MODELING = {
    "DispatchedParams",
    "UserCpuOffloadHook",
    "cpu_offload",
    "cpu_offload_with_hook",
    "disk_offload",
    "dispatch_model",
    "dispatch_params",
    "init_empty_weights",
    "init_on_device",
    "load_checkpoint_and_dispatch",
}
_MODELING_UTILS = {
    "abstract_params",
    "compute_module_sizes",
    "find_tied_parameters",
    "get_balanced_memory",
    "get_max_memory",
    "infer_auto_device_map",
    "load_checkpoint_in_params",
}
_QUANTIZATION = {
    "QuantizationConfig",
    "QuantizedArray",
    "load_and_quantize_model",
    "quantize_params",
    "dequantize_params",
}
