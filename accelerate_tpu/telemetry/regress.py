"""Continuous perf-regression sentinel over bench payloads.

``python -m accelerate_tpu.telemetry regress BASELINE CANDIDATE [...]``
compares bench payloads (driver ``BENCH_*.json`` wrappers, raw ``bench.py``
final-line dicts, or JSONL logs whose last line is the payload) and emits a
NOISE / IMPROVED / REGRESSION verdict per metric, with exit codes a CI gate
can consume (``make bench-check``):

- ``0`` — clean: every compared metric is NOISE or IMPROVED,
- ``1`` — at least one REGRESSION (the output names the metric),
- ``2`` — refusal or error: cross-environment comparison, unusable payloads,
  or fewer than two usable payloads.

Two numbers are only comparable when their **environment fingerprints**
match (device kind/count — stamped into every payload by
``benchmarks/_common.env_fingerprint``; older payloads fall back to their
``device_kind``/``n_chips`` fields). A TPU v5e number vs a CPU number is a
hardware change, not a perf change, and the sentinel refuses it rather than
reporting a 25x "regression".

The **metric registry** (:data:`DEFAULT_SPECS`) gives each metric family a
direction (higher/lower is better), a relative noise tolerance (doubled on
CPU fingerprints — CI boxes are loud), and an optional hard bar that flags a
candidate regardless of the baseline (a 0.0 headline is a dead run, not a
slow one). :func:`register` prepends project-specific specs.

**Waivers** let a justified, documented exception ride without editing
committed payloads: ``--waive METRIC[=reason]`` (repeatable, fnmatch
patterns allowed), ``--waiver-file PATH``, or — in ``--scan`` mode — an
auto-discovered :data:`WAIVER_FILENAME` file next to the payloads (one
``metric  # reason`` per line). A waived regression still prints its full
REGRESSION row plus a loud ``WAIVED`` marker and is named again in the
verdict line; it just stops failing the gate (exit 0 when every regression
is waived). Silence is the one thing a waiver must never buy."""

from __future__ import annotations

import argparse
import fnmatch
import glob
import json
import os
from dataclasses import dataclass
from typing import Iterable, Optional

NOISE = "NOISE"
IMPROVED = "IMPROVED"
REGRESSION = "REGRESSION"


@dataclass(frozen=True)
class MetricSpec:
    """Comparison policy for metric names matching ``pattern`` (fnmatch,
    case-insensitive, first match wins)."""

    pattern: str
    direction: str = "higher"  # "higher" | "lower" is better
    tolerance: float = 0.05    # relative noise band
    hard_min: Optional[float] = None  # candidate below this: REGRESSION outright
    hard_max: Optional[float] = None  # candidate above this: REGRESSION outright


#: first match wins; the trailing catch-all makes every numeric comparable
DEFAULT_SPECS: "list[MetricSpec]" = [
    MetricSpec("*latency*", "lower", 0.10),
    MetricSpec("*ttft*", "lower", 0.10),
    MetricSpec("*stall*", "lower", 0.15),
    MetricSpec("*compile*", "lower", 0.15),
    # speculative decoding + paged prefill kernel (bench serving config):
    # accept rate and the spec-on/off tok/s ratio are higher-better (wider
    # band — they move with the synthetic workload mix); the prefill-kernel
    # microbench is a per-token time, lower-better. Latency-named spec
    # metrics (e.g. spec_decode per-token p50/p99) are caught by *latency*
    # above.
    MetricSpec("*accept_rate*", "higher", 0.15),
    MetricSpec("*spec_decode*", "higher", 0.10),
    MetricSpec("*prefill_kernel*", "lower", 0.15),
    # attention kernel grid + fp8 train step (bench attention config, ISSUE
    # 20): per-token kernel time and the fp8 step ms are lower-better; the
    # best fraction-of-roofline across the grid is higher-better. Must sit
    # before the generic time specs — *attn_kernel* names end in *_token and
    # the mfu fraction would otherwise fall through to the catch-all.
    MetricSpec("*attn_kernel*", "lower", 0.10),
    MetricSpec("*fp8*step*", "lower", 0.10),
    MetricSpec("*mfu*", "higher", 0.05),
    MetricSpec("*seconds*", "lower", 0.10),
    MetricSpec("*_s", "lower", 0.10),
    MetricSpec("*_ms", "lower", 0.10),
    # a zero/absent headline is a dead run — flag it even vs a dead baseline
    MetricSpec("headline", "higher", 0.10, hard_min=1e-9),
    MetricSpec("*", "higher", 0.05),
]

_EXTRA_SPECS: "list[MetricSpec]" = []


def register(spec: MetricSpec) -> None:
    """Prepend a project-specific spec (takes precedence over defaults)."""
    _EXTRA_SPECS.insert(0, spec)


def spec_for(name: str) -> MetricSpec:
    low = name.lower()
    for spec in _EXTRA_SPECS + DEFAULT_SPECS:
        if fnmatch.fnmatch(low, spec.pattern):
            return spec
    return MetricSpec("*")  # unreachable: the catch-all matches


# ---------------------------------------------------------------------------
# payload loading + environment fingerprints

def load_payload(path: str) -> Optional[dict]:
    """A bench payload from ``path``: a driver ``BENCH_*.json`` wrapper (its
    ``parsed`` field), a raw payload dict, or a JSONL log (last parseable
    object line). None when nothing usable is inside."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return None
    payload: Optional[dict] = None
    try:
        obj = json.loads(text)
        payload = obj if isinstance(obj, dict) else None
    except json.JSONDecodeError:
        for line in reversed(text.strip().splitlines()):
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict):
                payload = obj
                break
    if payload is not None and "parsed" in payload and "rc" in payload:
        payload = payload["parsed"] if isinstance(payload["parsed"], dict) else None
    return payload


def fingerprint(payload: dict) -> dict:
    """The environment identity a comparison must hold fixed. Prefers the
    stamped ``env`` block; falls back to the payload's own device fields for
    pre-stamp payloads."""
    env = payload.get("env") if isinstance(payload.get("env"), dict) else {}
    kind = env.get("device_kind") or payload.get("device_kind")
    count = env.get("device_count") or payload.get("n_chips")
    return {
        "device_kind": str(kind) if kind else None,
        "device_count": int(count) if count else None,
        "jaxlib": env.get("jaxlib"),
    }


def fingerprint_label(fp: dict) -> str:
    kind = fp.get("device_kind") or "unknown"
    count = fp.get("device_count")
    return f"{kind} x{count}" if count else str(kind)


def comparable(a: dict, b: dict) -> bool:
    """Same device kind (known on both sides) and, when both report one, the
    same device count."""
    if not a.get("device_kind") or not b.get("device_kind"):
        return False
    if a["device_kind"] != b["device_kind"]:
        return False
    ca, cb = a.get("device_count"), b.get("device_count")
    return ca is None or cb is None or ca == cb


def extract_metrics(payload: dict) -> "dict[str, float]":
    """Flatten a payload into comparable named numbers: the headline value
    (named by its ``metric`` string when that is a bare identifier, else
    ``headline``), ``mfu``, every ``configs.<name>`` sub-benchmark value, and
    every entry of a config's optional ``guarded`` dict — the contract for a
    sub-benchmark to put MORE than its headline under regression guard
    (``configs.<name>.<metric>``; the serving config guards its spec-decode
    accept rate / tok-s ratio and the prefill-kernel microbench this way)."""
    out: "dict[str, float]" = {}

    def _num(v) -> Optional[float]:
        return float(v) if isinstance(v, (int, float)) and not isinstance(v, bool) else None

    headline = _num(payload.get("value"))
    if headline is not None:
        metric = str(payload.get("metric", ""))
        name = metric if metric and metric.isidentifier() else "headline"
        out[name] = headline
    mfu = _num(payload.get("mfu"))
    if mfu is not None:
        out["mfu"] = mfu
    configs = payload.get("configs")
    if isinstance(configs, dict):
        for cfg, entry in sorted(configs.items()):
            if isinstance(entry, dict):
                v = _num(entry.get("value"))
                if v is not None:
                    out[f"configs.{cfg}"] = v
                guarded = entry.get("guarded")
                if isinstance(guarded, dict):
                    for gname, gval in sorted(guarded.items()):
                        gv = _num(gval)
                        if gv is not None:
                            out[f"configs.{cfg}.{gname}"] = gv
    return out


# ---------------------------------------------------------------------------
# comparison

def compare_metrics(
    baseline: dict,
    candidate: dict,
    tolerance: Optional[float] = None,
    cpu_noise_factor: float = 2.0,
) -> "list[dict]":
    """Per-metric verdicts over the metric names both payloads carry."""
    base = extract_metrics(baseline)
    cand = extract_metrics(candidate)
    is_cpu = (fingerprint(candidate).get("device_kind") or "").lower() == "cpu"
    verdicts: "list[dict]" = []
    for name in sorted(set(base) & set(cand)):
        spec = spec_for(name)
        tol = tolerance if tolerance is not None else spec.tolerance
        if is_cpu:
            tol *= cpu_noise_factor
        b, c = base[name], cand[name]
        verdict = NOISE
        reason = ""
        if spec.hard_min is not None and c < spec.hard_min:
            verdict, reason = REGRESSION, f"hard bar: {c:g} < {spec.hard_min:g}"
        elif spec.hard_max is not None and c > spec.hard_max:
            verdict, reason = REGRESSION, f"hard bar: {c:g} > {spec.hard_max:g}"
        elif b != 0:
            delta = (c - b) / abs(b)
            gain = delta if spec.direction == "higher" else -delta
            if gain > tol:
                verdict = IMPROVED
            elif gain < -tol:
                verdict = REGRESSION
        elif c != 0:
            verdict = IMPROVED if spec.direction == "higher" else REGRESSION
        delta_pct = ((c - b) / abs(b) * 100.0) if b else None
        verdicts.append({
            "metric": name,
            "baseline": b,
            "candidate": c,
            "delta_pct": round(delta_pct, 3) if delta_pct is not None else None,
            "tolerance_pct": round(tol * 100.0, 3),
            "direction": spec.direction,
            "verdict": verdict,
            **({"reason": reason} if reason else {}),
        })
    return verdicts


def _format_comparison(base_name: str, cand_name: str, fp: dict,
                       verdicts: "list[dict]") -> "list[str]":
    lines = [
        f"regress: baseline={base_name} candidate={cand_name} "
        f"env={fingerprint_label(fp)}"
    ]
    if not verdicts:
        lines.append("  (no common metrics)")
    width = max((len(v["metric"]) for v in verdicts), default=0)
    for v in verdicts:
        delta = (
            f"{v['delta_pct']:+.1f}%" if v["delta_pct"] is not None else "n/a"
        )
        extra = f", {v['reason']}" if v.get("reason") else ""
        lines.append(
            f"  {v['verdict']:<10} {v['metric']:<{width}}  "
            f"{v['baseline']:g} -> {v['candidate']:g}  "
            f"({delta}, tol {v['tolerance_pct']:g}%, "
            f"{v['direction']} is better{extra})"
        )
        if v.get("waived"):
            # a waiver buys the exit code, never silence: the REGRESSION
            # row above stays, and the waiver justifies itself here
            lines.append(
                f"  ^ WAIVED   {v['metric']:<{width}}  {v['waiver_reason']}"
            )
    return lines


def scan_dir(directory: str) -> "list[str]":
    """The ``BENCH_*.json`` payload files under ``directory``, oldest first
    (lexicographic — the driver numbers them r01, r02, ...)."""
    return sorted(glob.glob(os.path.join(directory, "BENCH_*.json")))


# ---------------------------------------------------------------------------
# waivers

#: auto-discovered next to the payloads in --scan mode
WAIVER_FILENAME = "BENCH_WAIVERS"


def parse_waiver_line(line: str) -> "Optional[tuple[str, str]]":
    """``metric  # reason`` -> (metric, reason); None for blanks/comments."""
    body, _, comment = line.partition("#")
    body = body.strip()
    if not body:
        return None
    metric = body.split()[0]
    return metric, (comment.strip() or "no reason recorded")


def load_waiver_file(path: str) -> "dict[str, str]":
    waivers: "dict[str, str]" = {}
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return waivers
    for line in lines:
        parsed = parse_waiver_line(line)
        if parsed is not None:
            waivers[parsed[0]] = parsed[1]
    return waivers


def waiver_for(metric: str, waivers: "dict[str, str]") -> Optional[str]:
    """The waiver reason covering ``metric``, or None. Keys are fnmatch
    patterns (case-insensitive, like the metric registry); an exact name
    is its own pattern."""
    low = metric.lower()
    for pattern, reason in waivers.items():
        if fnmatch.fnmatch(low, pattern.lower()):
            return reason
    return None


def run_regress(paths: "list[str]", tolerance: Optional[float] = None,
                as_json: bool = False, scan: Optional[str] = None,
                waive: Optional["list[str]"] = None,
                waiver_file: Optional[str] = None) -> int:
    """CLI body. With ``scan``, compares the two newest usable payloads in
    the directory; with explicit paths, the first is the baseline and every
    later payload is compared against it. ``waive`` entries are
    ``METRIC[=reason]``; ``waiver_file`` (and, in scan mode, an
    auto-discovered ``BENCH_WAIVERS`` next to the payloads) add more."""
    out_lines: "list[str]" = []
    result: dict = {"comparisons": [], "refusals": []}

    waivers: "dict[str, str]" = {}
    if scan:
        auto = os.path.join(scan, WAIVER_FILENAME)
        loaded_auto = load_waiver_file(auto)
        if loaded_auto:
            out_lines.append(
                f"regress: loaded {len(loaded_auto)} waiver(s) from {auto}"
            )
            waivers.update(loaded_auto)
    if waiver_file:
        loaded_file = load_waiver_file(waiver_file)
        if not loaded_file:
            out_lines.append(
                f"regress: waiver file {waiver_file} has no usable entries"
            )
        waivers.update(loaded_file)
    for entry in waive or []:
        metric, _, reason = entry.partition("=")
        waivers[metric.strip()] = reason.strip() or "waived on the command line"

    if scan:
        paths = scan_dir(scan)
    loaded = []
    for p in paths:
        payload = load_payload(p)
        if payload is None:
            out_lines.append(f"regress: skipping {os.path.basename(p)} (no parseable payload)")
            continue
        loaded.append((os.path.basename(p), payload))
    if scan:
        loaded = loaded[-2:]
    if len(loaded) < 2:
        out_lines.append("regress: need at least two usable payloads to compare")
        print("\n".join(out_lines))
        return 2

    base_name, baseline = loaded[0]
    base_fp = fingerprint(baseline)
    regressions: "list[str]" = []
    waived: "dict[str, str]" = {}
    improved = noise = 0
    refused = False
    for cand_name, candidate in loaded[1:]:
        cand_fp = fingerprint(candidate)
        if not comparable(base_fp, cand_fp):
            msg = (
                f"regress: REFUSING {base_name} vs {cand_name} — environment "
                f"fingerprints differ ({fingerprint_label(base_fp)} vs "
                f"{fingerprint_label(cand_fp)}); a hardware change is not a "
                "perf change"
            )
            out_lines.append(msg)
            result["refusals"].append({
                "baseline": base_name, "candidate": cand_name,
                "baseline_env": base_fp, "candidate_env": cand_fp,
            })
            refused = True
            continue
        verdicts = compare_metrics(baseline, candidate, tolerance=tolerance)
        for v in verdicts:
            if v["verdict"] == REGRESSION:
                reason = waiver_for(v["metric"], waivers)
                if reason is not None:
                    v["waived"] = True
                    v["waiver_reason"] = reason
        out_lines.extend(_format_comparison(base_name, cand_name, cand_fp, verdicts))
        result["comparisons"].append({
            "baseline": base_name, "candidate": cand_name,
            "env": cand_fp, "verdicts": verdicts,
        })
        for v in verdicts:
            if v["verdict"] == REGRESSION:
                if v.get("waived"):
                    waived[v["metric"]] = v["waiver_reason"]
                else:
                    regressions.append(v["metric"])
            elif v["verdict"] == IMPROVED:
                improved += 1
            else:
                noise += 1

    waived_s = "; ".join(f"{m} ({r})" for m, r in sorted(waived.items()))
    if refused:
        rc = 2
        summary = "regress verdict: REFUSED (mismatched environment fingerprints)"
    elif regressions:
        rc = 1
        summary = (
            f"regress verdict: REGRESSION — {len(regressions)} metric(s): "
            + ", ".join(sorted(set(regressions)))
        )
        if waived:
            summary += f"; {len(waived)} more WAIVED: {waived_s}"
    elif waived:
        rc = 0
        summary = (
            f"regress verdict: OK with {len(waived)} regression(s) WAIVED: "
            f"{waived_s} — {improved} improved, {noise} within noise"
        )
    else:
        rc = 0
        summary = (
            f"regress verdict: OK — {improved} improved, {noise} within noise"
        )
    out_lines.append(summary)
    result["verdict"] = summary
    result["exit_code"] = rc
    print(json.dumps(result, indent=2) if as_json else "\n".join(out_lines))
    return rc


def add_parser(sub) -> None:
    """Attach the ``regress`` subcommand to the telemetry CLI's subparsers."""
    p = sub.add_parser(
        "regress",
        help="compare bench payloads: NOISE/IMPROVED/REGRESSION with exit codes",
    )
    p.add_argument("paths", nargs="*",
                   help="payload files; first is the baseline")
    p.add_argument("--scan", metavar="DIR",
                   help="compare the two newest BENCH_*.json payloads in DIR")
    p.add_argument("--tolerance", type=float, default=None,
                   help="override every spec's relative noise tolerance")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the structured comparison dict")
    p.add_argument("--waive", action="append", metavar="METRIC[=REASON]",
                   help="waive a regressing metric (repeatable; fnmatch "
                        "patterns allowed; waivers print loudly)")
    p.add_argument("--waiver-file", metavar="PATH",
                   help="file of 'metric  # reason' lines to waive; in "
                        "--scan mode a BENCH_WAIVERS file next to the "
                        "payloads is picked up automatically")


def run_from_args(args) -> int:
    if not args.paths and not args.scan:
        print("regress: pass payload files or --scan DIR")
        return 2
    return run_regress(args.paths, tolerance=args.tolerance,
                       as_json=args.as_json, scan=args.scan,
                       waive=getattr(args, "waive", None),
                       waiver_file=getattr(args, "waiver_file", None))
