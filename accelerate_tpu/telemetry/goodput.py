"""Fleet goodput/badput ledger: attribute every wall-clock second of a run.

Large fleets lose throughput not in the step function but *between* steps —
compiles, input stalls, checkpoint stalls, restart downtime, cold scale-ups.
The ledger turns the event streams every subsystem already writes into a
fixed taxonomy, per rank, per restart generation and fleet-aggregated, so
"how much of the fleet's wall-clock bought training/serving work?" is one
number (``goodput_fraction``) with an attributed remainder.

Two halves:

- **Post-hoc ledger** (:func:`build_ledger`): pure function of the merged
  event list the report CLI already loads. Wall-clock is segmented per rank
  stream at each ``meta`` record (every process incarnation writes a fresh
  meta line, so metas are the generation boundaries) and attributed from the
  records inside the segment: ``step`` execute/compile/data-wait splits,
  exposed ``checkpoint`` phases, ``serving`` step/warmup durations, and the
  supervisor's ``restart``/``autoscale`` records for cross-incarnation
  downtime. The serving side additionally carries a **token goodput**:
  useful emitted tokens vs total computed, with re-prefill/abandoned/handoff
  waste attribution.
- **Live meter** (:func:`note_step` & friends): cumulative in-process
  counters fed from the same call sites that emit the records, flushed as
  periodic ``goodput`` snapshot records and Prometheus gauges
  (:data:`~accelerate_tpu.telemetry.metrics.GOODPUT_GAUGES`). Disabled cost
  is one ``is_enabled`` check per call — no files, no threads of its own.

The restart-downtime computation lives HERE (:func:`restart_stats`) and is
the single implementation both the report CLI's restarts section and the
ledger consume — the two can never disagree.

Taxonomy (seconds buckets; ``good`` vs ``badput`` vs the honest remainder):

===================  =====  ====================================================
category             kind   evidence
===================  =====  ====================================================
productive           good   ``step`` ``execute_s`` minus critical data wait
serving_execute      good   ``serving`` step ``dur_s`` (engine busy)
compile              bad    ``step`` ``compile_s`` (segment saw cache hits/no cache)
compile_cold         bad    ``step`` ``compile_s`` in a segment with a compile-cache
                            miss/fallback (PR 13 records)
warmup               bad    ``serving`` warmup ``dur_s`` (lattice compile/load)
data_wait            bad    critical input-pipeline wait inside steps (PR 3)
checkpoint_stall     bad    non-hidden ``checkpoint`` phase durations (PR 5)
restart_downtime     bad    supervisor ``restart`` records x cohort size (PR 10)
scaleup_wait         bad    ``autoscale`` scale-up ``time_to_ready_s`` (PR 16)
init                 bad    segment head before the first step/warmup starts
idle                 bad    evidenced idle serving gaps (empty engine on both ends)
unattributed         --     wall minus everything above (must stay < 5%)
===================  =====  ====================================================
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from . import events as tel
from . import metrics as _metrics

GOOD_CATEGORIES = ("productive", "serving_execute")
BADPUT_CATEGORIES = (
    "compile",
    "compile_cold",
    "warmup",
    "data_wait",
    "checkpoint_stall",
    "restart_downtime",
    "scaleup_wait",
    "init",
    "idle",
)
#: token-waste causes in the serving token ledger
TOKEN_WASTE_CAUSES = (
    "preemption_reprefill",  # LIFO preempt/resume re-prefills (PR 11)
    "failover_reprefill",    # replica-death resume re-prefills (PR 12)
    "handoff_rerun",         # corrupt/dropped KV handoff -> prefill re-run (PR 16)
    "abandoned",             # dispatched but failed/expired: all its tokens
    "draft_rejected",        # speculative-decode verify rows past the accept point
)


# ---------------------------------------------------------------------------
# THE shared restart-downtime computation (report restarts section + ledger)

def restart_stats(events: "list[dict]") -> dict:
    """Aggregate supervisor ``restart`` records into the downtime facts both
    the report CLI's restarts section and the goodput ledger consume.

    ``downtime_s`` sums the supervisor-measured failure-detection→respawn
    gaps; ``chip_downtime_s`` weights each gap by the cohort size it idled
    (``processes`` on the record — a 8-process cohort down 3s lost 24
    chip-seconds); ``by_generation`` attributes each gap to the generation it
    *spawned* (the downtime paid to reach it)."""
    restarts = [e for e in events if e.get("kind") == "restart"]
    causes: dict = {}
    by_generation: dict = {}
    downtime = 0.0
    chip_downtime = 0.0
    for r in restarts:
        cause = str(r.get("cause", "?"))
        causes[cause] = causes.get(cause, 0) + 1
        d = float(r.get("downtime_s", 0.0))
        downtime += d
        chip = d * max(1, int(r.get("processes") or 1))
        chip_downtime += chip
        gen = int(r.get("generation", 0))
        by_generation[gen] = round(by_generation.get(gen, 0.0) + chip, 6)
    return {
        "count": sum(1 for r in restarts if not r.get("gave_up")),
        "downtime_s": round(downtime, 3),
        "chip_downtime_s": round(chip_downtime, 3),
        "causes": dict(sorted(causes.items())),
        "by_generation": by_generation,
    }


# ---------------------------------------------------------------------------
# post-hoc ledger

def _segments(events: "list[dict]") -> "list[dict]":
    """Split the merged event list into per-incarnation segments: one per
    ``meta`` record in each rank stream (every respawn opens its stream with
    a fresh meta line, so the k-th meta in a file IS local generation k).
    Supervisor streams (``role: supervisor``, no ``process_index``) carry no
    rank wall-clock and are excluded."""
    by_file: dict = {}
    for e in events:
        by_file.setdefault(e.get("_file") or "?", []).append(e)
    segments: "list[dict]" = []
    for file, evs in sorted(by_file.items()):
        current: Optional[dict] = None
        gen = -1
        for e in evs:
            if e.get("kind") == "meta":
                if e.get("process_index") is None:
                    current = None  # supervisor/unknown stream: skip until next rank meta
                    continue
                gen += 1
                current = {
                    "file": file,
                    "rank": int(e["process_index"]),
                    "generation": gen,
                    "t0": float(e.get("t", 0.0)),
                    "events": [],
                }
                segments.append(current)
            elif current is not None:
                current["events"].append(e)
    return segments


def _attribute_segment(seg: dict) -> dict:
    """One incarnation's wall-clock, attributed. Sum-based with clamps: the
    buckets are built from disjoint evidence (step internals never overlap
    checkpoint/warmup records, which are emitted between steps), and the
    remainder is reported honestly as ``unattributed``."""
    evs = seg["events"]
    t0 = seg["t0"]
    # the meta line is stamped when the stream file is first written, which
    # can be AFTER early records were stamped (records carry their END time,
    # so work like a serving warmup may straddle the lazy meta write) —
    # anchor at the earliest evidence so the wall doesn't collapse to zero
    starts = [float(e.get("t", t0)) - float(e.get("dur_s", 0.0)) for e in evs]
    t0 = min([t0] + starts)
    t_last = max([float(e.get("t", t0)) for e in evs] + [t0])
    wall = max(0.0, t_last - t0)
    buckets = {c: 0.0 for c in GOOD_CATEGORIES + BADPUT_CATEGORIES}

    steps = [e for e in evs if e.get("kind") == "step"]
    cold = any(
        e.get("kind") == "compile_cache"
        and e.get("event") in ("miss", "fallback", "corrupt")
        for e in evs
    )
    compile_key = "compile_cold" if cold else "compile"
    # a step's drained data_wait_s covers waits since the PREVIOUS step's
    # drain — the loader fetch usually stalls in the gap BETWEEN step windows
    # (``for batch in loader: step(batch)``), so charge the wait against the
    # inter-step gap first and only the remainder against execute time
    prev_end: Optional[float] = None
    for s in steps:
        t = float(s.get("t", t0))
        dur = float(s.get("dur_s", 0.0))
        gap = max(0.0, (t - dur) - prev_end) if prev_end is not None else 0.0
        execute = float(s.get("execute_s", 0.0))
        wait = max(0.0, float(s.get("data_wait_s", 0.0)))
        gap_wait = min(wait, gap)
        in_step_wait = min(wait - gap_wait, execute)
        buckets["data_wait"] += gap_wait + in_step_wait
        buckets["productive"] += max(0.0, execute - in_step_wait)
        buckets[compile_key] += float(s.get("compile_s", 0.0))
        prev_end = t

    for c in evs:
        if c.get("kind") == "checkpoint" and not c.get("hidden", False):
            buckets["checkpoint_stall"] += float(c.get("dur_s", 0.0))

    serving_steps = [
        e for e in evs if e.get("kind") == "serving" and e.get("phase") == "step"
    ]
    for e in evs:
        if e.get("kind") == "serving" and e.get("phase") == "warmup":
            buckets["warmup"] += float(e.get("dur_s", 0.0))
        if e.get("kind") == "serving" and e.get("phase") == "idle":
            buckets["idle"] += float(e.get("dur_s", 0.0))
    for s in serving_steps:
        buckets["serving_execute"] += float(s.get("dur_s", 0.0))

    # segment head/tail: framework time outside any recorded unit of work —
    # imports, device init and loader spin-up before the first step, and
    # teardown (final saves, summary emits, log close) after the last one.
    # Records carry their END time; subtract dur_s to recover the start.
    work = steps + serving_steps + [
        e
        for e in evs
        if (e.get("kind") == "serving" and e.get("phase") in ("warmup", "idle"))
        or (e.get("kind") == "checkpoint" and not e.get("hidden", False))
    ]
    if work:
        work_starts = [
            float(e.get("t", t0)) - float(e.get("dur_s", 0.0)) for e in work
        ]
        work_ends = [float(e.get("t", t0)) for e in work]
        buckets["init"] = max(0.0, min(work_starts) - t0)
        buckets["init"] += max(0.0, t_last - max(work_ends))

    attributed = sum(buckets.values())
    unattributed = max(0.0, wall - attributed)
    return {
        "rank": seg["rank"],
        "generation": seg["generation"],
        "wall_s": round(wall, 6),
        "buckets": {k: round(v, 6) for k, v in buckets.items()},
        "unattributed_s": round(unattributed, 6),
        "overattributed": attributed > wall * 1.05 + 1e-6,
    }


def _token_ledger(events: "list[dict]") -> Optional[dict]:
    """Serving token goodput: useful emitted tokens vs total computed."""
    serving_steps = [
        e for e in events if e.get("kind") == "serving" and e.get("phase") == "step"
    ]
    if not serving_steps:
        return None
    computed = sum(
        # decode_tokens counts EMITTED tokens; speculative-decode verify rows
        # past the accept point were computed too, so add them back here
        int(s.get("prefill_tokens", 0)) + int(s.get("decode_tokens", 0))
        + int(s.get("draft_rejected_tokens", 0))
        for s in serving_steps
    )
    waste = {c: 0 for c in TOKEN_WASTE_CAUSES}
    waste["preemption_reprefill"] = sum(
        int(s.get("preempt_reprefill_tokens", 0)) for s in serving_steps
    )
    waste["failover_reprefill"] = sum(
        int(s.get("resume_reprefill_tokens", 0)) for s in serving_steps
    )
    waste["draft_rejected"] = sum(
        int(s.get("draft_rejected_tokens", 0)) for s in serving_steps
    )
    routed = [
        e for e in events if e.get("kind") == "router" and e.get("phase") == "request"
    ]
    prompt_by_rid = {str(r.get("rid")): int(r.get("prompt_tokens") or 0) for r in routed}
    shed = 0
    for r in routed:
        outcome = str(r.get("outcome", ""))
        if outcome == "shed":
            shed += 1  # never dispatched: zero compute wasted, counted anyway
        elif outcome in ("failed", "expired") and (
            r.get("replica") is not None or int(r.get("new_tokens") or 0) > 0
        ):
            waste["abandoned"] += int(r.get("prompt_tokens") or 0) + int(
                r.get("new_tokens") or 0
            )
    reruns = 0
    for h in events:
        if h.get("kind") == "kv_handoff" and h.get("outcome") not in (None, "ok"):
            reruns += 1
            waste["handoff_rerun"] += prompt_by_rid.get(str(h.get("rid")), 0)
    wasted = min(computed, sum(waste.values()))
    useful = computed - wasted
    return {
        "computed_tokens": computed,
        "useful_tokens": useful,
        "wasted_tokens": wasted,
        "waste_by_cause": waste,
        "shed_requests": shed,
        "handoff_reruns": reruns,
        "token_goodput_fraction": (
            round(useful / computed, 6) if computed else None
        ),
    }


def build_ledger(events: "list[dict]", by_rank: bool = False) -> Optional[dict]:
    """The fleet goodput ledger over a merged event list (the report CLI's
    ``load_events`` output). Returns None when there is no wall-clock
    evidence at all (no rank stream ever opened)."""
    segments = [_attribute_segment(s) for s in _segments(events)]
    restarts = restart_stats(events)
    scaleup = sum(
        float(a.get("time_to_ready_s", 0.0))
        for a in events
        if a.get("kind") == "autoscale" and a.get("action") == "scale_up"
    )
    if not segments and not restarts["count"]:
        return None

    total = {c: 0.0 for c in GOOD_CATEGORIES + BADPUT_CATEGORIES}
    wall = 0.0
    unattributed = 0.0
    by_generation: dict = {}
    by_rank_out: dict = {}
    for seg in segments:
        wall += seg["wall_s"]
        unattributed += seg["unattributed_s"]
        for c, v in seg["buckets"].items():
            total[c] += v
        g = by_generation.setdefault(
            seg["generation"], {"wall_s": 0.0, "good_s": 0.0, "badput_s": 0.0,
                               "unattributed_s": 0.0, "restart_downtime_s": 0.0}
        )
        g["wall_s"] += seg["wall_s"]
        g["good_s"] += sum(seg["buckets"][c] for c in GOOD_CATEGORIES)
        g["badput_s"] += sum(seg["buckets"][c] for c in BADPUT_CATEGORIES)
        g["unattributed_s"] += seg["unattributed_s"]
        if by_rank:
            r = by_rank_out.setdefault(
                seg["rank"], {"wall_s": 0.0, "good_s": 0.0, "unattributed_s": 0.0}
            )
            r["wall_s"] += seg["wall_s"]
            r["good_s"] += sum(seg["buckets"][c] for c in GOOD_CATEGORIES)
            r["unattributed_s"] += seg["unattributed_s"]

    # cross-incarnation costs the rank streams cannot see: supervisor-measured
    # restart downtime (chip-seconds) and autoscaler cold scale-up waits
    total["restart_downtime"] = restarts["chip_downtime_s"]
    total["scaleup_wait"] += scaleup
    for gen, d in restarts["by_generation"].items():
        g = by_generation.setdefault(
            gen, {"wall_s": 0.0, "good_s": 0.0, "badput_s": 0.0,
                  "unattributed_s": 0.0, "restart_downtime_s": 0.0}
        )
        g["restart_downtime_s"] += d
        g["badput_s"] += d
        g["wall_s"] += d
    wall += restarts["chip_downtime_s"] + scaleup

    good = sum(total[c] for c in GOOD_CATEGORIES)
    badput = {c: round(total[c], 6) for c in BADPUT_CATEGORIES if total[c] > 0}
    top = max(
        list(badput.items()) + [("unattributed", unattributed)],
        key=lambda kv: kv[1],
        default=None,
    )
    ledger = {
        "wall_s": round(wall, 6),
        "good_s": round(good, 6),
        "goodput_fraction": round(good / wall, 6) if wall > 0 else None,
        "good_by_category": {
            c: round(total[c], 6) for c in GOOD_CATEGORIES if total[c] > 0
        },
        "badput_s": badput,
        "unattributed_s": round(unattributed, 6),
        "unattributed_fraction": round(unattributed / wall, 6) if wall > 0 else None,
        "top_badput": (
            {"cause": top[0], "seconds": round(top[1], 6),
             "fraction": round(top[1] / wall, 6) if wall > 0 else None}
            if top and top[1] > 0 else None
        ),
        "segments": len(segments),
        "by_generation": {
            str(k): {kk: round(vv, 6) for kk, vv in v.items()}
            for k, v in sorted(by_generation.items())
        },
        "restarts": restarts,
        "overattributed": any(s["overattributed"] for s in segments),
    }
    if by_rank and by_rank_out:
        fractions = {
            r: (v["good_s"] / v["wall_s"] if v["wall_s"] > 0 else 0.0)
            for r, v in by_rank_out.items()
        }
        ledger["by_rank"] = {
            str(r): {
                "wall_s": round(v["wall_s"], 6),
                "good_s": round(v["good_s"], 6),
                "goodput_fraction": round(fractions[r], 6),
                "unattributed_s": round(v["unattributed_s"], 6),
            }
            for r, v in sorted(by_rank_out.items())
        }
        if len(fractions) > 1:
            ledger["rank_skew"] = round(
                max(fractions.values()) - min(fractions.values()), 6
            )
    tokens = _token_ledger(events)
    if tokens is not None:
        ledger["tokens"] = tokens
    ledger["verdict"] = verdict_line(ledger)
    return ledger


def verdict_line(ledger: dict) -> str:
    """The per-run one-liner: goodput fraction + the top badput cause."""
    frac = ledger.get("goodput_fraction")
    frac_s = f"{frac * 100:.1f}%" if frac is not None else "n/a"
    top = ledger.get("top_badput")
    top_s = (
        f" — top badput: {top['cause']} ({top['fraction'] * 100:.1f}%)"
        if top and top.get("fraction") is not None
        else ""
    )
    tok = ledger.get("tokens") or {}
    tok_frac = tok.get("token_goodput_fraction")
    tok_s = f", token goodput {tok_frac * 100:.1f}%" if tok_frac is not None else ""
    return (
        f"goodput {frac_s} of {ledger['wall_s']:.1f}s fleet wall-clock"
        f"{top_s}{tok_s}"
    )


# ---------------------------------------------------------------------------
# live meter: cumulative counters -> periodic `goodput` records + gauges

_LOCK = threading.Lock()
_SECONDS: "dict[str, float]" = {}
_TOKENS = {"computed": 0, "wasted": 0}
_LAST_EMIT = 0.0
_EMIT_INTERVAL_S = 30.0


def note(category: str, seconds: float) -> None:
    """Charge ``seconds`` to a taxonomy category. One ``is_enabled`` check
    when telemetry is off — no state is touched, no files or threads exist."""
    if not tel.is_enabled() or seconds <= 0:
        return
    with _LOCK:
        _SECONDS[category] = _SECONDS.get(category, 0.0) + float(seconds)


def note_step(execute_s: float, compile_s: float, data_wait_s: float) -> None:
    """Per-train-step feed (step_profiler exit): the execute/compile/wait
    split, charged to productive/compile/data_wait."""
    if not tel.is_enabled():
        return
    wait = min(max(0.0, data_wait_s), max(0.0, execute_s))
    with _LOCK:
        _SECONDS["productive"] = _SECONDS.get("productive", 0.0) + max(
            0.0, execute_s - wait
        )
        _SECONDS["data_wait"] = _SECONDS.get("data_wait", 0.0) + wait
        if compile_s > 0:
            _SECONDS["compile"] = _SECONDS.get("compile", 0.0) + compile_s


def note_serving_step(dur_s: float, computed_tokens: int = 0,
                      wasted_tokens: int = 0) -> None:
    """Per-engine-step feed: busy seconds + the step's token accounting."""
    if not tel.is_enabled():
        return
    with _LOCK:
        if dur_s > 0:
            _SECONDS["serving_execute"] = (
                _SECONDS.get("serving_execute", 0.0) + dur_s
            )
        _TOKENS["computed"] += int(computed_tokens)
        _TOKENS["wasted"] += int(wasted_tokens)


def maybe_emit(now: Optional[float] = None) -> bool:
    """Throttled snapshot: at most one ``goodput`` record (+ gauge refresh)
    per interval, emitted from whatever hot path calls this. Cheap when off."""
    global _LAST_EMIT
    if not tel.is_enabled():
        return False
    now = time.monotonic() if now is None else now
    if now - _LAST_EMIT < _EMIT_INTERVAL_S:
        return False
    _LAST_EMIT = now
    emit_now()
    return True


def emit_now(final: bool = False) -> Optional[dict]:
    """Flush the meter: one cumulative ``goodput`` record and the Prometheus
    gauges (when the PR 15 registry is armed). Returns the record fields."""
    if not tel.is_enabled():
        return None
    with _LOCK:
        seconds = dict(_SECONDS)
        tokens = dict(_TOKENS)
    good = sum(seconds.get(c, 0.0) for c in GOOD_CATEGORIES)
    bad = sum(v for c, v in seconds.items() if c not in GOOD_CATEGORIES)
    accounted = good + bad
    frac = good / accounted if accounted > 0 else None
    useful = max(0, tokens["computed"] - tokens["wasted"])
    tok_frac = useful / tokens["computed"] if tokens["computed"] else None
    fields: "dict[str, Any]" = {
        "good_s": round(good, 6),
        "badput_s": round(bad, 6),
        "by_category": {k: round(v, 6) for k, v in sorted(seconds.items())},
        "goodput_fraction": round(frac, 6) if frac is not None else None,
        "computed_tokens": tokens["computed"],
        "wasted_tokens": tokens["wasted"],
        "token_goodput_fraction": (
            round(tok_frac, 6) if tok_frac is not None else None
        ),
    }
    if final:
        fields["final"] = True
    tel.emit("goodput", **fields)
    if _metrics.is_enabled():
        if frac is not None:
            _metrics.set_gauge(_metrics.GOODPUT_FRACTION_GAUGE, round(frac, 6))
        if tok_frac is not None:
            _metrics.set_gauge(
                _metrics.TOKEN_GOODPUT_FRACTION_GAUGE, round(tok_frac, 6)
            )
        for cause, v in seconds.items():
            if cause not in GOOD_CATEGORIES:
                _metrics.set_gauge(
                    _metrics.BADPUT_SECONDS_GAUGE, round(v, 6), cause=cause
                )
    return fields


def _reset_for_tests() -> None:
    global _LAST_EMIT
    with _LOCK:
        _SECONDS.clear()
        _TOKENS["computed"] = 0
        _TOKENS["wasted"] = 0
        _LAST_EMIT = 0.0
