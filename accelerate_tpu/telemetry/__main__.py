"""``python -m accelerate_tpu.telemetry <command>`` entry point: ``report``
(event-stream aggregation; ``--follow`` streams it), ``top`` (the live
fleet dashboard; ``--once`` for a single pipe-safe frame), ``doctor``
(self-check), and ``regress`` (the perf-regression sentinel over bench
payloads — ``make bench-check``)."""

import sys

from .report import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
