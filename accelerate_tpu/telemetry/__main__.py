"""``python -m accelerate_tpu.telemetry report <dir>`` entry point."""

import sys

from .report import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
