"""Per-step performance attribution: hardware peaks, MFU, and roofline buckets.

ROADMAP item 2 ("raw-speed push to MFU >= 0.7") needs per-step *evidence*:
where device time goes, which functions are compute-bound vs HBM-bound, and
how the measured step compares to the chip's roofline. This module is the
shared substrate:

- **Hardware peak registry** — bf16 peak FLOP/s and HBM bandwidth per chip
  generation (public TPU specs), with a *nominal* CPU fallback so dev-box runs
  still produce relative MFU numbers (env-overridable). ``bench.py`` and the
  telemetry layer both read THIS table, so they can never disagree on peaks.
- **Compile-time cost capture** — :func:`capture_compiled` lowers a jitted
  step function once (AOT), records XLA's own ``cost_analysis()`` (FLOPs,
  bytes accessed — remat recompute *included*: hardware utilization, not
  model-MFU) and ``memory_analysis()`` (argument/output/temp bytes, checked
  against device capacity by :mod:`.memory`), and emits one ``perf`` record.
  The :class:`~accelerate_tpu.accelerator.Accelerator` runs it automatically
  on the first call of every tracked step function while telemetry is on.
- **Per-step folding** — the captured cost is handed to the step profiler, so
  every ``step`` record carries ``mfu``, ``arithmetic_intensity`` and its
  ``roofline`` bucket (``compute-bound`` vs ``hbm-bound``), and the report
  CLI's "performance" section can plot the MFU trend per function.

The capture costs one extra XLA compile per step function (the AOT executable
is not shared with the jit call cache). It only runs while telemetry is
enabled and can be killed independently with ``ACCELERATE_PERF_CAPTURE=0``;
the compile it triggers is *excluded* from step compile/execute accounting
(see :func:`~accelerate_tpu.telemetry.step_profiler.exclude_compiles`).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Optional

from . import events as tel

PERF_CAPTURE_ENV_VAR = "ACCELERATE_PERF_CAPTURE"

# bf16 peak FLOP/s per chip by device kind (public TPU specs; fall back to
# v5e for unknown TPU generations). THE peak table — bench.py imports it.
PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# HBM bandwidth per chip in bytes/s (public specs), for roofline ridge points
HBM_BYTES_PER_S = {
    "TPU v2": 700e9,
    "TPU v3": 900e9,
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
}

# Nominal CPU stand-ins: dev boxes have no published "peak"; these make MFU a
# *relative* signal (comparable run-over-run on the same box), never an
# absolute utilization claim. Override per box via the env knobs.
CPU_PEAK_FLOPS_ENV_VAR = "ACCELERATE_CPU_PEAK_FLOPS"
CPU_HBM_GBPS_ENV_VAR = "ACCELERATE_CPU_HBM_GBPS"
_CPU_NOMINAL_FLOPS = 1e11
_CPU_NOMINAL_HBM_GBPS = 25.0


@dataclass(frozen=True)
class HardwarePeaks:
    """Peak throughput of one chip: ``flops`` (bf16 FLOP/s) and
    ``hbm_bytes_per_s``. ``nominal=True`` marks the CPU/dev-box stand-in whose
    MFU numbers are relative, not absolute (``source`` says where the numbers
    came from: ``table`` / ``env`` / ``cpu-nominal``)."""

    device_kind: str
    flops: float
    hbm_bytes_per_s: Optional[float]
    nominal: bool = False
    source: str = "table"

    @property
    def ridge_intensity(self) -> Optional[float]:
        """FLOP/byte at the roofline ridge: below it a kernel is HBM-bound."""
        if not self.hbm_bytes_per_s or not self.flops:
            return None
        return self.flops / self.hbm_bytes_per_s


def peaks_for_device(device: Optional[Any] = None) -> HardwarePeaks:
    """Peak registry lookup for ``device`` (default: ``jax.devices()[0]``).

    TPUs match on ``device_kind`` prefix, unknown TPU kinds fall back to v5e;
    anything else gets the *nominal* CPU peaks (env-overridable via
    ``ACCELERATE_CPU_PEAK_FLOPS`` FLOP/s / ``ACCELERATE_CPU_HBM_GBPS`` GB/s)
    so MFU stays a usable relative signal on dev boxes."""
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = str(getattr(device, "device_kind", "") or "")
    for name, flops in PEAK_FLOPS.items():
        if kind.startswith(name):
            return HardwarePeaks(kind, flops, HBM_BYTES_PER_S.get(name))
    if "TPU" in kind.upper():
        return HardwarePeaks(
            kind, PEAK_FLOPS["TPU v5e"], HBM_BYTES_PER_S["TPU v5e"], source="table"
        )
    from ..utils.environment import parse_optional_float_from_env

    env_flops = parse_optional_float_from_env(CPU_PEAK_FLOPS_ENV_VAR)
    env_bw = parse_optional_float_from_env(CPU_HBM_GBPS_ENV_VAR)
    return HardwarePeaks(
        kind or "cpu",
        env_flops if env_flops else _CPU_NOMINAL_FLOPS,
        (env_bw if env_bw else _CPU_NOMINAL_HBM_GBPS) * 1e9,
        nominal=True,
        source="env" if (env_flops or env_bw) else "cpu-nominal",
    )


def device_peak_flops(device: Optional[Any] = None, include_nominal: bool = False) -> float:
    """Peak bf16 FLOP/s, or ``0.0`` for non-TPU devices unless
    ``include_nominal`` (bench payloads omit MFU on dev boxes; telemetry
    reports relative MFU there instead)."""
    peaks = peaks_for_device(device)
    if peaks.nominal and not include_nominal:
        return 0.0
    return peaks.flops


def device_hbm_bandwidth(device: Optional[Any] = None, include_nominal: bool = False) -> Optional[float]:
    """Peak HBM bytes/s, or ``None`` for non-TPU devices unless ``include_nominal``."""
    peaks = peaks_for_device(device)
    if peaks.nominal and not include_nominal:
        return None
    return peaks.hbm_bytes_per_s


# ------------------------------------------------------------- MFU math ----
def train_flops_per_sample(config: Any, seq_len: int, n_params: int) -> float:
    """Model FLOPs per trained sample: 6*N per token (fwd 2N + bwd 4N) plus
    the attention score/context matmuls 12 * L * d_model * T per token.
    ``config`` needs ``n_layers`` and ``dim`` (any transformer config here)."""
    per_token = 6.0 * n_params + 12.0 * config.n_layers * config.dim * seq_len
    return per_token * seq_len


def lm_train_mfu(
    tokens_per_sec: float, n_params: int, config: Any, seq_len: int
) -> Optional[float]:
    """Model-FLOPs utilization for an LM train config, ``None`` off-TPU —
    the one MFU methodology bench.py and telemetry share (remat recompute is
    NOT counted: model-MFU, comparable across remat policies)."""
    import jax

    peak = device_peak_flops(jax.devices()[0])
    if not peak:
        return None
    per_token = train_flops_per_sample(config, seq_len, n_params) / seq_len
    return round(tokens_per_sec * per_token / peak, 4)


def mfu(flops_per_step: float, step_seconds: float, peak_flops: float) -> Optional[float]:
    """Utilization of one step: achieved FLOP/s over peak (``None`` when
    either side is unknown/zero)."""
    if not flops_per_step or not step_seconds or not peak_flops:
        return None
    return flops_per_step / step_seconds / peak_flops


def arithmetic_intensity(flops: float, bytes_accessed: float) -> Optional[float]:
    """FLOPs per byte of memory traffic — the roofline x-axis."""
    if not flops or not bytes_accessed:
        return None
    return flops / bytes_accessed


def roofline_bucket(intensity: Optional[float], peaks: HardwarePeaks) -> Optional[str]:
    """``"compute-bound"`` when the kernel's arithmetic intensity clears the
    chip's ridge point (peak FLOPs / peak HBM bytes), else ``"hbm-bound"``."""
    ridge = peaks.ridge_intensity
    if intensity is None or ridge is None:
        return None
    return "compute-bound" if intensity >= ridge else "hbm-bound"


# -------------------------------------------------------- cost capture ----
@dataclass
class CompiledCost:
    """One step function's XLA-reported cost: what `cost_analysis()` /
    `memory_analysis()` said at compile time, plus the derived roofline
    placement against the chip's peaks."""

    name: str
    flops: float
    bytes_accessed: float
    peaks: HardwarePeaks
    memory: Optional[dict] = None

    @property
    def intensity(self) -> Optional[float]:
        return arithmetic_intensity(self.flops, self.bytes_accessed)

    @property
    def roofline(self) -> Optional[str]:
        return roofline_bucket(self.intensity, self.peaks)

    def mfu(self, step_seconds: float) -> Optional[float]:
        return mfu(self.flops, step_seconds, self.peaks.flops)

    def record(self) -> dict:
        """The ``perf`` event payload (stable field names — schema in
        docs/telemetry.md)."""
        out = {
            "fn": self.name,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "arithmetic_intensity": _round(self.intensity),
            "roofline": self.roofline,
            "peak_flops": self.peaks.flops,
            "peak_hbm_bytes_per_s": self.peaks.hbm_bytes_per_s,
            "peak_source": self.peaks.source,
            "device_kind": self.peaks.device_kind,
        }
        if self.memory:
            out.update({f"memory_{k}": v for k, v in self.memory.items()})
        return out


def _round(x: Optional[float], digits: int = 6) -> Optional[float]:
    return None if x is None else round(float(x), digits)


def capture_enabled() -> bool:
    """Cost capture runs iff telemetry is on and ``ACCELERATE_PERF_CAPTURE``
    is not explicitly falsy (it costs one extra XLA compile per step fn)."""
    if not tel.is_enabled():
        return False
    return os.environ.get(PERF_CAPTURE_ENV_VAR, "").strip().lower() not in (
        "0",
        "false",
        "no",
        "off",
    )


def cost_from_compiled(name: str, compiled: Any) -> Optional[CompiledCost]:
    """Extract a :class:`CompiledCost` from an already-compiled executable
    (``jitted.lower(...).compile()``). Returns ``None`` when the backend
    reports no cost data."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    if not isinstance(ca, dict):
        return None
    flops = float(ca.get("flops", 0.0) or 0.0)
    bytes_accessed = float(ca.get("bytes accessed", 0.0) or 0.0)
    if flops <= 0.0 and bytes_accessed <= 0.0:
        return None
    from .memory import compiled_memory_analysis

    return CompiledCost(
        name=name,
        flops=flops,
        bytes_accessed=bytes_accessed,
        peaks=peaks_for_device(),
        memory=compiled_memory_analysis(compiled),
    )


def capture_compiled(
    name: str,
    fn: Any,
    args: tuple,
    kwargs: Optional[dict] = None,
    mesh: Optional[Any] = None,
) -> Optional[CompiledCost]:
    """AOT-lower ``fn`` with ``args`` and record its XLA cost + memory
    analysis; emits one ``perf`` event and a capacity check (see
    :func:`~accelerate_tpu.telemetry.memory.check_memory_fit`).

    The compile this triggers is excluded from the step profiler's
    compile-second accounting, so step records keep meaning "compiles the
    *training* path paid". Since the compile is already paid, the executable
    is also EXPORTED to the persistent compile cache (when configured —
    :mod:`accelerate_tpu.compile_cache`), which is what lets the next
    restart generation skip this function's compile entirely. Never raises:
    an uncapturable backend returns ``None`` and training proceeds
    untouched."""
    from . import step_profiler

    if not hasattr(fn, "lower"):
        return None  # eager (disable_jit) or already-AOT: nothing to lower
    c0, s0 = step_profiler.raw_compile_snapshot()
    try:
        lowered = fn.lower(*args, **(kwargs or {}))
        compiled = lowered.compile()
        cost = cost_from_compiled(name, compiled)
    except Exception:
        cost = None
    else:
        try:
            from ..compile_cache import maybe_export

            maybe_export(name, lowered, compiled, mesh=mesh)
        except Exception:
            pass  # an unexportable backend must not cost the capture
    finally:
        c1, s1 = step_profiler.raw_compile_snapshot()
        step_profiler.exclude_compiles(c1 - c0, s1 - s0)
    if cost is None:
        return None
    tel.emit("perf", **cost.record())
    if cost.memory:
        from .memory import check_memory_fit

        check_memory_fit(name, cost.memory)
    return cost


def capture_from_executable(name: str, executable: Any) -> Optional[CompiledCost]:
    """The zero-compile twin of :func:`capture_compiled`, for a step
    executable LOADED from the persistent compile cache: the cost analysis is
    read off the deserialized executable, so a warm restart's step records
    still carry mfu/roofline without paying the capture's AOT compile."""
    cost = cost_from_compiled(name, executable)
    if cost is None:
        return None
    tel.emit("perf", **cost.record())
    if cost.memory:
        from .memory import check_memory_fit

        check_memory_fit(name, cost.memory)
    return cost
