"""SLO burn-rate monitoring: "are we burning our latency budget RIGHT NOW".

An SLO here is the standard SRE shape: a target fraction of GOOD events
(e.g. "99% of requests get their first token within 250 ms", "99.9% of
admitted requests finish", "at most 1% of traffic is shed"), an error
budget of ``1 - target``, and a **burn rate** — the observed bad fraction
divided by the budget. Burn rate 1.0 spends the budget exactly at the
window's length; 14.4 spends a 30-day budget in ~2 days (the classic
page-level threshold). Alerts fire on MULTI-window agreement — a fast
window (default 5 m) so pages are prompt, and a slow window (default 1 h)
so a single bad second cannot page — both over the threshold at once.

- :class:`SLObjective` — one declarative objective: a name, the good
  target, how to classify an event (``latency`` with a threshold against a
  measured value, or ``availability``-style good/bad), windows and the burn
  threshold.
- :class:`SLOMonitor` — feed it events (:meth:`observe`), ask it
  :meth:`evaluate`: per-objective fast/slow burn rates, violation entry/exit
  with hysteresis (one ``slo_violation`` telemetry record per episode
  transition, re-armed when the fast window recovers), and per-``source``
  attribution so the serving router can treat a *burning replica* as
  DRAINING pressure (:meth:`burning_sources`). The clock is injectable —
  the burn-window tests run on a synthetic clock.
- :func:`serving_slos` — the stock serving objectives (ttft latency,
  availability, shed rate) the router wires by default when handed a
  monitor without explicit objectives; ``ACCELERATE_SLO_TTFT_S`` and
  friends tune them from the environment.

Training-side consumers: the elastic supervisor holds a restart-downtime
objective (every restart's ``downtime_s`` is one event) and the Accelerator
an optional step-latency objective — same monitor, same records, same
report section.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from . import events as _events
from . import metrics as _metrics
from ..utils.environment import parse_optional_float_from_env

SLO_TTFT_ENV_VAR = "ACCELERATE_SLO_TTFT_S"
SLO_TTFT_TARGET_ENV_VAR = "ACCELERATE_SLO_TTFT_TARGET"
SLO_AVAILABILITY_TARGET_ENV_VAR = "ACCELERATE_SLO_AVAILABILITY_TARGET"
SLO_SHED_TARGET_ENV_VAR = "ACCELERATE_SLO_SHED_TARGET"
SLO_STEP_LATENCY_ENV_VAR = "ACCELERATE_SLO_STEP_LATENCY_S"
SLO_RESTART_DOWNTIME_ENV_VAR = "ACCELERATE_SLO_RESTART_DOWNTIME_S"

#: default multi-window pair (seconds): fast pages promptly, slow keeps a
#: blip from paging
FAST_WINDOW_S = 300.0
SLOW_WINDOW_S = 3600.0
#: default page-level burn threshold (Google SRE workbook: 14.4x spends a
#: 30-day budget in 2 days)
BURN_THRESHOLD = 14.4


@dataclass(frozen=True)
class SLObjective:
    """One declarative objective.

    ``kind``:

    - ``"latency"`` — an event is GOOD when its measured value is
      ``<= threshold`` (ttft, step wall time, restart downtime…);
    - ``"availability"`` — the caller classifies good/bad directly
      (finished vs failed, served vs shed).

    ``target`` is the good fraction promised (0.99 = "99% good"); the error
    budget is ``1 - target``.
    """

    name: str
    kind: str = "availability"  # "latency" | "availability"
    target: float = 0.99
    threshold_s: Optional[float] = None  # latency objectives only
    fast_window_s: float = FAST_WINDOW_S
    slow_window_s: float = SLOW_WINDOW_S
    burn_threshold: float = BURN_THRESHOLD
    description: str = ""

    def __post_init__(self):
        if not (0.0 < self.target < 1.0):
            raise ValueError(f"target must be in (0, 1), got {self.target}")
        if self.kind not in ("latency", "availability"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if self.kind == "latency" and self.threshold_s is None:
            raise ValueError(f"latency objective {self.name!r} needs threshold_s")
        if self.fast_window_s >= self.slow_window_s:
            raise ValueError(
                f"fast window ({self.fast_window_s}s) must be shorter than the "
                f"slow window ({self.slow_window_s}s)"
            )

    @property
    def budget(self) -> float:
        return 1.0 - self.target


@dataclass
class _WindowState:
    """Per-objective event ring: (t, bad, source) tuples bounded by the slow
    window (the fast window is a suffix of it). ``window_bad`` is the
    rolling bad count over the CURRENT ring (maintained by observe/trim),
    so the slow-window burn is O(1) instead of a full-ring rescan on every
    evaluate — at serving rates the ring holds 10^5-10^6 events."""

    events: "deque[tuple[float, bool, Optional[str]]]" = field(default_factory=deque)
    total: int = 0
    bad_total: int = 0
    window_bad: int = 0
    violating: bool = False
    violations: int = 0


class SLOMonitor:
    """Multi-window burn-rate evaluation over a set of objectives."""

    def __init__(
        self,
        objectives: Iterable[SLObjective],
        *,
        clock: Callable[[], float] = time.monotonic,
        min_events: int = 10,
    ):
        objectives = list(objectives)
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"objective names must be unique: {names}")
        self.objectives: "dict[str, SLObjective]" = {o.name: o for o in objectives}
        self.clock = clock
        #: below this many slow-window events a burn rate is noise, not a
        #: signal — no violation fires (a single bad first request must not
        #: page at burn rate 1/budget)
        self.min_events = int(min_events)
        self._state: "dict[str, _WindowState]" = {n: _WindowState() for n in names}
        self._lock = threading.Lock()

    # -- feeding -------------------------------------------------------------

    def observe(
        self,
        name: str,
        *,
        value: Optional[float] = None,
        good: Optional[bool] = None,
        source: Optional[str] = None,
        now: Optional[float] = None,
    ) -> bool:
        """Record one event for objective ``name``: a measured ``value`` for
        latency objectives, a ``good`` verdict for availability ones.
        ``source`` attributes the event (a replica name) for
        :meth:`burning_sources`. Returns the event's good/bad verdict."""
        slo = self.objectives[name]
        if slo.kind == "latency":
            if value is None:
                raise ValueError(f"latency objective {name!r} needs value=")
            good = float(value) <= float(slo.threshold_s)
        elif good is None:
            raise ValueError(f"availability objective {name!r} needs good=")
        now = self.clock() if now is None else now
        state = self._state[name]
        with self._lock:
            state.events.append((now, not good, source))
            state.total += 1
            if not good:
                state.bad_total += 1
                state.window_bad += 1
            self._trim(state, slo, now)
        return bool(good)

    def _trim(self, state: _WindowState, slo: SLObjective, now: float) -> None:
        horizon = now - slo.slow_window_s
        events = state.events
        while events and events[0][0] < horizon:
            _, was_bad, _ = events.popleft()
            if was_bad:
                state.window_bad -= 1

    # -- evaluation ----------------------------------------------------------

    def _burn(self, slo: SLObjective, state: _WindowState, now: float,
              window_s: float, source: Optional[str] = None) -> "tuple[float, int, int]":
        """(burn rate, events, bad) over the trailing window. The unsourced
        slow window is O(1) off the rolling counters (the ring IS the slow
        window after trim); fast/per-source reads scan only the window's
        suffix (the reversed walk breaks at the horizon)."""
        if source is None and window_s >= slo.slow_window_s:
            total, bad = len(state.events), state.window_bad
            if total == 0:
                return 0.0, 0, 0
            return (bad / total) / slo.budget, total, bad
        horizon = now - window_s
        total = bad = 0
        for t, is_bad, src in reversed(state.events):
            if t < horizon:
                break
            if source is not None and src != source:
                continue
            total += 1
            if is_bad:
                bad += 1
        if total == 0:
            return 0.0, 0, 0
        return (bad / total) / slo.budget, total, bad

    def evaluate(self, now: Optional[float] = None, emit: bool = True) -> "list[dict]":
        """Per-objective burn status. A VIOLATION needs both windows over
        the objective's threshold (and ``min_events`` slow-window events);
        each episode emits ONE ``slo_violation`` record on entry (hysteresis:
        re-armed once the fast window drops back under threshold)."""
        now = self.clock() if now is None else now
        results = []
        for name, slo in self.objectives.items():
            state = self._state[name]
            with self._lock:
                self._trim(state, slo, now)
                fast, fast_n, fast_bad = self._burn(slo, state, now, slo.fast_window_s)
                slow, slow_n, slow_bad = self._burn(slo, state, now, slo.slow_window_s)
            burning = (
                slow_n >= self.min_events
                and fast >= slo.burn_threshold
                and slow >= slo.burn_threshold
            )
            entered = burning and not state.violating
            if not burning and state.violating and fast < slo.burn_threshold:
                state.violating = False  # fast-window recovery re-arms the episode
            rec = {
                "slo": name,
                # "slo_kind", not "kind": events.emit reserves the record kind
                "slo_kind": slo.kind,
                "target": slo.target,
                "threshold_s": slo.threshold_s,
                "fast_burn": round(fast, 4),
                "slow_burn": round(slow, 4),
                "fast_window_s": slo.fast_window_s,
                "slow_window_s": slo.slow_window_s,
                "burn_threshold": slo.burn_threshold,
                "fast_events": fast_n,
                "fast_bad": fast_bad,
                "slow_events": slow_n,
                "slow_bad": slow_bad,
                "violating": burning,
                # True exactly once per episode — callers that write their
                # own record stream (the supervisor) key off this
                "entered": entered,
            }
            if entered:
                state.violating = True
                state.violations += 1
                if emit:
                    _events.emit("slo_violation", **rec)
                    _metrics.inc("accelerate_slo_violations_total", slo=name)
            results.append(rec)
        return results

    def burning_sources(self, name: str, now: Optional[float] = None) -> "list[str]":
        """Sources (replicas) whose FAST-window burn for ``name`` is over the
        threshold — the router's DRAINING-pressure signal. Per-source burn
        needs at least ``min_events`` fast-window events from that source to
        count (one slow request out of one must not drain a replica)."""
        slo = self.objectives[name]
        state = self._state[name]
        now = self.clock() if now is None else now
        with self._lock:
            sources = {
                src for t, _, src in state.events
                if src is not None and t >= now - slo.fast_window_s
            }
            burning = []
            for src in sorted(sources):
                burn, n, _ = self._burn(slo, state, now, slo.fast_window_s, source=src)
                if n >= self.min_events and burn >= slo.burn_threshold:
                    burning.append(src)
        return burning

    def stats(self) -> dict:
        return {
            name: {
                "events": s.total,
                "bad": s.bad_total,
                "violations": s.violations,
                "violating": s.violating,
            }
            for name, s in sorted(self._state.items())
        }


# ---------------------------------------------------------------------------
# stock objective sets


def _env_float(key: str, default: float) -> float:
    """The repo's defensive env parse (utils.environment), with a required
    default — garbage/unset never crashes an SLO-armed process."""
    value = parse_optional_float_from_env(key)
    return default if value is None else value


def serving_slos(
    *,
    ttft_threshold_s: Optional[float] = None,
    ttft_target: Optional[float] = None,
    availability_target: Optional[float] = None,
    shed_target: Optional[float] = None,
    fast_window_s: float = FAST_WINDOW_S,
    slow_window_s: float = SLOW_WINDOW_S,
    burn_threshold: float = BURN_THRESHOLD,
) -> "list[SLObjective]":
    """The stock serving objectives (env-tunable): ttft latency,
    availability (admitted requests finish), shed rate."""
    kw = dict(fast_window_s=fast_window_s, slow_window_s=slow_window_s,
              burn_threshold=burn_threshold)
    return [
        SLObjective(
            name="ttft",
            kind="latency",
            threshold_s=(
                ttft_threshold_s if ttft_threshold_s is not None
                else _env_float(SLO_TTFT_ENV_VAR, 1.0)
            ),
            target=(
                ttft_target if ttft_target is not None
                else _env_float(SLO_TTFT_TARGET_ENV_VAR, 0.99)
            ),
            description="first token within threshold",
            **kw,
        ),
        SLObjective(
            name="availability",
            kind="availability",
            target=(
                availability_target if availability_target is not None
                else _env_float(SLO_AVAILABILITY_TARGET_ENV_VAR, 0.999)
            ),
            description="admitted requests finish (failed/expired = bad)",
            **kw,
        ),
        SLObjective(
            name="shed_rate",
            kind="availability",
            target=(
                shed_target if shed_target is not None
                else _env_float(SLO_SHED_TARGET_ENV_VAR, 0.99)
            ),
            description="submitted requests admitted (shed = bad)",
            **kw,
        ),
    ]


def step_latency_slo_from_env() -> Optional[SLObjective]:
    """Training-side: ``ACCELERATE_SLO_STEP_LATENCY_S=<seconds>`` arms a
    step-wall-time objective (target tunable via
    ``ACCELERATE_SLO_STEP_LATENCY_TARGET``, default 0.99). None when unset —
    the Accelerator's hot path stays a single ``is None`` check."""
    threshold = parse_optional_float_from_env(SLO_STEP_LATENCY_ENV_VAR)
    if threshold is None:
        return None
    return SLObjective(
        name="step_latency",
        kind="latency",
        threshold_s=threshold,
        target=_env_float("ACCELERATE_SLO_STEP_LATENCY_TARGET", 0.99),
        description="train step wall time within threshold",
    )


def restart_downtime_slo_from_env() -> Optional[SLObjective]:
    """Supervisor-side: ``ACCELERATE_SLO_RESTART_DOWNTIME_S=<seconds>`` arms
    a restart-downtime objective (every restart is one event; default
    target 0.9 — restarts are rare, so the budget math runs on small
    counts and ``min_events=1`` at the caller)."""
    threshold = parse_optional_float_from_env(SLO_RESTART_DOWNTIME_ENV_VAR)
    if threshold is None:
        return None
    return SLObjective(
        name="restart_downtime",
        kind="latency",
        threshold_s=threshold,
        target=_env_float("ACCELERATE_SLO_RESTART_DOWNTIME_TARGET", 0.9),
        # restarts are RARE events: one over-budget restart must already
        # page (burn 1/(1-0.9) = 10 from a single bad event), so the
        # threshold is "any budget burn", not the page-level 14.4 that
        # high-volume request objectives use
        burn_threshold=1.0,
        description="restart downtime within threshold",
    )
