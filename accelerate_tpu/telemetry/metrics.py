"""Streaming serving metrics: a typed registry with a Prometheus exporter.

The JSONL event log (:mod:`.events`) answers *post-hoc* questions; a serving
fleet also needs *live* ones — "what is the ttft p99 right now", "how deep is
the queue", "is the block pool about to reject". This module is that plane:

- **typed registry** — :class:`Counter` (monotone), :class:`Gauge` (last
  value), :class:`Histogram` (fixed cumulative buckets + sum/count, the
  Prometheus layout), created through one process-wide
  :class:`MetricsRegistry`. The serving router, admission controller,
  scheduler, engine, block allocator and compile cache all feed it.
- **Prometheus exposition** — :meth:`MetricsRegistry.render` emits the
  standard text format; :func:`serve` runs it from a stdlib ``http.server``
  daemon thread (``GET /metrics``). Armed by ``ACCELERATE_METRICS_PORT``
  (off by default; port 0 picks a free one — read it back from
  :func:`server_port`).
- **snapshots** — :func:`maybe_snapshot` periodically freezes the whole
  registry into one ``metrics`` telemetry record
  (``ACCELERATE_METRICS_SNAPSHOT_S``, default 1s between snapshots), so the
  report CLI and benches consume the same numbers a live scrape would show.
- **THE histogram/percentile implementation** — :func:`percentile` (exact,
  nearest-rank) and :meth:`Histogram.quantile` (bucket-interpolated, the
  ``histogram_quantile`` math) are the repo's single definitions; the report
  CLI and every bench import them instead of carrying private copies
  (``tests/test_observability.py`` ratchets that).

Zero-overhead contract (the :mod:`.events` pattern): the module-level
helpers (:func:`inc`, :func:`set_gauge`, :func:`observe`) are a single
``is None`` check when no registry is active — no allocation, no lock, no
syscall. :func:`enable` / ``ACCELERATE_METRICS_PORT`` / telemetry being on
arm the registry.
"""

from __future__ import annotations

import bisect
import math
import os
import re
import threading
import time
from typing import Any, Iterable, Optional

import warnings

from . import events as _events
from ..utils.environment import parse_optional_int_from_env, parse_seconds_from_env

METRICS_PORT_ENV_VAR = "ACCELERATE_METRICS_PORT"
METRICS_SNAPSHOT_ENV_VAR = "ACCELERATE_METRICS_SNAPSHOT_S"

#: default latency buckets (seconds) — wide enough for CPU toy runs and real
#: TPU serving alike; ttft / request latency / per-token latency share them
#: so cross-metric comparisons line up bucket for bucket
LATENCY_BUCKETS_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)
#: queue depth / outstanding counts
DEPTH_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)

#: goodput-plane gauge names, set by the :mod:`.goodput` live meter on each
#: periodic flush: the run's goodput fraction, the serving token goodput
#: fraction, and per-cause badput seconds (labelled ``cause=<taxonomy key>``).
#: Declared here so dashboards and tests share one spelling with the meter.
GOODPUT_FRACTION_GAUGE = "accelerate_goodput_fraction"
TOKEN_GOODPUT_FRACTION_GAUGE = "accelerate_token_goodput_fraction"
BADPUT_SECONDS_GAUGE = "accelerate_badput_seconds"
#: occupancies are fractions in [0, 1]
OCCUPANCY_BUCKETS = (0.1, 0.25, 0.5, 0.75, 0.9, 1.0)


def percentile(values: "list[float]", p: float, presorted: bool = False) -> float:
    """Nearest-rank (ceil-rank) percentile of a list — the repo's ONE exact
    percentile definition (the report CLI and the benches both import it;
    bucketed estimation is :meth:`Histogram.quantile`). ``presorted=True``
    skips the defensive sort for callers that already hold sorted data (the
    report's per-distribution loop)."""
    if not values:
        return 0.0
    if not presorted:
        values = sorted(values)
    idx = min(len(values) - 1, max(0, math.ceil(p / 100.0 * len(values)) - 1))
    return values[idx]


def quantile_from_buckets(
    bounds: "tuple[float, ...]", counts: "list[int]", total: int, q: float,
) -> float:
    """``histogram_quantile`` over cumulative bucket ``counts`` (one per
    finite upper bound in ``bounds``, plus the +Inf bucket implied by
    ``total``): linear interpolation inside the bucket containing rank
    ``q * total``. A rank landing past the last finite bound returns that
    bound (the honest answer a fixed lattice can give). This exact function
    is what makes a live ``/metrics`` scrape and the report CLI agree."""
    if total <= 0:
        return 0.0
    rank = q * total
    prev_count = 0
    prev_bound = 0.0
    for bound, count in zip(bounds, counts):
        if count >= rank:
            in_bucket = count - prev_count
            if in_bucket <= 0:
                return bound
            frac = (rank - prev_count) / in_bucket
            return prev_bound + frac * (bound - prev_bound)
        prev_count = count
        prev_bound = bound
    return bounds[-1] if bounds else 0.0


class Counter:
    """Monotone counter (optionally labeled)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._values: "dict[tuple, float]" = {}
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def value(self, **labels) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0.0)

    def total(self) -> float:
        return sum(self._values.values())

    def render(self) -> "list[str]":
        with self._lock:  # a scrape racing a first-label inc must not blow up
            values = dict(self._values)
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        for key, v in sorted(values.items()):
            lines.append(f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(v)}")
        if not values:
            lines.append(f"{self.name} 0")
        return lines

    def to_dict(self) -> dict:
        with self._lock:
            values = dict(self._values)
        if not values or values.keys() == {()}:
            return {"type": "counter", "value": sum(values.values())}
        return {
            "type": "counter",
            "value": sum(values.values()),
            "by_label": {_label_key(dict(k)): v for k, v in sorted(values.items())},
        }


class Gauge:
    """Last-write-wins gauge."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._values: "dict[tuple, float]" = {}
        self._lock = threading.Lock()

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] = float(v)

    def value(self, **labels) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0.0)

    def render(self) -> "list[str]":
        with self._lock:
            values = dict(self._values)
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        for key, v in sorted(values.items()):
            lines.append(f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(v)}")
        if not values:
            lines.append(f"{self.name} 0")
        return lines

    def to_dict(self) -> dict:
        with self._lock:
            values = dict(self._values)
        if not values or values.keys() == {()}:
            return {"type": "gauge", "value": values.get((), 0.0)}
        return {
            "type": "gauge",
            "by_label": {_label_key(dict(k)): v for k, v in sorted(values.items())},
        }


class Histogram:
    """Fixed-bucket histogram in the Prometheus layout: CUMULATIVE counts per
    upper bound plus the implicit +Inf bucket, a running sum, and (beyond
    Prometheus, for the report's dist lines) the exact observed max.

    One instance is a complete, mergeable digest: :meth:`quantile` estimates
    percentiles by linear interpolation inside the covering bucket — the
    same math a ``histogram_quantile`` over the scraped series computes, so
    a live dashboard and the post-hoc report cannot disagree."""

    kind = "histogram"

    def __init__(self, name: str, buckets: "tuple[float, ...]" = LATENCY_BUCKETS_S,
                 help: str = ""):
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError(f"buckets must be non-empty, sorted, unique: {buckets}")
        self.name = name
        self.help = help
        self.bounds = tuple(float(b) for b in buckets)
        # per-bucket (NON-cumulative) counts; +1 slot for the +Inf overflow.
        # Cumulated on read — observe stays O(log buckets).
        self._counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0
        self.max = 0.0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if v > self.max:
                self.max = v
            self._counts[bisect.bisect_left(self.bounds, v)] += 1

    def observe_many(self, values: Iterable[float]) -> "Histogram":
        for v in values:
            self.observe(v)
        return self

    def _snapshot(self) -> "tuple[list[int], int, float, float]":
        """One consistent locked view of (per-bucket counts, count, sum,
        max) — a scrape racing an observe must never emit a histogram whose
        ``_count`` disagrees with its buckets."""
        with self._lock:
            return list(self._counts), self.count, self.sum, self.max

    @staticmethod
    def _cumulate(counts: "list[int]") -> "list[int]":
        out = []
        running = 0
        for c in counts[:-1]:
            running += c
            out.append(running)
        return out

    def cumulative_counts(self) -> "list[int]":
        """Cumulative count per finite upper bound (the ``_bucket`` series)."""
        return self._cumulate(self._snapshot()[0])

    def quantile(self, q: float) -> float:
        counts, count, _, _ = self._snapshot()
        return quantile_from_buckets(self.bounds, self._cumulate(counts), count, q)

    def dist(self, percentiles: "tuple[int, ...]" = (50, 90, 99)) -> dict:
        """The report CLI's distribution shape (count/mean/max + p<k>),
        estimated from the buckets — identical numbers to a scrape of the
        same observations."""
        counts, count, total_sum, vmax = self._snapshot()
        if not count:
            return {"count": 0}
        cumulative = self._cumulate(counts)
        return {
            "count": count,
            "mean": round(total_sum / count, 6),
            "max": round(vmax, 6),
            **{
                f"p{p}": round(
                    quantile_from_buckets(self.bounds, cumulative, count, p / 100.0), 6
                )
                for p in percentiles
            },
        }

    def render(self) -> "list[str]":
        counts, count, total_sum, _ = self._snapshot()
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        for bound, cum in zip(self.bounds, self._cumulate(counts)):
            lines.append(f'{self.name}_bucket{{le="{_fmt_value(bound)}"}} {cum}')
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {count}')
        lines.append(f"{self.name}_sum {_fmt_value(total_sum)}")
        lines.append(f"{self.name}_count {count}")
        return lines

    def to_dict(self) -> dict:
        # the persisted form carries CUMULATIVE counts (the wire/scrape shape)
        counts, count, total_sum, vmax = self._snapshot()
        return {
            "type": "histogram",
            "buckets": list(self.bounds),
            "counts": self._cumulate(counts),
            "count": count,
            "sum": round(total_sum, 9),
            "max": round(vmax, 9),
        }

    @classmethod
    def from_dict(cls, name: str, payload: dict) -> "Histogram":
        h = cls(name, buckets=tuple(payload["buckets"]))
        h._set_cumulative([int(c) for c in payload["counts"]], int(payload["count"]))
        h.sum = float(payload["sum"])
        h.max = float(payload.get("max", 0.0))
        return h

    def _set_cumulative(self, cumulative: "list[int]", total: int) -> None:
        prev = 0
        for i, c in enumerate(cumulative):
            self._counts[i] = c - prev
            prev = c
        self._counts[-1] = total - prev
        self.count = total


def hist_dist(values: "list[float]", buckets: "tuple[float, ...]" = LATENCY_BUCKETS_S,
              percentiles: "tuple[int, ...]" = (50, 90, 99)) -> dict:
    """Distribution summary of ``values`` through a fixed-bucket
    :class:`Histogram` — the serving/router report sections use this so
    their percentiles are the scrape's percentiles."""
    return Histogram("adhoc", buckets=buckets).observe_many(values).dist(percentiles)


def _fmt_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _label_key(labels: dict) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items())) or ""


def _escape_label_value(v) -> str:
    """Prometheus exposition escaping: backslash, double-quote, newline.
    Label values are user-controlled (replica names) — an unescaped quote
    would invalidate the whole scrape."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class MetricsRegistry:
    """One process-wide family table. ``counter``/``gauge``/``histogram``
    create-or-return by name, so instrumentation sites never need to
    coordinate declaration order."""

    def __init__(self):
        self._metrics: "dict[str, Any]" = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, Counter, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, Gauge, help)

    def histogram(self, name: str, buckets: "tuple[float, ...]" = LATENCY_BUCKETS_S,
                  help: str = "") -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, buckets=buckets, help=help)
                self._metrics[name] = m
            elif not isinstance(m, Histogram):
                raise TypeError(f"metric {name} already registered as {m.kind}")
            return m

    def _get(self, name: str, cls, help: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help=help)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name} already registered as {m.kind}")
            return m

    def get(self, name: str):
        return self._metrics.get(name)

    def names(self) -> "list[str]":
        return sorted(self._metrics)

    def render(self) -> str:
        """The Prometheus text exposition of every registered family."""
        with self._lock:  # a scrape racing a first-time family registration
            metrics = dict(self._metrics)
        lines: "list[str]" = []
        for name in sorted(metrics):
            lines.extend(metrics[name].render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-able freeze of the whole registry (the ``metrics`` telemetry
        record payload)."""
        with self._lock:
            metrics = dict(self._metrics)
        return {name: m.to_dict() for name, m in sorted(metrics.items())}


# ---------------------------------------------------------------------------
# module-level singleton + zero-overhead shims (the events.py pattern): every
# helper below costs one attribute load + ``is None`` check when disabled.

_ACTIVE: Optional[MetricsRegistry] = None
_SERVER = None  # (http.server instance, thread)
_SHUTTING_DOWN = False  # /healthz readiness: flipped before the socket dies
_LAST_SNAPSHOT = 0.0
_SNAPSHOT_LOCK = threading.Lock()
#: snapshot throttle, parsed ONCE at enable() (the hot loops call
#: maybe_snapshot every step — no per-step env reads)
_SNAPSHOT_INTERVAL_S = 1.0


def is_enabled() -> bool:
    return _ACTIVE is not None


def get_registry() -> Optional[MetricsRegistry]:
    return _ACTIVE


def enable() -> MetricsRegistry:
    """Arm the registry (idempotent)."""
    global _ACTIVE, _SNAPSHOT_INTERVAL_S
    if _ACTIVE is None:
        _ACTIVE = MetricsRegistry()
        # defensive parse, once (never crash — and never re-read per step)
        _SNAPSHOT_INTERVAL_S = parse_seconds_from_env(METRICS_SNAPSHOT_ENV_VAR, 1.0)
    return _ACTIVE


def disable() -> None:
    """Drop the registry and stop the exporter thread."""
    global _ACTIVE, _LAST_SNAPSHOT
    stop_server()
    _ACTIVE = None
    _LAST_SNAPSHOT = 0.0


def maybe_enable_from_env() -> Optional[MetricsRegistry]:
    """Arm iff ``ACCELERATE_METRICS_PORT`` is set (also starts the exporter)
    or telemetry is already on (registry only — snapshots still flow into
    the event log). Off by default: an unconfigured process pays one env
    read here and one ``is None`` per instrumentation site afterwards."""
    if _ACTIVE is not None:
        return _ACTIVE
    port = parse_optional_int_from_env(METRICS_PORT_ENV_VAR)
    if port is not None:
        reg = enable()
        serve(port)
        return reg
    if _events.is_enabled():
        return enable()
    return None


def inc(name: str, n: float = 1.0, **labels) -> None:
    if _ACTIVE is not None:
        _ACTIVE.counter(name).inc(n, **labels)


def set_gauge(name: str, v: float, **labels) -> None:
    if _ACTIVE is not None:
        _ACTIVE.gauge(name).set(v, **labels)


def observe(name: str, v: float, buckets: "tuple[float, ...]" = LATENCY_BUCKETS_S) -> None:
    if _ACTIVE is not None:
        _ACTIVE.histogram(name, buckets=buckets).observe(v)


def snapshot_now() -> None:
    """Freeze the registry into one ``metrics`` telemetry record."""
    if _ACTIVE is not None and _events.is_enabled():
        _events.emit("metrics", metrics=_ACTIVE.snapshot())


def maybe_snapshot(now: Optional[float] = None) -> bool:
    """Throttled :func:`snapshot_now` — at most one record per
    ``ACCELERATE_METRICS_SNAPSHOT_S`` (default 1s). The serving step/poll
    loops call this; True when a record was written."""
    global _LAST_SNAPSHOT
    if _ACTIVE is None or not _events.is_enabled():
        return False
    now = time.monotonic() if now is None else now
    with _SNAPSHOT_LOCK:
        if now - _LAST_SNAPSHOT < _SNAPSHOT_INTERVAL_S:
            return False
        _LAST_SNAPSHOT = now
    snapshot_now()
    return True


# ---------------------------------------------------------------------------
# the exporter: GET /metrics from a stdlib http.server daemon thread


def serve(port: int, host: str = "127.0.0.1"):
    """Start the Prometheus endpoint (idempotent; ``port=0`` binds a free
    port — :func:`server_port` reports the real one).

    Never crashes the caller: a second :func:`serve` keeps the existing
    server (warning when a DIFFERENT fixed port was requested — scrapes of
    the requested port would get connection refused), and a bind failure
    (``EADDRINUSE`` — e.g. a child process inheriting the parent's
    ``ACCELERATE_METRICS_PORT``) degrades to registry-only with a warning
    instead of killing engine construction."""
    global _SERVER, _SHUTTING_DOWN
    _SHUTTING_DOWN = False
    if _SERVER is not None:
        bound = _SERVER[0].server_address[1]
        if int(port) not in (0, bound):
            warnings.warn(
                f"metrics exporter already bound to port {bound}; "
                f"ignoring requested port {port}",
                stacklevel=2,
            )
        return _SERVER[0]
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    enable()

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (http.server API)
            path = self.path.split("?")[0]
            if path == "/healthz":
                # readiness, not content: external probes (k8s, the fleet
                # supervisor) ask this instead of scraping-and-parsing.
                # 200 while the registry is live, 503 once shutdown began
                # so load balancers stop routing before the socket dies.
                ok = _ACTIVE is not None and not _SHUTTING_DOWN
                body = (b"ok\n" if ok else b"shutting down\n")
                self.send_response(200 if ok else 503)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if path not in ("/metrics", "/"):
                self.send_response(404)
                self.end_headers()
                return
            body = (_ACTIVE.render() if _ACTIVE is not None else "").encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapes must not spam stderr
            pass

    try:
        server = ThreadingHTTPServer((host, int(port)), _Handler)
    except OSError as exc:
        warnings.warn(
            f"metrics exporter could not bind {host}:{port} ({exc}); "
            "serving disabled, registry stays armed",
            stacklevel=2,
        )
        return None
    thread = threading.Thread(
        target=server.serve_forever, name="accelerate-tpu-metrics", daemon=True
    )
    thread.start()
    _SERVER = (server, thread)
    return server


def server_port() -> Optional[int]:
    return _SERVER[0].server_address[1] if _SERVER is not None else None


def stop_server() -> None:
    global _SERVER, _SHUTTING_DOWN
    # flip readiness FIRST: a /healthz probe racing the shutdown sees 503
    # and stops routing before the socket actually closes
    _SHUTTING_DOWN = True
    if _SERVER is None:
        return
    server, thread = _SERVER
    _SERVER = None
    try:
        server.shutdown()
        server.server_close()
    except OSError:
        pass
    thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# scrape-side parsing (tests + doctor check 16 verify a live scrape against
# the report through this, not through a second ad-hoc parser)


def parse_prometheus_text(text: str) -> dict:
    """Parse the exposition format back into
    ``{name: {"type", "samples": [(labels, value)]}}`` — enough to rebuild a
    histogram (`*_bucket`/`*_sum`/`*_count` samples fold under the family
    name) and check counters/gauges."""
    families: dict = {}
    types: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            types[name] = kind
            continue
        if line.startswith("#"):
            continue
        name, labels, value = _parse_sample(line)
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in types:
                family = name[: -len(suffix)]
                break
        rec = families.setdefault(
            family, {"type": types.get(family, "untyped"), "samples": []}
        )
        rec["samples"].append((name, labels, value))
    return families


# one label pair: key="value" with \\, \" and \n escapes inside the value
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_sample(line: str) -> "tuple[str, dict, float]":
    if "{" in line:
        name, rest = line.split("{", 1)
        labels_s, value_s = rest.rsplit("}", 1)
        labels = {}
        for k, v in _LABEL_RE.findall(labels_s):
            labels[k] = (
                v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
            )
        return name.strip(), labels, float(value_s)
    name, value_s = line.rsplit(None, 1)
    return name.strip(), {}, float(value_s)


def histogram_from_scrape(families: dict, name: str) -> Optional[Histogram]:
    """Rebuild a :class:`Histogram` from parsed scrape samples so its
    :meth:`~Histogram.quantile` can be compared 1:1 with the report's."""
    fam = families.get(name)
    if fam is None or fam["type"] != "histogram":
        return None
    bounds: "list[float]" = []
    counts: "list[int]" = []
    total = 0
    total_sum = 0.0
    for sample_name, labels, value in fam["samples"]:
        if sample_name == f"{name}_bucket":
            le = labels.get("le", "")
            if le == "+Inf":
                total = int(value)
            else:
                bounds.append(float(le))
                counts.append(int(value))
        elif sample_name == f"{name}_count":
            total = int(value)
        elif sample_name == f"{name}_sum":
            total_sum = float(value)
    if not bounds:
        return None
    order = sorted(range(len(bounds)), key=lambda i: bounds[i])
    h = Histogram(name, buckets=tuple(bounds[i] for i in order))
    h._set_cumulative([counts[i] for i in order], total)
    h.sum = total_sum
    return h
