"""Request-scoped distributed tracing for the serving path.

"Where did request X spend its 900 ms p99" needs ONE coherent timeline per
request across router → replica → engine: admission/queue wait, each
dispatch (with retry lineage when a replica died mid-decode), every prefill
chunk (with prefix-cache hit / copy-on-write annotations), every batched
decode step, and completion. This module is the dependency-free span model
and context-propagation glue that builds it:

- **spans** are plain dicts — ``trace_id`` / ``span_id`` / ``parent_id``,
  ``name``, monotonic-ns ``t0_ns``/``t1_ns`` (the clock
  :func:`time.monotonic_ns`, the SAME timebase the step profiler and XLA
  trace windows stamp, so traces join by timestamp), plus free-form
  attributes. :func:`span_open` / :func:`span_close` / :func:`make_span`
  build them; holders (the router request, the engine request) accumulate
  them in a list.
- **context propagation** — a :class:`TraceContext` is a 3-field JSON-able
  dict (``trace_id``, ``parent_id``, ``sampled``) that rides the existing
  transports verbatim: the router puts it in the submit payload, the
  ``LocalReplica`` queue and the ``ProcessReplica`` JSON-lines pipe carry it
  untouched, and the engine parents its spans under ``parent_id``.
  Engine-side spans ship BACK over the same event stream (inside ``done``
  events) and the router emits the assembled trace — one writer per trace,
  so two processes never interleave one request's records.
- **sampling** — ``ACCELERATE_TRACE_SAMPLE`` arms the module (a rate in
  (0, 1]; unset/0 keeps every hot-path check a single ``is None`` branch).
  The keep/drop decision is per TRACE (deterministic in the trace id) and
  applied at EMIT time: armed code always records spans, and
  :func:`finish_trace` force-emits unsampled traces whose outcome is
  SHED/FAILED/EXPIRED or that survived a failover — the requests an
  operator is guaranteed to ask about.
- **export** — emitted spans are ``span`` telemetry records (they carry
  ``trace_id``, unlike the :meth:`EventLog.span <accelerate_tpu.telemetry.
  events.EventLog.span>` timing records); :func:`chrome_trace` converts a
  span list to a Chrome ``trace.json`` (the xplane chrome conventions —
  load it in ``chrome://tracing``/Perfetto next to an XLA window), and
  :func:`validate_span_tree` is the gap-free-tree oracle the tests and
  ``make doctor`` check 16 assert.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Iterable, Optional

from . import events as _events
from ..utils.environment import _TRUE, parse_optional_float_from_env

TRACE_SAMPLE_ENV_VAR = "ACCELERATE_TRACE_SAMPLE"

#: sample rate when armed; None = disarmed (the one-branch hot path)
_ARMED: Optional[float] = None
_ID_LOCK = threading.Lock()
_ID_COUNTER = 0


def is_armed() -> bool:
    return _ARMED is not None


def sample_rate() -> Optional[float]:
    return _ARMED


def arm(sample: float = 1.0) -> None:
    """Arm tracing at ``sample`` (a keep-fraction in (0, 1])."""
    global _ARMED
    if not (0.0 < sample <= 1.0):
        raise ValueError(f"sample must be in (0, 1], got {sample}")
    _ARMED = float(sample)


def disarm() -> None:
    global _ARMED
    _ARMED = None


def maybe_arm_from_env() -> Optional[float]:
    """Honor ``ACCELERATE_TRACE_SAMPLE``: a float rate, or a plain truthy
    value for 1.0. Unset/0/garbage leaves tracing disarmed."""
    raw = os.environ.get(TRACE_SAMPLE_ENV_VAR, "").strip().lower()
    if not raw:
        return _ARMED
    if raw in _TRUE:
        arm(1.0)
        return _ARMED
    rate = parse_optional_float_from_env(TRACE_SAMPLE_ENV_VAR)
    if rate is not None and 0.0 < rate <= 1.0:
        arm(rate)
    return _ARMED


# ---------------------------------------------------------------------------
# ids + context


def _new_id(bits: int = 64) -> str:
    """Unique hex id: entropy + a process-local counter (collision-proof
    within a process even if the entropy source repeats)."""
    global _ID_COUNTER
    with _ID_LOCK:
        _ID_COUNTER += 1
        n = _ID_COUNTER
    raw = int.from_bytes(os.urandom(bits // 8), "big") ^ (n << 1)
    return f"{raw & ((1 << bits) - 1):0{bits // 4}x}"


def _sampled(trace_id: str, rate: float) -> bool:
    """Deterministic per-trace keep/drop: the id's low 32 bits as a uniform
    draw — every component holding the same ctx agrees without coordination."""
    return (int(trace_id[-8:], 16) / float(1 << 32)) < rate


class TraceContext(dict):
    """The 3 fields that cross a transport: ``trace_id``, ``parent_id`` (the
    span new work should parent under), ``sampled``. It IS a dict so it
    serializes through the JSON-lines replica protocol verbatim."""

    @property
    def trace_id(self) -> str:
        return self["trace_id"]

    @property
    def parent_id(self) -> Optional[str]:
        return self.get("parent_id")

    @property
    def sampled(self) -> bool:
        return bool(self.get("sampled"))

    def child(self, parent_id: str) -> "TraceContext":
        """The ctx to hand the next hop: same trace, new parent span."""
        return TraceContext(self, parent_id=parent_id)

    @classmethod
    def from_wire(cls, payload) -> "Optional[TraceContext]":
        if not isinstance(payload, dict) or "trace_id" not in payload:
            return None
        return cls(payload)


def new_trace(sampled: Optional[bool] = None) -> TraceContext:
    """Root context for one request. ``sampled`` defaults to the armed
    rate's deterministic per-trace draw."""
    trace_id = _new_id()
    if sampled is None:
        sampled = _sampled(trace_id, _ARMED if _ARMED is not None else 0.0)
    return TraceContext(trace_id=trace_id, parent_id=None, sampled=bool(sampled))


# ---------------------------------------------------------------------------
# spans


def now_ns() -> int:
    return time.monotonic_ns()


def span_open(
    ctx: TraceContext, name: str, t0_ns: Optional[int] = None,
    parent_id: Optional[str] = None, **attrs: Any,
) -> dict:
    """Open span dict (no ``t1_ns`` yet); parent defaults to the context's
    ``parent_id`` (None = this is the trace root)."""
    span = {
        "trace_id": ctx["trace_id"],
        "span_id": _new_id(),
        "parent_id": parent_id if parent_id is not None else ctx.get("parent_id"),
        "name": name,
        "t0_ns": now_ns() if t0_ns is None else int(t0_ns),
    }
    if attrs:
        span["attrs"] = dict(attrs)
    return span


def span_close(span: dict, t1_ns: Optional[int] = None, **attrs: Any) -> dict:
    span["t1_ns"] = now_ns() if t1_ns is None else int(t1_ns)
    if span["t1_ns"] < span["t0_ns"]:  # monotone even under clock races
        span["t1_ns"] = span["t0_ns"]
    if attrs:
        span.setdefault("attrs", {}).update(attrs)
    return span


def make_span(
    ctx: TraceContext, name: str, t0_ns: int, t1_ns: int,
    parent_id: Optional[str] = None, **attrs: Any,
) -> dict:
    return span_close(span_open(ctx, name, t0_ns=t0_ns, parent_id=parent_id, **attrs),
                      t1_ns=t1_ns)


def emit_spans(spans: Iterable[dict]) -> int:
    """Write spans as ``span`` telemetry records (no-op while telemetry is
    off). Open spans are closed at emit time — a crash-path trace must not
    lose its last span to a missing ``t1_ns``."""
    n = 0
    for span in spans:
        if "t1_ns" not in span:
            span_close(span)
        _events.emit("span", **span)
        n += 1
    return n


def should_emit(ctx: Optional[TraceContext], forced: bool = False) -> bool:
    """The emit decision for one finished trace: sampled, or forced (bad
    outcome / failover survivor — always kept)."""
    if ctx is None:
        return False
    return forced or ctx.sampled


def finish_trace(ctx: Optional[TraceContext], spans: "list[dict]",
                 forced: bool = False) -> bool:
    """Emit the trace's spans iff sampled-or-forced; True when written."""
    if not should_emit(ctx, forced=forced) or not spans:
        return False
    emit_spans(spans)
    return True


# ---------------------------------------------------------------------------
# analysis / export


def spans_by_trace(events: Iterable[dict]) -> "dict[str, list[dict]]":
    """Group ``span`` telemetry records by trace id (input: the report
    loader's merged event list)."""
    traces: "dict[str, list[dict]]" = {}
    for e in events:
        if e.get("kind") == "span" and e.get("trace_id"):
            traces.setdefault(str(e["trace_id"]), []).append(e)
    for spans in traces.values():
        spans.sort(key=lambda s: int(s.get("t0_ns", 0)))
    return traces


def validate_span_tree(spans: "list[dict]") -> "list[str]":
    """Structural integrity of one trace: exactly one root, every
    ``parent_id`` resolvable, every span closed with ``t1_ns >= t0_ns``, and
    every child inside its parent's [t0, t1] window. Returns the list of
    violations — empty means the tree is gap-free (the doctor-16 oracle)."""
    problems: "list[str]" = []
    if not spans:
        return ["no spans"]
    by_id = {}
    for s in spans:
        sid = s.get("span_id")
        if sid is None:
            problems.append(f"span {s.get('name')} has no span_id")
            continue
        if sid in by_id:
            problems.append(f"duplicate span_id {sid}")
        by_id[sid] = s
    trace_ids = {s.get("trace_id") for s in spans}
    if len(trace_ids) != 1:
        problems.append(f"spans from {len(trace_ids)} traces: {sorted(map(str, trace_ids))}")
    roots = [s for s in spans if not s.get("parent_id")]
    if len(roots) != 1:
        problems.append(f"{len(roots)} root span(s), expected exactly 1")
    for s in spans:
        name = s.get("name", "?")
        if "t1_ns" not in s:
            problems.append(f"span {name} never closed")
            continue
        if int(s["t1_ns"]) < int(s["t0_ns"]):
            problems.append(f"span {name} ends before it starts")
        parent_id = s.get("parent_id")
        if parent_id:
            parent = by_id.get(parent_id)
            if parent is None:
                problems.append(f"span {name} orphaned: parent {parent_id} missing")
            elif "t1_ns" in parent and not (
                int(parent["t0_ns"]) <= int(s["t0_ns"])
                and int(s["t1_ns"]) <= int(parent["t1_ns"])
            ):
                problems.append(
                    f"span {name} escapes its parent {parent.get('name', '?')} window"
                )
    return problems


def span_children(spans: "list[dict]") -> "dict[Optional[str], list[dict]]":
    children: "dict[Optional[str], list[dict]]" = {}
    for s in sorted(spans, key=lambda x: int(x.get("t0_ns", 0))):
        children.setdefault(s.get("parent_id") or None, []).append(s)
    return children


def chrome_trace(spans: Iterable[dict]) -> dict:
    """Spans → Chrome ``trace.json``: complete ("ph": "X") events in
    microseconds on the shared monotonic timebase, one pid/tid lane per
    emitting component (the ``component`` attr; default the span name's
    prefix), so the export drops straight next to an XLA trace window."""
    trace_events = []
    tids: "dict[str, int]" = {}
    for s in spans:
        attrs = dict(s.get("attrs") or {})
        component = str(attrs.pop("component", s.get("name", "?").split(":")[0]))
        tid = tids.setdefault(component, len(tids) + 1)
        t0 = int(s.get("t0_ns", 0))
        t1 = int(s.get("t1_ns", t0))
        args = {
            "trace_id": s.get("trace_id"),
            "span_id": s.get("span_id"),
            "parent_id": s.get("parent_id"),
            **attrs,
        }
        trace_events.append(
            {
                "name": s.get("name", "?"),
                "ph": "X",
                "ts": t0 / 1e3,
                "dur": max(t1 - t0, 0) / 1e3,
                "pid": 1,
                "tid": tid,
                "args": args,
            }
        )
    trace_events.extend(
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
         "args": {"name": component}}
        for component, tid in tids.items()
    )
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def format_timeline(spans: "list[dict]") -> str:
    """Indented one-request timeline (the ``report --request`` rendering):
    offsets/durations in ms relative to the trace root."""
    if not spans:
        return "  (no spans)"
    children = span_children(spans)
    base = min(int(s.get("t0_ns", 0)) for s in spans)
    lines: "list[str]" = []

    def _walk(span: dict, depth: int) -> None:
        t0 = int(span.get("t0_ns", base))
        t1 = int(span.get("t1_ns", t0))
        attrs = span.get("attrs") or {}
        attr_s = ""
        if attrs:
            attr_s = "  [" + ", ".join(f"{k}={v}" for k, v in sorted(attrs.items())) + "]"
        lines.append(
            f"  {'  ' * depth}{span.get('name', '?'):<{max(2, 30 - 2 * depth)}} "
            f"+{(t0 - base) / 1e6:9.3f}ms  {(t1 - t0) / 1e6:9.3f}ms{attr_s}"
        )
        for child in children.get(span.get("span_id"), []):
            _walk(child, depth + 1)

    for root in children.get(None, []):
        _walk(root, 0)
    orphans = [
        s for s in spans
        if s.get("parent_id") and s["parent_id"] not in {x.get("span_id") for x in spans}
    ]
    for s in orphans:
        lines.append(f"  (orphan) {s.get('name', '?')}")
    return "\n".join(lines)
