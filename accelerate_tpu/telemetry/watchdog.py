"""Hang/straggler watchdog: a heartbeat thread that turns a silent stall into
a named, dumped, per-rank diagnosis.

Pod-scale TPU systems live or die by hang attribution: one rank blocked in a
collective blocks every rank, and the only symptom is "nothing is happening".
The watchdog watches two complementary liveness signals:

1. **Heartbeat sources** — components that should make regular progress
   register (:meth:`Watchdog.register`) and :meth:`Watchdog.beat` on each unit
   of work: the Accelerator's train step beats per step, the data-loader's
   prefetch producer beats per produced batch. A source whose last beat is
   older than the timeout is stalled — and because each source is named, a
   stuck *producer* is distinguishable from a stuck *collective*.
2. **Open phases** — blocking regions annotated via
   :func:`flight_recorder.phase` (collectives in ``utils/operations.py``,
   backend init in the bench probe, data fetch in the loader). A phase older
   than the timeout means a thread is blocked *inside* it; the stall report
   names it (``collective:gather``), which is the answer a hang report needs.

On a stall the watchdog emits a ``watchdog_stall`` event, writes the flight
record (ring buffer + all-thread stacks + open phases, see
:mod:`.flight_recorder`), hard-flushes the EventLog, and — when
``abort_on_stall`` — exits the process with code 101 so an orchestrator
restarts the rank instead of wedging the pod.

Each check interval also emits one ``heartbeat`` record (step, source ages,
open phases) into the JSONL stream when telemetry is enabled; the report CLI's
``--by-rank`` view merges these into per-rank heartbeat-gap timelines.

Two GIL escape hatches for hangs a Python thread cannot observe: the loop
re-arms ``faulthandler.dump_traceback_later`` as a dead-man switch (if the
watchdog thread itself is starved — a C call holding the GIL — the C-level
dumper still writes all-thread stacks to ``watchdog-rank<k>.stacks``), and
``flight_recorder.install`` separately covers SIGSEGV/SIGABRT.

Disabled-path contract: nothing here starts a thread or opens a file unless
:func:`start` (or ``ACCELERATE_WATCHDOG_TIMEOUT`` > 0 via
:func:`maybe_start_from_env`) asks; the hot-path helpers (:func:`beat`) are a
single ``is None`` check while inactive.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Optional

from . import events as tel
from . import flight_recorder

WATCHDOG_TIMEOUT_ENV_VAR = "ACCELERATE_WATCHDOG_TIMEOUT"
WATCHDOG_INTERVAL_ENV_VAR = "ACCELERATE_WATCHDOG_INTERVAL"
WATCHDOG_ABORT_ENV_VAR = "ACCELERATE_WATCHDOG_ABORT"
HEARTBEAT_FILE_ENV_VAR = "ACCELERATE_HEARTBEAT_FILE"

_TRUE = {"1", "true", "yes", "y", "on"}
# RESERVED: "stall abort". A rank exiting 101 dumped a stall diagnosis and
# aborted itself; the elastic supervisor (resilience/supervisor.py
# classify_exit) maps it to restart-with-dump-link. Nothing else in this
# codebase may exit with 101.
ABORT_EXIT_CODE = 101


def env_timeout() -> float:
    """``ACCELERATE_WATCHDOG_TIMEOUT`` in seconds; 0.0 (disabled) when unset
    or malformed. Same parser as ``WatchdogConfig.timeout`` so the env-armed
    and config-armed paths can never disagree on the same variable."""
    # lazy import: utils/__init__ pulls in operations -> telemetry, so a
    # module-level import here would re-enter a partially initialized package
    from ..utils.environment import parse_seconds_from_env

    return parse_seconds_from_env(WATCHDOG_TIMEOUT_ENV_VAR)


class Watchdog:
    """One heartbeat/stall-detection thread for this process."""

    def __init__(
        self,
        timeout: float,
        interval: Optional[float] = None,
        abort_on_stall: bool = False,
        out_dir: Optional[str] = None,
    ):
        if timeout <= 0:
            raise ValueError(f"watchdog timeout must be > 0 seconds, got {timeout}")
        self.timeout = float(timeout)
        self.interval = (
            float(interval) if interval else max(0.05, min(self.timeout / 4.0, 5.0))
        )
        self.abort_on_stall = bool(abort_on_stall)
        self.out_dir = out_dir
        self.stall_count = 0
        self.dump_paths: "list[str]" = []
        self._sources: "dict[str, list]" = {}  # name -> [last_beat_t, info, stalled]
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stacks_file = None
        self._dumped_phases: "set[tuple]" = set()
        # Out-of-process liveness channel (the elastic supervisor watches this
        # file's mtime): every tick touches it, so a stale mtime means even
        # the watchdog thread is dead — a hang class no exit code can report.
        self.heartbeat_file = os.environ.get(HEARTBEAT_FILE_ENV_VAR, "").strip() or None

    # ------------------------------------------------------------- liveness --
    def register(self, name: str, **info: Any) -> None:
        """Start watching a named progress source; its clock starts now."""
        with self._lock:
            self._sources[name] = [time.monotonic(), dict(info), False]

    def unregister(self, name: str) -> None:
        """Stop watching a source (clean shutdown is not a stall)."""
        with self._lock:
            self._sources.pop(name, None)

    def beat(self, name: str, **info: Any) -> None:
        """Record progress for ``name`` (auto-registers on first beat)."""
        with self._lock:
            rec = self._sources.get(name)
            if rec is None:
                self._sources[name] = [time.monotonic(), dict(info), False]
                return
            rec[0] = time.monotonic()
            if info:
                rec[1].update(info)
            rec[2] = False  # a beat ends any stall episode

    def sources(self) -> "dict[str, dict]":
        """``{name: {"age_s": ..., **info}}`` snapshot."""
        now = time.monotonic()
        with self._lock:
            return {
                name: {"age_s": round(now - rec[0], 3), **rec[1]}
                for name, rec in self._sources.items()
            }

    # ------------------------------------------------------------ lifecycle --
    def start(self) -> "Watchdog":
        if self._thread is not None:
            return self
        self.out_dir = self.out_dir or flight_recorder.get_recorder()._resolve_out_dir()
        try:
            from ..state import process_identity

            rank = process_identity().get("process_index", 0)
            os.makedirs(self.out_dir, exist_ok=True)
            self._stacks_file = open(
                os.path.join(self.out_dir, f"watchdog-rank{rank}.stacks"), "a"
            )
        except OSError:
            self._stacks_file = None
        self._touch_heartbeat_file()  # exists-from-start: a supervisor can
        # tell "never armed" from "armed then went silent"
        self._arm_deadman()
        self._thread = threading.Thread(
            target=self._run, name="accelerate-tpu-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval * 4 + 1.0)
            self._thread = None
        try:
            import faulthandler

            faulthandler.cancel_dump_traceback_later()
        except Exception:
            pass
        if self._stacks_file is not None:
            try:
                self._stacks_file.close()
            except OSError:
                pass
            self._stacks_file = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # ----------------------------------------------------------- internals --
    def _arm_deadman(self) -> None:
        # GIL-proof backstop: if THIS thread stops being scheduled (a C call
        # holding the GIL), faulthandler's C-level timer still dumps stacks.
        # Re-armed every tick, so it only fires when the loop is starved.
        try:
            import faulthandler

            faulthandler.dump_traceback_later(
                self.timeout + 4 * self.interval,
                file=self._stacks_file or sys.stderr,
            )
        except Exception:
            pass

    def _touch_heartbeat_file(self) -> None:
        if self.heartbeat_file is None:
            return
        try:
            with open(self.heartbeat_file, "a"):
                pass
            os.utime(self.heartbeat_file, None)
        except OSError:
            pass  # liveness reporting must never kill the watchdog

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self._arm_deadman()
            self._touch_heartbeat_file()
            try:
                self._tick()
            except Exception:  # the watchdog must outlive anything it watches
                pass

    def _tick(self) -> None:
        now = time.monotonic()
        stalls: "list[dict]" = []
        with self._lock:
            for name, rec in self._sources.items():
                age = now - rec[0]
                if age > self.timeout and not rec[2]:
                    rec[2] = True  # one dump per stall episode
                    stalls.append(
                        {"what": "source", "name": name, "age_s": round(age, 3), **rec[1]}
                    )
        phases = flight_recorder.current_phases()
        for thread_name, ph in phases.items():
            if ph.get("age_s", 0.0) <= self.timeout:
                continue
            key = (ph.get("thread_id"), ph.get("phase"), ph.get("enter_t"))
            if key in self._dumped_phases:
                continue
            self._dumped_phases.add(key)
            stalls.append(
                {
                    "what": "phase",
                    "name": ph.get("phase"),
                    "thread": thread_name,
                    "age_s": ph.get("age_s"),
                }
            )
        if len(self._dumped_phases) > 4096:  # bound memory across a long run
            self._dumped_phases.clear()
        tel.emit(
            "heartbeat",
            step=flight_recorder.get_recorder().step,
            sources={n: s["age_s"] for n, s in self.sources().items()},
            phases={t: {"phase": p["phase"], "age_s": p["age_s"]} for t, p in phases.items()},
        )
        if stalls:
            self._handle_stalls(stalls)

    def _handle_stalls(self, stalls: "list[dict]") -> None:
        descs = "; ".join(
            f"{s['what']} '{s['name']}' stalled for {s['age_s']:.1f}s"
            + (f" in thread {s['thread']}" if s.get("thread") else "")
            for s in stalls
        )
        reason = f"watchdog: {descs} (timeout {self.timeout:g}s)"
        tel.emit("watchdog_stall", reason=reason, stalls=stalls)
        flight_recorder.record("watchdog_stall", reason=reason)
        path = flight_recorder.dump(
            reason,
            out_dir=self.out_dir,
            extra={
                "watchdog": {
                    "timeout_s": self.timeout,
                    "stalls": stalls,
                    "sources": self.sources(),
                }
            },
        )
        self.stall_count += 1
        if path:
            self.dump_paths.append(path)
        print(
            f"[accelerate-tpu watchdog] {reason}"
            + (f" — flight record: {path}" if path else ""),
            file=sys.stderr,
            flush=True,
        )
        if self.abort_on_stall:
            tel.hard_flush()
            os._exit(ABORT_EXIT_CODE)


# ---------------------------------------------------------------------------
# Module-level singleton + zero-overhead shims (same contract as events.py:
# every helper is one ``is None`` check while no watchdog is active).

_ACTIVE: Optional[Watchdog] = None


def start(
    timeout: Optional[float] = None,
    interval: Optional[float] = None,
    abort_on_stall: Optional[bool] = None,
    out_dir: Optional[str] = None,
) -> Watchdog:
    """Start the process watchdog (idempotent: returns the active one).
    ``timeout`` defaults from ``ACCELERATE_WATCHDOG_TIMEOUT``."""
    global _ACTIVE
    if _ACTIVE is not None:
        return _ACTIVE
    if timeout is None:
        timeout = env_timeout()
    if interval is None:
        raw = os.environ.get(WATCHDOG_INTERVAL_ENV_VAR, "").strip()
        if raw:
            try:
                interval = float(raw)
            except ValueError:
                interval = None
    if abort_on_stall is None:
        abort_on_stall = os.environ.get(WATCHDOG_ABORT_ENV_VAR, "").strip().lower() in _TRUE
    _ACTIVE = Watchdog(
        timeout, interval=interval, abort_on_stall=abort_on_stall, out_dir=out_dir
    ).start()
    return _ACTIVE


def stop() -> None:
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.stop()
        _ACTIVE = None


def maybe_start_from_env(out_dir: Optional[str] = None) -> Optional[Watchdog]:
    """Start iff ``ACCELERATE_WATCHDOG_TIMEOUT`` > 0 and none is active yet.
    Returns None — no thread, no file — otherwise."""
    if _ACTIVE is not None:
        return _ACTIVE
    timeout = env_timeout()
    if timeout <= 0:
        return None
    return start(timeout=timeout, out_dir=out_dir)


def get_watchdog() -> Optional[Watchdog]:
    return _ACTIVE


def is_active() -> bool:
    return _ACTIVE is not None


def beat(name: str, **info: Any) -> None:
    if _ACTIVE is not None:
        _ACTIVE.beat(name, **info)


def register(name: str, **info: Any) -> None:
    if _ACTIVE is not None:
        _ACTIVE.register(name, **info)


def unregister(name: str) -> None:
    if _ACTIVE is not None:
        _ACTIVE.unregister(name)
