"""Flight recorder: an always-on in-memory ring of recent events plus crash
handlers that turn a dead process into a post-mortem artifact.

The JSONL event log (:mod:`.events`) explains a *slow* step; this module
explains a *dead* one. Three observed failure modes motivate it (bench rounds
3-5): a TPU probe that "hung past 150s (killed)" with zero evidence of where,
a SIGTERM from the driver that took the buffered event log with it, and
multihost stalls with no per-rank visibility.

Design contract:

- **The ring is always on.** :func:`record` appends a small dict to a bounded
  ``deque`` — no lock, no syscall, no file — so the last
  ``ACCELERATE_FLIGHT_CAPACITY`` (default 256) events exist in memory even
  when JSONL telemetry is disabled. A dump written seconds after a hang
  therefore shows the *minutes before* it.
- **Phases name what a thread is blocked in.** ``with phase("collective:gather")``
  marks a region a thread may block inside (collectives, backend init, data
  fetch). :func:`current_phases` reports each thread's innermost open phase
  and its age — the watchdog (:mod:`.watchdog`) uses exactly this to say
  *which collective* a rank is stuck in.
- **Crash handlers are opt-in** (:func:`install`): a SIGTERM handler and an
  ``sys.excepthook`` wrapper dump ``flight-rank<k>.json`` and hard-flush the
  EventLog before the process dies; ``faulthandler`` is enabled against
  ``crash-rank<k>.stacks`` for the signals Python-level JSON cannot survive
  (SIGSEGV/SIGABRT). Nothing is installed — no handler, no thread, no file —
  until :func:`install` (or the Accelerator, when forensics are enabled) asks.

The dump itself (:meth:`FlightRecorder.dump`) contains the ring, all-thread
Python stacks, the current step and open phases, a device-memory snapshot
(only when a jax backend is *already* initialized — dumping must never touch a
possibly-hung backend), and the rank/host identity from
:func:`accelerate_tpu.state.process_identity`.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Optional

from . import events as tel

FLIGHT_ENV_VAR = "ACCELERATE_FLIGHT"
FLIGHT_DIR_ENV_VAR = "ACCELERATE_FLIGHT_DIR"
FLIGHT_CAPACITY_ENV_VAR = "ACCELERATE_FLIGHT_CAPACITY"
FLIGHT_SCHEMA_VERSION = 1
FLIGHT_FILE_PREFIX = "flight-rank"

_TRUE = {"1", "true", "yes", "y", "on"}


def _default_capacity() -> int:
    try:
        return max(16, int(os.environ.get(FLIGHT_CAPACITY_ENV_VAR, 256)))
    except (TypeError, ValueError):
        return 256


class _Phase:
    """Open-region marker: records enter/exit in the ring and exposes the
    region to :func:`current_phases` while a thread is inside it."""

    __slots__ = ("rec", "name", "attrs", "t0", "ident")

    def __init__(self, rec: "FlightRecorder", name: str, attrs: dict):
        self.rec = rec
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0
        self.ident = 0

    def __enter__(self) -> "_Phase":
        self.ident = threading.get_ident()
        self.t0 = time.monotonic()
        # per-thread stack: only this thread appends/pops its own list, so no
        # lock is needed; readers (watchdog/dump) take snapshots
        self.rec._phases.setdefault(self.ident, []).append(self)
        self.rec.record("phase_enter", name=self.name, **self.attrs)
        return self

    def __exit__(self, *exc) -> bool:
        stack = self.rec._phases.get(self.ident)
        if stack and stack[-1] is self:
            stack.pop()
        self.rec.record(
            "phase_exit", name=self.name, dur_s=round(time.monotonic() - self.t0, 6)
        )
        return False


class FlightRecorder:
    """Bounded in-memory event ring + dump/crash-handler machinery for one
    process. Normally used through the module-level singleton
    (:func:`get_recorder` / :func:`record` / :func:`phase`)."""

    def __init__(self, capacity: Optional[int] = None):
        self.events: deque = deque(maxlen=capacity or _default_capacity())
        self.step: Optional[int] = None
        self.out_dir: Optional[str] = None
        self.meta: dict = {}
        self.dump_count = 0
        self.last_dump_path: Optional[str] = None
        self._phases: "dict[int, list[_Phase]]" = {}
        # rolling fingerprint of the (collective, shapes, dtypes) sequence —
        # the runtime cross-check for jaxlint R4: ranks whose fingerprints
        # diverge took different collective schedules (deadlock imminent)
        self.collective_count = 0
        self.collective_hash = 0
        self.collective_recent: deque = deque(maxlen=64)
        self._collective_lock = threading.Lock()
        self._installed = False
        self._prev_sigterm = None
        self._prev_excepthook = None
        self._crash_stacks_file = None
        self._prev_faulthandler_enabled = False

    # ------------------------------------------------------------ recording --
    def record(self, kind: str, **fields: Any) -> None:
        """Append one event to the ring. Allocation-cheap and thread-safe
        (``deque.append`` is atomic); never touches a file."""
        rec: dict = {"t": round(time.monotonic(), 6), "kind": kind}
        if self.step is not None:
            rec["step"] = self.step
        if fields:
            rec.update(fields)
        self.events.append(rec)

    def set_step(self, step: Optional[int]) -> None:
        self.step = step

    def record_collective(self, op: str, signature: str) -> None:
        """Fold one collective call into the rank's schedule fingerprint.

        ``signature`` describes the payload (shapes/dtypes). The hash rolls
        over the ordered ``op|signature`` sequence, so two ranks have
        equal hashes iff they issued the same collectives with the same
        payload shapes in the same order — exactly the property a divergent
        ``if is_main_process: gather(...)`` breaks. A bounded window of
        recent entries rides along so a ``--by-rank`` report can name the
        first differing call, not just that they differ.

        Locked: callers are single-threaded in the multihost configurations
        that matter (the dispatcher downgrades prefetch to sync under
        num_processes > 1 exactly so collectives stay ordered on one
        thread), but a lost read-modify-write from an unconventional caller
        must corrupt nothing. The rolling hash is ``zlib.crc32`` with the
        previous hash as the seed — C speed (a params-sized signature costs
        microseconds, not a per-byte Python loop under the lock) and
        deterministic across processes, which the cross-rank comparison
        requires."""
        import zlib

        payload = f"{op}|{signature}".encode()
        with self._collective_lock:
            h = zlib.crc32(payload, self.collective_hash) & 0xFFFFFFFF
            self.collective_count += 1
            self.collective_hash = h
            self.collective_recent.append(
                {
                    "seq": self.collective_count,
                    "op": op,
                    "sig": signature,
                    "hash": f"{h:08x}",
                }
            )

    def collective_schedule(self) -> dict:
        # the dump path must NEVER deadlock: a SIGTERM handler runs on the
        # main thread, which may already hold the lock inside
        # record_collective — timeout and fall back to a best-effort read
        # rather than hang the crash handler
        acquired = self._collective_lock.acquire(timeout=0.5)
        try:
            return {
                "count": self.collective_count,
                "hash": f"{self.collective_hash:08x}",
                "recent": list(self.collective_recent),
            }
        finally:
            if acquired:
                self._collective_lock.release()

    def phase(self, name: str, **attrs: Any) -> _Phase:
        """``with recorder.phase("collective:gather", op="gather"): ...`` —
        annotate a region this thread may block in."""
        return _Phase(self, name, attrs)

    def current_phases(self) -> "dict[str, dict]":
        """Innermost open phase per thread: ``{thread_name: {"phase", "age_s",
        "thread_id", ...attrs}}``. Safe to call from any thread."""
        now = time.monotonic()
        names = {t.ident: t.name for t in threading.enumerate()}
        out: dict = {}
        for ident, stack in list(self._phases.items()):
            try:
                ph = stack[-1]
            except IndexError:  # owner thread popped between check and read
                continue
            key = names.get(ident, f"thread-{ident}")
            if key in out:  # same-named threads (e.g. two prefetch producers)
                key = f"{key}#{ident}"
            out[key] = {
                "phase": ph.name,
                "age_s": round(now - ph.t0, 3),
                "enter_t": round(ph.t0, 6),
                "thread_id": ident,
                **ph.attrs,
            }
        return out

    def snapshot(self) -> "list[dict]":
        # deque.append is atomic, but iterating while another thread appends
        # raises RuntimeError — retry; the ring is bounded so a quiet window
        # always comes
        for _ in range(8):
            try:
                return list(self.events)
            except RuntimeError:
                continue
        return []

    # ----------------------------------------------------------------- dump --
    @staticmethod
    def _thread_stacks() -> "list[dict]":
        names = {t.ident: (t.name, t.daemon) for t in threading.enumerate()}
        out = []
        for ident, frame in sys._current_frames().items():
            name, daemon = names.get(ident, (f"thread-{ident}", None))
            out.append(
                {
                    "thread_id": ident,
                    "name": name,
                    "daemon": daemon,
                    "stack": traceback.format_stack(frame),
                }
            )
        return out

    @staticmethod
    def _memory_snapshot() -> Optional[dict]:
        """Memory view IF it can be taken without waking a possibly-hung
        backend: device stats only when a jax backend already exists."""
        snap: dict = {}
        try:
            from .memory import host_memory_bytes

            snap["host_rss_bytes"] = host_memory_bytes()
        except Exception:
            pass
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                from jax._src import xla_bridge

                initialized = bool(getattr(xla_bridge, "_backends", None))
            except Exception:
                initialized = False
            if initialized:
                try:
                    from .memory import device_memory_stats, live_array_bytes

                    snap["live_array_bytes"] = live_array_bytes()
                    snap["devices"] = device_memory_stats()
                except Exception:
                    pass
        return snap or None

    def _resolve_out_dir(self, out_dir: Optional[str] = None) -> str:
        if out_dir:
            return out_dir
        if self.out_dir:
            return self.out_dir
        env = os.environ.get(FLIGHT_DIR_ENV_VAR) or os.environ.get(
            tel.TELEMETRY_DIR_ENV_VAR
        )
        if env:
            return env
        log = tel.get_event_log()
        if log is not None:
            return log.out_dir
        return "telemetry"

    def dump(
        self, reason: str, out_dir: Optional[str] = None, extra: Optional[dict] = None
    ) -> Optional[str]:
        """Write ``flight-rank<k>.json`` (atomic replace) and hard-flush the
        EventLog. Returns the path, or None — a dump must never raise into the
        crashing/watching code path."""
        def _part(fn, default):
            # one torn section (a racing thread, a sick backend) must not cost
            # the whole post-mortem
            try:
                return fn()
            except Exception:
                return default

        try:
            from ..state import process_identity

            ident = dict(process_identity())
            ident.update(self.meta)
            out_dir = self._resolve_out_dir(out_dir)
            payload = {
                "kind": "flight_record",
                "schema": FLIGHT_SCHEMA_VERSION,
                "reason": reason,
                "unix_time": time.time(),
                "t": round(time.monotonic(), 6),
                "meta": ident,
                "step": self.step,
                "phases": _part(self.current_phases, {}),
                "events": _part(self.snapshot, []),
                "threads": _part(self._thread_stacks, []),
                "memory": _part(self._memory_snapshot, None),
                "collective_schedule": _part(self.collective_schedule, None),
            }
            if extra:
                payload.update(extra)
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(
                out_dir, f"{FLIGHT_FILE_PREFIX}{ident.get('process_index', 0)}.json"
            )
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
            self.dump_count += 1
            self.last_dump_path = path
        except Exception:
            return None
        finally:
            try:
                tel.hard_flush()
            except Exception:
                pass
        return path

    # ------------------------------------------------------- crash handlers --
    def install(self, out_dir: Optional[str] = None, meta: Optional[dict] = None) -> None:
        """Arm the crash handlers (idempotent): SIGTERM → dump + chain,
        unhandled exception → dump + chain, SIGSEGV/SIGABRT/... → faulthandler
        stacks into ``crash-rank<k>.stacks``."""
        if out_dir:
            self.out_dir = out_dir
        if meta:
            self.meta.update(meta)
        if self._installed:
            return
        self._installed = True

        def _on_sigterm(signum, frame):
            self.record("signal", signum=signum)
            self.dump(f"signal {signal.Signals(signum).name}")
            prev = self._prev_sigterm
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)  # die with the signal's exit status

        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:  # not the main thread: signal handlers unavailable
            self._prev_sigterm = None

        prev_hook = sys.excepthook
        self._prev_excepthook = prev_hook

        def _on_exception(exc_type, exc, tb):
            self.dump(f"unhandled exception: {exc_type.__name__}: {exc}")
            prev_hook(exc_type, exc, tb)

        sys.excepthook = _on_exception

        try:
            import faulthandler

            from ..state import process_identity

            self._prev_faulthandler_enabled = faulthandler.is_enabled()
            rank = process_identity().get("process_index", 0)
            stacks_dir = self._resolve_out_dir()
            os.makedirs(stacks_dir, exist_ok=True)
            self._crash_stacks_file = open(
                os.path.join(stacks_dir, f"crash-rank{rank}.stacks"), "a"
            )
            faulthandler.enable(file=self._crash_stacks_file)
        except Exception:
            self._crash_stacks_file = None
        atexit.register(self._at_exit)

    def _at_exit(self) -> None:
        # normal exits are not crashes: no dump, but nothing may stay buffered
        self.record("atexit")
        try:
            tel.hard_flush()
        except Exception:
            pass

    def uninstall(self) -> None:
        """Restore the pre-install handlers (tests / explicit teardown)."""
        if not self._installed:
            return
        self._installed = False
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        if self._crash_stacks_file is not None:
            try:
                import faulthandler

                if self._prev_faulthandler_enabled:
                    faulthandler.enable()  # restore the user's stderr handler
                else:
                    faulthandler.disable()
                self._crash_stacks_file.close()
            except Exception:
                pass
            self._crash_stacks_file = None

    @property
    def installed(self) -> bool:
        return self._installed


# ---------------------------------------------------------------------------
# Module-level singleton: the ring exists from import (it is just a deque);
# handlers/dirs are configured by install().

_RECORDER = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _RECORDER


def record(kind: str, **fields: Any) -> None:
    _RECORDER.record(kind, **fields)


def set_step(step: Optional[int]) -> None:
    _RECORDER.step = step


def phase(name: str, **attrs: Any) -> _Phase:
    return _RECORDER.phase(name, **attrs)


def record_collective(op: str, signature: str) -> None:
    _RECORDER.record_collective(op, signature)


def current_phases() -> "dict[str, dict]":
    return _RECORDER.current_phases()


def dump(reason: str, out_dir: Optional[str] = None, extra: Optional[dict] = None):
    return _RECORDER.dump(reason, out_dir=out_dir, extra=extra)


def install(out_dir: Optional[str] = None, meta: Optional[dict] = None) -> FlightRecorder:
    _RECORDER.install(out_dir=out_dir, meta=meta)
    return _RECORDER


def uninstall() -> None:
    _RECORDER.uninstall()


def installed() -> bool:
    return _RECORDER.installed


def enabled_from_env() -> bool:
    """Forensics opt-in: ``ACCELERATE_FLIGHT`` truthy or a flight dir given."""
    if os.environ.get(FLIGHT_ENV_VAR, "").strip().lower() in _TRUE:
        return True
    return bool(os.environ.get(FLIGHT_DIR_ENV_VAR))


def iter_flight_files(paths) -> "list[str]":
    """All ``flight-rank*.json`` files under the given dirs (files pass
    through) — the report CLI's merge input."""
    files: list[str] = []
    for path in paths:
        if os.path.isdir(path):
            files.extend(
                sorted(
                    os.path.join(path, name)
                    for name in os.listdir(path)
                    if name.startswith(FLIGHT_FILE_PREFIX) and name.endswith(".json")
                )
            )
        elif os.path.basename(path).startswith(FLIGHT_FILE_PREFIX) and path.endswith(
            ".json"
        ):
            files.append(path)
    return files


def load_flight_records(paths) -> "list[dict]":
    records: list[dict] = []
    for file in iter_flight_files(paths):
        try:
            with open(file) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(rec, dict):
            rec.setdefault("_file", os.path.basename(file))
            records.append(rec)
    return records
