"""Mirror telemetry summaries into the existing experiment trackers.

Users already have a logging destination (``tracking.py``: JSONL, TensorBoard,
W&B, ...). This bridge flattens the report aggregates into scalar metrics under
a ``telemetry/`` prefix and hands them to every tracker's ``log_telemetry``
(default implementation: ``log``), so step-time percentiles, recompile counts
and comms traffic land wherever the user's metrics already go — no second
dashboard to remember.
"""

from __future__ import annotations

from typing import Optional

from . import events as tel
from .report import build_report


def summary_metrics(report: Optional[dict] = None, out_dir: Optional[str] = None) -> "dict[str, float]":
    """Flatten a telemetry report (built from ``out_dir`` or the active event
    log's directory when not given) into scalar metrics. Empty dict when there
    is nothing to summarize."""
    if report is None:
        if out_dir is None:
            log = tel.get_event_log()
            if log is None:
                return {}
            log.flush()
            out_dir = log.out_dir
        report = build_report([out_dir])
    if not report.get("steps", {}).get("count") and not report.get("events"):
        return {}
    flat: dict = {}
    steps = report["steps"]
    flat["telemetry/steps"] = steps["count"]
    for key in ("wall_s", "data_wait_s", "execute_s"):
        dist = steps.get(key) or {}
        for stat in ("p50", "p90", "p99", "mean", "max"):
            if stat in dist:
                flat[f"telemetry/{key}_{stat}"] = dist[stat]
    flat["telemetry/compile_s_total"] = steps.get("compile_s_total", 0.0)
    flat["telemetry/recompiles"] = report["recompiles"]["total"]
    for name, count in report["recompiles"]["by_fn"].items():
        if count:
            flat[f"telemetry/recompiles/{name}"] = count
    mem = report["memory"]
    flat["telemetry/device_peak_bytes"] = mem["device_peak_bytes"]
    flat["telemetry/live_array_peak_bytes"] = mem["live_array_peak_bytes"]
    flat["telemetry/host_rss_peak_bytes"] = mem["host_rss_peak_bytes"]
    comms = report["comms"]
    flat["telemetry/comm_calls"] = comms["total_calls"]
    flat["telemetry/comm_bytes"] = comms["total_bytes"]
    for op, rec in comms["by_op"].items():
        flat[f"telemetry/comm_bytes/{op}"] = rec["bytes"]
    return flat


def mirror_to_trackers(trackers, summary: Optional[dict] = None, step: Optional[int] = None,
                       out_dir: Optional[str] = None) -> "dict[str, float]":
    """Push the flattened summary into every tracker; returns what was logged."""
    flat = summary if summary is not None else summary_metrics(out_dir=out_dir)
    if not flat:
        return {}
    for tracker in trackers:
        log_fn = getattr(tracker, "log_telemetry", None) or tracker.log
        log_fn(flat, step=step)
    return flat
