"""Aggregate telemetry JSONL streams into a human/driver-readable report.

``python -m accelerate_tpu.telemetry report <dir-or-file>...`` reads every
``*.jsonl`` stream (one per rank), merges them, and prints:

- per-step wall-time / data-wait / execute percentiles (p50/p90/p99),
- compile totals and the recompile count per compiled function — a nonzero
  recompile total after warmup is the classic silent reshape cliff,
- a data-pipeline section: per-phase input wait (fetch / transfer / stall),
  prefetch queue occupancy and the overlap ratio — how much of the input
  pipeline was hidden behind device compute,
- a checkpoints section: saves, bytes written, per-phase time
  (snapshot / serialize / write / commit / backpressure) and the
  exposed-vs-hidden split — how many checkpoint seconds the train loop
  actually paid vs how many the async writer overlapped,
- a performance section (telemetry/perf.py + xplane.py): per-step MFU
  distribution and first→last trend, a per-function roofline table (XLA
  cost-analysis FLOPs, arithmetic intensity, compute-vs-HBM-bound bucket,
  projected memory fit), and the trace-window accounting — top-k op/fusion
  durations, the compute/collective/idle device-time split and the
  comms-overlap ratio,
- device/host memory peaks,
- comms traffic per collective op (calls + payload bytes),
- per-rank event counts and the dropped-event total in the header — silent
  data loss must read as a warning, not as "clean run".

``--by-rank`` adds the cross-rank forensics section: per-step rank skew with
slowest-rank attribution (the straggler), per-rank heartbeat-gap timelines
from the watchdog's records, and merged ``flight-rank<k>.json`` crash/hang
post-mortems. ``--json`` emits the raw report dict for drivers. The
``doctor`` subcommand self-checks the forensics pipeline end to end
(flight dump → watchdog stall detection → straggler report).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Iterable, Optional

# THE percentile/histogram implementations live in telemetry.metrics — the
# report re-exports `percentile` for its callers but owns no private math
# (tests/test_observability.py ratchets that across the repo)
from . import goodput as _goodput
from . import regress as _regress
from .metrics import hist_dist, percentile

PERCENTILES = (50, 90, 99)


def iter_event_files(paths: Iterable[str]) -> "list[str]":
    files: list[str] = []
    for path in paths:
        if os.path.isdir(path):
            files.extend(
                sorted(
                    os.path.join(path, name)
                    for name in os.listdir(path)
                    if name.endswith(".jsonl")
                )
            )
        else:
            files.append(path)
    return files


def load_events(paths: Iterable[str]) -> "list[dict]":
    events: list[dict] = []
    for file in iter_event_files(paths):
        try:
            with open(file) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail line from a killed run
                    if isinstance(rec, dict):
                        rec.setdefault("_file", os.path.basename(file))
                        events.append(rec)
        except OSError:
            continue
    return events


def _dist(values: "list[float]") -> dict:
    values = sorted(values)
    if not values:
        return {"count": 0}
    return {
        "count": len(values),
        "mean": round(sum(values) / len(values), 6),
        "max": round(values[-1], 6),
        # presorted: one sort per distribution, not four
        **{f"p{p}": round(percentile(values, p, presorted=True), 6) for p in PERCENTILES},
    }


def _rank_of_event(event: dict, file_rank: "dict[str, int]") -> Optional[int]:
    """Rank attribution for a merged event: the stream's ``meta`` record wins,
    the ``events-rank<k>`` filename is the fallback for torn streams whose
    meta line never made it to disk."""
    file = event.get("_file")
    if file in file_rank:
        return file_rank[file]
    m = re.search(r"rank(\d+)", file or "")
    return int(m.group(1)) if m else None


def _per_rank_counts(events: "list[dict]", file_rank: "dict[str, int]") -> "dict":
    per_rank: dict = {}
    for e in events:
        rank = _rank_of_event(e, file_rank)
        key = "?" if rank is None else str(rank)
        rec = per_rank.setdefault(key, {"events": 0, "dropped": 0})
        rec["events"] += 1
        if e.get("kind") == "dropped":
            rec["dropped"] += int(e.get("count", 0))
    return dict(sorted(per_rank.items()))


def _collective_divergence(schedules: "dict[int, dict]") -> Optional[dict]:
    """Cross-rank comparison of the flight recorder's collective-schedule
    fingerprints — the runtime confirmation of a jaxlint R4 finding.

    Equal (count, hash) across ranks means every rank issued the same
    collectives with the same payload shapes in the same order. On mismatch,
    the overlapping portions of the per-rank ``recent`` windows name the
    first differing call when the divergence is recent enough to still be
    in the window."""
    if len(schedules) < 2:
        return None
    per_rank = {
        str(r): {"count": s.get("count", 0), "hash": s.get("hash")}
        for r, s in sorted(schedules.items())
    }
    out: dict = {"per_rank": per_rank, "diverged": False}

    # a rank dumped before its first collective has an (empty) schedule that
    # is trivially a prefix of every other — exclude it from the comparison
    # (but DON'T let it mask divergence among the remaining ranks)
    compared = {r: s for r, s in schedules.items() if s.get("count", 0) > 0}
    zero_ranks = sorted(set(schedules) - set(compared))
    if zero_ranks:
        out["prefix_skew"] = {
            str(r): s.get("count", 0) for r, s in sorted(schedules.items())
        }
    if len(compared) < 2:
        return out

    hashes = {(s.get("count", 0), s.get("hash")) for s in compared.values()}
    if len(hashes) <= 1:
        return out

    # count skew alone is not divergence: dumps are taken at slightly
    # different moments, so a healthy run shows one rank a call or two
    # ahead with an IDENTICAL common prefix. The per-seq cumulative hashes
    # in the recent windows let us check: if every compared rank agrees on
    # the hash at the minimum common count, the shorter schedules are
    # prefixes of the longer ones.
    counts = [s.get("count", 0) for s in compared.values()]
    min_count = min(counts)
    hash_at_min: "dict[int, str]" = {}
    for rank, sched in compared.items():
        if sched.get("count", 0) == min_count and sched.get("hash"):
            hash_at_min[rank] = sched["hash"]
        else:
            for entry in sched.get("recent") or []:
                if entry.get("seq") == min_count:
                    hash_at_min[rank] = entry.get("hash")
                    break
    prefix_provable = len(hash_at_min) == len(compared)
    if (
        prefix_provable
        and len(set(hash_at_min.values())) == 1
        and len(set(counts)) > 1
    ):
        skew = {
            str(r): s.get("count", 0) - min_count for r, s in sorted(compared.items())
        }
        out["prefix_skew"] = {**out.get("prefix_skew", {}), **skew}
        return out

    # align recent windows by seq and find the first disagreement visible
    by_seq: "dict[int, dict]" = {}
    for rank, sched in compared.items():
        for entry in sched.get("recent") or []:
            seq = entry.get("seq")
            if seq is None:
                continue
            by_seq.setdefault(int(seq), {})[rank] = (
                entry.get("op"),
                entry.get("sig"),
            )
    first = None
    for seq in sorted(by_seq):
        calls = by_seq[seq]
        if len(calls) >= 2 and len(set(calls.values())) > 1:
            first = {
                "seq": seq,
                "calls": {
                    str(r): {"op": op, "sig": sig}
                    for r, (op, sig) in sorted(calls.items())
                },
            }
            break
    if len(set(counts)) > 1:
        out["count_skew"] = {
            str(r): s.get("count", 0) for r, s in sorted(compared.items())
        }
    if first is not None:
        out["diverged"] = True  # a same-seq call provably differs
        out["first_divergence"] = first
    elif prefix_provable and len(set(hash_at_min.values())) > 1:
        # the cumulative hashes at the minimum common count disagree:
        # provably divergent at or before that call, even though the
        # differing entry itself rotated out of every window
        out["diverged"] = True
        out["first_divergence"] = None
    elif len(set(counts)) == 1:
        # equal lengths, unequal hashes: provably divergent even though the
        # differing call has rotated out of every window
        out["diverged"] = True
        out["first_divergence"] = None
    else:
        # counts differ and the skew outran the recent windows: cannot
        # distinguish dump-timing skew from divergence — report as
        # indeterminate rather than crying deadlock on a healthy run
        out["indeterminate"] = True
    return out


def _rank_section(events: "list[dict]", file_rank: "dict[str, int]", paths) -> dict:
    """Cross-rank straggler forensics: per-step skew + slowest-rank
    attribution, heartbeat-gap timelines, and merged flight records."""
    from .flight_recorder import load_flight_records

    steps_by_rank: "dict[int, dict[int, float]]" = {}
    mfu_by_rank: "dict[int, list[float]]" = {}
    heartbeats: "dict[int, list[float]]" = {}
    ranks: "dict[int, dict]" = {}
    for e in events:
        rank = _rank_of_event(e, file_rank)
        if rank is None:
            continue
        info = ranks.setdefault(rank, {"events": 0, "steps": 0, "dropped": 0})
        info["events"] += 1
        kind = e.get("kind")
        if kind == "step":
            info["steps"] += 1
            if e.get("step") is not None:
                steps_by_rank.setdefault(rank, {})[int(e["step"])] = float(
                    e.get("dur_s", 0.0)
                )
            if e.get("mfu") is not None:
                mfu_by_rank.setdefault(rank, []).append(float(e["mfu"]))
        elif kind == "heartbeat":
            heartbeats.setdefault(rank, []).append(float(e.get("t", 0.0)))
        elif kind == "dropped":
            info["dropped"] += int(e.get("count", 0))

    # per-step skew over the steps at least two ranks both measured
    per_step: "list[dict]" = []
    slowest_counts: "dict[int, int]" = {}
    excess: "dict[int, list[float]]" = {}
    all_steps = sorted({s for per in steps_by_rank.values() for s in per})
    for s in all_steps:
        durs = {r: per[s] for r, per in steps_by_rank.items() if s in per}
        if len(durs) < 2:
            continue
        slowest = max(durs, key=durs.get)
        fastest_dur = min(durs.values())
        slowest_counts[slowest] = slowest_counts.get(slowest, 0) + 1
        for r, d in durs.items():
            excess.setdefault(r, []).append(d - fastest_dur)
        per_step.append(
            {
                "step": s,
                "skew_s": round(durs[slowest] - fastest_dur, 6),
                "slowest_rank": slowest,
                "durs_s": {str(r): round(d, 6) for r, d in sorted(durs.items())},
            }
        )
    straggler = None
    if slowest_counts:
        rank = max(slowest_counts, key=slowest_counts.get)
        exc = excess.get(rank, [])
        straggler = {
            "rank": rank,
            "slowest_steps": slowest_counts[rank],
            "steps_compared": len(per_step),
            "mean_excess_s": round(sum(exc) / len(exc), 6) if exc else 0.0,
        }

    heartbeat_gaps: dict = {}
    for rank, ts in sorted(heartbeats.items()):
        ts = sorted(ts)
        gaps = [b - a for a, b in zip(ts, ts[1:])]
        heartbeat_gaps[str(rank)] = {
            "beats": len(ts),
            "max_gap_s": round(max(gaps), 6) if gaps else 0.0,
            "p50_gap_s": round(percentile(sorted(gaps), 50), 6) if gaps else 0.0,
        }

    flights = []
    schedules: "dict[int, dict]" = {}
    for rec in load_flight_records(paths):
        phases = rec.get("phases") or {}
        rank = (rec.get("meta") or {}).get("process_index")
        flights.append(
            {
                "file": rec.get("_file"),
                "rank": rank,
                "reason": rec.get("reason"),
                "step": rec.get("step"),
                "phases": {
                    t: {"phase": p.get("phase"), "age_s": p.get("age_s")}
                    for t, p in phases.items()
                },
            }
        )
        sched = rec.get("collective_schedule")
        if rank is not None and isinstance(sched, dict):
            schedules[int(rank)] = sched

    return {
        "per_rank": {
            str(r): dict(
                info,
                wall_s=_dist(list(steps_by_rank.get(r, {}).values())),
                mfu=_dist(mfu_by_rank.get(r, [])),
            )
            for r, info in sorted(ranks.items())
        },
        "steps_compared": len(per_step),
        "skew_s": _dist([p["skew_s"] for p in per_step]),
        "worst_steps": sorted(per_step, key=lambda p: -p["skew_s"])[:5],
        "slowest_counts": {str(r): n for r, n in sorted(slowest_counts.items())},
        "straggler": straggler,
        "heartbeat_gaps": heartbeat_gaps,
        "flight_records": flights,
        "collective_divergence": _collective_divergence(schedules),
    }


def _performance_section(events: "list[dict]", steps: "list[dict]") -> Optional[dict]:
    """MFU/roofline/trace attribution (telemetry/perf.py + xplane.py):
    ``None`` when the streams predate the performance layer (no ``perf`` /
    ``trace`` records and no step carries ``mfu``)."""
    perfs = [e for e in events if e.get("kind") == "perf"]
    traces = [e for e in events if e.get("kind") == "trace" and not e.get("error")]
    projections = [e for e in events if e.get("kind") == "memory_projection"]
    mfu_steps = [s for s in steps if s.get("mfu") is not None]
    if not perfs and not traces and not mfu_steps:
        return None

    proj_by_fn = {str(p.get("fn", "?")): p for p in projections}
    by_fn: dict = {}
    for p in perfs:
        fn = str(p.get("fn", "?"))
        rec = {
            "flops": float(p.get("flops", 0.0)),
            "bytes_accessed": float(p.get("bytes_accessed", 0.0)),
            "arithmetic_intensity": p.get("arithmetic_intensity"),
            "roofline": p.get("roofline"),
            "peak_flops": p.get("peak_flops"),
            "peak_hbm_bytes_per_s": p.get("peak_hbm_bytes_per_s"),
            "peak_source": p.get("peak_source"),
            "device_kind": p.get("device_kind"),
        }
        proj = proj_by_fn.get(fn)
        if proj:
            rec["projected_peak_bytes"] = proj.get("projected_peak_bytes")
            rec["memory_fits"] = proj.get("fits")
        by_fn[fn] = rec
    for fn, rec in by_fn.items():
        rec["mfu"] = _dist(
            [float(s["mfu"]) for s in mfu_steps if s.get("perf_fn") == fn]
        )

    mfus = [float(s["mfu"]) for s in mfu_steps]
    trend = None
    if len(mfus) >= 2:
        half = len(mfus) // 2
        first = sum(mfus[:half]) / half
        last = sum(mfus[half:]) / (len(mfus) - half)
        trend = {
            "first_half_mean": round(first, 6),
            "second_half_mean": round(last, 6),
            "delta": round(last - first, 6),
        }

    trace_section = None
    if traces:
        top: dict = {}
        for t in traces:
            for op in t.get("top_ops") or []:
                rec = top.setdefault(
                    str(op.get("op", "?")),
                    {"op": str(op.get("op", "?")), "total_s": 0.0, "count": 0,
                     "collective": bool(op.get("collective"))},
                )
                rec["total_s"] += float(op.get("total_s", 0.0))
                rec["count"] += int(op.get("count", 0))
        collective_s = sum(float(t.get("collective_s", 0.0)) for t in traces)
        overlap_s = sum(float(t.get("collective_overlap_s", 0.0)) for t in traces)
        op_total = sum(r["total_s"] for r in top.values())
        top_ops = sorted(top.values(), key=lambda r: -r["total_s"])[:10]
        for rec in top_ops:
            rec["total_s"] = round(rec["total_s"], 6)
            rec["share"] = round(rec["total_s"] / op_total, 4) if op_total else 0.0
        trace_section = {
            "windows": len(traces),
            "events": sum(int(t.get("events", 0)) for t in traces),
            "compute_s": round(sum(float(t.get("compute_s", 0.0)) for t in traces), 6),
            "collective_s": round(collective_s, 6),
            "idle_s": round(sum(float(t.get("idle_s", 0.0)) for t in traces), 6),
            "collective_overlap_s": round(overlap_s, 6),
            "comms_overlap_ratio": round(overlap_s / collective_s, 4) if collective_s else None,
            "top_ops": top_ops,
        }

    return {
        "mfu": _dist(mfus),
        "mfu_trend": trend,
        "by_fn": dict(sorted(by_fn.items())),
        "trace": trace_section,
        "trace_errors": sum(1 for e in events if e.get("kind") == "trace" and e.get("error")),
    }


def _spec_decode_dist(steps: "list[dict]") -> Optional[dict]:
    """Aggregate the speculative-decoding fields of ``serving`` step records
    (``serving/engine.py``): draft proposed/accepted token totals, the accept
    rate, and the per-slot-step accepted-count histogram (index = draft
    tokens accepted, summed elementwise over the per-step deltas). ``None``
    when no step carried spec-decode fields (the engine ran without it)."""
    proposed = sum(int(s.get("draft_proposed_tokens", 0)) for s in steps)
    accepted = sum(int(s.get("draft_accepted_tokens", 0)) for s in steps)
    hist: "list[int]" = []
    for s in steps:
        h = s.get("spec_accept_hist")
        if not isinstance(h, list):
            continue
        if len(h) > len(hist):
            hist += [0] * (len(h) - len(hist))
        for i, c in enumerate(h):
            hist[i] += int(c)
    if not hist and not proposed:
        return None
    return {
        "draft_proposed_tokens": proposed,
        "draft_accepted_tokens": accepted,
        "draft_rejected_tokens": proposed - accepted,
        "accept_rate": round(accepted / proposed, 6) if proposed else 0.0,
        "accept_hist": hist,
    }


def _serving_section(events: "list[dict]") -> Optional[dict]:
    """Aggregate the serving engine's per-step ``serving`` records and
    per-completion ``serving_request`` records (``serving/engine.py``):
    queue depth / batch occupancy / block-pool distributions, the
    prefill-vs-decode token split, aggregate decode tokens/s over the record
    span, and per-request latency + time-to-first-token percentiles.
    ``None`` when the streams carry no serving records."""
    steps = [e for e in events if e.get("kind") == "serving" and e.get("phase") == "step"]
    reqs = [e for e in events if e.get("kind") == "serving_request"]
    if not steps and not reqs:
        return None
    decode_tokens = sum(int(s.get("decode_tokens", 0)) for s in steps)
    prefill_tokens = sum(int(s.get("prefill_tokens", 0)) for s in steps)
    # prompt tokens served straight from the prefix cache (prefill skipped);
    # hit rate is over ALL prompt tokens = saved / (saved + prefilled)
    prefix_hit_tokens = sum(int(s.get("prefix_hit_tokens", 0)) for s in steps)
    prompt_tokens = prefix_hit_tokens + prefill_tokens
    ts = sorted(float(s.get("t", 0.0)) for s in steps)
    span = ts[-1] - ts[0] if len(ts) >= 2 else 0.0
    completed = [r for r in reqs if not r.get("error")]
    section = {
        "steps": len(steps),
        "queue_depth": _dist([float(s.get("queue_depth", 0)) for s in steps]),
        "occupancy": _dist([float(s.get("occupancy", 0.0)) for s in steps]),
        "block_occupancy": _dist([float(s.get("block_occupancy", 0.0)) for s in steps]),
        "fragmentation": _dist([float(s.get("fragmentation", 0.0)) for s in steps]),
        "decode_tokens": decode_tokens,
        "prefill_tokens": prefill_tokens,
        "prefill_tokens_saved": prefix_hit_tokens,
        "prefix_hit_rate": (
            round(prefix_hit_tokens / prompt_tokens, 6) if prompt_tokens else 0.0
        ),
        "tokens_per_s": round(decode_tokens / span, 2) if span > 0 else None,
        "preemptions": max((int(s.get("preemptions", 0)) for s in steps), default=0),
        "spec_decode": _spec_decode_dist(steps),
        "requests": {
            "completed": len(completed),
            "rejected": sum(1 for r in reqs if r.get("error")),
            "preempted": sum(1 for r in completed if r.get("preemptions")),
            "new_tokens": sum(int(r.get("new_tokens", 0)) for r in completed),
            # latency/ttft go through the SHARED fixed-bucket histogram
            # (telemetry.metrics), so these percentiles are bit-identical to
            # what a live /metrics scrape of the same run computes
            "latency_s": hist_dist(
                [float(r["latency_s"]) for r in completed if r.get("latency_s") is not None]
            ),
            "ttft_s": hist_dist(
                [float(r["ttft_s"]) for r in completed if r.get("ttft_s") is not None]
            ),
        },
    }
    return section


def _slo_section(events: "list[dict]") -> Optional[dict]:
    """Aggregate ``slo_violation`` records (``telemetry/slo.py``): one per
    burn-episode ENTRY, so the count is "how many times did we start burning
    through the budget", with the worst observed burn rates per objective.
    ``None`` when the streams carry no SLO records — runs without a monitor
    armed don't grow an empty section."""
    violations = [e for e in events if e.get("kind") == "slo_violation"]
    if not violations:
        return None
    by_slo: dict = {}
    for v in violations:
        name = str(v.get("slo", "?"))
        rec = by_slo.setdefault(
            name,
            {
                "violations": 0,
                "kind": v.get("slo_kind"),
                "target": v.get("target"),
                "threshold_s": v.get("threshold_s"),
                "burn_threshold": v.get("burn_threshold"),
                "worst_fast_burn": 0.0,
                "worst_slow_burn": 0.0,
                "fast_window_s": v.get("fast_window_s"),
                "slow_window_s": v.get("slow_window_s"),
            },
        )
        rec["violations"] += 1
        rec["worst_fast_burn"] = max(rec["worst_fast_burn"], float(v.get("fast_burn", 0.0)))
        rec["worst_slow_burn"] = max(rec["worst_slow_burn"], float(v.get("slow_burn", 0.0)))
    return {"violations": len(violations), "by_slo": dict(sorted(by_slo.items()))}


def format_slo_section(slo: dict) -> str:
    """Human rendering of the SLO burn-rate violations (see
    ``docs/observability.md`` for how to write an objective)."""
    lines = [f"SLO: {slo['violations']} violation episode(s)"]
    for name, rec in (slo.get("by_slo") or {}).items():
        target = rec.get("target")
        thr = rec.get("threshold_s")
        obj = f"{target:.2%} good" if target is not None else "?"
        if thr is not None:
            obj += f" @ {thr * 1e3:.0f}ms"
        lines.append(
            f"  {name}: {rec['violations']} episode(s) — objective {obj}, worst "
            f"burn fast={rec['worst_fast_burn']:.1f}x slow={rec['worst_slow_burn']:.1f}x "
            f"(threshold {rec.get('burn_threshold')}x over "
            f"{rec.get('fast_window_s', 0) / 60:.0f}m/{rec.get('slow_window_s', 0) / 60:.0f}m)"
        )
    return "\n".join(lines)


def _compile_cache_section(events: "list[dict]") -> Optional[dict]:
    """Aggregate the persistent compile cache's ``compile_cache`` records
    (``compile_cache/runtime.py``): hit/miss/store/corrupt/fallback counts,
    bytes loaded+stored, load seconds saved into milliseconds paid, and the
    per-function outcome table. ``None`` when the streams carry no cache
    records. A nonzero ``corrupt`` count names quarantined entries — the run
    survived them by fallback compiles, but the operator should look."""
    all_recs = [e for e in events if e.get("kind") == "compile_cache"]
    # supervisor pre-touch probes carry `status` instead of `event`
    pretouch = [str(r.get("status")) for r in all_recs if r.get("status")]
    recs = [r for r in all_recs if r.get("event")]
    degraded = any(s in ("missing", "readonly", "error") for s in pretouch)
    if not recs and not degraded:
        # an unconfigured/healthy pre-touch alone is not a cache story —
        # don't grow every supervised run's report with an empty section
        return None
    by_event: dict = {}
    by_fn: dict = {}
    quarantined: "list[str]" = []
    bytes_loaded = 0
    bytes_stored = 0
    load_s = 0.0
    for r in recs:
        ev = str(r.get("event", "?"))
        by_event[ev] = by_event.get(ev, 0) + 1
        fn = str(r.get("fn", "?"))
        by_fn.setdefault(fn, {})[ev] = by_fn.setdefault(fn, {}).get(ev, 0) + 1
        if ev == "hit":
            bytes_loaded += int(r.get("bytes", 0) or 0)
            load_s += float(r.get("load_s", 0.0) or 0.0)
        elif ev.startswith("store"):
            bytes_stored += int(r.get("bytes", 0) or 0)
        if ev == "corrupt" and r.get("quarantined_to"):
            quarantined.append(str(r["quarantined_to"]))
    pretouch_counts: dict = {}
    for s in pretouch:
        pretouch_counts[s] = pretouch_counts.get(s, 0) + 1
    return {
        "events": len(all_recs),
        "pretouch": dict(sorted(pretouch_counts.items())),
        "hits": by_event.get("hit", 0),
        "misses": by_event.get("miss", 0),
        "stores": by_event.get("store", 0),
        "corrupt": by_event.get("corrupt", 0),
        "fallbacks": by_event.get("fallback", 0),
        "by_event": dict(sorted(by_event.items())),
        "by_fn": dict(sorted(by_fn.items())),
        "bytes_loaded": bytes_loaded,
        "bytes_stored": bytes_stored,
        "load_s": round(load_s, 6),
        "quarantined": quarantined,
    }


def _router_section(events: "list[dict]") -> Optional[dict]:
    """Aggregate the serving router's ``router`` records (``phase: "poll"``
    carries cumulative counters, ``phase: "request"`` one terminal outcome
    per request) and per-replica ``serving_replica`` records
    (``serving/router.py``): replica health table, dispatch/failover totals,
    shed/expired attribution, and finished-request latency percentiles.
    ``None`` when the streams carry no router records."""
    polls = [e for e in events if e.get("kind") == "router" and e.get("phase") == "poll"]
    reqs = [e for e in events if e.get("kind") == "router" and e.get("phase") == "request"]
    reps = [e for e in events if e.get("kind") == "serving_replica"]
    handoffs = [e for e in events if e.get("kind") == "kv_handoff"]
    if not polls and not reqs and not reps:
        return None
    outcomes: dict = {}
    shed_reasons: dict = {}
    for r in reqs:
        outcome = str(r.get("outcome", "?"))
        outcomes[outcome] = outcomes.get(outcome, 0) + 1
        if outcome == "shed" and r.get("error"):
            reason = str(r["error"]).split("shed: ", 1)[-1]
            shed_reasons[reason] = shed_reasons.get(reason, 0) + 1
    finished = [r for r in reqs if r.get("outcome") == "finished"]

    def _cum(key: str) -> int:
        # poll records carry cumulative counters; fall back to per-request
        # outcome counts when only request records made it into the stream
        if polls:
            return max(int(p.get(key, 0)) for p in polls)
        return 0

    # request-record reconstructions for poll-less streams (must stay
    # consistent with `requests.retried` — a section claiming retries
    # happened with zero failovers would read as data loss)
    retries_total = sum(int(r.get("retries", 0)) for r in reqs)
    ran = [r for r in reqs if r.get("outcome") in ("finished", "failed") and r.get("replica")]

    replicas: dict = {}
    for r in reps:
        name = str(r.get("replica", "?"))
        rec = replicas.setdefault(
            name,
            {"state": "?", "role": "serving", "dispatched": 0, "completed": 0,
             "failovers": 0},
        )
        rec["state"] = str(r.get("state", rec["state"]))  # records are in order
        if r.get("role"):
            rec["role"] = str(r["role"])
        for key in ("dispatched", "completed", "failovers"):
            if r.get(key) is not None:
                rec[key] = max(rec[key], int(r[key]))

    # -- disaggregated tiers: only when any record carries the role/handoff
    # markers (monolithic streams keep the old shape + a None tiers key) ------
    disagg_reqs = [r for r in reqs if r.get("prefill_replica")]
    tiers = None
    if handoffs or disagg_reqs or any(
        rec["role"] in ("prefill", "decode") for rec in replicas.values()
    ):
        ho_outcomes: dict = {}
        for h in handoffs:
            o = str(h.get("outcome", "?"))
            ho_outcomes[o] = ho_outcomes.get(o, 0) + 1
        disagg_finished = [r for r in disagg_reqs if r.get("outcome") == "finished"]
        tiers = {
            "prefill_replicas": sorted(
                n for n, rec in replicas.items() if rec["role"] == "prefill"
            ),
            "decode_replicas": sorted(
                n for n, rec in replicas.items() if rec["role"] != "prefill"
            ),
            "handoffs": len(handoffs),
            "handoff_outcomes": dict(sorted(ho_outcomes.items())),
            "handoff_blocks": sum(int(h.get("blocks", 0)) for h in handoffs),
            "handoff_bytes": sum(int(h.get("bytes", 0)) for h in handoffs),
            # the prefill hop's dispatch->handoff wall time, per finished
            # request — the decode hop is latency_s minus this
            "prefill_s": hist_dist(
                [float(r["prefill_s"]) for r in disagg_finished
                 if r.get("prefill_s") is not None]
            ),
            "disagg_finished": len(disagg_finished),
        }
    return {
        "polls": len(polls),
        "queue_depth": _dist([float(p.get("queued", 0)) for p in polls]),
        "dispatched": _cum("dispatched") or len(ran) + retries_total,
        "completed": _cum("completed") or outcomes.get("finished", 0),
        "failovers": _cum("failovers") or retries_total,
        "shed": _cum("shed") or outcomes.get("shed", 0),
        "expired": _cum("expired") or outcomes.get("expired", 0),
        "failed": _cum("failed") or outcomes.get("failed", 0),
        "outcomes": dict(sorted(outcomes.items())),
        "shed_reasons": dict(sorted(shed_reasons.items())),
        "requests": {
            "finished": len(finished),
            "retried": sum(1 for r in finished if int(r.get("retries", 0)) > 0),
            # the shared fixed-bucket histogram (telemetry.metrics): report
            # percentiles == live-scrape percentiles over the same events
            "latency_s": hist_dist(
                [float(r["latency_s"]) for r in finished if r.get("latency_s") is not None]
            ),
            "ttft_s": hist_dist(
                [float(r["ttft_s"]) for r in finished if r.get("ttft_s") is not None]
            ),
        },
        "replicas": dict(sorted(replicas.items())),
        "tiers": tiers,
    }


def _autoscaler_section(events: "list[dict]") -> Optional[dict]:
    """Aggregate the :class:`~accelerate_tpu.serving.autoscaler.
    AutoscalerPolicy`'s ``autoscale`` records: every scale decision with its
    trigger objective, and for each join whether it was warm (zero compiles,
    thanks to pre-shipping) plus its time-to-ready. ``None`` when the stream
    carries no autoscale records."""
    recs = [e for e in events if e.get("kind") == "autoscale"]
    if not recs:
        return None
    actions: dict = {}
    for r in recs:
        a = str(r.get("action", "?"))
        actions[a] = actions.get(a, 0) + 1
    joins = [r for r in recs if r.get("action") == "join_ready"]
    warm = sum(1 for j in joins if j.get("warm"))
    return {
        "actions": dict(sorted(actions.items())),
        "scale_ups": actions.get("scale_up", 0),
        "scale_downs": actions.get("scale_down", 0),
        "joins": {
            "ready": len(joins),
            "failed": actions.get("join_failed", 0),
            "warm": warm,
            "cold": len(joins) - warm,
            "compiles": sum(int(j.get("join_compiles", 0)) for j in joins),
            "time_to_ready_s": _dist(
                [float(j["time_to_ready_s"]) for j in joins
                 if j.get("time_to_ready_s") is not None]
            ),
        },
        "events": [
            {
                k: r.get(k)
                for k in ("action", "replica", "trigger", "fast_burn", "warm",
                          "join_compiles", "time_to_ready_s", "idle_s", "reason")
                if r.get(k) is not None
            }
            for r in recs
        ],
    }


def build_report(paths: Iterable[str], by_rank: bool = False) -> dict:
    events = load_events(paths)
    return build_report_from_events(events, by_rank=by_rank, paths=paths)


def build_report_from_events(
    events: "list[dict]", by_rank: bool = False, paths: Optional[Iterable[str]] = None
) -> dict:
    """Build the report from already-loaded records.

    This is THE aggregation path: :func:`build_report` is ``load_events``
    plus this, and the live hub (:mod:`.hub`) feeds its tailed stream
    through the same function — the shared-formatter invariant (live and
    post-hoc views render the same numbers for the same records) holds
    because there is only one fold. Records must be in per-file order
    (``load_events`` and the hub's tailing both guarantee that; sections
    only rely on within-file ordering)."""
    metas = [e for e in events if e.get("kind") == "meta"]
    steps = [e for e in events if e.get("kind") == "step"]
    misses = [e for e in events if e.get("kind") == "jit_cache_miss"]
    memory = [e for e in events if e.get("kind") == "memory"]
    comms = [e for e in events if e.get("kind") == "comm"]
    waits = [e for e in events if e.get("kind") == "data_wait"]

    file_rank = {
        m["_file"]: int(m["process_index"])
        for m in metas
        if m.get("_file") and m.get("process_index") is not None
    }
    per_rank_events = _per_rank_counts(events, file_rank)

    by_fn: dict = {}
    for m in misses:
        fn = str(m.get("fn", "?"))
        by_fn[fn] = by_fn.get(fn, 0) + int(m.get("recompiles", 0))
    comm_ops: dict = {}
    for c in comms:
        op = str(c.get("op", "?"))
        rec = comm_ops.setdefault(op, {"calls": 0, "bytes": 0})
        rec["calls"] += 1
        rec["bytes"] += int(c.get("bytes", 0))

    # -- data pipeline: per-phase waits + prefetch overlap --------------------
    by_phase: dict = {}
    critical_wait = 0.0
    for w in waits:
        phase = str(w.get("phase", "?"))
        dur = float(w.get("dur_s", 0.0))
        by_phase.setdefault(phase, []).append(dur)
        # records predating the async pipeline carry no flag: they were
        # synchronous, i.e. critical
        if w.get("critical", True):
            critical_wait += dur
    summaries = [e for e in events if e.get("kind") == "prefetch_summary"]
    occupancy = [
        float(e.get("value", 0))
        for e in events
        if e.get("kind") == "gauge" and e.get("name") == "prefetch_queue"
    ]
    prefetch: dict = {
        "epochs": len(summaries),
        "batches": sum(int(s.get("batches", 0)) for s in summaries),
        "fetch_s": round(sum(float(s.get("fetch_s", 0.0)) for s in summaries), 6),
        "transfer_s": round(sum(float(s.get("transfer_s", 0.0)) for s in summaries), 6),
        "stall_s": round(sum(float(s.get("stall_s", 0.0)) for s in summaries), 6),
        "queue_occupancy": _dist(occupancy),
    }
    busy = prefetch["fetch_s"] + prefetch["transfer_s"]
    if busy > 0:
        prefetch["overlap_ratio"] = round(
            max(0.0, min(1.0, 1.0 - prefetch["stall_s"] / busy)), 6
        )

    # -- checkpoints: per-phase time, exposed (train loop blocked) vs hidden --
    ckpts = [e for e in events if e.get("kind") == "checkpoint"]
    ck_phases: dict = {}
    ck_exposed = 0.0
    ck_hidden = 0.0
    for c in ckpts:
        phase = str(c.get("phase", "?"))
        dur = float(c.get("dur_s", 0.0))
        ck_phases.setdefault(phase, []).append(dur)
        # records predating the async writer carry no flag: they were
        # synchronous, i.e. exposed stall on the train loop
        if c.get("hidden", False):
            ck_hidden += dur
        else:
            ck_exposed += dur
    checkpoints = {
        "saves": sum(1 for c in ckpts if c.get("phase") == "commit" and c.get("committed", True)),
        "bytes": sum(int(c.get("bytes", 0)) for c in ckpts if c.get("phase") == "write"),
        "exposed_s": round(ck_exposed, 6),
        "hidden_s": round(ck_hidden, 6),
        "phases": {
            p: dict(_dist(v), total=round(sum(v), 6)) for p, v in sorted(ck_phases.items())
        },
    }

    report = {
        "schema": max((int(m.get("schema", 0)) for m in metas), default=0),
        "runs": sorted({str(m.get("run_id")) for m in metas if m.get("run_id")}),
        "processes": len({m.get("process_index") for m in metas}) or None,
        "events": len(events),
        "per_rank_events": per_rank_events,
        "dropped_events": sum(r["dropped"] for r in per_rank_events.values()),
        "steps": {
            "count": len(steps),
            "wall_s": _dist([float(s.get("dur_s", 0.0)) for s in steps]),
            "data_wait_s": _dist([float(s.get("data_wait_s", 0.0)) for s in steps]),
            "execute_s": _dist([float(s.get("execute_s", 0.0)) for s in steps]),
            "compile_s_total": round(sum(float(s.get("compile_s", 0.0)) for s in steps), 6),
        },
        "recompiles": {
            "total": sum(by_fn.values()),
            "initial_compiles": sum(1 for m in misses if m.get("first")),
            "by_fn": dict(sorted(by_fn.items())),
        },
        "memory": {
            "device_peak_bytes": max((int(m.get("device_peak_bytes", 0)) for m in memory), default=0),
            "live_array_peak_bytes": max((int(m.get("live_array_bytes", 0)) for m in memory), default=0),
            "host_rss_peak_bytes": max((int(m.get("host_rss_bytes", 0)) for m in memory), default=0),
        },
        "comms": {
            "total_calls": sum(r["calls"] for r in comm_ops.values()),
            "total_bytes": sum(r["bytes"] for r in comm_ops.values()),
            "by_op": dict(sorted(comm_ops.items())),
        },
        "data_pipeline": {
            "phases": {
                p: dict(_dist(v), total=round(sum(v), 6)) for p, v in sorted(by_phase.items())
            },
            "critical_wait_s": round(critical_wait, 6),
            "prefetch": prefetch,
        },
        "data_wait_events": len(waits),
        "checkpoints": checkpoints,
        "performance": _performance_section(events, steps),
        "serving": _serving_section(events),
        "router": _router_section(events),
        "autoscaler": _autoscaler_section(events),
        "slo": _slo_section(events),
        # trace roots only: legacy EventLog.span timing records share the
        # kind but carry no trace_id
        "traces": sum(
            1 for e in events
            if e.get("kind") == "span" and e.get("trace_id") and not e.get("parent_id")
        ),
        "restarts": _restarts_section(events),
        "compile_cache": _compile_cache_section(events),
        "anomalies": _anomaly_section(events),
        "canary": _canary_section(events),
        "goodput": _goodput.build_ledger(events, by_rank=by_rank),
    }
    if by_rank:
        report["ranks"] = _rank_section(events, file_rank, paths or [])
    return report


def _anomaly_section(events: "list[dict]") -> dict:
    """Fold the online detectors' ``anomaly`` records (:mod:`.anomaly`):
    episode counts per detector plus the most recent episode's cause
    hypothesis — the post-hoc trace of what the live plane paged on."""
    recs = [e for e in events if e.get("kind") == "anomaly"]
    by_det: dict = {}
    for r in recs:
        det = str(r.get("detector", "?"))
        ent = by_det.setdefault(det, {"episodes": 0, "last": None})
        ent["episodes"] += 1
        ent["last"] = {
            "value": r.get("value"),
            "z": r.get("z"),
            "slope": r.get("slope"),
            "cause": r.get("cause"),
            "source": r.get("source"),
        }
    return {"episodes": len(recs), "by_detector": dict(sorted(by_det.items()))}


def _canary_section(events: "list[dict]") -> dict:
    """Fold the router's ``canary`` / ``canary_failure`` records
    (:mod:`accelerate_tpu.serving.canary`): per-replica probe pass/fail
    tallies and the named bitwise mismatches."""
    probes = [e for e in events if e.get("kind") == "canary"]
    failures = [e for e in events if e.get("kind") == "canary_failure"]
    by_replica: dict = {}
    for p in probes:
        name = str(p.get("replica", "?"))
        ent = by_replica.setdefault(name, {"probes": 0, "failures": 0})
        ent["probes"] += 1
        if p.get("result") == "mismatch":
            ent["failures"] += 1
    return {
        "probes": len(probes),
        "failures": len(failures),
        "by_replica": dict(sorted(by_replica.items())),
        "mismatches": [
            {
                "replica": f.get("replica"),
                "rid": f.get("rid"),
                "golden": f.get("golden"),
                "mismatch_index": f.get("mismatch_index"),
                "expected_token": f.get("expected_token"),
                "got_token": f.get("got_token"),
                "drained": bool(f.get("drained")),
            }
            for f in failures
        ],
    }


def _restarts_section(events: "list[dict]") -> dict:
    """Aggregate the elastic supervisor's ``restart``/``elastic`` records
    (``events-supervisor.jsonl``): generation count, total downtime, cause
    attribution (each restart record carries the classified cause and the
    flight-dump link the supervisor harvested), and how the run ended."""
    restarts = [e for e in events if e.get("kind") == "restart"]
    elastic = [e for e in events if e.get("kind") == "elastic"]
    reshards = [e for e in elastic if e.get("phase") == "reshard"]
    chaos = [e for e in events if e.get("kind") == "chaos_fault"]
    dumps: "list[str]" = []
    for r in restarts:
        if r.get("dump"):
            dumps.append(str(r["dump"]))
    gave_up = next((r for r in restarts if r.get("gave_up")), None)
    # THE downtime/cause computation is goodput.restart_stats — shared with
    # the goodput ledger so the two sections agree by construction
    stats = _goodput.restart_stats(events)
    section = {
        "count": stats["count"],
        "generations": max(
            [int(r.get("generation", 0)) for r in restarts + elastic] or [0]
        ),
        "downtime_s": stats["downtime_s"],
        "causes": stats["causes"],
        "dumps": dumps,
        "reshards": [
            {"saved_mesh": r.get("saved_mesh"), "current_mesh": r.get("current_mesh")}
            for r in reshards
        ],
        "chaos_faults": len(chaos),
        "completed": any(e.get("phase") == "done" for e in elastic),
    }
    if gave_up is not None:
        section["gave_up"] = {
            "cause": gave_up.get("cause"),
            "step": gave_up.get("step"),
            "budget_exhausted": bool(gave_up.get("budget_exhausted")),
        }
    return section


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TiB"


def format_report(report: dict) -> str:
    lines = []
    runs = ", ".join(report.get("runs") or []) or "<none>"
    lines.append(f"telemetry report — run(s): {runs}, "
                 f"{report.get('processes') or 0} process(es), {report['events']} events")
    per_rank = report.get("per_rank_events") or {}
    if per_rank:
        lines.append(
            "  events by rank: "
            + ", ".join(f"rank{r}={c['events']}" for r, c in per_rank.items())
        )
    dropped = report.get("dropped_events", 0)
    if dropped:
        by_rank_drops = ", ".join(
            f"rank{r}={c['dropped']}" for r, c in per_rank.items() if c["dropped"]
        )
        lines.append(
            f"  WARNING: {dropped} event(s) DROPPED on flush failure ({by_rank_drops}) "
            "— these streams are incomplete"
        )
    s = report["steps"]
    lines.append(f"steps: {s['count']}")
    for key, label in (("wall_s", "step time"), ("data_wait_s", "data wait"), ("execute_s", "execute")):
        d = s[key]
        if d.get("count"):
            lines.append(
                f"  {label:<10} p50={d['p50'] * 1e3:.2f}ms  p90={d['p90'] * 1e3:.2f}ms  "
                f"p99={d['p99'] * 1e3:.2f}ms  max={d['max'] * 1e3:.2f}ms"
            )
    lines.append(f"  compile total: {s['compile_s_total'] * 1e3:.2f}ms")
    r = report["recompiles"]
    lines.append(f"recompiles: {r['total']} (initial compiles: {r['initial_compiles']})")
    for fn, n in r["by_fn"].items():
        if n:
            lines.append(f"  {fn}: {n} recompile(s) — check for varying input shapes/dtypes")
    dp = report.get("data_pipeline") or {}
    if dp.get("phases"):
        lines.append(
            f"data pipeline: critical wait {dp['critical_wait_s'] * 1e3:.2f}ms"
        )
        for phase, d in dp["phases"].items():
            if d.get("count"):
                lines.append(
                    f"  {phase:<10} n={d['count']}  total={d['total'] * 1e3:.2f}ms  "
                    f"p50={d['p50'] * 1e3:.2f}ms  max={d['max'] * 1e3:.2f}ms"
                )
        pf = dp.get("prefetch") or {}
        if pf.get("epochs"):
            ratio = pf.get("overlap_ratio")
            ratio_s = f"{ratio * 100:.1f}% of input work hidden" if ratio is not None else "n/a"
            occ = pf.get("queue_occupancy") or {}
            occ_s = f", queue occupancy p50={occ['p50']:.1f}" if occ.get("count") else ""
            lines.append(
                f"  prefetch: {pf['batches']} batch(es) over {pf['epochs']} epoch(s), "
                f"overlap {ratio_s}{occ_s}"
            )
    ck = report.get("checkpoints") or {}
    if ck.get("saves") or (ck.get("phases") or {}):
        lines.append(
            f"checkpoints: {ck.get('saves', 0)} save(s), {_fmt_bytes(ck.get('bytes', 0))} "
            f"written — exposed stall {ck.get('exposed_s', 0.0) * 1e3:.2f}ms, "
            f"hidden (overlapped) {ck.get('hidden_s', 0.0) * 1e3:.2f}ms"
        )
        for phase, d in (ck.get("phases") or {}).items():
            if d.get("count"):
                lines.append(
                    f"  {phase:<12} n={d['count']}  total={d['total'] * 1e3:.2f}ms  "
                    f"p50={d['p50'] * 1e3:.2f}ms  max={d['max'] * 1e3:.2f}ms"
                )
    rs = report.get("restarts") or {}
    if (rs.get("count") or rs.get("generations") or rs.get("gave_up")
            or rs.get("chaos_faults") or rs.get("reshards")):
        ended = "completed" if rs.get("completed") else (
            "GAVE UP" if rs.get("gave_up") else "in flight/unknown"
        )
        lines.append(
            f"restarts: {rs.get('count', 0)} restart(s) over "
            f"{rs.get('generations', 0) + 1} generation(s), downtime "
            f"{rs.get('downtime_s', 0.0):.1f}s — run {ended}"
        )
        for cause, n in (rs.get("causes") or {}).items():
            lines.append(f"  cause {cause}: {n}")
        for r in rs.get("reshards") or []:
            lines.append(
                f"  elastic reshard: {r.get('saved_mesh')} -> {r.get('current_mesh')}"
            )
        if rs.get("dumps"):
            lines.append(f"  flight dump(s): {', '.join(rs['dumps'][-3:])}")
        if rs.get("chaos_faults"):
            lines.append(f"  chaos faults injected: {rs['chaos_faults']}")
        gu = rs.get("gave_up")
        if gu:
            why = "restart budget exhausted" if gu.get("budget_exhausted") else (
                f"poison step {gu.get('step')}" if gu.get("cause") == "poison_step"
                else str(gu.get("cause"))
            )
            lines.append(f"  gave up: {why}")
    perf = report.get("performance")
    if perf:
        lines.append(format_performance_section(perf))
    serving = report.get("serving")
    if serving:
        lines.append(format_serving_section(serving))
    router = report.get("router")
    if router:
        lines.append(format_router_section(router))
    autoscaler = report.get("autoscaler")
    if autoscaler:
        lines.append(format_autoscaler_section(autoscaler))
    slo = report.get("slo")
    if slo:
        lines.append(format_slo_section(slo))
    anomalies = report.get("anomalies")
    if anomalies and anomalies.get("episodes"):
        lines.append(format_anomaly_section(anomalies))
    canary = report.get("canary")
    if canary and canary.get("probes"):
        lines.append(format_canary_section(canary))
    if report.get("traces"):
        lines.append(
            f"traces: {report['traces']} request trace(s) recorded — "
            "`report --request <id>` renders one, `--trace-out` exports Chrome JSON"
        )
    ccache = report.get("compile_cache")
    if ccache:
        lines.append(format_compile_cache_section(ccache))
    gp = report.get("goodput")
    if gp:
        lines.append(format_goodput_section(gp))
    m = report["memory"]
    lines.append(
        "memory peaks: device "
        + _fmt_bytes(m["device_peak_bytes"])
        + ", live arrays "
        + _fmt_bytes(m["live_array_peak_bytes"])
        + ", host rss "
        + _fmt_bytes(m["host_rss_peak_bytes"])
    )
    c = report["comms"]
    lines.append(f"comms: {c['total_calls']} call(s), {_fmt_bytes(c['total_bytes'])} total")
    for op, rec in c["by_op"].items():
        lines.append(f"  {op}: {rec['calls']} call(s), {_fmt_bytes(rec['bytes'])}")
    if report.get("ranks"):
        lines.append(format_rank_section(report["ranks"]))
    return "\n".join(lines)


def _fmt_flops(n: float) -> str:
    n = float(n)
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(n) < 1000 or unit == "P":
            return f"{n:.1f} {unit}FLOP" if unit else f"{n:.0f} FLOP"
        n /= 1000.0
    return f"{n:.1f} PFLOP"


def format_goodput_section(gp: dict) -> str:
    """Human rendering of the fleet goodput/badput ledger
    (:mod:`~accelerate_tpu.telemetry.goodput`)."""
    lines = [f"goodput: {gp.get('verdict', '')}"]
    good = gp.get("good_by_category") or {}
    if good:
        lines.append(
            "  good: " + ", ".join(f"{c} {v:.2f}s" for c, v in good.items())
        )
    wall = gp.get("wall_s") or 0.0
    bad = gp.get("badput_s") or {}
    if bad:
        parts = [
            f"{c} {v:.2f}s ({v / wall * 100:.1f}%)" if wall else f"{c} {v:.2f}s"
            for c, v in sorted(bad.items(), key=lambda kv: -kv[1])
        ]
        lines.append("  badput: " + ", ".join(parts))
    ua = gp.get("unattributed_s") or 0.0
    uf = gp.get("unattributed_fraction")
    lines.append(
        f"  unattributed: {ua:.2f}s"
        + (f" ({uf * 100:.1f}%)" if uf is not None else "")
    )
    if gp.get("overattributed"):
        lines.append(
            "  WARNING: attributed seconds exceed wall-clock — overlapping "
            "records; fractions are approximate"
        )
    gens = gp.get("by_generation") or {}
    if len(gens) > 1 or any(g.get("restart_downtime_s") for g in gens.values()):
        for gen, g in gens.items():
            frac = g["good_s"] / g["wall_s"] * 100 if g.get("wall_s") else 0.0
            down = (
                f", restart downtime {g['restart_downtime_s']:.2f}s"
                if g.get("restart_downtime_s")
                else ""
            )
            lines.append(
                f"  gen {gen}: wall {g['wall_s']:.2f}s, good {frac:.1f}%{down}"
            )
    ranks = gp.get("by_rank") or {}
    if ranks:
        skew = gp.get("rank_skew")
        skew_s = f" (goodput skew {skew * 100:.1f}pp)" if skew is not None else ""
        lines.append(
            "  by rank" + skew_s + ": "
            + ", ".join(
                f"rank{r}={v['goodput_fraction'] * 100:.1f}%"
                for r, v in ranks.items()
            )
        )
    tok = gp.get("tokens")
    if tok:
        frac = tok.get("token_goodput_fraction")
        frac_s = f" ({frac * 100:.1f}%)" if frac is not None else ""
        lines.append(
            f"  tokens: computed {tok['computed_tokens']}, "
            f"useful {tok['useful_tokens']}{frac_s}"
        )
        waste = {
            c: n for c, n in (tok.get("waste_by_cause") or {}).items() if n
        }
        if waste or tok.get("shed_requests"):
            parts = [f"{c} {n}" for c, n in sorted(waste.items(), key=lambda kv: -kv[1])]
            if tok.get("shed_requests"):
                parts.append(f"shed {tok['shed_requests']} request(s)")
            lines.append("    waste: " + ", ".join(parts))
    return "\n".join(lines)


def format_performance_section(perf: dict) -> str:
    """Human rendering of the MFU/roofline/trace attribution."""
    lines = ["performance:"]
    d = perf.get("mfu") or {}
    if d.get("count"):
        trend = perf.get("mfu_trend")
        trend_s = ""
        if trend:
            arrow = "↑" if trend["delta"] >= 0 else "↓"
            trend_s = (
                f"  trend {trend['first_half_mean']:.4f}→"
                f"{trend['second_half_mean']:.4f} {arrow}"
            )
        lines.append(
            f"  MFU over {d['count']} step(s): p50={d['p50']:.4f}  "
            f"mean={d['mean']:.4f}  max={d['max']:.4f}{trend_s}"
        )
    by_fn = perf.get("by_fn") or {}
    if by_fn:
        sample = next(iter(by_fn.values()))
        peak_s = ""
        if sample.get("peak_flops"):
            bw = sample.get("peak_hbm_bytes_per_s")
            peak_s = (
                f" (peaks [{sample.get('peak_source')}]: "
                f"{sample['peak_flops'] / 1e12:.1f} TFLOP/s"
                + (f", {bw / 1e9:.0f} GB/s" if bw else "")
                + ")"
            )
        lines.append(f"  roofline{peak_s}:")
        for fn, rec in by_fn.items():
            ai = rec.get("arithmetic_intensity")
            mfu_d = rec.get("mfu") or {}
            mfu_s = f"  mfu p50={mfu_d['p50']:.4f}" if mfu_d.get("count") else ""
            fit = rec.get("memory_fits")
            fit_s = "" if fit is None else ("" if fit else "  MEMORY OVER CAPACITY")
            lines.append(
                f"    {fn:<18} {_fmt_flops(rec.get('flops', 0.0))}/step  "
                f"AI={ai:.1f} FLOP/B  {rec.get('roofline') or '?'}{mfu_s}{fit_s}"
                if ai is not None
                else f"    {fn:<18} {_fmt_flops(rec.get('flops', 0.0))}/step  "
                f"{rec.get('roofline') or '?'}{mfu_s}{fit_s}"
            )
    tr = perf.get("trace")
    if tr:
        lines.append(
            f"  trace windows: {tr['windows']} ({tr['events']} device event(s)) — "
            f"compute {tr['compute_s'] * 1e3:.2f}ms, collective "
            f"{tr['collective_s'] * 1e3:.2f}ms, idle {tr['idle_s'] * 1e3:.2f}ms"
        )
        ratio = tr.get("comms_overlap_ratio")
        lines.append(
            f"  comms overlap: {ratio * 100:.1f}% of collective time hidden under compute"
            if ratio is not None
            else "  comms overlap: n/a (no collective device time traced)"
        )
        for i, op in enumerate(tr.get("top_ops") or [], 1):
            tag = "  [collective]" if op.get("collective") else ""
            lines.append(
                f"    top op {i}: {op['op']}  {op['total_s'] * 1e3:.2f}ms "
                f"({op['share'] * 100:.1f}%, n={op['count']}){tag}"
            )
    if perf.get("trace_errors"):
        lines.append(
            f"  WARNING: {perf['trace_errors']} trace window(s) failed to start "
            "(another profiler session was active)"
        )
    return "\n".join(lines)


def format_serving_section(serving: dict) -> str:
    """Human rendering of the serving engine's queue/occupancy/latency
    aggregation (see ``docs/serving.md`` for how to read it)."""
    lines = ["serving:"]
    tok_s = serving.get("tokens_per_s")
    lines.append(
        f"  {serving['steps']} engine step(s) — decode {serving['decode_tokens']} "
        f"token(s), prefill {serving['prefill_tokens']} token(s)"
        + (f", {tok_s:.1f} decode tok/s" if tok_s is not None else "")
    )
    occ = serving.get("occupancy") or {}
    qd = serving.get("queue_depth") or {}
    blk = serving.get("block_occupancy") or {}
    if occ.get("count"):
        lines.append(
            f"  batch occupancy p50={occ['p50']:.2f} max={occ['max']:.2f}  "
            f"queue depth p50={qd['p50']:.1f} max={qd['max']:.0f}  "
            f"block occupancy p50={blk['p50']:.2f} max={blk['max']:.2f}"
        )
    if serving.get("prefill_tokens_saved"):
        lines.append(
            f"  prefix cache: {serving['prefill_tokens_saved']} prefill token(s) "
            f"saved (hit rate {serving['prefix_hit_rate']:.1%})"
        )
    spec = serving.get("spec_decode") or {}
    if spec.get("accept_hist"):
        hist = spec["accept_hist"]
        bars = " ".join(f"{i}:{c}" for i, c in enumerate(hist))
        lines.append(
            f"  spec decode: accept rate {spec['accept_rate']:.1%} "
            f"({spec['draft_accepted_tokens']}/{spec['draft_proposed_tokens']} "
            f"draft token(s)), accepted-per-step histogram [{bars}]"
        )
    if serving.get("preemptions"):
        lines.append(f"  preemptions: {serving['preemptions']} (pool pressure evictions)")
    reqs = serving.get("requests") or {}
    if reqs.get("completed"):
        lat = reqs.get("latency_s") or {}
        ttft = reqs.get("ttft_s") or {}
        lat_s = (
            f"  latency p50={lat['p50'] * 1e3:.1f}ms p99={lat['p99'] * 1e3:.1f}ms"
            if lat.get("count") else ""
        )
        ttft_s = f"  ttft p50={ttft['p50'] * 1e3:.1f}ms" if ttft.get("count") else ""
        lines.append(
            f"  requests: {reqs['completed']} completed "
            f"({reqs.get('preempted', 0)} preempted-and-resumed, "
            f"{reqs.get('rejected', 0)} rejected), "
            f"{reqs['new_tokens']} token(s) generated{lat_s}{ttft_s}"
        )
    return "\n".join(lines)


def format_compile_cache_section(ccache: dict) -> str:
    """Human rendering of the persistent compile cache outcomes (see
    ``docs/compile_cache.md`` for how to read it)."""
    lines = ["compile cache:"]
    lines.append(
        f"  {ccache.get('hits', 0)} hit(s) ({_fmt_bytes(ccache.get('bytes_loaded', 0))} "
        f"loaded in {ccache.get('load_s', 0.0) * 1e3:.1f}ms), "
        f"{ccache.get('misses', 0)} miss(es), {ccache.get('stores', 0)} store(s) "
        f"({_fmt_bytes(ccache.get('bytes_stored', 0))})"
    )
    for fn, evs in (ccache.get("by_fn") or {}).items():
        parts = ", ".join(f"{ev} x{n}" for ev, n in sorted(evs.items()))
        lines.append(f"    {fn}: {parts}")
    if ccache.get("corrupt"):
        lines.append(
            f"  WARNING: {ccache['corrupt']} corrupt entr(ies) quarantined, "
            f"{ccache.get('fallbacks', 0)} fallback compile(s) paid"
        )
        for q in (ccache.get("quarantined") or [])[-3:]:
            lines.append(f"    quarantined: {q}")
    degraded = {
        s: n for s, n in (ccache.get("pretouch") or {}).items()
        if s in ("missing", "readonly", "error")
    }
    if degraded:
        parts = ", ".join(f"{s} x{n}" for s, n in degraded.items())
        lines.append(
            f"  WARNING: supervisor pre-touch found the cache {parts} — "
            "those generations cold-started"
        )
    return "\n".join(lines)


def format_anomaly_section(anomalies: dict) -> str:
    """Human rendering of the online detectors' episode fold
    (:mod:`~accelerate_tpu.telemetry.anomaly`)."""
    lines = [f"anomalies: {anomalies.get('episodes', 0)} episode(s)"]
    for det, ent in (anomalies.get("by_detector") or {}).items():
        last = ent.get("last") or {}
        detail = []
        if last.get("z") is not None:
            detail.append(f"z={last['z']:.1f}")
        if last.get("slope") is not None:
            detail.append(f"slope={last['slope']:.4f}")
        if last.get("source"):
            detail.append(f"source={last['source']}")
        suffix = f" ({', '.join(detail)})" if detail else ""
        lines.append(f"  {det}: {ent.get('episodes', 0)} episode(s){suffix}")
        if last.get("cause"):
            lines.append(f"    hypothesis: {last['cause']}")
    return "\n".join(lines)


def format_canary_section(canary: dict) -> str:
    """Human rendering of the bitwise correctness-canary fold
    (:mod:`~accelerate_tpu.serving.canary`)."""
    failures = canary.get("failures", 0)
    verdict = "ALL BITWISE" if not failures else f"{failures} MISMATCH(ES)"
    lines = [f"canaries: {canary.get('probes', 0)} probe(s), {verdict}"]
    for name, ent in (canary.get("by_replica") or {}).items():
        lines.append(
            f"  {name}: {ent.get('probes', 0)} probe(s), "
            f"{ent.get('failures', 0)} failure(s)"
        )
    for m in canary.get("mismatches") or []:
        drained = ", replica drained" if m.get("drained") else ""
        lines.append(
            f"  MISMATCH on {m.get('replica')}: golden {m.get('golden')} token "
            f"{m.get('mismatch_index')} expected {m.get('expected_token')} "
            f"got {m.get('got_token')}{drained}"
        )
    return "\n".join(lines)


def format_router_section(router: dict) -> str:
    """Human rendering of the serving router's replica-health / failover /
    shed aggregation (see ``docs/serving.md`` "Running replicated")."""
    lines = ["router:"]
    replicas = router.get("replicas") or {}
    if replicas:
        by_state: dict = {}
        for rec in replicas.values():
            by_state[rec["state"]] = by_state.get(rec["state"], 0) + 1
        states = ", ".join(f"{n} {s}" for s, n in sorted(by_state.items()))
        lines.append(f"  replicas: {len(replicas)} ({states})")
        for name, rec in replicas.items():
            fo = f", {rec['failovers']} failover(s)" if rec.get("failovers") else ""
            role = rec.get("role", "serving")
            role_s = f" [{role}]" if role in ("prefill", "decode") else ""
            lines.append(
                f"    {name}{role_s}: {rec['state']} — dispatched {rec['dispatched']}, "
                f"completed {rec['completed']}{fo}"
            )
    tiers = router.get("tiers")
    if tiers:
        lines.append(
            f"  tiers: {len(tiers.get('prefill_replicas') or [])} prefill / "
            f"{len(tiers.get('decode_replicas') or [])} decode — "
            f"{tiers.get('handoffs', 0)} KV handoff(s), "
            f"{tiers.get('handoff_blocks', 0)} block(s), "
            f"{_fmt_bytes(tiers.get('handoff_bytes', 0))}"
        )
        bad = {
            o: n for o, n in (tiers.get("handoff_outcomes") or {}).items()
            if o != "ok" and n
        }
        if bad:
            lines.append(
                "    handoff outcomes: "
                + ", ".join(f"{o} {n}" for o, n in sorted(bad.items()))
            )
        pf = tiers.get("prefill_s") or {}
        if pf.get("count"):
            lines.append(
                f"    prefill hop p50={pf['p50'] * 1e3:.1f}ms "
                f"p99={pf['p99'] * 1e3:.1f}ms over "
                f"{tiers.get('disagg_finished', 0)} disaggregated request(s)"
            )
    lines.append(
        f"  dispatched {router.get('dispatched', 0)}, completed "
        f"{router.get('completed', 0)}, failover re-dispatches "
        f"{router.get('failovers', 0)}"
    )
    qd = router.get("queue_depth") or {}
    if qd.get("count"):
        lines.append(f"  queue depth p50={qd['p50']:.1f} max={qd['max']:.0f}")
    shed = router.get("shed", 0)
    expired = router.get("expired", 0)
    failed = router.get("failed", 0)
    if shed or expired or failed:
        reasons = router.get("shed_reasons") or {}
        reason_s = (
            " (" + ", ".join(f"{r} {n}" for r, n in reasons.items()) + ")"
            if reasons else ""
        )
        lines.append(f"  shed {shed}{reason_s}, expired {expired}, failed {failed}")
    reqs = router.get("requests") or {}
    if reqs.get("finished"):
        lat = reqs.get("latency_s") or {}
        ttft = reqs.get("ttft_s") or {}
        lat_s = (
            f"  latency p50={lat['p50'] * 1e3:.1f}ms p99={lat['p99'] * 1e3:.1f}ms"
            if lat.get("count") else ""
        )
        ttft_s = f"  ttft p50={ttft['p50'] * 1e3:.1f}ms" if ttft.get("count") else ""
        lines.append(
            f"  requests: {reqs['finished']} finished "
            f"({reqs.get('retried', 0)} resumed across replicas){lat_s}{ttft_s}"
        )
    return "\n".join(lines)


def format_autoscaler_section(autoscaler: dict) -> str:
    """Human rendering of the SLO-driven autoscaler's decision log (see
    ``docs/observability.md`` "Autoscaler signal")."""
    joins = autoscaler.get("joins") or {}
    lines = [
        "autoscaler: "
        f"{autoscaler.get('scale_ups', 0)} scale-up(s), "
        f"{autoscaler.get('scale_downs', 0)} scale-down(s), "
        f"{joins.get('ready', 0)} join(s) "
        f"({joins.get('warm', 0)} warm, {joins.get('cold', 0)} cold, "
        f"{joins.get('failed', 0)} failed)"
    ]
    ttr = joins.get("time_to_ready_s") or {}
    if ttr.get("count"):
        lines.append(
            f"  time-to-ready p50={ttr['p50']:.2f}s max={ttr['max']:.2f}s, "
            f"join compiles {joins.get('compiles', 0)} "
            f"(0 == every warmup point pre-shipped)"
        )
    for ev in autoscaler.get("events") or []:
        action = ev.get("action", "?")
        if action == "scale_up":
            detail = f"+{ev.get('replica')} (trigger {ev.get('trigger')})"
        elif action == "scale_down":
            detail = (
                f"-{ev.get('replica')} (trigger {ev.get('trigger')}, "
                f"idle {ev.get('idle_s', 0):.1f}s)"
            )
        elif action == "join_ready":
            detail = (
                f"{ev.get('replica')} ready in {ev.get('time_to_ready_s', 0):.2f}s, "
                f"{ev.get('join_compiles', 0)} compile(s) "
                f"({'warm' if ev.get('warm') else 'COLD'})"
            )
        else:
            detail = f"{ev.get('replica')} ({ev.get('reason', '?')})"
        lines.append(f"  {action}: {detail}")
    return "\n".join(lines)


def format_rank_section(ranks: dict) -> str:
    """Human rendering of the ``--by-rank`` straggler forensics."""
    lines = ["per-rank stragglers:"]
    for rank, info in (ranks.get("per_rank") or {}).items():
        wall = info.get("wall_s") or {}
        wall_s = (
            f", wall p50={wall['p50'] * 1e3:.2f}ms max={wall['max'] * 1e3:.2f}ms"
            if wall.get("count")
            else ""
        )
        rank_mfu = info.get("mfu") or {}
        mfu_s = f", mfu p50={rank_mfu['p50']:.4f}" if rank_mfu.get("count") else ""
        dropped_s = f", {info['dropped']} dropped" if info.get("dropped") else ""
        lines.append(
            f"  rank {rank}: {info['events']} event(s), {info['steps']} step(s)"
            f"{wall_s}{mfu_s}{dropped_s}"
        )
    skew = ranks.get("skew_s") or {}
    if skew.get("count"):
        lines.append(
            f"  step skew over {ranks['steps_compared']} shared step(s): "
            f"p50={skew['p50'] * 1e3:.2f}ms  p90={skew['p90'] * 1e3:.2f}ms  "
            f"max={skew['max'] * 1e3:.2f}ms"
        )
    straggler = ranks.get("straggler")
    if straggler:
        lines.append(
            f"  straggler: rank {straggler['rank']} — slowest in "
            f"{straggler['slowest_steps']}/{straggler['steps_compared']} step(s), "
            f"mean excess {straggler['mean_excess_s'] * 1e3:.2f}ms over the fastest rank"
        )
    for step in ranks.get("worst_steps") or []:
        durs = "  ".join(f"rank{r}={d * 1e3:.2f}ms" for r, d in step["durs_s"].items())
        lines.append(
            f"    step {step['step']}: skew {step['skew_s'] * 1e3:.2f}ms "
            f"(slowest rank {step['slowest_rank']}: {durs})"
        )
    gaps = ranks.get("heartbeat_gaps") or {}
    if gaps:
        lines.append(
            "  heartbeat gaps: "
            + ", ".join(
                f"rank{r} max={g['max_gap_s']:.2f}s over {g['beats']} beat(s)"
                for r, g in gaps.items()
            )
        )
    div = ranks.get("collective_divergence")
    if div:
        if div.get("diverged"):
            lines.append(
                "  COLLECTIVE SCHEDULE DIVERGENCE: ranks issued different "
                "collective sequences (deadlock risk — see jaxlint R4)"
            )
            for r, s in (div.get("per_rank") or {}).items():
                lines.append(f"    rank {r}: {s['count']} collective(s), hash {s['hash']}")
            first = div.get("first_divergence")
            if first:
                calls = ", ".join(
                    f"rank{r}={c['op']}({c['sig']})"
                    for r, c in first["calls"].items()
                )
                lines.append(f"    first visible divergence at call #{first['seq']}: {calls}")
        elif div.get("indeterminate"):
            lines.append(
                "  collective schedules: INDETERMINATE — counts differ and "
                "the skew outran the recent-call windows; re-dump closer "
                "together (or raise the window) to distinguish timing skew "
                "from divergence"
            )
        elif div.get("prefix_skew"):
            ahead = ", ".join(
                f"rank{r}+{n}" for r, n in div["prefix_skew"].items() if n
            )
            lines.append(
                "  collective schedules: identical common prefix, dump-timing "
                f"skew only ({ahead} call(s) ahead) — not divergence"
            )
        else:
            sample = next(iter((div.get("per_rank") or {}).values()), {})
            lines.append(
                f"  collective schedules: consistent across ranks "
                f"({sample.get('count', 0)} call(s), hash {sample.get('hash')})"
            )
    flights = ranks.get("flight_records") or []
    if flights:
        lines.append("  flight records:")
        for rec in flights:
            phases = ", ".join(
                f"{t}:{p['phase']}@{p['age_s']}s" for t, p in (rec["phases"] or {}).items()
            )
            step_s = f" (step {rec['step']})" if rec.get("step") is not None else ""
            lines.append(
                f"    {rec['file']}: {rec['reason']}{step_s}"
                + (f" — open phases: {phases}" if phases else "")
            )
    return "\n".join(lines)


def find_request_trace(events: "list[dict]", rid: str) -> "tuple[Optional[str], list[dict]]":
    """Locate one request's trace among merged ``span`` records: by the root
    span's ``rid`` attribute (the router's ``q<n>`` / the engine's integer
    rid) or by a raw trace id. Returns ``(trace_id, spans)``."""
    from . import tracing as _tracing

    traces = _tracing.spans_by_trace(events)
    if rid in traces:
        return rid, traces[rid]
    for tid, spans in traces.items():
        for s in spans:
            if not s.get("parent_id") and str((s.get("attrs") or {}).get("rid")) == str(rid):
                return tid, spans
    return None, []


def render_request(paths: Iterable[str], rid: str,
                   trace_out: Optional[str] = None) -> "tuple[int, str]":
    """The ``report --request <id>`` body: one request's span timeline
    (queue → dispatch → prefill chunks → decode steps → failover hops) from
    the trace records, optionally exported as Chrome ``trace.json``."""
    from . import tracing as _tracing

    events = load_events(paths)
    trace_id, spans = find_request_trace(events, rid)
    if not spans:
        available = sorted(
            str((s.get("attrs") or {}).get("rid"))
            for t in _tracing.spans_by_trace(events).values()
            for s in t
            if not s.get("parent_id")
        )
        hint = f" (traced requests: {', '.join(available[:10])})" if available else (
            " (no span records — was ACCELERATE_TRACE_SAMPLE set on the serving run?)"
        )
        return 1, f"no trace found for request {rid!r}{hint}"
    problems = _tracing.validate_span_tree(spans)
    root = next((s for s in spans if not s.get("parent_id")), spans[0])
    attrs = root.get("attrs") or {}
    header = (
        f"request {rid} — trace {trace_id}, {len(spans)} span(s), "
        f"outcome {attrs.get('outcome', '?')}"
        + (f", {attrs.get('retries')} failover retr(ies)" if attrs.get("retries") else "")
    )
    lines = [header, _tracing.format_timeline(spans)]
    if problems:
        lines.append("  WARNING: span tree has gaps: " + "; ".join(problems))
    if trace_out:
        with open(trace_out, "w") as f:
            json.dump(_tracing.chrome_trace(spans), f)
        lines.append(f"  chrome trace written to {trace_out}")
    return 0, "\n".join(lines)


def export_traces(paths: Iterable[str], trace_out: str) -> "tuple[int, str]":
    """``report --trace-out`` without ``--request``: every recorded span as
    one Chrome trace file (all requests side by side)."""
    from . import tracing as _tracing

    events = load_events(paths)
    # trace spans only (legacy EventLog.span timing records have no trace_id)
    spans = [e for e in events if e.get("kind") == "span" and e.get("trace_id")]
    with open(trace_out, "w") as f:
        json.dump(_tracing.chrome_trace(spans), f)
    return 0, f"{len(spans)} span(s) written to {trace_out}"


def run_doctor() -> int:
    """Self-check the forensics pipeline: flight dump → watchdog stall
    detection → straggler report. Exercises the real code paths against
    synthetic inputs in a temp dir; prints one PASS/FAIL line per check."""
    import tempfile
    import threading
    import time as _time

    from . import flight_recorder
    from .flight_recorder import FlightRecorder
    from .watchdog import Watchdog

    failures = 0

    def _check(name: str, ok: bool, detail: str = "") -> None:
        nonlocal failures
        print(f"doctor: {name:<28} {'PASS' if ok else 'FAIL'}"
              + (f" ({detail})" if detail and not ok else ""))
        failures += 0 if ok else 1

    with tempfile.TemporaryDirectory() as tmp:
        # 1. flight recorder: ring + dump with all-thread stacks
        rec = FlightRecorder(capacity=32)
        for i in range(40):
            rec.record("doctor_tick", i=i)
        path = rec.dump("doctor self-check", out_dir=tmp)
        ok = False
        detail = "dump returned None"
        if path and os.path.exists(path):
            data = json.load(open(path))
            ok = (
                len(data["events"]) == 32
                and any("run_doctor" in "".join(t["stack"]) for t in data["threads"])
                and data["reason"] == "doctor self-check"
            )
            detail = "dump missing ring/stacks/reason"
        _check("flight recorder dump", ok, detail)

        # 2. watchdog: a thread blocked in a phase must produce a named dump
        wd = Watchdog(timeout=0.3, interval=0.1, out_dir=tmp).start()

        def _stall():
            with flight_recorder.phase("doctor:fake_stall"):
                _time.sleep(1.2)

        worker = threading.Thread(target=_stall, name="doctor-staller", daemon=True)
        worker.start()
        deadline = _time.monotonic() + 5.0
        while _time.monotonic() < deadline and not wd.dump_paths:
            _time.sleep(0.05)
        worker.join()
        wd.stop()
        ok = bool(wd.dump_paths)
        detail = "no stall dump within 5s"
        if ok:
            data = json.load(open(wd.dump_paths[0]))
            ok = "doctor:fake_stall" in data["reason"]
            detail = "dump does not name the stalled phase"
        _check("watchdog stall detection", ok, detail)

        # 3. straggler report over synthetic two-rank streams (rank 1 3x slower)
        for rank, scale in ((0, 1.0), (1, 3.0)):
            with open(os.path.join(tmp, f"events-rank{rank}.jsonl"), "w") as f:
                f.write(json.dumps({"kind": "meta", "schema": 1, "run_id": "doctor",
                                    "process_index": rank, "num_processes": 2}) + "\n")
                for s in range(8):
                    f.write(json.dumps({"kind": "step", "step": s, "t": float(s),
                                        "dur_s": 0.01 * scale}) + "\n")
        rep = build_report([tmp], by_rank=True)
        straggler = (rep.get("ranks") or {}).get("straggler") or {}
        _check(
            "straggler attribution",
            straggler.get("rank") == 1 and rep["ranks"]["skew_s"]["count"] == 8,
            f"straggler={straggler}",
        )

        # 4. collective-schedule divergence: rank 0 took an extra gather
        # (the `if is_main_process: gather()` shape) while rank 1 moved on
        # to the barrier — their call #3 disagrees
        for rank, ops in ((0, ["gather", "reduce:mean", "gather", "barrier"]),
                          (1, ["gather", "reduce:mean", "barrier"])):
            fr = FlightRecorder(capacity=16)
            for op in ops:
                fr.record_collective(op, "(8, 4)/float32")
            with open(os.path.join(tmp, f"flight-rank{rank}.json"), "w") as f:
                json.dump(
                    {
                        "kind": "flight_record",
                        "reason": "doctor divergence",
                        "meta": {"process_index": rank},
                        "collective_schedule": fr.collective_schedule(),
                    },
                    f,
                )
        rep = build_report([tmp], by_rank=True)
        div = (rep.get("ranks") or {}).get("collective_divergence") or {}
        _check(
            "collective divergence",
            bool(div.get("diverged"))
            and (div.get("first_divergence") or {}).get("seq") == 3,
            f"divergence={div}",
        )

        # 5. static analyzer: a seeded host-sync + rank-divergent collective
        # must both be caught by the lint engine (make lint's substrate)
        snippet = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "from accelerate_tpu.utils.operations import gather\n\n"
            "@jax.jit\n"
            "def step(params, batch):\n"
            "    loss = jnp.mean(batch['x'] @ params['w'])\n"
            "    return float(loss)\n\n"
            "def log_metrics(state, metrics):\n"
            "    if state.is_main_process:\n"
            "        return gather(metrics)\n"
            "    return None\n"
        )
        lint_dir = os.path.join(tmp, "lint")
        os.makedirs(lint_dir, exist_ok=True)
        with open(os.path.join(lint_dir, "doctor_lint_case.py"), "w") as f:
            f.write(snippet)
        try:
            from ..analysis import run_lint

            result = run_lint([lint_dir], use_baseline=False)
            rules_hit = {f.rule for f in result.new_findings}
            _check(
                "static analyzer (jaxlint)",
                {"R1", "R4"} <= rules_hit,
                f"rules_hit={sorted(rules_hit)}",
            )
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("static analyzer (jaxlint)", False, f"{type(exc).__name__}: {exc}")

        # 6. perf cost capture: XLA cost analysis of a real jitted fn must
        # yield FLOPs and a roofline placement (telemetry/perf.py)
        try:
            import jax
            import jax.numpy as jnp

            from . import perf as _perf

            @jax.jit
            def _doctor_step(x, y):
                return jnp.tanh(x @ y).sum()

            ones = jnp.ones((64, 64), jnp.float32)
            compiled = _doctor_step.lower(ones, ones).compile()
            cost = _perf.cost_from_compiled("doctor_step", compiled)
            ok = (
                cost is not None
                and cost.flops > 0
                and (cost.mfu(1e-3) or 0) > 0
                and cost.roofline in ("compute-bound", "hbm-bound")
            )
            _check("perf cost capture", ok, f"cost={cost}")
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("perf cost capture", False, f"{type(exc).__name__}: {exc}")

        # 7. xplane trace parse: a real jax.profiler window must decode into
        # op events with durations (telemetry/xplane.py, no-TF pb parser).
        # Builds its own jitted fixture: a check-6 failure must not leak a
        # NameError here and misdiagnose the trace parser.
        try:
            import jax
            import jax.numpy as jnp

            from . import xplane as _xplane

            @jax.jit
            def _trace_step(x, y):
                return jnp.tanh(x @ y).sum()

            ones = jnp.ones((64, 64), jnp.float32)
            trace_dir = os.path.join(tmp, "trace")
            jax.profiler.start_trace(trace_dir)
            for _ in range(3):
                _trace_step(ones, ones).block_until_ready()
            jax.profiler.stop_trace()
            summary = _xplane.summarize_trace(trace_dir)
            ok = summary["events"] > 0 and bool(summary["top_ops"]) and summary["busy_s"] > 0
            _check("xplane trace parse", ok,
                   f"events={summary.get('events')} files={summary.get('files')}")
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("xplane trace parse", False, f"{type(exc).__name__}: {exc}")

        # 8. performance report section: synthetic cost-analysis + trace
        # fixture must render with non-zero MFU and an overlap ratio
        try:
            _doctor_performance_section(tmp, _check)
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("performance report section", False, f"{type(exc).__name__}: {exc}")

        # 9. fused ZeRO-1 weight update (ISSUE 9): the fused step's module must
        # lint clean under the donation (R3) + collectives (R4) rules, and its
        # COMPILED form on an 8-virtual-device mesh must contain collectives
        # moving real bytes (run in a subprocess — the device count is fixed at
        # backend init, which already happened in this process)
        try:
            _doctor_fused_zero1(_check)
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("fused zero1 weight update", False, f"{type(exc).__name__}: {exc}")

        # 11. elastic auto-resume (ISSUE 10): the resilience supervisor must
        # ride through a SIGKILLed toy run — restart within the budget, let
        # generation 1 finish, and leave restart telemetry the "restarts"
        # report section can attribute
        try:
            _doctor_elastic(tmp, _check)
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("elastic auto-resume", False, f"{type(exc).__name__}: {exc}")

        # 12. serving engine (ISSUE 11): continuous batching over the paged
        # KV cache on CPU — staggered variable-length requests must all match
        # their single-stream reference, batch occupancy must exceed 1, and
        # the warmed bucket lattice must absorb all churn with ZERO
        # post-warmup recompiles (the jit caches are the oracle)
        try:
            _doctor_serving(tmp, _check)
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("serving engine", False, f"{type(exc).__name__}: {exc}")

        # 13. replicated serving router (ISSUE 12): two warmed CPU replicas
        # behind the router, a seeded chaos fault killing one MID-LOAD — the
        # survivor must absorb the failover with token-exact resume, every
        # request must complete exactly once bitwise-equal to its
        # single-stream reference, and the router report section must render
        try:
            _doctor_router(tmp, _check)
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("replicated serving router", False, f"{type(exc).__name__}: {exc}")

        # 14. persistent compile cache (ISSUE 13): a subprocess compiles a
        # jitted step into a temp cache and commits it; a SECOND subprocess
        # ("the restart") must hit that entry with ZERO backend compiles and
        # zero jit-cache growth; then the entry is bit-flipped and a third
        # subprocess must fall back to a clean fresh compile with the poison
        # quarantined — never a crash, never a wrong result
        try:
            _doctor_compile_cache(tmp, _check)
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("persistent compile cache", False, f"{type(exc).__name__}: {exc}")

        # 15. prefix-cached paged KV + copy-on-write (ISSUE 14): two requests
        # sharing a long prefix then diverging must produce outputs
        # bitwise-equal to unshared single-stream runs, shared blocks must
        # never be freed while referenced (pool-churn use-after-free probe),
        # and the jit caches must stay frozen post-warmup with the cache on
        try:
            _doctor_prefix_cache(tmp, _check)
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("prefix cache + COW", False, f"{type(exc).__name__}: {exc}")

        # 16. observability plane (ISSUE 15): a 2-replica CPU router with
        # tracing + metrics ON under a seeded workload with one injected
        # kill — every completed request must carry a GAP-FREE span tree
        # (admission→dispatch→prefill→decode, failover hops included), the
        # live /metrics scrape's ttft histogram count must equal the
        # completions (and its quantiles match the report's serving
        # section), and one slo_violation must fire under an artificially
        # tight ttft objective
        try:
            _doctor_observability(tmp, _check)
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("observability plane", False, f"{type(exc).__name__}: {exc}")

        # 17. disaggregated prefill/decode (ISSUE 16): a 2-tier fleet (2
        # prefill + 2 decode) under a seeded chaos kill at the kv_handoff
        # point (prefill dies after prefilling, before the handoff lands)
        # plus one seeded handoff corruption — the router must re-run
        # prefill exactly-once in both cases and every request must finish
        # bitwise-equal to its single-stream greedy reference, with the
        # report rendering the per-tier breakdown
        try:
            _doctor_disagg(tmp, _check)
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("disaggregated serving", False, f"{type(exc).__name__}: {exc}")

        # 18. goodput ledger (ISSUE 17): a supervised toy run under a seeded
        # SIGKILL + slow-data chaos schedule — the ledger must attribute the
        # injected badput to restart_downtime and data_wait, leave <5% of
        # fleet wall-clock unattributed, agree with the restarts section
        # (one shared restart_stats computation), and render with a verdict
        try:
            _doctor_goodput(tmp, _check)
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("goodput ledger", False, f"{type(exc).__name__}: {exc}")

        # 19. speculative decoding (ISSUE 18): the CPU engine with a
        # truncated-layer self-draft proposing k tokens per step — every
        # completion must stay bitwise-equal to the non-speculative
        # single-stream reference, the jit caches must freeze at the warmed
        # counts (draft + k-verify lattice points included), and the
        # accept-rate histogram must render in the report's serving section
        try:
            _doctor_spec_decode(tmp, _check)
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("speculative decoding", False, f"{type(exc).__name__}: {exc}")

        # 20. live observability plane (ISSUE 19): a supervised fleet under
        # seeded chaos (one SIGKILL restart, one injected slow fault) tailed
        # LIVE by the hub while its streams grow — the step-latency detector
        # must fire exactly one episode with a cause hypothesis, a seeded
        # canary corruption (one replica built from different param_seed)
        # must drain the bad replica with the bitwise mismatch named and
        # zero false positives on the healthy one, and `top --once` must
        # render the degraded fleet through the report CLI's own section
        # formatters (the shared-formatter invariant, asserted string-exact)
        try:
            _doctor_live_plane(tmp, _check)
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("live observability plane", False, f"{type(exc).__name__}: {exc}")

        # 21. fp8 through fused ZeRO-1 (ISSUE 20): an fp8 train step on an
        # 8-virtual-device mesh must KEEP the fused bucketed path engaged —
        # the delayed-scaling meta leaves ride as passthrough slots, the
        # optimizer state shards 1/N per replica, losses match the
        # replicated stage-0 baseline, and the compiled step's jit cache is
        # frozen after warmup (run in a subprocess — the device count is
        # fixed at backend init, which already happened in this process)
        try:
            _doctor_fp8_train_step(_check)
        except Exception as exc:  # pragma: no cover - doctor must not crash
            _check("fp8 fused zero1 train step", False, f"{type(exc).__name__}: {exc}")

    print("doctor: all checks passed" if not failures
          else f"doctor: {failures} check(s) FAILED")
    return 1 if failures else 0


def _doctor_compile_cache(tmp: str, _check) -> None:
    """Doctor check 14 body: three subprocess generations against one temp
    cache dir — gen A compiles a jitted step and commits it; gen B (the
    restart) must load it with a cache HIT, zero backend compiles and zero
    jit-cache growth (RecompileWatcher); after a bit-flip, gen C must
    quarantine the poison and fall back to a clean fresh compile producing
    the same result."""
    import subprocess
    import sys

    from ..compile_cache import PAYLOAD_NAME, CompileCache

    cache_dir = os.path.join(tmp, "compile-cache")
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(p for p in (repo, env.get("PYTHONPATH")) if p)
    child = (
        "import json, os, sys\n"
        "import jax, jax.numpy as jnp\n"
        "from accelerate_tpu import compile_cache as cc\n"
        "from accelerate_tpu.telemetry import step_profiler as sp\n"
        "sp.install_compile_listener()\n"
        "step = jax.jit(lambda p, x: {'w': p['w'] - 0.1 * (p['w'] @ x)[:, None] * x[None, :]})\n"
        "params = {'w': jnp.ones((16, 16))}\n"
        "x = jnp.ones((16,))\n"
        "watcher = sp.RecompileWatcher()\n"
        "watcher.register('doctor_step', step)\n"
        "c0 = sp.raw_compile_snapshot()[0]\n"
        f"ex, outcome = cc.aot_compile('doctor_step', step, (params, x), directory={cache_dir!r})\n"
        "out = (ex if ex is not None else step)(params, x)\n"
        "c1 = sp.raw_compile_snapshot()[0]\n"
        "print(json.dumps({'outcome': outcome, 'backend_compiles': c1 - c0,\n"
        "                  'jit_entries': int(step._cache_size()),\n"
        "                  'recompiles': sum(watcher.poll(emit=False).values()),\n"
        "                  'result': float(out['w'][0, 0])}))\n"
    )

    def _gen() -> dict:
        res = subprocess.run(
            [sys.executable, "-c", child], env=env, capture_output=True,
            text=True, timeout=240,
        )
        if res.returncode != 0:
            raise RuntimeError(f"child rc={res.returncode}: {res.stderr[-800:]}")
        return json.loads(res.stdout.strip().splitlines()[-1])

    a = _gen()  # cold: compile + commit
    b = _gen()  # restart: must hit with zero compiles anywhere
    cache = CompileCache(cache_dir)
    entry = cache.entries()[0] if cache.entries() else None
    if entry is not None:  # poison: flip one payload byte
        payload = os.path.join(entry, PAYLOAD_NAME)
        blob = bytearray(open(payload, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(payload, "wb").write(bytes(blob))
    c = _gen()  # poisoned restart: quarantine + clean fallback compile
    quarantined = cache.stats()["quarantined"]
    ok = (
        a["outcome"] == "miss" and a["backend_compiles"] >= 1
        and b["outcome"] == "hit" and b["backend_compiles"] == 0
        and b["jit_entries"] == 0 and b["recompiles"] == 0
        and b["result"] == a["result"]
        and entry is not None
        and c["outcome"] == "corrupt" and c["backend_compiles"] >= 1
        and c["result"] == a["result"]
        and quarantined >= 1
    )
    _check(
        "persistent compile cache",
        ok,
        f"cold={a} restart={b} poisoned={c} quarantined={quarantined}",
    )


def _doctor_elastic(tmp: str, _check) -> None:
    """Doctor check 11 body: supervise a toy child that SIGKILLs itself in
    generation 0 and completes in generation 1; the supervisor must classify
    the kill, restart within the budget, exit 0, and emit restart records
    that aggregate into the report's restarts section."""
    import sys

    from ..resilience.supervisor import RestartPolicy, Supervisor

    sup_dir = os.path.join(tmp, "elastic")
    os.makedirs(sup_dir, exist_ok=True)
    done = os.path.join(sup_dir, "DONE")
    child = (
        "import os, signal\n"
        "if os.environ.get('ACCELERATE_RESTART_GENERATION', '0') == '0':\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"
        f"open({done!r}, 'w').write('ok')\n"
    )
    sup = Supervisor(
        [[sys.executable, "-c", child]],
        policy=RestartPolicy(max_restarts=2, backoff_base_s=0.05, grace_period_s=1.0),
        telemetry_dir=sup_dir,
    )
    rc = sup.run()
    rep = build_report([sup_dir])
    rs = rep.get("restarts") or {}
    text = format_report(rep)
    ok = (
        rc == 0
        and sup.restarts_used == 1
        and os.path.isfile(done)
        and rs.get("count") == 1
        and rs.get("completed")
        and rs.get("causes", {}).get("killed") == 1
        and "restarts: 1 restart(s)" in text
    )
    _check("elastic auto-resume", ok, f"rc={rc} restarts={rs}")


def _doctor_serving(tmp: str, _check) -> None:
    """Doctor check 12 body: spin up the serving engine on the CPU backend,
    submit staggered variable-length greedy requests, and require (a) every
    completion identical to its single-stream ``greedy_generate`` reference,
    (b) batch occupancy > 1 at some step (continuous batching actually
    batched), (c) jit caches frozen at the warmed bucket counts (zero
    post-warmup recompiles), and (d) the serving report section renders."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..generation import greedy_generate
    from ..models import LlamaConfig, init_llama
    from ..serving import BucketLattice, ServingEngine
    from . import events as tel_events

    config = LlamaConfig.tiny()
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), init_llama(config, jax.random.PRNGKey(0))
    )
    serve_dir = os.path.join(tmp, "serving")
    tel_events.enable(out_dir=serve_dir, run_id="doctor-serving")
    try:
        engine = ServingEngine(
            params, config, num_blocks=33, block_size=8, max_slots=4,
            lattice=BucketLattice(
                slot_buckets=(2, 4), block_buckets=(4,), prefill_buckets=(32,)
            ),
        )
        warmed = engine.warmup()
        rng = np.random.default_rng(0)
        specs = [(5, 7), (13, 11), (21, 5), (9, 9), (12, 6)]
        prompts = [rng.integers(0, config.vocab_size, (s,)).astype(np.int32) for s, _ in specs]
        # staggered arrivals: two up front, the rest injected mid-flight
        reqs = [engine.submit(prompts[i], specs[i][1], rng_seed=i) for i in range(2)]
        for i in range(2, len(specs)):
            engine.step()
            reqs.append(engine.submit(prompts[i], specs[i][1], rng_seed=i))
        engine.run()
    finally:
        tel_events.disable()
    mismatched = []
    for i, ((_, max_new), req) in enumerate(zip(specs, reqs)):
        ref = greedy_generate(params, prompts[i][None], config, max_new_tokens=max_new)
        if not np.array_equal(np.asarray(ref[0]), req.output_ids()):
            mismatched.append(i)
    stats = engine.stats()
    report = build_report([serve_dir])
    serving = report.get("serving") or {}
    text = format_report(report)
    ok = (
        not mismatched
        and stats["max_running"] > 1
        and engine.jit_cache_sizes() == warmed
        and (serving.get("requests") or {}).get("completed") == len(specs)
        and "serving:" in text
        and "batch occupancy" in text
    )
    _check(
        "serving engine",
        ok,
        f"mismatched={mismatched} max_running={stats['max_running']} "
        f"caches={engine.jit_cache_sizes()} warmed={warmed}",
    )


def _doctor_spec_decode(tmp: str, _check) -> None:
    """Doctor check 19 body: the serving engine with speculative decoding on
    (k=3 draft tokens from a 1-layer truncated self-draft) must (a) complete
    every staggered greedy request bitwise-equal to the single-stream
    ``greedy_generate`` reference — the bitwise-accept contract, (b) keep the
    jit caches frozen at the warmed counts with the draft and k-verify
    lattice points included, and (c) render the accept-rate histogram in the
    report's serving section."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..generation import greedy_generate
    from ..models import LlamaConfig, init_llama
    from ..serving import BucketLattice, ServingEngine
    from . import events as tel_events

    config = LlamaConfig.tiny()
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), init_llama(config, jax.random.PRNGKey(0))
    )
    serve_dir = os.path.join(tmp, "spec_decode")
    tel_events.enable(out_dir=serve_dir, run_id="doctor-spec-decode")
    try:
        engine = ServingEngine(
            params, config, num_blocks=33, block_size=8, max_slots=4,
            lattice=BucketLattice(
                slot_buckets=(2, 4), block_buckets=(4,), prefill_buckets=(32,)
            ),
            spec_tokens=3, draft_layers=1,
        )
        warmed = engine.warmup()
        rng = np.random.default_rng(0)
        specs = [(5, 7), (13, 11), (21, 5), (9, 9), (12, 6)]
        prompts = [rng.integers(0, config.vocab_size, (s,)).astype(np.int32) for s, _ in specs]
        reqs = [engine.submit(prompts[i], specs[i][1], rng_seed=i) for i in range(2)]
        for i in range(2, len(specs)):
            engine.step()
            reqs.append(engine.submit(prompts[i], specs[i][1], rng_seed=i))
        engine.run()
    finally:
        tel_events.disable()
    mismatched = []
    for i, ((_, max_new), req) in enumerate(zip(specs, reqs)):
        ref = greedy_generate(params, prompts[i][None], config, max_new_tokens=max_new)
        if not np.array_equal(np.asarray(ref[0]), req.output_ids()):
            mismatched.append(i)
    stats = engine.stats()
    report = build_report([serve_dir])
    serving = report.get("serving") or {}
    spec = serving.get("spec_decode") or {}
    text = format_report(report)
    caches = engine.jit_cache_sizes()
    ok = (
        not mismatched
        and caches == warmed
        and "verify_compiles" in warmed
        and "draft_compiles" in warmed
        and stats["draft_proposed_tokens"] > 0
        and sum(spec.get("accept_hist") or []) > 0
        and "spec decode: accept rate" in text
    )
    _check(
        "speculative decoding",
        ok,
        f"mismatched={mismatched} caches={caches} warmed={warmed} "
        f"accept_rate={stats.get('spec_accept_rate')}",
    )


def _doctor_prefix_cache(tmp: str, _check) -> None:
    """Doctor check 15 body: automatic prefix caching with copy-on-write must
    be INVISIBLE in every output. Two requests share a long block-aligned
    prefix then diverge; the first finishes and frees while the second still
    decodes, and fresh requests are submitted immediately after so any
    erroneously-freed shared block would be reclaimed and overwritten under
    the survivor (the use-after-free probe — corruption would break its
    bitwise parity). Requires (a) every completion bitwise-equal to its
    unshared single-stream ``greedy_generate`` reference, (b) the shared
    prefix actually shared (shared block count and hit tokens > 0 mid-flight),
    (c) jit caches frozen at the warmed counts with the cache enabled, and
    (d) the serving report section renders the prefix-cache savings line."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..generation import greedy_generate
    from ..models import LlamaConfig, init_llama
    from ..serving import BucketLattice, RequestStatus, ServingEngine
    from . import events as tel_events

    config = LlamaConfig.tiny()
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), init_llama(config, jax.random.PRNGKey(0))
    )
    serve_dir = os.path.join(tmp, "prefix_cache")
    tel_events.enable(out_dir=serve_dir, run_id="doctor-prefix-cache")
    try:
        engine = ServingEngine(
            params, config, num_blocks=33, block_size=8, max_slots=4,
            lattice=BucketLattice(
                slot_buckets=(2, 4), block_buckets=(8,), prefill_buckets=(32,)
            ),
            prefix_cache=True,
        )
        warmed = engine.warmup()
        rng = np.random.default_rng(15)
        shared = rng.integers(0, config.vocab_size, (24,)).astype(np.int32)  # 3 full blocks
        tails = [rng.integers(0, config.vocab_size, (n,)).astype(np.int32)
                 for n in (6, 10)]
        prompts = [np.concatenate([shared, t]) for t in tails]
        a = engine.submit(prompts[0], 6, rng_seed=0)
        engine.step()  # a prefilled: its full blocks are content-indexed
        b = engine.submit(prompts[1], 14, rng_seed=1)
        engine.step()  # b admitted: maps a's 3 shared blocks (refcount 2)
        shared_mid = engine.allocator.shared_blocks()
        reqs = [a, b]
        churned = False
        while not engine.scheduler.idle():
            engine.step()
            if not churned and a.status is RequestStatus.FINISHED:
                # a freed its references while b still decodes: flood the pool
                # with fresh requests so a wrongly-freed shared block would be
                # reclaimed and OVERWRITTEN under b before it finishes
                churned = True
                for i in (2, 3):
                    p = rng.integers(0, config.vocab_size, (20,)).astype(np.int32)
                    prompts.append(p)
                    reqs.append(engine.submit(p, 8, rng_seed=i))
    finally:
        tel_events.disable()
    mismatched = []
    for i, req in enumerate(reqs):
        ref = greedy_generate(
            params, prompts[i][None], config, max_new_tokens=req.max_new_tokens
        )
        if not np.array_equal(np.asarray(ref[0]), req.output_ids()):
            mismatched.append(i)
    hit_tokens = engine.allocator.prefix_hit_tokens
    text = format_report(build_report([serve_dir]))
    ok = (
        not mismatched
        and churned
        and shared_mid >= 3
        and hit_tokens >= 24
        and engine.jit_cache_sizes() == warmed
        and "prefix cache:" in text
    )
    _check(
        "prefix cache + COW",
        ok,
        f"mismatched={mismatched} churned={churned} shared_mid={shared_mid} "
        f"hit_tokens={hit_tokens} caches={engine.jit_cache_sizes()} warmed={warmed}",
    )


def _doctor_router(tmp: str, _check) -> None:
    """Doctor check 13 body: spin two thread-backed CPU replicas behind the
    ServingRouter, arm a seeded chaos ``crash`` fault at the serving_decode
    point (the in-process stand-in for SIGKILL — the real-SIGKILL /
    wedge-forever variants run as the slow-marked subprocess tests in
    ``tests/test_router.py``), kill one replica mid-load, and require (a)
    exactly one replica DEAD with ≥1 failover, (b) every request FINISHED
    exactly once with output bitwise-equal to its single-stream
    ``greedy_generate`` reference, (c) an overload burst sheds by priority
    against a bounded queue (batch displaced by interactive, overflow shed
    with the distinct SHED status, everything admitted still finishing),
    and (d) the router report section renders with the replica table."""
    import dataclasses

    import numpy as np

    from ..models import LlamaConfig
    from ..resilience import chaos
    from ..resilience.chaos import ChaosSchedule, Fault
    from ..serving import (
        PRIORITY_INTERACTIVE,
        AdmissionController,
        LocalReplica,
        ReplicaSpec,
        ReplicaState,
        RouterRequestStatus,
        ServingRouter,
    )
    from . import events as tel_events

    config = LlamaConfig.tiny()
    spec = ReplicaSpec(
        model=dataclasses.asdict(config), num_blocks=33, block_size=8,
        max_slots=2, slot_buckets=(2,), block_buckets=(4,), prefill_buckets=(16,),
    )
    router_dir = os.path.join(tmp, "router")
    tel_events.enable(out_dir=router_dir, run_id="doctor-router")
    router = None
    try:
        # the fault is once-matched under a lock, so EXACTLY one replica
        # thread dies when it reaches engine step 4 mid-decode
        chaos.arm(ChaosSchedule(
            faults=[Fault(kind="crash", point="serving_decode", step=4)]
        ))
        replicas = [LocalReplica(f"r{i}", spec) for i in range(2)]
        router = ServingRouter(
            replicas,
            admission=AdmissionController(max_queue=8),
            health_timeout_s=10.0,
        )
        router.wait_ready(timeout_s=300)
        rng = np.random.default_rng(0)
        reqs = []
        for i in range(6):
            prompt = rng.integers(0, config.vocab_size, (int(rng.integers(4, 12)),))
            reqs.append((prompt.astype(np.int32), 8,
                         router.submit(prompt.astype(np.int32), 8, rng_seed=i)))
        router.run(timeout_s=300)

        # overload burst against the 8-deep bound, submitted without polling
        # so nothing dispatches: batch fills the queue, interactive displaces
        # the newest batch entry, batch overflow sheds outright
        small = np.arange(4, dtype=np.int32) + 1
        burst = [router.submit(small, 4, rng_seed=50 + i) for i in range(8)]
        displacer = router.submit(small, 4, priority=PRIORITY_INTERACTIVE, rng_seed=60)
        overflow = router.submit(small, 4, rng_seed=61)
        depth_bounded = router.admission.depth <= 8
        router.run(timeout_s=300)
    finally:
        chaos.arm(None)
        if router is not None:
            router.close()
        tel_events.disable()

    from ..generation import greedy_generate

    params = spec.build_params()
    mismatched = []
    not_finished = []
    for i, (prompt, max_new, req) in enumerate(reqs):
        if req.status is not RouterRequestStatus.FINISHED:
            not_finished.append((i, req.status.value, req.error))
            continue
        ref = greedy_generate(params, prompt[None], config, max_new_tokens=max_new)
        if not np.array_equal(np.asarray(ref[0]), req.output_ids()):
            mismatched.append(i)
    dead = [n for n, r in router.replicas.items() if r.state is ReplicaState.DEAD]
    report = build_report([router_dir])
    text = format_report(report)
    section = report.get("router") or {}
    admitted_burst = [r for r in burst if r.status is not RouterRequestStatus.SHED]
    shed_ok = (
        depth_bounded
        # interactive displaced exactly one batch request, overflow was shed
        and displacer.status is RouterRequestStatus.FINISHED
        and overflow.status is RouterRequestStatus.SHED
        and "queue-full" in (overflow.error or "")
        and sum(1 for r in burst if r.status is RouterRequestStatus.SHED) == 1
        and "displaced" in (burst[-1].error or "")
        and all(r.status is RouterRequestStatus.FINISHED for r in admitted_burst)
    )
    ok = (
        not not_finished
        and not mismatched
        and len(dead) == 1
        and router.failovers >= 1
        and shed_ok
        and section.get("completed") == len(reqs) + len(admitted_burst) + 1
        and (section.get("shed_reasons") or {}).get("queue-full") == 1
        and "router:" in text
        and "failover re-dispatches" in text
        and any(f"{dead[0]}: dead" in line for line in text.splitlines())
    )
    _check(
        "replicated serving router",
        ok,
        f"not_finished={not_finished} mismatched={mismatched} dead={dead} "
        f"failovers={router.failovers} shed_ok={shed_ok} "
        f"section_completed={section.get('completed')}",
    )


def _doctor_observability(tmp: str, _check) -> None:
    """Doctor check 16 body: two thread-backed CPU replicas behind the
    router with tracing + metrics + SLO monitoring armed, a seeded chaos
    ``crash`` killing one replica mid-decode. Requires (a) every FINISHED
    request carries a gap-free span tree and the failover survivor shows
    its retry lineage (two dispatch spans, one trace_id), (b) the live
    ``/metrics`` scrape's router-ttft histogram count equals the
    completions and its quantiles match the report CLI's router section
    (same shared histogram math), and (c) at least one ``slo_violation``
    fires under an artificially tight ttft objective."""
    import dataclasses
    import urllib.request

    import numpy as np

    from ..models import LlamaConfig
    from ..resilience import chaos
    from ..resilience.chaos import ChaosSchedule, Fault
    from ..serving import (
        AdmissionController,
        LocalReplica,
        ReplicaSpec,
        ReplicaState,
        RouterRequestStatus,
        ServingRouter,
    )
    from . import events as tel_events
    from . import metrics as _metrics
    from . import tracing as _tracing
    from .slo import SLOMonitor, serving_slos

    config = LlamaConfig.tiny()
    spec = ReplicaSpec(
        model=dataclasses.asdict(config), num_blocks=33, block_size=8,
        max_slots=2, slot_buckets=(2,), block_buckets=(4,), prefill_buckets=(16,),
    )
    obs_dir = os.path.join(tmp, "observability")
    tel_events.enable(out_dir=obs_dir, run_id="doctor-observability")
    router = None
    try:
        _tracing.arm(1.0)
        # earlier checks ran serving engines with telemetry on, which arms
        # the process-wide registry — this check compares scrape counts
        # against ITS run, so it starts from a fresh one
        _metrics.disable()
        _metrics.enable()
        _metrics.serve(0)  # a real HTTP scrape, not a registry shortcut
        port = _metrics.server_port()
        chaos.arm(ChaosSchedule(
            faults=[Fault(kind="crash", point="serving_decode", step=4)]
        ))
        monitor = SLOMonitor(
            # ttft threshold of 1µs: every request is "bad", the burn rate
            # saturates, and the violation machinery must fire
            serving_slos(ttft_threshold_s=1e-6), min_events=4,
        )
        replicas = [LocalReplica(f"r{i}", spec) for i in range(2)]
        router = ServingRouter(
            replicas,
            admission=AdmissionController(max_queue=16),
            health_timeout_s=10.0,
            slo_monitor=monitor,
            slo_eval_interval_s=0.0,
        )
        router.wait_ready(timeout_s=300)
        rng = np.random.default_rng(16)
        reqs = []
        for i in range(8):
            prompt = rng.integers(0, config.vocab_size, (int(rng.integers(4, 12)),))
            reqs.append(router.submit(prompt.astype(np.int32), 8, rng_seed=i))
        router.run(timeout_s=300)
        scrape = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
    finally:
        chaos.arm(None)
        if router is not None:
            router.close()
        _tracing.disarm()
        _metrics.disable()
        tel_events.disable()

    finished = [r for r in reqs if r.status is RouterRequestStatus.FINISHED]
    tree_problems = {
        r.rid: _tracing.validate_span_tree(r.trace_spans)
        for r in finished
        if _tracing.validate_span_tree(r.trace_spans)
    }
    retried = [r for r in reqs if r.retries > 0]
    lineage_ok = bool(retried) and all(
        sum(1 for s in r.trace_spans if s["name"] == "dispatch") >= 2
        and len({s["trace_id"] for s in r.trace_spans}) == 1
        for r in retried
    )
    dead = [n for n, r in router.replicas.items() if r.state is ReplicaState.DEAD]

    hist = _metrics.histogram_from_scrape(
        _metrics.parse_prometheus_text(scrape), "accelerate_router_ttft_seconds"
    )
    report = build_report([obs_dir])
    router_section = report.get("router") or {}
    report_ttft = (router_section.get("requests") or {}).get("ttft_s") or {}
    scrape_matches = (
        hist is not None
        and hist.count == len(finished)
        # identical bucket math: the record values round at 1e-6, so agree
        # to that precision
        and abs(hist.quantile(0.50) - report_ttft.get("p50", -1)) < 2e-6
        and abs(hist.quantile(0.99) - report_ttft.get("p99", -1)) < 2e-6
    )
    slo_section = report.get("slo") or {}
    text = format_report(report)
    ok = (
        len(finished) == len(reqs)
        and not tree_problems
        and len(dead) == 1
        and lineage_ok
        and scrape_matches
        and (slo_section.get("by_slo") or {}).get("ttft", {}).get("violations", 0) >= 1
        and "SLO:" in text
    )
    _check(
        "observability plane",
        ok,
        f"finished={len(finished)}/{len(reqs)} tree_problems={tree_problems} "
        f"dead={dead} lineage_ok={lineage_ok} hist_count={getattr(hist, 'count', None)} "
        f"report_ttft={report_ttft} slo={slo_section}",
    )


def _doctor_disagg(tmp: str, _check) -> None:
    """Doctor check 17 body: 2 prefill + 2 decode thread-backed CPU replicas
    behind the DisaggRouter. A seeded chaos ``crash`` at the ``kv_handoff``
    point kills one prefill replica after it prefilled but before the
    handoff shipped (the handoff is DROPPED), and a seeded ``corrupt``
    fault damages one handoff payload in flight. Requires (a) every request
    FINISHED exactly once with output bitwise-equal to its single-stream
    ``greedy_generate`` reference (the router re-ran prefill from scratch
    in both fault cases), (b) exactly one prefill replica DEAD and at least
    one corrupt handoff detected by the wire verify, and (c) the router
    report section renders the per-tier breakdown with handoff counts."""
    import dataclasses

    import numpy as np

    from ..models import LlamaConfig
    from ..resilience import chaos
    from ..resilience.chaos import ChaosSchedule, Fault
    from ..serving import (
        DisaggRouter,
        LocalReplica,
        ReplicaSpec,
        ReplicaState,
        RouterRequestStatus,
    )
    from . import events as tel_events

    config = LlamaConfig.tiny()
    spec = ReplicaSpec(
        model=dataclasses.asdict(config), num_blocks=33, block_size=8,
        max_slots=2, slot_buckets=(2,), block_buckets=(4,), prefill_buckets=(16,),
    )
    pspec = dataclasses.replace(spec, role="prefill")
    dspec = dataclasses.replace(spec, role="decode")
    disagg_dir = os.path.join(tmp, "disagg")
    tel_events.enable(out_dir=disagg_dir, run_id="doctor-disagg")
    router = None
    try:
        # once-matched under a lock: exactly one prefill thread dies
        # mid-handoff (crash) and exactly one handoff arrives damaged
        # (corrupt) — the router must recover both without duplicating or
        # losing a single token
        chaos.arm(ChaosSchedule(faults=[
            Fault(kind="corrupt", point="kv_handoff", step=1),
            Fault(kind="crash", point="kv_handoff", step=2),
        ]))
        router = DisaggRouter(
            [LocalReplica(f"p{i}", pspec) for i in range(2)],
            [LocalReplica(f"d{i}", dspec) for i in range(2)],
            health_timeout_s=10.0,
        )
        router.wait_ready(timeout_s=300)
        rng = np.random.default_rng(17)
        reqs = []
        for i in range(6):
            prompt = rng.integers(0, config.vocab_size, (int(rng.integers(4, 14)),))
            reqs.append((prompt.astype(np.int32), 7,
                         router.submit(prompt.astype(np.int32), 7, rng_seed=i)))
        router.run(timeout_s=300)
    finally:
        chaos.arm(None)
        if router is not None:
            router.close()
        tel_events.disable()

    from ..generation import greedy_generate

    params = spec.build_params()
    mismatched = []
    not_finished = []
    for i, (prompt, max_new, req) in enumerate(reqs):
        if req.status is not RouterRequestStatus.FINISHED:
            not_finished.append((i, req.status.value, req.error))
            continue
        ref = greedy_generate(params, prompt[None], config, max_new_tokens=max_new)
        if not np.array_equal(np.asarray(ref[0]), req.output_ids()):
            mismatched.append(i)
    dead = [n for n, r in router.replicas.items() if r.state is ReplicaState.DEAD]
    report = build_report([disagg_dir])
    text = format_report(report)
    tiers = (report.get("router") or {}).get("tiers") or {}
    ok = (
        not not_finished
        and not mismatched
        and len(dead) == 1
        and dead[0] in ("p0", "p1")
        and router.completed == len(reqs)
        and router.handoffs >= len(reqs)
        and router.handoff_corrupt >= 1
        and tiers.get("handoffs", 0) >= len(reqs)
        and (tiers.get("handoff_outcomes") or {}).get("corrupt", 0) >= 1
        and "  tiers: " in text
        and "KV handoff" in text
    )
    _check(
        "disaggregated serving",
        ok,
        f"not_finished={not_finished} mismatched={mismatched} dead={dead} "
        f"completed={router.completed} handoffs={router.handoffs} "
        f"corrupt={router.handoff_corrupt} tiers={tiers}",
    )


def _doctor_goodput(tmp: str, _check) -> None:
    """Doctor check 18 body: a supervised toy training run under a seeded
    chaos schedule — a SIGKILL at train_step 4 in generation 0 (restart
    downtime) plus persistent slow faults at the prefetch point (data-wait
    stalls). The goodput ledger over the run's event streams must attribute
    the injected badput to its causes (restart_downtime > 0, data_wait
    evidence), leave <5% of fleet wall-clock unattributed, agree with the
    report's restarts section by construction (shared restart_stats), and
    render as the report's ``goodput`` section with a verdict line."""
    import subprocess as _subprocess
    import sys

    from . import goodput as _goodput
    from ..resilience.chaos import ChaosSchedule, Fault
    from ..resilience.supervisor import RestartPolicy, Supervisor

    sup_dir = os.path.join(tmp, "goodput")
    os.makedirs(sup_dir, exist_ok=True)
    schedule = ChaosSchedule(faults=[
        Fault(kind="sigkill", point="train_step", step=4, generation=0),
        Fault(kind="slow", point="prefetch", duration_s=0.1, once=False),
    ])
    env = dict(os.environ)
    env.update({
        "ACCELERATE_TELEMETRY": "1",
        "ACCELERATE_TELEMETRY_DIR": sup_dir,
        "JAX_PLATFORMS": "cpu",
        "ACCELERATE_CHAOS_SCHEDULE": schedule.to_json(),
    })
    sup = Supervisor(
        [[sys.executable, "-m", "accelerate_tpu.resilience._toy_train",
          "--project-dir", os.path.join(sup_dir, "project"),
          "--steps", "20", "--save-every", "8"]],
        env=env,
        policy=RestartPolicy(max_restarts=2, backoff_base_s=0.05, grace_period_s=1.0),
        telemetry_dir=sup_dir,
    )
    rc = sup.run()
    rep = build_report([sup_dir])
    gp = rep.get("goodput") or {}
    badput = gp.get("badput_s") or {}
    unattr = gp.get("unattributed_fraction")
    # the unified-computation satellite, asserted: the ledger's restart
    # stats and the restarts section are the same restart_stats() output
    rs = rep.get("restarts") or {}
    agree = (
        (gp.get("restarts") or {}).get("count") == rs.get("count")
        and (gp.get("restarts") or {}).get("downtime_s") == rs.get("downtime_s")
    )
    text = format_report(rep)
    ok = (
        rc == 0
        and sup.restarts_used == 1
        and badput.get("restart_downtime", 0.0) > 0
        and badput.get("data_wait", 0.0) > 0.04
        and unattr is not None and unattr < 0.05
        and agree
        and "goodput: goodput " in text
        and "restart_downtime" in text
    )
    _check(
        "goodput ledger",
        ok,
        f"rc={rc} restarts={sup.restarts_used} "
        f"downtime={badput.get('restart_downtime')} "
        f"data_wait={badput.get('data_wait')} unattributed={unattr} "
        f"agree={agree}",
    )


def _doctor_fused_zero1(_check) -> None:
    """Doctor check 9 body: jaxlint R3/R4 over the fused-update module +
    accelerator, then a subprocess self_check compiling the fused step and
    summing collective bytes out of its HLO."""
    import subprocess
    import sys

    from ..analysis import run_lint

    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    targets = [
        os.path.join(pkg_dir, "parallel", "weight_update.py"),
        os.path.join(pkg_dir, "accelerator.py"),
    ]
    result = run_lint(targets, use_baseline=False)
    bad = [f for f in result.new_findings if f.rule in ("R3", "R4")]
    _check(
        "fused zero1 lints clean (R3/R4)",
        not bad,
        "; ".join(f"{f.rule}:{os.path.basename(f.file)}:{f.line}" for f in bad),
    )

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # self_check sets the virtual device count
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import json; from accelerate_tpu.parallel.weight_update import "
            "self_check; print(json.dumps(self_check()))",
        ],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
        cwd=os.path.dirname(pkg_dir),
    )
    ok = False
    detail = f"exit {proc.returncode}: {proc.stderr[-300:]}"
    if proc.returncode == 0:
        try:
            payload = json.loads(proc.stdout.strip().splitlines()[-1])
            ok = (
                payload["hlo_total_collective_bytes"] > 0
                and payload["plan_collective_bytes"] > 0
                and payload["opt_state_shard_fraction"] == 1.0 / payload["n_devices"]
                and payload["parity_max_abs_delta"] < 1.5e-7
            )
            detail = f"payload={payload}"
        except Exception as exc:
            detail = f"unparseable self_check output: {exc}"
    _check("fused zero1 compiled collectives", ok, detail)


def _doctor_fp8_train_step(_check) -> None:
    """Doctor check 21 body: subprocess ``ops.fp8.self_check`` — the fp8
    train step through the FUSED ZeRO-1 path on 8 virtual devices. The
    payload must show the fused path engaged (not demoted by the meta
    leaves), meta riding as passthrough slots, 1/N opt-state sharding,
    loss parity with the replicated stage-0 baseline, rolled amax
    histories, and a jit cache frozen after the warmup compile."""
    import subprocess
    import sys

    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # self_check sets the virtual device count
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import json; from accelerate_tpu.ops.fp8 import "
            "self_check; print(json.dumps(self_check()))",
        ],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
        cwd=os.path.dirname(pkg_dir),
    )
    ok = False
    detail = f"exit {proc.returncode}: {proc.stderr[-300:]}"
    if proc.returncode == 0:
        try:
            payload = json.loads(proc.stdout.strip().splitlines()[-1])
            ok = (
                payload["fused_engaged"] is True
                and payload["plan_fused"] is True
                and payload["passthrough_leaves"] > 0
                and payload["opt_state_shard_fraction"] == 1.0 / payload["n_devices"]
                and payload["loss_parity_max_rel_delta"] < 1.5e-7
                and payload["meta_histories_rolled"] is True
                and payload["jit_cache_at_end"] == payload["jit_cache_after_warmup"] == 1
            )
            detail = f"payload={payload}"
        except Exception as exc:
            detail = f"unparseable self_check output: {exc}"
    _check("fp8 fused zero1 train step", ok, detail)


def _doctor_performance_section(tmp: str, _check) -> None:
    """Doctor check 8 body: synthetic perf/step/trace records must aggregate
    and render as a performance section with non-zero MFU."""
    perf_dir = os.path.join(tmp, "perfrep")
    os.makedirs(perf_dir, exist_ok=True)
    with open(os.path.join(perf_dir, "events-rank0.jsonl"), "w") as f:
        f.write(json.dumps({"kind": "meta", "schema": 1, "run_id": "doctor",
                            "process_index": 0, "num_processes": 1}) + "\n")
        f.write(json.dumps({
            "kind": "perf", "t": 0.0, "fn": "train_step", "flops": 1e9,
            "bytes_accessed": 1e7, "arithmetic_intensity": 100.0,
            "roofline": "compute-bound", "peak_flops": 1e11,
            "peak_hbm_bytes_per_s": 2.5e10, "peak_source": "cpu-nominal",
            "device_kind": "cpu"}) + "\n")
        for s in range(4):
            f.write(json.dumps({
                "kind": "step", "step": s, "t": float(s), "dur_s": 0.02,
                "compile_s": 0.0, "execute_s": 0.02, "mfu": 0.5,
                "arithmetic_intensity": 100.0, "roofline": "compute-bound",
                "perf_fn": "train_step"}) + "\n")
        f.write(json.dumps({
            "kind": "trace", "t": 5.0, "events": 10, "ops": 3,
            "span_s": 0.1, "busy_s": 0.09, "idle_s": 0.01,
            "compute_s": 0.08, "collective_s": 0.02,
            "collective_overlap_s": 0.015, "comms_overlap_ratio": 0.75,
            "top_ops": [{"op": "fusion.1", "total_s": 0.05, "count": 4,
                         "share": 0.6, "collective": False},
                        {"op": "all-reduce.2", "total_s": 0.02, "count": 2,
                         "share": 0.24, "collective": True}]}) + "\n")
    rep = build_report([perf_dir])
    perf_section = rep.get("performance") or {}
    text = format_report(rep)
    ok = (
        (perf_section.get("mfu") or {}).get("p50", 0) > 0
        and (perf_section.get("trace") or {}).get("comms_overlap_ratio") == 0.75
        and "performance:" in text
        and "compute-bound" in text
        and "75.0% of collective time hidden" in text
    )
    _check("performance report section", ok, f"performance={perf_section}")


def _doctor_live_plane(tmp: str, _check) -> None:
    """Doctor check 20 body: the live observability plane end to end.

    Four sub-scenarios share one telemetry dir: (a) a supervised child is
    SIGKILLed in generation 0 and completes in generation 1, streaming
    live ``supervisor`` status records; (b) the hub tails a rank stream
    WHILE it grows — across a slow-step burst and a torn trailing line —
    and the step-latency detector fires exactly one episode, live, with a
    cause hypothesis; (c) a two-replica CPU fleet under a seeded slow
    fault runs bitwise canaries where one replica's params come from a
    different seed (genuinely corrupt weights): the bad replica must
    drain on its first mismatch with the differing token named, and the
    healthy replica must show zero false positives; (d) ``top --once``
    over the same dir must contain the post-hoc report's router and
    canary sections string-exact — the shared-formatter invariant."""
    import dataclasses
    import io
    import sys
    import time

    from ..models import LlamaConfig
    from ..resilience import chaos
    from ..resilience.chaos import ChaosSchedule, Fault
    from ..resilience.supervisor import RestartPolicy, Supervisor
    from ..serving import (
        CanaryProbe,
        LocalReplica,
        ReplicaSpec,
        ReplicaState,
        ServingRouter,
        precompute_goldens,
    )
    from . import events as tel_events
    from .anomaly import AnomalyEngine
    from .hub import EventHub, run_top

    live_dir = os.path.join(tmp, "live")
    os.makedirs(live_dir, exist_ok=True)

    # (a) supervised fleet under seeded SIGKILL: generation 0 kills itself,
    # generation 1 completes; status_interval_s=0 streams a `supervisor`
    # status record every watch iteration for the hub to fold live.
    child = (
        "import os, signal\n"
        "if os.environ.get('ACCELERATE_RESTART_GENERATION', '0') == '0':\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"
    )
    sup = Supervisor(
        [[sys.executable, "-c", child]],
        policy=RestartPolicy(max_restarts=2, backoff_base_s=0.05, grace_period_s=1.0),
        telemetry_dir=live_dir,
        status_interval_s=0.0,
    )
    sup_rc = sup.run()

    # (b) tail a stream WHILE it grows: three installments with a hub poll
    # between each — warmup steps, then a slow burst ending in a torn
    # line, then the torn line's completion. The burst must fire exactly
    # one live episode; the torn record must parse exactly once, whole.
    hub = EventHub([live_dir], anomaly=AnomalyEngine(emit_records=False))
    hub.poll()
    sup_folded = hub.model.supervisor is not None and hub.model.generation == 1
    rank_path = os.path.join(live_dir, "events-rank7.jsonl")
    with open(rank_path, "w") as f:
        f.write(json.dumps({"kind": "meta", "schema": 1, "run_id": "doctor-live",
                            "process_index": 7, "num_processes": 8}) + "\n")
        for s in range(30):
            f.write(json.dumps({"kind": "step", "step": s, "t": float(s),
                                "dur_s": 0.01, "execute_s": 0.01}) + "\n")
    n1 = len(hub.poll())
    episodes_warm = hub.anomaly.step_latency.episodes
    with open(rank_path, "a") as f:
        for s in range(30, 36):
            f.write(json.dumps({"kind": "step", "step": s, "t": float(s),
                                "dur_s": 0.2, "execute_s": 0.2}) + "\n")
        f.write('{"kind": "step", "step": 36, "t"')  # torn mid-record
    n2 = len(hub.poll())  # 6 slow steps + 1 synthetic anomaly record
    episodes_live = hub.anomaly.step_latency.episodes
    with open(rank_path, "a") as f:
        f.write(': 36.0, "dur_s": 0.01}\n')  # the writer finishes the line
    n3 = len(hub.poll())
    first_anomaly = hub.anomaly.anomalies[0] if hub.anomaly.anomalies else {}
    tail_ok = (
        sup_folded
        and n1 == 31 and episodes_warm == 0
        and n2 == 7 and episodes_live == 1
        and n3 == 1
        and hub.anomaly.step_latency.episodes == 1  # hysteresis held
        and "straggler" in str(first_anomaly.get("cause"))
        and first_anomaly.get("source") == "events-rank7.jsonl"
    )

    # (c) bitwise canaries against a seeded corruption: the bad replica
    # shares the fleet spec but builds its params from a different seed —
    # init is deterministic, so its weights are genuinely wrong and its
    # canary answers diverge bitwise while the healthy replica's match,
    # even with a seeded slow fault injected into the decode path.
    config = LlamaConfig.tiny()
    spec = ReplicaSpec(
        model=dataclasses.asdict(config), num_blocks=33, block_size=8,
        max_slots=2, slot_buckets=(2,), block_buckets=(4,), prefill_buckets=(16,),
    )
    bad_spec = dataclasses.replace(spec, param_seed=1234)
    goldens = precompute_goldens(spec, max_new_tokens=6)
    probe = CanaryProbe(goldens, interval_s=0.05)
    tel_events.enable(out_dir=live_dir, run_id="doctor-live")
    router = None
    try:
        chaos.arm(ChaosSchedule(
            faults=[Fault(kind="slow", point="serving_decode", step=4,
                          duration_s=0.2, once=True)]
        ))
        router = ServingRouter(
            [LocalReplica("good", spec), LocalReplica("bad", bad_spec)],
            canary=probe,
            health_timeout_s=10.0,
        )
        router.wait_ready(timeout_s=300)
        deadline = time.monotonic() + 300
        while (probe.by_replica.get("bad", {}).get("failures", 0) < 1
               or probe.by_replica.get("good", {}).get("probes", 0) < 1
               or router._inflight):
            router.poll()
            if time.monotonic() > deadline:
                raise RuntimeError("canary scenario timed out")
            time.sleep(0.002)
    finally:
        chaos.arm(None)
        if router is not None:
            router.close()
        tel_events.disable()

    # (d) the shared-formatter invariant: `top --once` must render the
    # degraded fleet through the report CLI's own section formatters, so
    # the post-hoc report's router and canary sections appear in the live
    # frame string-exact.
    post = build_report([live_dir])
    canary_sec = post.get("canary") or {}
    mismatches = canary_sec.get("mismatches") or []
    buf = io.StringIO()
    rc_top = run_top([live_dir], once=True, out=buf)
    frame = buf.getvalue()
    shared_ok = (
        rc_top == 0
        and format_router_section(post.get("router") or {}) in frame
        and format_canary_section(canary_sec) in frame
        and "bad: draining" in frame
    )
    canary_ok = (
        router.replicas["bad"].state is ReplicaState.DRAINING
        and probe.by_replica.get("good", {}).get("failures") == 0
        and probe.by_replica.get("bad", {}).get("failures", 0) >= 1
        and bool(mismatches)
        and mismatches[0].get("replica") == "bad"
        and mismatches[0].get("mismatch_index") is not None
    )
    ok = sup_rc == 0 and sup.restarts_used == 1 and tail_ok and canary_ok and shared_ok
    _check(
        "live observability plane",
        ok,
        f"sup_rc={sup_rc} restarts={sup.restarts_used} tail_ok={tail_ok} "
        f"(n1={n1} n2={n2} n3={n3} episodes={hub.anomaly.step_latency.episodes}) "
        f"canary_ok={canary_ok} (probe={probe.stats()}) shared_ok={shared_ok}",
    )


def main(argv: Optional["list[str]"] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m accelerate_tpu.telemetry",
        description="Aggregate accelerate_tpu telemetry JSONL event streams.",
    )
    sub = parser.add_subparsers(dest="command")
    rep = sub.add_parser("report", help="aggregate one or more event dirs/files")
    rep.add_argument("paths", nargs="+", help="telemetry dir(s) or .jsonl file(s)")
    rep.add_argument("--json", action="store_true", help="print the raw report dict")
    rep.add_argument(
        "--by-rank",
        action="store_true",
        help="cross-rank straggler section: per-step rank skew, heartbeat gaps, "
        "flight records",
    )
    rep.add_argument(
        "--request",
        metavar="ID",
        help="render one request's distributed-trace span timeline (router rid "
        "like q3, an engine rid, or a raw trace id) instead of the aggregate report",
    )
    rep.add_argument(
        "--trace-out",
        metavar="FILE",
        help="write the span records as a Chrome trace.json (with --request: "
        "that request only; alone: every recorded trace)",
    )
    rep.add_argument(
        "--follow",
        action="store_true",
        help="stream: tail the event files live and re-render the report "
        "whenever they grow (telemetry/hub.py)",
    )
    rep.add_argument(
        "--interval",
        type=float,
        default=2.0,
        metavar="S",
        help="poll interval for --follow / top (seconds, default 2)",
    )
    rep.add_argument(
        "--follow-ticks",
        type=int,
        default=None,
        metavar="N",
        help="stop --follow after N polls (tests/CI; default: run forever)",
    )
    top = sub.add_parser(
        "top",
        help="live fleet dashboard over the tailed event streams "
        "(telemetry/hub.py): replica health, queues, SLO burn, anomalies, "
        "canaries",
    )
    top.add_argument("paths", nargs="+", help="telemetry dir(s) or .jsonl file(s)")
    top.add_argument(
        "--once",
        action="store_true",
        help="render a single frame with no ANSI clear and exit (tests/CI)",
    )
    top.add_argument("--interval", type=float, default=2.0, metavar="S",
                     help="refresh interval (seconds, default 2)")
    top.add_argument("--ticks", type=int, default=None, metavar="N",
                     help="stop after N frames (default: run until ^C)")
    sub.add_parser("doctor", help="self-check the watchdog/flight-recorder/report pipeline")
    _regress.add_parser(sub)
    args = parser.parse_args(argv)
    if args.command == "doctor":
        return run_doctor()
    if args.command == "regress":
        return _regress.run_from_args(args)
    if args.command == "top":
        # lazy import: hub imports this module — the CLI edge must not
        # turn that into an import cycle at load time
        from . import hub as _hub

        return _hub.run_top(
            args.paths, once=args.once, interval_s=args.interval,
            max_ticks=args.ticks,
        )
    if args.command != "report":
        parser.print_help()
        return 2
    if args.follow:
        from . import hub as _hub

        return _hub.run_follow(
            args.paths, by_rank=args.by_rank, interval_s=args.interval,
            max_ticks=args.follow_ticks,
        )
    if args.request is not None:
        rc, text = render_request(args.paths, args.request, trace_out=args.trace_out)
        print(text)
        return rc
    if args.trace_out is not None:
        rc, text = export_traces(args.paths, args.trace_out)
        print(text)
        return rc
    report = build_report(args.paths, by_rank=args.by_rank)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(format_report(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
