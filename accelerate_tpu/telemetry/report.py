"""Aggregate telemetry JSONL streams into a human/driver-readable report.

``python -m accelerate_tpu.telemetry report <dir-or-file>...`` reads every
``*.jsonl`` stream (one per rank), merges them, and prints:

- per-step wall-time / data-wait / execute percentiles (p50/p90/p99),
- compile totals and the recompile count per compiled function — a nonzero
  recompile total after warmup is the classic silent reshape cliff,
- a data-pipeline section: per-phase input wait (fetch / transfer / stall),
  prefetch queue occupancy and the overlap ratio — how much of the input
  pipeline was hidden behind device compute,
- device/host memory peaks,
- comms traffic per collective op (calls + payload bytes).

``--json`` emits the raw report dict for drivers.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Iterable, Optional

PERCENTILES = (50, 90, 99)


def iter_event_files(paths: Iterable[str]) -> "list[str]":
    files: list[str] = []
    for path in paths:
        if os.path.isdir(path):
            files.extend(
                sorted(
                    os.path.join(path, name)
                    for name in os.listdir(path)
                    if name.endswith(".jsonl")
                )
            )
        else:
            files.append(path)
    return files


def load_events(paths: Iterable[str]) -> "list[dict]":
    events: list[dict] = []
    for file in iter_event_files(paths):
        try:
            with open(file) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail line from a killed run
                    if isinstance(rec, dict):
                        rec.setdefault("_file", os.path.basename(file))
                        events.append(rec)
        except OSError:
            continue
    return events


def percentile(values: "list[float]", p: int) -> float:
    """Nearest-rank percentile (ceil rank) of an already-sorted list."""
    if not values:
        return 0.0
    idx = min(len(values) - 1, max(0, math.ceil(p / 100.0 * len(values)) - 1))
    return values[idx]


def _dist(values: "list[float]") -> dict:
    values = sorted(values)
    if not values:
        return {"count": 0}
    return {
        "count": len(values),
        "mean": round(sum(values) / len(values), 6),
        "max": round(values[-1], 6),
        **{f"p{p}": round(percentile(values, p), 6) for p in PERCENTILES},
    }


def build_report(paths: Iterable[str]) -> dict:
    events = load_events(paths)
    metas = [e for e in events if e.get("kind") == "meta"]
    steps = [e for e in events if e.get("kind") == "step"]
    misses = [e for e in events if e.get("kind") == "jit_cache_miss"]
    memory = [e for e in events if e.get("kind") == "memory"]
    comms = [e for e in events if e.get("kind") == "comm"]
    waits = [e for e in events if e.get("kind") == "data_wait"]

    by_fn: dict = {}
    for m in misses:
        fn = str(m.get("fn", "?"))
        by_fn[fn] = by_fn.get(fn, 0) + int(m.get("recompiles", 0))
    comm_ops: dict = {}
    for c in comms:
        op = str(c.get("op", "?"))
        rec = comm_ops.setdefault(op, {"calls": 0, "bytes": 0})
        rec["calls"] += 1
        rec["bytes"] += int(c.get("bytes", 0))

    # -- data pipeline: per-phase waits + prefetch overlap --------------------
    by_phase: dict = {}
    critical_wait = 0.0
    for w in waits:
        phase = str(w.get("phase", "?"))
        dur = float(w.get("dur_s", 0.0))
        by_phase.setdefault(phase, []).append(dur)
        # records predating the async pipeline carry no flag: they were
        # synchronous, i.e. critical
        if w.get("critical", True):
            critical_wait += dur
    summaries = [e for e in events if e.get("kind") == "prefetch_summary"]
    occupancy = [
        float(e.get("value", 0))
        for e in events
        if e.get("kind") == "gauge" and e.get("name") == "prefetch_queue"
    ]
    prefetch: dict = {
        "epochs": len(summaries),
        "batches": sum(int(s.get("batches", 0)) for s in summaries),
        "fetch_s": round(sum(float(s.get("fetch_s", 0.0)) for s in summaries), 6),
        "transfer_s": round(sum(float(s.get("transfer_s", 0.0)) for s in summaries), 6),
        "stall_s": round(sum(float(s.get("stall_s", 0.0)) for s in summaries), 6),
        "queue_occupancy": _dist(occupancy),
    }
    busy = prefetch["fetch_s"] + prefetch["transfer_s"]
    if busy > 0:
        prefetch["overlap_ratio"] = round(
            max(0.0, min(1.0, 1.0 - prefetch["stall_s"] / busy)), 6
        )

    report = {
        "schema": max((int(m.get("schema", 0)) for m in metas), default=0),
        "runs": sorted({str(m.get("run_id")) for m in metas if m.get("run_id")}),
        "processes": len({m.get("process_index") for m in metas}) or None,
        "events": len(events),
        "steps": {
            "count": len(steps),
            "wall_s": _dist([float(s.get("dur_s", 0.0)) for s in steps]),
            "data_wait_s": _dist([float(s.get("data_wait_s", 0.0)) for s in steps]),
            "execute_s": _dist([float(s.get("execute_s", 0.0)) for s in steps]),
            "compile_s_total": round(sum(float(s.get("compile_s", 0.0)) for s in steps), 6),
        },
        "recompiles": {
            "total": sum(by_fn.values()),
            "initial_compiles": sum(1 for m in misses if m.get("first")),
            "by_fn": dict(sorted(by_fn.items())),
        },
        "memory": {
            "device_peak_bytes": max((int(m.get("device_peak_bytes", 0)) for m in memory), default=0),
            "live_array_peak_bytes": max((int(m.get("live_array_bytes", 0)) for m in memory), default=0),
            "host_rss_peak_bytes": max((int(m.get("host_rss_bytes", 0)) for m in memory), default=0),
        },
        "comms": {
            "total_calls": sum(r["calls"] for r in comm_ops.values()),
            "total_bytes": sum(r["bytes"] for r in comm_ops.values()),
            "by_op": dict(sorted(comm_ops.items())),
        },
        "data_pipeline": {
            "phases": {
                p: dict(_dist(v), total=round(sum(v), 6)) for p, v in sorted(by_phase.items())
            },
            "critical_wait_s": round(critical_wait, 6),
            "prefetch": prefetch,
        },
        "data_wait_events": len(waits),
    }
    return report


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TiB"


def format_report(report: dict) -> str:
    lines = []
    runs = ", ".join(report.get("runs") or []) or "<none>"
    lines.append(f"telemetry report — run(s): {runs}, "
                 f"{report.get('processes') or 0} process(es), {report['events']} events")
    s = report["steps"]
    lines.append(f"steps: {s['count']}")
    for key, label in (("wall_s", "step time"), ("data_wait_s", "data wait"), ("execute_s", "execute")):
        d = s[key]
        if d.get("count"):
            lines.append(
                f"  {label:<10} p50={d['p50'] * 1e3:.2f}ms  p90={d['p90'] * 1e3:.2f}ms  "
                f"p99={d['p99'] * 1e3:.2f}ms  max={d['max'] * 1e3:.2f}ms"
            )
    lines.append(f"  compile total: {s['compile_s_total'] * 1e3:.2f}ms")
    r = report["recompiles"]
    lines.append(f"recompiles: {r['total']} (initial compiles: {r['initial_compiles']})")
    for fn, n in r["by_fn"].items():
        if n:
            lines.append(f"  {fn}: {n} recompile(s) — check for varying input shapes/dtypes")
    dp = report.get("data_pipeline") or {}
    if dp.get("phases"):
        lines.append(
            f"data pipeline: critical wait {dp['critical_wait_s'] * 1e3:.2f}ms"
        )
        for phase, d in dp["phases"].items():
            if d.get("count"):
                lines.append(
                    f"  {phase:<10} n={d['count']}  total={d['total'] * 1e3:.2f}ms  "
                    f"p50={d['p50'] * 1e3:.2f}ms  max={d['max'] * 1e3:.2f}ms"
                )
        pf = dp.get("prefetch") or {}
        if pf.get("epochs"):
            ratio = pf.get("overlap_ratio")
            ratio_s = f"{ratio * 100:.1f}% of input work hidden" if ratio is not None else "n/a"
            occ = pf.get("queue_occupancy") or {}
            occ_s = f", queue occupancy p50={occ['p50']:.1f}" if occ.get("count") else ""
            lines.append(
                f"  prefetch: {pf['batches']} batch(es) over {pf['epochs']} epoch(s), "
                f"overlap {ratio_s}{occ_s}"
            )
    m = report["memory"]
    lines.append(
        "memory peaks: device "
        + _fmt_bytes(m["device_peak_bytes"])
        + ", live arrays "
        + _fmt_bytes(m["live_array_peak_bytes"])
        + ", host rss "
        + _fmt_bytes(m["host_rss_peak_bytes"])
    )
    c = report["comms"]
    lines.append(f"comms: {c['total_calls']} call(s), {_fmt_bytes(c['total_bytes'])} total")
    for op, rec in c["by_op"].items():
        lines.append(f"  {op}: {rec['calls']} call(s), {_fmt_bytes(rec['bytes'])}")
    return "\n".join(lines)


def main(argv: Optional["list[str]"] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m accelerate_tpu.telemetry",
        description="Aggregate accelerate_tpu telemetry JSONL event streams.",
    )
    sub = parser.add_subparsers(dest="command")
    rep = sub.add_parser("report", help="aggregate one or more event dirs/files")
    rep.add_argument("paths", nargs="+", help="telemetry dir(s) or .jsonl file(s)")
    rep.add_argument("--json", action="store_true", help="print the raw report dict")
    args = parser.parse_args(argv)
    if args.command != "report":
        parser.print_help()
        return 2
    report = build_report(args.paths)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(format_report(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
