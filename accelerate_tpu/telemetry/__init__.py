"""TPU-native observability: structured step events, recompile/memory/comms
metrics, and a report CLI.

The reference stack treats observability as an external concern (trackers
only); here it is a subsystem, because the signals that decide TPU throughput
— XLA recompiles, device-memory watermarks, collective traffic — are invisible
to a loss-curve tracker. Layout:

- :mod:`.events` — JSONL event log with an env kill switch
  (``ACCELERATE_TELEMETRY=1`` to enable, ``ACCELERATE_TELEMETRY_DIR`` for the
  output directory). Zero overhead when disabled.
- :mod:`.step_profiler` — per-step wall/data-wait/compile/execute split plus
  recompile detection (per-function jit cache-miss counting).
- :mod:`.memory` — device/host memory watermarks sampled at step boundaries,
  plus compile-time ``memory_analysis()`` projections checked against device
  capacity (the OOM caught before it happens).
- :mod:`.perf` — performance attribution: hardware peak registry (bf16
  FLOP/s + HBM bandwidth per chip generation), compile-time
  ``cost_analysis()`` capture for every tracked step function, and the
  per-step MFU / arithmetic-intensity / roofline folding.
- :mod:`.xplane` — programmatic ``jax.profiler`` trace windows
  (every-Nth-step / one-shot via ``ProfileConfig`` or ``ACCELERATE_TRACE_*``)
  and a dependency-free ``*.xplane.pb`` parser producing top-k op durations,
  the compute/collective/idle device-time split, and the comms-overlap ratio.
- :mod:`.flight_recorder` — always-on in-memory ring of recent events plus
  crash handlers (SIGTERM / unhandled exception / faulthandler) that dump
  ``flight-rank<k>.json`` post-mortems: ring, all-thread stacks, open phases,
  memory snapshot.
- :mod:`.watchdog` — heartbeat thread (``ACCELERATE_WATCHDOG_TIMEOUT``) that
  detects stalled heartbeat sources and blocked phases (e.g. a rank stuck in
  ``collective:gather``), dumps the flight record and optionally aborts.
- :mod:`.tracing` — request-scoped distributed tracing for the serving
  path: a dependency-free span model with context propagation across the
  replica transports (``ACCELERATE_TRACE_SAMPLE`` arms it; SHED/FAILED/
  failover traces are always kept), Chrome ``trace.json`` export and the
  gap-free span-tree validator.
- :mod:`.metrics` — the streaming metrics plane: typed
  counter/gauge/histogram registry fed by the serving stack, Prometheus
  text exposition from a stdlib HTTP thread (``ACCELERATE_METRICS_PORT``),
  periodic ``metrics`` snapshot records, and THE shared
  histogram/percentile implementation the report CLI and benches use.
- :mod:`.slo` — SLO burn-rate monitoring: declarative objectives (ttft,
  availability, shed rate, step latency, restart downtime) evaluated over
  fast/slow windows, ``slo_violation`` records, and the burning-replica
  signal the serving router folds into dispatch.
- :mod:`.goodput` — the fleet goodput/badput ledger: every wall-clock
  second attributed into a fixed taxonomy (productive execute vs compile,
  data-wait, exposed checkpoint stalls, restart downtime, cold compiles,
  scale-up waits, serving idle) from the existing event streams, plus the
  serving-side token ledger (useful vs re-computed tokens). Renders as the
  report CLI's ``goodput`` section, periodic ``goodput`` snapshot records,
  and Prometheus gauges; every run ends in a one-line verdict.
- :mod:`.regress` — the continuous perf-regression sentinel:
  ``python -m accelerate_tpu.telemetry regress`` compares bench payloads
  grouped by environment fingerprint against a per-metric registry
  (direction, noise tolerance, hard bars) and exits nonzero on regression
  (``make bench-check``).
- :mod:`.report` — ``python -m accelerate_tpu.telemetry report <dir>``
  aggregation CLI (percentiles, recompile totals, memory peaks, comms bytes;
  ``--request <id>`` renders one request's span timeline, ``--trace-out``
  exports it as a Chrome trace; ``--by-rank`` adds cross-rank
  straggler/heartbeat/flight forensics; ``--follow`` streams it) and the
  ``doctor`` self-check subcommand.
- :mod:`.hub` — the live fleet hub: a stdlib-only file tailer over the
  event streams (rotation/truncation/torn-line safe) folding into one
  ``FleetModel``, the ``python -m accelerate_tpu.telemetry top`` dashboard
  rendering through the report CLI's own section formatters, and
  ``report --follow``.
- :mod:`.anomaly` — online anomaly detectors over the live streams:
  EWMA z-scores (step latency, ttft, spec accept rate, heartbeat gaps)
  and a block-pool-leak trend detector, hysteresis one ``anomaly`` record
  per episode with a cause hypothesis, plus an
  ``accelerate_anomalies_total`` counter.
- :mod:`.tracker_bridge` — mirrors report summaries into ``tracking.py``
  trackers so the metrics land wherever users already log.

Comms counters live in :mod:`accelerate_tpu.utils.operations` (the ops being
counted) and write through :mod:`.events`.
"""

from . import flight_recorder, goodput, metrics, perf, regress, slo, tracing, watchdog, xplane
from .events import (
    TELEMETRY_DIR_ENV_VAR,
    TELEMETRY_ENV_VAR,
    TELEMETRY_SCHEMA_VERSION,
    EventLog,
    counter,
    disable,
    emit,
    enable,
    enabled_from_env,
    gauge,
    get_event_log,
    hard_flush,
    is_enabled,
    maybe_enable_from_env,
    set_step,
    span,
)
from .flight_recorder import FlightRecorder
from .memory import MemoryMonitor, device_memory_stats, host_memory_bytes, live_array_bytes
from .metrics import Histogram, MetricsRegistry
from .perf import CompiledCost, HardwarePeaks, capture_compiled, lm_train_mfu, peaks_for_device
from .slo import SLObjective, SLOMonitor
from .step_profiler import RecompileWatcher, StepTelemetry, record_data_wait
from .tracing import TraceContext
from .tracker_bridge import mirror_to_trackers, summary_metrics
from .watchdog import Watchdog
from .xplane import TraceWindows, summarize_trace

__all__ = [
    "TELEMETRY_DIR_ENV_VAR",
    "TELEMETRY_ENV_VAR",
    "TELEMETRY_SCHEMA_VERSION",
    "CompiledCost",
    "EventLog",
    "FlightRecorder",
    "HardwarePeaks",
    "Histogram",
    "MemoryMonitor",
    "MetricsRegistry",
    "RecompileWatcher",
    "SLOMonitor",
    "SLObjective",
    "StepTelemetry",
    "TraceContext",
    "TraceWindows",
    "Watchdog",
    "capture_compiled",
    "counter",
    "device_memory_stats",
    "disable",
    "emit",
    "enable",
    "enabled_from_env",
    "flight_recorder",
    "gauge",
    "get_event_log",
    "goodput",
    "hard_flush",
    "host_memory_bytes",
    "is_enabled",
    "live_array_bytes",
    "lm_train_mfu",
    "maybe_enable_from_env",
    "metrics",
    "mirror_to_trackers",
    "peaks_for_device",
    "perf",
    "record_data_wait",
    "regress",
    "set_step",
    "slo",
    "span",
    "summarize_trace",
    "summary_metrics",
    "tracing",
    "watchdog",
    "xplane",
]
