"""Live fleet observability hub: tail the telemetry streams while they grow.

Every other observability surface in this package is post-hoc — the report
CLI, the goodput ledger, and the regress sentinel all read finished JSONL
streams. The hub is the live plane over the SAME streams: a stdlib-only
file-tailing collector (no new wire protocol — the telemetry files already
are the fleet's bus) that follows ``events-rank*.jsonl``,
``events-supervisor.jsonl``, and the router/replica record streams
incrementally, and folds every record into one :class:`FleetModel`.

Three design rules keep the live and post-hoc views honest with each other:

- **One fold.** ``FleetModel.snapshot_report()`` runs the accumulated
  records through :func:`~.report.build_report_from_events` — the exact
  function ``report`` uses — and :func:`render_top` renders sections with
  the report CLI's own ``format_*_section`` formatters. ``top --once`` and
  ``report`` over the same stream print the same numbers because they are
  the same code (``make doctor`` check 20 asserts the strings match).
- **Tailing must survive the writer.** :class:`FileTail` keeps a byte
  offset, the file's identity (inode), and the trailing partial line; a
  rotated file (identity changed) or a truncated one (size shrank under
  the offset) restarts from zero, and a torn final line is buffered until
  its newline arrives — records are parsed exactly once, whole.
- **Detection happens on the way in.** An
  :class:`~.anomaly.AnomalyEngine` observes every tailed record; fired
  episodes are folded back into the model (kind ``anomaly``, synthetic
  stream :data:`HUB_STREAM`) so the dashboard pages with a cause
  hypothesis while the run is still degrading.

Entry points: ``python -m accelerate_tpu.telemetry top <dir>`` (ANSI live
dashboard; ``--once`` renders a single frame for tests/CI) and
``python -m accelerate_tpu.telemetry report --follow <dir>`` (append-only
streaming report). Both take an injectable clock/sleep for determinism.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Callable, Iterable, Optional

from . import anomaly as _anomaly
from . import goodput as _goodput
from . import report as _report

__all__ = ["FileTail", "FleetModel", "EventHub", "render_top", "run_top", "run_follow"]

#: ``_file`` stamp for records the hub synthesizes (fired anomalies) —
#: a name no real rank stream can collide with, and one the goodput
#: ledger's per-stream segmenting ignores (it carries no ``meta`` line).
HUB_STREAM = "<hub>"

#: ANSI: clear screen + home cursor, printed between live frames.
ANSI_CLEAR = "\x1b[2J\x1b[H"


class FileTail:
    """Incremental reader for one growing JSONL stream.

    ``poll()`` returns the complete records appended since the last poll,
    each stamped with ``_file`` (basename) exactly like
    :func:`~.report.load_events` does. Rotation (same path, new file
    identity) and truncation (size shrank below our offset) reset the tail
    to byte 0; a partial trailing line is held in a buffer until the writer
    finishes it; unparseable lines are skipped, matching ``load_events``'s
    torn-line tolerance."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self.resets = 0
        self._identity: Optional["tuple[int, int]"] = None
        self._buf = b""

    def poll(self) -> "list[dict]":
        try:
            st = os.stat(self.path)
        except OSError:
            return []
        identity = (st.st_dev, st.st_ino)
        if self._identity is not None and identity != self._identity:
            # rotation: a new file moved in under the same name
            self.offset = 0
            self._buf = b""
            self.resets += 1
        self._identity = identity
        if st.st_size < self.offset:
            # truncation: the writer restarted the file in place
            self.offset = 0
            self._buf = b""
            self.resets += 1
        if st.st_size == self.offset and not self._buf:
            return []
        try:
            with open(self.path, "rb") as f:
                f.seek(self.offset)
                chunk = f.read()
                self.offset = f.tell()
        except OSError:
            return []
        data = self._buf + chunk
        lines = data.split(b"\n")
        # the final element is the bytes after the last newline: a torn
        # trailing line (or b""). Hold it until the writer completes it.
        self._buf = lines.pop()
        base = os.path.basename(self.path)
        records: "list[dict]" = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line.decode("utf-8"))
            except (UnicodeDecodeError, ValueError):
                continue
            if isinstance(rec, dict):
                rec["_file"] = base
                records.append(rec)
        return records


class FleetModel:
    """The live fold: accumulated records plus cheap per-record state for
    the dashboard header (replica health, queue depth, supervisor status,
    episode tallies). The heavy aggregation is NOT duplicated here —
    :meth:`snapshot_report` defers to the report CLI's
    :func:`~.report.build_report_from_events` over ``self.records``."""

    def __init__(self):
        self.records: "list[dict]" = []
        self.kinds: "dict[str, int]" = {}
        self.replicas: "dict[str, dict]" = {}
        self.router_poll: Optional[dict] = None
        self.supervisor: Optional[dict] = None
        self.generation = 0
        self.restarts = 0
        self.slo_violations = 0
        self.anomaly_episodes = 0
        self.canary_probes = 0
        self.canary_failures = 0
        self.last_t: Optional[float] = None

    def fold(self, rec: dict) -> None:
        self.records.append(rec)
        kind = str(rec.get("kind", "?"))
        self.kinds[kind] = self.kinds.get(kind, 0) + 1
        t = rec.get("t")
        if isinstance(t, (int, float)):
            self.last_t = max(self.last_t or 0.0, float(t))
        if kind == "serving_replica" and rec.get("replica"):
            self.replicas[str(rec["replica"])] = rec
        elif kind == "router" and rec.get("phase") == "poll":
            self.router_poll = rec
        elif kind == "supervisor":
            self.supervisor = rec
            self.generation = max(self.generation, int(rec.get("generation", 0)))
        elif kind in ("restart", "elastic"):
            if kind == "restart":
                self.restarts += 1
            self.generation = max(self.generation, int(rec.get("generation", 0)))
        elif kind == "slo_violation":
            self.slo_violations += 1
        elif kind == "anomaly":
            self.anomaly_episodes += 1
        elif kind == "canary":
            self.canary_probes += 1
            if rec.get("result") == "mismatch":
                self.canary_failures += 1

    def replica_states(self) -> "dict[str, int]":
        out: "dict[str, int]" = {}
        for rec in self.replicas.values():
            state = str(rec.get("state", "?"))
            out[state] = out.get(state, 0) + 1
        return dict(sorted(out.items()))

    def snapshot_report(self, by_rank: bool = False) -> dict:
        return _report.build_report_from_events(list(self.records), by_rank=by_rank)


class EventHub:
    """Tail every stream under ``paths`` into one :class:`FleetModel`.

    ``poll()`` discovers new ``*.jsonl`` files (replicas spawn mid-run),
    drains each tail, folds the records, and runs them through the anomaly
    engine; fired episodes are folded back as synthetic ``anomaly``
    records on :data:`HUB_STREAM`. Returns the newly folded records."""

    def __init__(
        self,
        paths: Iterable[str],
        *,
        model: Optional[FleetModel] = None,
        anomaly: Optional[_anomaly.AnomalyEngine] = None,
    ):
        self.paths = list(paths)
        self.model = model if model is not None else FleetModel()
        self.anomaly = anomaly
        self._tails: "dict[str, FileTail]" = {}
        self.polls = 0

    def _discover(self) -> None:
        for path in _report.iter_event_files(self.paths):
            if path not in self._tails:
                self._tails[path] = FileTail(path)

    def poll(self) -> "list[dict]":
        self._discover()
        new: "list[dict]" = []
        for path in sorted(self._tails):
            for rec in self._tails[path].poll():
                self.model.fold(rec)
                new.append(rec)
                if self.anomaly is None:
                    continue
                for fired in self.anomaly.observe_record(rec):
                    synthetic = dict(fired)
                    synthetic["kind"] = "anomaly"
                    synthetic["_file"] = HUB_STREAM
                    self.model.fold(synthetic)
                    new.append(synthetic)
        self.polls += 1
        return new


def render_top(model: FleetModel, *, frame: Optional[int] = None) -> str:
    """One dashboard frame over the FleetModel.

    The header lines come from the model's cheap fold state; every section
    body is the report CLI's own formatter over
    :meth:`FleetModel.snapshot_report` — live and post-hoc views are the
    same code, so their numbers cannot drift apart."""
    report = model.snapshot_report()
    runs = ", ".join(report.get("runs") or []) or "<none>"
    frame_s = f", frame {frame}" if frame is not None else ""
    lines = [
        f"fleet top — run(s): {runs}, {report.get('processes') or 0} process(es), "
        f"{report['events']} record(s){frame_s}"
    ]
    if model.replicas:
        states = ", ".join(f"{k}={v}" for k, v in model.replica_states().items())
        lines.append(f"  replicas: {len(model.replicas)} ({states})")
    if model.router_poll is not None:
        rp = model.router_poll
        lines.append(
            f"  router: queued={rp.get('queued', 0)} inflight={rp.get('inflight', 0)} "
            f"completed={rp.get('completed', 0)} shed={rp.get('shed', 0)} "
            f"failovers={rp.get('failovers', 0)}"
        )
    if model.supervisor is not None:
        sup = model.supervisor
        lines.append(
            f"  supervisor: generation {int(sup.get('generation', 0))}, "
            f"{int(sup.get('processes', 0))} process(es), "
            f"restarts {int(sup.get('restarts_used', 0))}/{sup.get('max_restarts', '?')}"
        )
    if model.anomaly_episodes or model.slo_violations or model.canary_failures:
        lines.append(
            f"  ALERTS: {model.anomaly_episodes} anomaly episode(s), "
            f"{model.slo_violations} slo violation(s), "
            f"{model.canary_failures} canary failure(s)"
        )
    s = report["steps"]
    if s["count"]:
        d = s["wall_s"]
        lines.append(
            f"steps: {s['count']}  p50={d['p50'] * 1e3:.2f}ms  "
            f"p99={d['p99'] * 1e3:.2f}ms  max={d['max'] * 1e3:.2f}ms"
        )
    serving = report.get("serving")
    if serving:
        lines.append(_report.format_serving_section(serving))
    router = report.get("router")
    if router:
        lines.append(_report.format_router_section(router))
    autoscaler = report.get("autoscaler")
    if autoscaler:
        lines.append(_report.format_autoscaler_section(autoscaler))
    slo = report.get("slo")
    if slo:
        lines.append(_report.format_slo_section(slo))
    anomalies = report.get("anomalies")
    if anomalies and anomalies.get("episodes"):
        lines.append(_report.format_anomaly_section(anomalies))
    canary = report.get("canary")
    if canary and canary.get("probes"):
        lines.append(_report.format_canary_section(canary))
    rs = report.get("restarts") or {}
    if rs.get("count") or rs.get("chaos_faults"):
        lines.append(
            f"restarts: {rs.get('count', 0)} over {rs.get('generations', 0) + 1} "
            f"generation(s), downtime {rs.get('downtime_s', 0.0):.1f}s"
        )
    ccache = report.get("compile_cache")
    if ccache:
        lines.append(_report.format_compile_cache_section(ccache))
    gp = report.get("goodput")
    if gp:
        lines.append(_goodput.verdict_line(gp))
    return "\n".join(lines)


def run_top(
    paths: Iterable[str],
    *,
    once: bool = False,
    interval_s: float = 2.0,
    max_ticks: Optional[int] = None,
    sleep: Optional[Callable[[float], Any]] = None,
    out=None,
    anomaly: Optional[_anomaly.AnomalyEngine] = None,
) -> int:
    """The ``telemetry top`` loop: poll, render, clear, repeat.

    ``once`` renders a single frame with no ANSI clear (tests, CI, piping
    into files); ``max_ticks`` bounds a live run; ``sleep`` is injectable
    so tests run at machine speed."""
    out = out if out is not None else sys.stdout
    sleep_fn = sleep if sleep is not None else time.sleep
    engine = anomaly if anomaly is not None else _anomaly.AnomalyEngine()
    hub = EventHub(paths, anomaly=engine)
    frame = 0
    while True:
        hub.poll()
        frame += 1
        if once:
            out.write(render_top(hub.model) + "\n")
            out.flush()
            return 0
        out.write(ANSI_CLEAR + render_top(hub.model, frame=frame) + "\n")
        out.flush()
        if max_ticks is not None and frame >= max_ticks:
            return 0
        sleep_fn(interval_s)


def run_follow(
    paths: Iterable[str],
    *,
    by_rank: bool = False,
    interval_s: float = 2.0,
    max_ticks: Optional[int] = None,
    sleep: Optional[Callable[[float], Any]] = None,
    out=None,
    anomaly: Optional[_anomaly.AnomalyEngine] = None,
) -> int:
    """``report --follow``: re-render the full post-hoc report whenever the
    tailed streams grow — the streaming flavor of the same aggregation."""
    out = out if out is not None else sys.stdout
    sleep_fn = sleep if sleep is not None else time.sleep
    engine = anomaly if anomaly is not None else _anomaly.AnomalyEngine()
    hub = EventHub(paths, anomaly=engine)
    ticks = 0
    while True:
        new = hub.poll()
        ticks += 1
        if new:
            report = hub.model.snapshot_report(by_rank=by_rank)
            out.write(
                f"\n==== follow: +{len(new)} record(s), "
                f"{len(hub.model.records)} total ====\n"
            )
            out.write(_report.format_report(report) + "\n")
            out.flush()
        if max_ticks is not None and ticks >= max_ticks:
            return 0
        sleep_fn(interval_s)
