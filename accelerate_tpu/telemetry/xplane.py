"""XLA profiler trace windows + a dependency-free XSpace (xplane.pb) parser.

``jax.profiler`` answers the question the JSONL step records cannot: *inside*
one step, which fusions/kernels ate the device time, and did the collectives
overlap compute or serialize it? This module makes that answer programmatic:

- :class:`TraceWindows` — every-Nth-step or one-shot ``jax.profiler`` windows
  driven by :class:`~accelerate_tpu.utils.dataclasses.ProfileConfig`
  (``trace_every`` / ``trace_steps`` / ``trace_at``, env-seeded via
  ``ACCELERATE_TRACE_EVERY`` / ``ACCELERATE_TRACE_STEPS`` /
  ``ACCELERATE_TRACE_AT`` / ``ACCELERATE_TRACE_DIR`` so a launcher can turn
  on tracing with zero code changes). Each closed window is parsed
  immediately and lands as one ``trace`` event in the telemetry stream.
- :func:`parse_xspace` — a ~100-line protobuf *wire-format* decoder for the
  profiler's ``*.xplane.pb`` (the tensorflow ``XSpace`` schema), because this
  environment has no tensorboard/tensorflow to parse it with. Falls back to
  the ``*.trace.json.gz`` Chrome trace when no ``.pb`` is present.
- :func:`summarize_trace` — top-k op/fusion durations, a
  compute / collective / idle device-time split, and the **comms-overlap
  ratio**: what fraction of collective time ran concurrently with compute
  (the number ROADMAP item 3's weight-update sharding must move toward 1.0).

Heuristics, stated: events whose names look like C++ frames (``Foo::Bar``),
python tracing (``$file.py:123``), or runtime plumbing are *infra* and
excluded from op accounting; collective ops match the XLA HLO spellings
(``all-reduce``/``all-gather``/``reduce-scatter``/``all-to-all``/
``collective-permute``/``send``/``recv``). Device planes (``/device:TPU:N``)
are preferred; on the CPU backend the ``/host:CPU`` plane's XLA thunk lines
stand in (the ``python`` line is never op time).
"""

from __future__ import annotations

import glob
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional

from . import events as tel

TRACE_EVERY_ENV_VAR = "ACCELERATE_TRACE_EVERY"
TRACE_STEPS_ENV_VAR = "ACCELERATE_TRACE_STEPS"
TRACE_AT_ENV_VAR = "ACCELERATE_TRACE_AT"
TRACE_DIR_ENV_VAR = "ACCELERATE_TRACE_DIR"

_PS = 1e-12  # xplane durations are picoseconds
_US = 1e-6  # chrome-trace durations are microseconds

_COLLECTIVE_RE = re.compile(
    r"(^|[-_.\s])(all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute|collective-broadcast|ragged-all-to-all|send|recv)",
    re.IGNORECASE,
)
# runtime plumbing, not ops: C++ frames, python tracing, dispatch machinery
_INFRA_RE = re.compile(
    r"::|^\$|^PjitFunction|^ParseArguments|^ThreadpoolListener|"
    r"^ExecuteTask|^RunReady|^program_interpreter|^<unknown>"
)


# ---------------------------------------------------------------- data model
@dataclass
class XEvent:
    name: str
    start_s: float  # absolute seconds (line timestamp_ns + event offset_ps)
    dur_s: float

    @property
    def end_s(self) -> float:
        return self.start_s + self.dur_s


@dataclass
class XLine:
    name: str
    events: "list[XEvent]" = field(default_factory=list)


@dataclass
class XPlane:
    name: str
    lines: "list[XLine]" = field(default_factory=list)


# ------------------------------------------------------- protobuf wire parse
def _read_varint(buf: bytes, i: int) -> "tuple[int, int]":
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def _fields(buf: bytes) -> "Iterable[tuple[int, int, Any]]":
    """Yield ``(field_number, wire_type, value)`` triples of one message.
    Length-delimited values come back as ``bytes`` (nested messages are
    decoded by the caller that knows the schema)."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        fnum, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 1:
            v = buf[i : i + 8]
            i += 8
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i : i + ln]
            i += ln
        elif wt == 5:
            v = buf[i : i + 4]
            i += 4
        else:  # groups (3/4) never appear in xplane protos
            raise ValueError(f"unsupported wire type {wt}")
        yield fnum, wt, v


def _parse_event(buf: bytes, metadata: "dict[int, str]", epoch_s: float) -> Optional[XEvent]:
    # XEvent: metadata_id=1, offset_ps=2 (oneof with num_occurrences=4), duration_ps=3
    meta_id = offset_ps = dur_ps = None
    for fnum, wt, v in _fields(buf):
        if wt != 0:
            continue
        if fnum == 1:
            meta_id = v
        elif fnum == 2:
            offset_ps = v
        elif fnum == 3:
            dur_ps = v
    if meta_id is None or not dur_ps:
        return None  # instant/aggregated events carry no duration: not op time
    # proto3 omits zero-valued varints: an event starting AT the line epoch
    # has no offset_ps field on the wire — it is offset 0, not malformed
    return XEvent(
        metadata.get(meta_id, f"#{meta_id}"), epoch_s + (offset_ps or 0) * _PS, dur_ps * _PS
    )


def _parse_line(buf: bytes, metadata: "dict[int, str]") -> XLine:
    # XLine: id=1, name=2, timestamp_ns=3, events=4. Event offsets are
    # RELATIVE to this line's timestamp_ns — lines (streams/queues) of one
    # trace can carry different epochs, and the overlap/idle math intersects
    # intervals ACROSS lines, so events must be rebased to absolute time here.
    name = ""
    timestamp_ns = 0
    event_bufs: "list[bytes]" = []
    for fnum, wt, v in _fields(buf):
        if fnum == 2 and wt == 2:
            name = v.decode("utf-8", "replace")
        elif fnum == 3 and wt == 0:
            timestamp_ns = v
        elif fnum == 4 and wt == 2:
            event_bufs.append(v)
    epoch_s = timestamp_ns * 1e-9
    events = []
    for ev_buf in event_bufs:
        ev = _parse_event(ev_buf, metadata, epoch_s)
        if ev is not None:
            events.append(ev)
    return XLine(name, events)


def _parse_plane(buf: bytes) -> XPlane:
    # XPlane: id=1, name=2, lines=3, event_metadata map=4
    name = ""
    line_bufs: "list[bytes]" = []
    metadata: "dict[int, str]" = {}
    for fnum, wt, v in _fields(buf):
        if fnum == 2 and wt == 2:
            name = v.decode("utf-8", "replace")
        elif fnum == 3 and wt == 2:
            line_bufs.append(v)
        elif fnum == 4 and wt == 2:
            # map<int64, XEventMetadata>: key=1, value=2{id=1, name=2}
            key = None
            meta_name = None
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 0:
                    key = v2
                elif f2 == 2 and w2 == 2:
                    for f3, w3, v3 in _fields(v2):
                        if f3 == 2 and w3 == 2:
                            meta_name = v3.decode("utf-8", "replace")
            if key is not None and meta_name is not None:
                metadata[key] = meta_name
    return XPlane(name, [_parse_line(b, metadata) for b in line_bufs])


def parse_xspace(path: str) -> "list[XPlane]":
    """Decode one ``*.xplane.pb`` file into planes → lines → duration events.
    Event names are resolved through the plane's metadata table; durations
    are seconds."""
    with open(path, "rb") as f:
        data = f.read()
    planes = []
    for fnum, wt, v in _fields(data):
        if fnum == 1 and wt == 2:  # XSpace.planes
            planes.append(_parse_plane(v))
    return planes


# ----------------------------------------------------- chrome-trace fallback
def parse_chrome_trace(path: str) -> "list[XPlane]":
    """``*.trace.json.gz`` fallback: reconstruct the same plane/line/event
    shape from the Chrome trace's complete (``ph == "X"``) events."""
    import gzip

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    pid_names: dict = {}
    tid_names: dict = {}
    events_by: "dict[tuple, list[XEvent]]" = {}
    for ev in data.get("traceEvents", []):
        if not isinstance(ev, dict):
            continue
        if ev.get("ph") == "M":
            if ev.get("name") == "process_name":
                pid_names[ev.get("pid")] = (ev.get("args") or {}).get("name", "")
            elif ev.get("name") == "thread_name":
                tid_names[(ev.get("pid"), ev.get("tid"))] = (ev.get("args") or {}).get("name", "")
        elif ev.get("ph") == "X" and ev.get("dur"):
            key = (ev.get("pid"), ev.get("tid"))
            events_by.setdefault(key, []).append(
                XEvent(str(ev.get("name", "")), float(ev["ts"]) * _US, float(ev["dur"]) * _US)
            )
    planes: "dict[Any, XPlane]" = {}
    for (pid, tid), evs in events_by.items():
        plane = planes.setdefault(pid, XPlane(pid_names.get(pid, str(pid))))
        plane.lines.append(XLine(tid_names.get((pid, tid), str(tid)), evs))
    return list(planes.values())


# ------------------------------------------------------------- summarization
def find_trace_files(trace_dir: str) -> "tuple[list[str], list[str]]":
    """``(xplane_pb_files, chrome_json_files)`` under a profiler output dir
    (jax writes ``<dir>/plugins/profile/<timestamp>/<host>.xplane.pb``)."""
    pbs = sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True))
    jsons = sorted(glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True))
    return pbs, jsons


def _union(intervals: "list[tuple[float, float]]") -> "list[tuple[float, float]]":
    if not intervals:
        return []
    intervals = sorted(intervals)
    merged = [intervals[0]]
    for start, end in intervals[1:]:
        if start <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], end))
        else:
            merged.append((start, end))
    return merged


def _total(intervals: "list[tuple[float, float]]") -> float:
    return sum(end - start for start, end in intervals)


def _intersect(a: "list[tuple[float, float]]", b: "list[tuple[float, float]]") -> float:
    """Total overlap between two already-merged interval lists."""
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def is_collective_op(name: str) -> bool:
    return bool(_COLLECTIVE_RE.search(name))


def is_infra_event(name: str) -> bool:
    return bool(_INFRA_RE.search(name))


# device-plane lines that wrap whole steps/modules rather than individual
# ops — counting them as compute would cover every collective interval and
# fake a ~1.0 overlap ratio (the exact metric this module exists to guard)
_DEVICE_ENVELOPE_LINES = {
    "Steps", "XLA Modules", "XLA TraceMe", "Framework Name Scope",
    "Framework Ops", "Source code", "Source Code",
}


def _device_op_lines(plane: XPlane) -> "list[XLine]":
    """The op-level lines of a device plane: ``XLA Ops`` (plus async-op
    lines, where in-flight collectives land) when present; otherwise
    everything minus the known step/module envelope lines."""
    ops = [
        ln for ln in plane.lines
        if ln.name == "XLA Ops" or "Async" in ln.name
    ]
    if ops:
        return ops
    return [ln for ln in plane.lines if ln.name not in _DEVICE_ENVELOPE_LINES]


def _op_planes(planes: "list[XPlane]") -> "list[XPlane]":
    """The planes that carry op/kernel time: ``/device:*`` when present (TPU/
    GPU) filtered to their op-level lines, else the ``/host:CPU`` plane minus
    its ``python`` tracing line."""
    devices = []
    for plane in planes:
        if not plane.name.startswith("/device:"):
            continue
        lines = [ln for ln in _device_op_lines(plane) if ln.events]
        if lines:
            devices.append(XPlane(plane.name, lines))
    if devices:
        return devices
    hosts = []
    for plane in planes:
        if not plane.name.startswith("/host:"):
            continue
        lines = [ln for ln in plane.lines if ln.name != "python" and ln.events]
        if lines:
            hosts.append(XPlane(plane.name, lines))
    return hosts


def summarize_planes(planes: "list[XPlane]", top_k: int = 10) -> dict:
    """Op-level accounting over already-parsed planes (see
    :func:`summarize_trace` for the file-level entry point)."""
    by_op: "dict[str, dict]" = {}
    compute_iv: "list[tuple[float, float]]" = []
    collective_iv: "list[tuple[float, float]]" = []
    span_lo, span_hi = None, None
    n_events = 0
    for plane in _op_planes(planes):
        for line in plane.lines:
            for ev in line.events:
                if is_infra_event(ev.name):
                    continue
                n_events += 1
                rec = by_op.setdefault(
                    ev.name, {"op": ev.name, "total_s": 0.0, "count": 0}
                )
                rec["total_s"] += ev.dur_s
                rec["count"] += 1
                span_lo = ev.start_s if span_lo is None else min(span_lo, ev.start_s)
                span_hi = ev.end_s if span_hi is None else max(span_hi, ev.end_s)
                (collective_iv if is_collective_op(ev.name) else compute_iv).append(
                    (ev.start_s, ev.end_s)
                )
    compute_u = _union(compute_iv)
    collective_u = _union(collective_iv)
    busy_u = _union(compute_iv + collective_iv)
    span_s = (span_hi - span_lo) if span_lo is not None else 0.0
    compute_s = _total(compute_u)
    collective_s = _total(collective_u)
    overlap_s = _intersect(compute_u, collective_u)
    op_total = sum(r["total_s"] for r in by_op.values())
    top = sorted(by_op.values(), key=lambda r: -r["total_s"])[:top_k]
    for rec in top:
        rec["total_s"] = round(rec["total_s"], 6)
        rec["share"] = round(rec["total_s"] / op_total, 4) if op_total else 0.0
        rec["collective"] = is_collective_op(rec["op"])
    return {
        "events": n_events,
        "ops": len(by_op),
        "span_s": round(span_s, 6),
        "busy_s": round(_total(busy_u), 6),
        "idle_s": round(max(0.0, span_s - _total(busy_u)), 6),
        "compute_s": round(compute_s, 6),
        "collective_s": round(collective_s, 6),
        "collective_overlap_s": round(overlap_s, 6),
        # the ratio ROADMAP item 3 optimizes: collective time hidden under
        # compute / total collective time. None when the trace has no
        # collectives (single-chip runs) — "perfect overlap" would be a lie.
        "comms_overlap_ratio": round(overlap_s / collective_s, 4) if collective_s else None,
        "top_ops": top,
    }


def summarize_trace(trace_dir: str, top_k: int = 10) -> dict:
    """Parse every trace under ``trace_dir`` (``.xplane.pb`` preferred,
    Chrome ``.trace.json.gz`` fallback) and produce the op-level summary:
    top-k op durations, compute/collective/idle split, comms-overlap ratio."""
    pbs, jsons = find_trace_files(trace_dir)
    planes: "list[XPlane]" = []
    files = []
    for path in pbs:
        try:
            planes.extend(parse_xspace(path))
            files.append(os.path.relpath(path, trace_dir))
        except Exception:
            continue  # torn/foreign pb: the json fallback may still work
    if not planes:
        for path in jsons:
            try:
                planes.extend(parse_chrome_trace(path))
                files.append(os.path.relpath(path, trace_dir))
            except Exception:
                continue
    out = summarize_planes(planes, top_k=top_k)
    out["trace_dir"] = trace_dir
    out["files"] = files
    return out


# ----------------------------------------------------------- window driver --
class TraceWindows:
    """Automatic ``jax.profiler`` windows at step boundaries.

    Driven by :class:`~accelerate_tpu.utils.dataclasses.ProfileConfig`:
    every ``trace_every`` steps (or one-shot at ``trace_at``) a window of
    ``trace_steps`` steps is traced into ``<out_dir>/step<k>``, then parsed
    (:func:`summarize_trace`) into one ``trace`` telemetry event and a
    ``summary.json`` next to the raw trace. The Accelerator calls
    :meth:`on_step_start` / :meth:`on_step_end` around every tracked step;
    both are a couple of integer compares while no window is due.

    A profiler that refuses to start (another trace already active — e.g. a
    user's ``accelerator.profile()`` block) disables the driver for the rest
    of the run rather than erroring every step.

    Async-dispatch caveat: the window brackets the *dispatch* of the traced
    steps; device/thunk execution that completes after ``stop_trace`` is not
    in the file. A loop that wants every kernel of step N inside step N's
    window must force completion per step (``float(np.asarray(loss))`` —
    `block_until_ready` does not block through the remote TPU tunnel)."""

    def __init__(self, config, out_dir: str, top_k: int = 10):
        self.config = config
        self.out_dir = out_dir
        self.top_k = top_k
        self.tracing = False
        self.disabled = False
        self.window_dir: Optional[str] = None
        self.window_start: Optional[int] = None
        self.summaries: "list[dict]" = []

    @staticmethod
    def enabled_config(config) -> bool:
        return bool(
            getattr(config, "trace_every", 0) > 0
            or getattr(config, "trace_at", None) is not None
        )

    def _window_due(self, step: int) -> bool:
        # both triggers are honored: an env-seeded one-shot (trace_at) must
        # not silently disable a periodic schedule configured in code
        trace_at = getattr(self.config, "trace_at", None)
        if trace_at is not None and step == trace_at:
            return True
        every = getattr(self.config, "trace_every", 0)
        # step 0 pays compile: the first window lands at step `every`
        return every > 0 and step > 0 and step % every == 0

    def on_step_start(self, step: int) -> None:
        if self.tracing or self.disabled or not self._window_due(step):
            return
        import jax

        self.window_dir = os.path.join(self.out_dir, f"step{step}")
        try:
            if os.path.isdir(self.window_dir):
                # a restarted run reuses the same step index + pinned trace
                # dir: stale profile trees would merge into (and double-count)
                # this window's summary, which globs recursively
                import shutil

                shutil.rmtree(self.window_dir, ignore_errors=True)
            os.makedirs(self.window_dir, exist_ok=True)
            jax.profiler.start_trace(self.window_dir)
        except Exception as e:
            # another trace is active (user profile block / bench trace):
            # stand down for the run instead of failing every window
            self.disabled = True
            tel.emit("trace", step_start=step, error=f"{type(e).__name__}: {e}")
            return
        self.tracing = True
        self.window_start = step

    def on_step_end(self, step: int) -> None:
        if not self.tracing:
            return
        steps_traced = step - self.window_start + 1
        if steps_traced < max(1, getattr(self.config, "trace_steps", 1)):
            return
        self._close(step)

    def _close(self, last_step: Optional[int]) -> None:
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        self.tracing = False
        summary = summarize_trace(self.window_dir, top_k=self.top_k)
        summary["step_start"] = self.window_start
        summary["step_end"] = last_step
        self.summaries.append(summary)
        try:
            with open(os.path.join(self.window_dir, "summary.json"), "w") as f:
                json.dump(summary, f, indent=2)
        except OSError:
            pass
        tel.emit("trace", **summary)

    def close(self) -> None:
        """Stop an open window (end of training mid-window)."""
        if self.tracing:
            self._close(None)
