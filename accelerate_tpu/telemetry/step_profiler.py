"""Per-step profiler: wall time, data-wait, compile-vs-execute split, and
recompile detection.

Two complementary recompile signals, because silent reshape-driven recompiles
are the single most common TPU throughput cliff (every new batch shape costs a
full XLA compile — seconds to minutes — while the step "just runs slower"):

1. **Global compile listener** (``jax.monitoring``): counts every
   ``backend_compile_duration`` event and accumulates compile seconds, so a
   step record can split its wall time into ``compile_s`` + ``execute_s`` even
   for compilations we did not register.
2. **Per-function jit-cache polling**: every compiled step the
   :class:`Accelerator` builds is registered here by name; at each step
   boundary the watcher polls ``fn._cache_size()`` and any growth *after the
   first entry* is a recompile, attributed to the function that suffered it —
   the "which function, which step" answer the global counter cannot give.

Data-wait time is accumulated by ``data_loader.py`` via
:func:`record_data_wait` and drained into each step record, so an input-bound
loop shows up as ``data_wait_s`` ≈ ``dur_s`` instead of a mystery.
"""

from __future__ import annotations

import time
from typing import Optional

from . import events as tel

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_compile_count = 0
_compile_secs = 0.0
# compiles triggered by telemetry itself (the perf cost-capture's AOT
# compile) — subtracted so step records only count what TRAINING paid
_excluded_count = 0
_excluded_secs = 0.0
_listener_installed = False

# data-wait seconds accumulated by the dataloader since the last step boundary
_data_wait_accum = 0.0


def _on_duration(event: str, duration: float, **kwargs) -> None:
    global _compile_count, _compile_secs
    if event == _COMPILE_EVENT:
        _compile_count += 1
        _compile_secs += float(duration)


def install_compile_listener() -> None:
    """Idempotently hook ``jax.monitoring`` so XLA backend compiles are counted
    process-wide. Installed lazily on the first telemetry-enabled step."""
    global _listener_installed
    if _listener_installed:
        return
    import jax.monitoring

    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    _listener_installed = True


def compile_snapshot() -> "tuple[int, float]":
    """(total backend compiles, total compile seconds) charged to training so
    far in this process — compiles the telemetry layer itself triggered (perf
    cost capture) are excluded."""
    return _compile_count - _excluded_count, _compile_secs - _excluded_secs


def raw_compile_snapshot() -> "tuple[int, float]":
    """Unadjusted compile totals, for bracketing a telemetry-internal compile
    (see :func:`exclude_compiles`)."""
    return _compile_count, _compile_secs


def exclude_compiles(count: int, seconds: float) -> None:
    """Mark ``count`` compiles / ``seconds`` as telemetry-internal: they will
    not appear in step records' ``compile_s`` or the report's compile totals.
    Called by :func:`~accelerate_tpu.telemetry.perf.capture_compiled` around
    its AOT compile."""
    global _excluded_count, _excluded_secs
    _excluded_count += max(0, int(count))
    _excluded_secs += max(0.0, float(seconds))


def record_data_wait(seconds: float) -> None:
    """Called by the dataloader: add input-pipeline wait time to the window the
    next step record drains."""
    global _data_wait_accum
    _data_wait_accum += seconds


def drain_data_wait() -> float:
    global _data_wait_accum
    out = _data_wait_accum
    _data_wait_accum = 0.0
    return out


class RecompileWatcher:
    """Counts jit cache misses per registered compiled function.

    ``register`` snapshots the function's current executable-cache size;
    ``poll`` reports growth since the last poll. The first entry per function
    is the expected initial compile (reported with ``first=True``); any later
    growth means a tracing-cache miss — almost always a silently changed input
    shape/dtype — and is a recompile.
    """

    # registered fns are strongly referenced (their executables stay pollable);
    # bound the registry so fresh-function-per-phase callers cannot leak
    MAX_TRACKED = 64

    def __init__(self):
        self._fns: dict = {}  # name -> [fn, last_size, ever_compiled]

    @staticmethod
    def _size(fn) -> Optional[int]:
        try:
            return int(fn._cache_size())
        except Exception:
            return None

    def register(self, name: str, fn) -> None:
        if not hasattr(fn, "_cache_size"):
            return  # eager (disable_jit) fns have no cache to miss
        size = self._size(fn)
        if size is None:
            return
        if name in self._fns and self._fns[name][0] is fn:
            return
        while len(self._fns) >= self.MAX_TRACKED:
            self._fns.pop(next(iter(self._fns)))  # evict oldest registration
        self._fns[name] = [fn, size, size > 0]

    def poll(self, emit: bool = True) -> "dict[str, int]":
        """``{name: recompile count since last poll}`` — cache growth minus the
        one expected initial compile per function; emits one ``jit_cache_miss``
        record per grown function when ``emit``."""
        out: dict = {}
        for name, rec in self._fns.items():
            fn, last, ever = rec
            size = self._size(fn)
            if size is None or size <= last:
                continue
            grew = size - last
            rec[1] = size
            rec[2] = True
            # growth from an empty cache includes the expected first compile;
            # everything past entry #1 is a recompile
            recompiles = grew - (0 if ever else 1)
            out[name] = recompiles
            if emit:
                tel.emit(
                    "jit_cache_miss",
                    fn=name,
                    count=grew,
                    cache_size=size,
                    recompiles=recompiles,
                    first=not ever,
                )
        return out

    def recompile_total(self) -> int:
        """Total cache entries beyond the first per function (live view)."""
        total = 0
        for name, (fn, last, ever) in self._fns.items():
            size = self._size(fn)
            if size is None:
                size = last
            total += max(0, size - 1)
        return total


class _StepContext:
    __slots__ = ("prof", "enabled", "t0", "c0", "s0")

    def __init__(self, prof: "StepTelemetry"):
        self.prof = prof
        self.enabled = False

    def __enter__(self):
        if not tel.is_enabled():
            return self
        self.enabled = True
        install_compile_listener()
        tel.set_step(self.prof.step_index)
        self.c0, self.s0 = compile_snapshot()
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        prof = self.prof
        if not self.enabled:
            prof.step_index += 1
            return False
        wall = time.monotonic() - self.t0
        c1, s1 = compile_snapshot()
        compiles = c1 - self.c0
        compile_s = s1 - self.s0
        recompiles = sum(prof.watcher.poll().values())
        fields: dict = {}
        cost = prof.step_cost
        if cost is not None:
            # roofline attribution (telemetry/perf.py): MFU over the step's
            # EXECUTE time (a compile-carrying step would otherwise read as a
            # utilization collapse), intensity/bucket are compile-time facts
            execute = max(wall - compile_s, 1e-9)
            step_mfu = cost.mfu(execute)
            if step_mfu is not None:
                fields["mfu"] = round(step_mfu, 6)
            if cost.intensity is not None:
                fields["arithmetic_intensity"] = round(cost.intensity, 6)
            if cost.roofline is not None:
                fields["roofline"] = cost.roofline
            fields["perf_fn"] = cost.name
        drained_wait = drain_data_wait()
        execute_s = max(0.0, wall - compile_s)
        tel.emit(
            "step",
            name=prof.name,
            dur_s=round(wall, 6),
            data_wait_s=round(drained_wait, 6),
            compile_s=round(compile_s, 6),
            execute_s=round(execute_s, 6),
            compiles=compiles,
            recompiles=max(0, recompiles),
            **fields,
        )
        from . import goodput as _goodput

        _goodput.note_step(execute_s, compile_s, drained_wait)
        _goodput.maybe_emit()
        if prof.memory_every and prof.step_index % prof.memory_every == 0:
            from .memory import MemoryMonitor

            if prof._memory is None:
                prof._memory = MemoryMonitor()
            prof._memory.sample()
        prof.step_index += 1
        tel.set_step(None)
        return False


class StepTelemetry:
    """Accelerator-integrated per-step telemetry driver.

    Cheap to construct and to carry while disabled: ``step()`` hands out a
    context whose enter/exit is a flag check when telemetry is off. Distinct
    from :class:`accelerate_tpu.accelerator.StepProfiler`, which drives
    ``jax.profiler`` *trace windows*; this records lightweight *metrics* for
    every step.
    """

    def __init__(self, name: str = "train_step", memory_every: int = 10):
        self.name = name
        self.memory_every = memory_every
        self.step_index = 0
        self.watcher = RecompileWatcher()
        self._memory = None
        # the XLA-reported cost of the step function about to run (set by the
        # Accelerator's perf capture); folded into each step record as
        # mfu / arithmetic_intensity / roofline
        self.step_cost = None
        if tel.is_enabled():
            install_compile_listener()

    def register_compiled(self, name: str, fn) -> None:
        """Track a jitted function's executable cache for recompile detection."""
        self.watcher.register(name, fn)

    def set_step_cost(self, cost) -> None:
        """Attach a :class:`~accelerate_tpu.telemetry.perf.CompiledCost` for
        the step function the NEXT :meth:`step` context will run (``None``
        clears it — records stop carrying MFU)."""
        self.step_cost = cost

    def step(self) -> _StepContext:
        """``with step_telemetry.step(): compiled_step(...)`` — one record per step."""
        return _StepContext(self)
