"""Online anomaly detection over the live telemetry streams.

The post-hoc report can tell you a run was slow; an operator watching a
fleet needs the detector to fire WHILE the regression is happening, with a
cause hypothesis attached. This module is the streaming half of that story:

- :class:`EwmaDetector` — exponentially-weighted mean/variance over one
  scalar series with a z-score trigger. An observation ``z_enter`` standard
  deviations out (in the detector's bad direction) ENTERS an episode;
  the episode re-arms only when the series falls back under ``z_exit``
  (hysteresis: one record per episode, the same contract as
  ``slo_violation`` in :mod:`.slo`).
- :class:`TrendDetector` — EWMA of successive deltas, for series whose bad
  failure mode is sustained drift rather than a spike (block-pool
  occupancy: a leak is allocated-minus-freed creeping up forever, which a
  z-score on the level never pages on until the pool is nearly gone).
- :class:`AnomalyEngine` — wires the stock detectors to the record kinds
  the hub tails (:mod:`.hub`): step latency, request ttft, speculative
  accept rate, replica heartbeat gaps, and block-pool occupancy trend.
  Every episode entry yields a typed ``anomaly`` record with a cause
  hypothesis derived from the triggering record's own fields (e.g. a slow
  step whose ``data_wait_s`` dominates is attributed to the input
  pipeline, not to the device), plus a Prometheus counter when the
  metrics registry is armed. Disabled cost is a single boolean check —
  no state is touched (``tests/test_anomaly.py`` holds that).

The detectors are deliberately clock-free: they consume whatever scalar the
caller feeds them, in stream order, so tests drive them with synthetic
series and the hub drives them with tailed records.
"""

from __future__ import annotations

import math
from typing import Any, Optional

from . import events as tel
from . import metrics as _metrics

__all__ = ["EwmaDetector", "TrendDetector", "AnomalyEngine"]

#: Prometheus counter bumped once per anomaly episode (labelled by detector)
ANOMALIES_TOTAL = "accelerate_anomalies_total"


class EwmaDetector:
    """Streaming z-score over an EWMA mean/variance estimate.

    ``direction`` names the bad side: ``"high"`` (latency-like — only
    upward excursions fire), ``"low"`` (rate-like — collapses fire), or
    ``"both"``. The first ``min_samples`` observations only train the
    estimate (a detector must never page off its own cold start), and
    ``min_std`` floors the variance so a perfectly flat warmup series
    doesn't turn the first jitter into an infinite z-score.
    """

    def __init__(
        self,
        name: str,
        *,
        alpha: float = 0.1,
        z_enter: float = 4.0,
        z_exit: float = 2.0,
        min_samples: int = 16,
        direction: str = "high",
        cause: str = "",
        min_std: float = 1e-9,
    ):
        if direction not in ("high", "low", "both"):
            raise ValueError(f"unknown direction {direction!r}")
        if z_exit > z_enter:
            raise ValueError(f"z_exit ({z_exit}) must not exceed z_enter ({z_enter})")
        self.name = name
        self.alpha = float(alpha)
        self.z_enter = float(z_enter)
        self.z_exit = float(z_exit)
        self.min_samples = int(min_samples)
        self.direction = direction
        self.cause = cause
        self.min_std = float(min_std)
        self.count = 0
        self.mean = 0.0
        self.var = 0.0
        self.in_episode = False
        self.episodes = 0

    def _signed_z(self, value: float) -> float:
        """The z-score in the detector's BAD direction (positive == worse)."""
        std = max(math.sqrt(max(self.var, 0.0)), self.min_std)
        z = (value - self.mean) / std
        if self.direction == "low":
            return -z
        if self.direction == "both":
            return abs(z)
        return z

    def observe(
        self,
        value: float,
        *,
        source: Optional[str] = None,
        hypothesis: Optional[str] = None,
    ) -> Optional[dict]:
        """Feed one observation; returns the anomaly record's fields on
        episode ENTRY, None otherwise (training, in-band, or mid-episode)."""
        value = float(value)
        fired: Optional[dict] = None
        if self.count >= self.min_samples:
            z = self._signed_z(value)
            if self.in_episode:
                if z < self.z_exit:
                    self.in_episode = False  # recovery re-arms the episode
            elif z >= self.z_enter:
                self.in_episode = True
                self.episodes += 1
                std = max(math.sqrt(max(self.var, 0.0)), self.min_std)
                fired = {
                    "detector": self.name,
                    "value": round(value, 6),
                    "mean": round(self.mean, 6),
                    "std": round(std, 6),
                    "z": round(z, 3),
                    "direction": self.direction,
                    "samples": self.count,
                    "episode": self.episodes,
                    "cause": hypothesis or self.cause,
                    "source": source,
                }
        # update AFTER scoring: an outlier must be judged against the
        # estimate it did not itself contaminate. It still feeds the
        # estimate, so a persistent level shift becomes the new normal and
        # the episode closes on its own (one record per episode).
        diff = value - self.mean
        incr = self.alpha * diff
        self.mean += incr
        self.var = (1.0 - self.alpha) * (self.var + diff * incr)
        self.count += 1
        return fired


class TrendDetector:
    """Sustained-drift detector: EWMA of successive deltas with hysteresis.

    Fires when the smoothed per-observation slope stays at or above
    ``slope_enter`` after ``min_samples`` observations — the leak signature
    (block-pool occupancy only ever creeping up means allocated minus freed
    is drifting). Re-arms when the slope falls back to ``slope_exit``
    (default ``slope_enter / 2``)."""

    def __init__(
        self,
        name: str,
        *,
        alpha: float = 0.1,
        min_samples: int = 30,
        slope_enter: float = 0.002,
        slope_exit: Optional[float] = None,
        cause: str = "",
    ):
        self.name = name
        self.alpha = float(alpha)
        self.min_samples = int(min_samples)
        self.slope_enter = float(slope_enter)
        self.slope_exit = (
            float(slope_exit) if slope_exit is not None else self.slope_enter / 2.0
        )
        self.cause = cause
        self.count = 0
        self.slope = 0.0
        self._prev: Optional[float] = None
        self.in_episode = False
        self.episodes = 0

    def observe(
        self,
        value: float,
        *,
        source: Optional[str] = None,
        hypothesis: Optional[str] = None,
    ) -> Optional[dict]:
        value = float(value)
        if self._prev is None:
            self._prev = value
            self.count = 1
            return None
        delta = value - self._prev
        self._prev = value
        self.slope = (1.0 - self.alpha) * self.slope + self.alpha * delta
        self.count += 1
        if self.count <= self.min_samples:
            return None
        if self.in_episode:
            if self.slope <= self.slope_exit:
                self.in_episode = False
            return None
        if self.slope < self.slope_enter:
            return None
        self.in_episode = True
        self.episodes += 1
        return {
            "detector": self.name,
            "value": round(value, 6),
            "slope": round(self.slope, 6),
            "slope_enter": self.slope_enter,
            "samples": self.count,
            "episode": self.episodes,
            "cause": hypothesis or self.cause,
            "source": source,
        }


class AnomalyEngine:
    """The stock detector set, dispatched over tailed telemetry records.

    One engine per hub: :meth:`observe_record` routes each record to the
    detectors that understand its kind and returns the anomaly records
    fired (usually none). When ``emit_records`` and the event log / metrics
    registry are armed, each episode also lands as a typed ``anomaly``
    record and a labelled :data:`ANOMALIES_TOTAL` bump — the same
    one-record-per-episode contract as ``slo_violation``."""

    def __init__(
        self,
        *,
        enabled: bool = True,
        emit_records: bool = True,
        step_latency: Optional[EwmaDetector] = None,
        ttft: Optional[EwmaDetector] = None,
        spec_accept: Optional[EwmaDetector] = None,
        heartbeat: Optional[EwmaDetector] = None,
        block_leak: Optional[TrendDetector] = None,
    ):
        self.enabled = bool(enabled)
        self.emit_records = bool(emit_records)
        self.step_latency = step_latency if step_latency is not None else EwmaDetector(
            "step_latency", cause="straggler or contended host (execute inflated)",
        )
        self.ttft = ttft if ttft is not None else EwmaDetector(
            "ttft", cause="queueing or prefill backlog on the serving path",
        )
        self.spec_accept = spec_accept if spec_accept is not None else EwmaDetector(
            "spec_accept_rate", direction="low",
            cause="draft/verifier divergence (speculative accept rate collapsed)",
        )
        self.heartbeat = heartbeat if heartbeat is not None else EwmaDetector(
            "heartbeat_gap",
            cause="replica wedged or starved (heartbeat gap widening)",
        )
        self.block_leak = block_leak if block_leak is not None else TrendDetector(
            "block_pool_leak",
            cause="block-pool leak: allocated-minus-freed occupancy drifting up",
        )
        self.observed = 0
        self.anomalies: "list[dict]" = []

    def detectors(self) -> "list[Any]":
        return [self.step_latency, self.ttft, self.spec_accept,
                self.heartbeat, self.block_leak]

    @staticmethod
    def _step_hypothesis(rec: dict) -> Optional[str]:
        """Name the slow step's dominant internal cost, when it tells us."""
        dur = float(rec.get("dur_s", 0.0) or 0.0)
        if dur <= 0:
            return None
        if float(rec.get("compile_s", 0.0) or 0.0) > 0:
            return "recompilation (compile_s > 0 inside the slow step)"
        if float(rec.get("data_wait_s", 0.0) or 0.0) >= 0.5 * dur:
            return "input pipeline stall (data_wait dominates the step)"
        return None

    def observe_record(self, rec: dict) -> "list[dict]":
        """Route one tailed record; returns the anomaly records fired."""
        if not self.enabled:
            return []
        kind = rec.get("kind")
        fired: "list[dict]" = []

        def _feed(detector, value, *, source=None, hypothesis=None):
            self.observed += 1
            out = detector.observe(float(value), source=source, hypothesis=hypothesis)
            if out is not None:
                fired.append(out)

        if kind == "step" and rec.get("dur_s") is not None:
            _feed(self.step_latency, rec["dur_s"], source=rec.get("_file"),
                  hypothesis=self._step_hypothesis(rec))
        elif kind == "router" and rec.get("phase") == "request":
            if rec.get("outcome") == "finished" and rec.get("ttft_s") is not None:
                _feed(self.ttft, rec["ttft_s"], source=rec.get("replica"))
        elif kind == "serving" and rec.get("phase") == "step":
            if rec.get("block_occupancy") is not None:
                _feed(self.block_leak, rec["block_occupancy"],
                      source=rec.get("_file"))
            proposed = int(rec.get("draft_proposed_tokens", 0) or 0)
            if proposed > 0:
                accepted = int(rec.get("draft_accepted_tokens", 0) or 0)
                _feed(self.spec_accept, accepted / proposed,
                      source=rec.get("_file"))
        elif kind == "serving_replica" and rec.get("heartbeat_age_s") is not None:
            _feed(self.heartbeat, rec["heartbeat_age_s"],
                  source=rec.get("replica"))

        for record in fired:
            self.anomalies.append(record)
            if self.emit_records:
                if tel.is_enabled():
                    tel.emit("anomaly", **record)
                if _metrics.is_enabled():
                    _metrics.inc(ANOMALIES_TOTAL, detector=record["detector"])
        return fired

    def stats(self) -> dict:
        return {
            "observed": self.observed,
            "anomalies": len(self.anomalies),
            "episodes": {d.name: d.episodes for d in self.detectors()},
        }
