"""Device/host memory watermarks sampled at step boundaries.

TPU HBM is the binding resource; an OOM three hours into a run is a telemetry
failure, not a model failure. Three complementary signals:

- ``device.memory_stats()`` — the runtime allocator's view (``bytes_in_use``,
  ``peak_bytes_in_use``, ``bytes_limit``). Authoritative on TPU/GPU; returns
  nothing on the CPU emulation backend.
- ``jax.live_arrays()`` — bytes held by live ``jax.Array`` objects. Works on
  every backend (the CPU-test stand-in for HBM) and catches Python-side leaks
  the allocator view can't attribute.
- host RSS — the process's resident set, for host-offload and input-pipeline
  bloat.
"""

from __future__ import annotations

from typing import Optional

from . import events as tel


def device_memory_stats() -> "list[dict]":
    """Per-local-device allocator stats; fields missing on backends that don't
    report them (CPU emulation reports none)."""
    import jax

    out = []
    for i, dev in enumerate(jax.local_devices()):
        rec: dict = {"device": i, "kind": getattr(dev, "device_kind", str(dev))}
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if stats:
            for src, dst in (
                ("bytes_in_use", "bytes_in_use"),
                ("peak_bytes_in_use", "peak_bytes_in_use"),
                ("bytes_limit", "bytes_limit"),
            ):
                if stats.get(src) is not None:
                    rec[dst] = int(stats[src])
        out.append(rec)
    return out


def live_array_bytes() -> int:
    """Total bytes of live ``jax.Array`` objects in this process."""
    import jax

    return sum(int(getattr(a, "nbytes", 0) or 0) for a in jax.live_arrays())


def host_memory_bytes() -> Optional[int]:
    """Current host RSS in bytes (Linux ``/proc``; ``getrusage`` peak as the
    fallback), or None when neither source exists."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        import os

        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        import sys

        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss is KiB on Linux, bytes on macOS
        return int(rss if sys.platform == "darwin" else rss * 1024)
    except Exception:
        return None


class MemoryMonitor:
    """Tracks watermarks across samples and emits one ``memory`` record per
    :meth:`sample` (when telemetry is enabled)."""

    def __init__(self):
        self.device_peak_bytes = 0
        self.live_array_peak_bytes = 0
        self.host_peak_bytes = 0

    def sample(self, emit: bool = True) -> dict:
        devices = device_memory_stats()
        in_use = sum(d.get("bytes_in_use", 0) for d in devices)
        dev_peak = sum(d.get("peak_bytes_in_use", d.get("bytes_in_use", 0)) for d in devices)
        live = live_array_bytes()
        host = host_memory_bytes() or 0
        self.device_peak_bytes = max(self.device_peak_bytes, dev_peak)
        self.live_array_peak_bytes = max(self.live_array_peak_bytes, live)
        self.host_peak_bytes = max(self.host_peak_bytes, host)
        record = {
            "device_bytes_in_use": in_use,
            "device_peak_bytes": self.device_peak_bytes,
            "live_array_bytes": live,
            "host_rss_bytes": host,
        }
        if emit:
            tel.emit("memory", **record)
        return record

    def watermarks(self) -> dict:
        return {
            "device_peak_bytes": self.device_peak_bytes,
            "live_array_peak_bytes": self.live_array_peak_bytes,
            "host_peak_bytes": self.host_peak_bytes,
        }


def log_memory_watermarks() -> dict:
    """One-shot convenience: sample now, return the record."""
    return MemoryMonitor().sample()


# ------------------------------------------------- compile-time projection --
def compiled_memory_analysis(compiled) -> Optional[dict]:
    """XLA's ``memory_analysis()`` of a compiled executable as a plain dict:
    ``argument_bytes`` / ``output_bytes`` / ``temp_bytes`` / ``alias_bytes``
    (and ``generated_code_bytes``), or ``None`` when the backend reports
    nothing. Unlike the runtime watermarks above this is a *pre-execution*
    fact — the projection that catches an OOM before it happens."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for src, dst in (
        ("argument_size_in_bytes", "argument_bytes"),
        ("output_size_in_bytes", "output_bytes"),
        ("temp_size_in_bytes", "temp_bytes"),
        ("alias_size_in_bytes", "alias_bytes"),
        ("generated_code_size_in_bytes", "generated_code_bytes"),
    ):
        value = getattr(ma, src, None)
        if value is not None:
            out[dst] = int(value)
    return out or None


def projected_peak_bytes(analysis: dict) -> int:
    """Device bytes the executable needs live at once: arguments + outputs +
    temporaries, minus aliased (donated) buffers counted on both sides."""
    return max(
        0,
        int(analysis.get("argument_bytes", 0))
        + int(analysis.get("output_bytes", 0))
        + int(analysis.get("temp_bytes", 0))
        - int(analysis.get("alias_bytes", 0)),
    )


def check_memory_fit(name: str, analysis: Optional[dict], emit: bool = True) -> Optional[dict]:
    """Compare a compiled function's projected peak against the device's
    reported capacity (``bytes_limit``); emits one ``memory_projection``
    record and a ``UserWarning`` when the projection exceeds capacity —
    the OOM-three-hours-in, caught at compile time. Returns the projection
    record (``None`` when there is nothing to project)."""
    if not analysis:
        return None
    projected = projected_peak_bytes(analysis)
    limit = 0
    for dev in device_memory_stats():
        # the step runs per device: the BINDING capacity is one device's
        limit = max(limit, int(dev.get("bytes_limit", 0)))
    record = {
        "fn": name,
        "projected_peak_bytes": projected,
        "device_bytes_limit": limit or None,
        "fits": (projected <= limit) if limit else None,
        **analysis,
    }
    if emit:
        tel.emit("memory_projection", **record)
    if limit and projected > limit:
        import warnings

        warnings.warn(
            f"compiled function {name!r} projects {projected / 1e9:.2f} GB of "
            f"device memory (args+outputs+temps) but the device reports only "
            f"{limit / 1e9:.2f} GB — expect an OOM; shrink the batch, enable "
            "remat, or donate/offload state",
            stacklevel=2,
        )
    return record
