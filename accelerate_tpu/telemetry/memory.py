"""Device/host memory watermarks sampled at step boundaries.

TPU HBM is the binding resource; an OOM three hours into a run is a telemetry
failure, not a model failure. Three complementary signals:

- ``device.memory_stats()`` — the runtime allocator's view (``bytes_in_use``,
  ``peak_bytes_in_use``, ``bytes_limit``). Authoritative on TPU/GPU; returns
  nothing on the CPU emulation backend.
- ``jax.live_arrays()`` — bytes held by live ``jax.Array`` objects. Works on
  every backend (the CPU-test stand-in for HBM) and catches Python-side leaks
  the allocator view can't attribute.
- host RSS — the process's resident set, for host-offload and input-pipeline
  bloat.
"""

from __future__ import annotations

from typing import Optional

from . import events as tel


def device_memory_stats() -> "list[dict]":
    """Per-local-device allocator stats; fields missing on backends that don't
    report them (CPU emulation reports none)."""
    import jax

    out = []
    for i, dev in enumerate(jax.local_devices()):
        rec: dict = {"device": i, "kind": getattr(dev, "device_kind", str(dev))}
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if stats:
            for src, dst in (
                ("bytes_in_use", "bytes_in_use"),
                ("peak_bytes_in_use", "peak_bytes_in_use"),
                ("bytes_limit", "bytes_limit"),
            ):
                if stats.get(src) is not None:
                    rec[dst] = int(stats[src])
        out.append(rec)
    return out


def live_array_bytes() -> int:
    """Total bytes of live ``jax.Array`` objects in this process."""
    import jax

    return sum(int(getattr(a, "nbytes", 0) or 0) for a in jax.live_arrays())


def host_memory_bytes() -> Optional[int]:
    """Current host RSS in bytes (Linux ``/proc``; ``getrusage`` peak as the
    fallback), or None when neither source exists."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        import os

        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        import sys

        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss is KiB on Linux, bytes on macOS
        return int(rss if sys.platform == "darwin" else rss * 1024)
    except Exception:
        return None


class MemoryMonitor:
    """Tracks watermarks across samples and emits one ``memory`` record per
    :meth:`sample` (when telemetry is enabled)."""

    def __init__(self):
        self.device_peak_bytes = 0
        self.live_array_peak_bytes = 0
        self.host_peak_bytes = 0

    def sample(self, emit: bool = True) -> dict:
        devices = device_memory_stats()
        in_use = sum(d.get("bytes_in_use", 0) for d in devices)
        dev_peak = sum(d.get("peak_bytes_in_use", d.get("bytes_in_use", 0)) for d in devices)
        live = live_array_bytes()
        host = host_memory_bytes() or 0
        self.device_peak_bytes = max(self.device_peak_bytes, dev_peak)
        self.live_array_peak_bytes = max(self.live_array_peak_bytes, live)
        self.host_peak_bytes = max(self.host_peak_bytes, host)
        record = {
            "device_bytes_in_use": in_use,
            "device_peak_bytes": self.device_peak_bytes,
            "live_array_bytes": live,
            "host_rss_bytes": host,
        }
        if emit:
            tel.emit("memory", **record)
        return record

    def watermarks(self) -> dict:
        return {
            "device_peak_bytes": self.device_peak_bytes,
            "live_array_peak_bytes": self.live_array_peak_bytes,
            "host_peak_bytes": self.host_peak_bytes,
        }


def log_memory_watermarks() -> dict:
    """One-shot convenience: sample now, return the record."""
    return MemoryMonitor().sample()
