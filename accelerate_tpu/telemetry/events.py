"""Structured JSONL telemetry event log — the measurement backbone.

No reference counterpart: the reference treats observability as an external
concern (trackers only, ``tracking.py``), but a TPU-native system lives or
dies by visibility into XLA recompiles, device-memory watermarks and
collective traffic. This module is the spine the rest of
``accelerate_tpu.telemetry`` writes through.

Design contract:

- **One JSONL stream per process** (``events-rank<k>.jsonl``), opened lazily on
  the first flush. The first line is a ``meta`` record carrying the schema
  version, run id, process topology and wall-clock anchor; every subsequent
  record carries a monotonic timestamp ``t`` (and the current ``step`` when one
  has been set), so files from different ranks can be merged by the report CLI
  without clock-skew lies.
- **Kill switch**: telemetry is OFF unless ``ACCELERATE_TELEMETRY`` is truthy
  (or :func:`enable` is called). When off, every module-level helper is a
  single ``is None`` check — no allocation, no syscall, no file.
- **Never crashes training**: writes are buffered and an ``OSError`` on flush
  drops the buffer (counted in ``dropped_events``) instead of raising.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Any, Optional

TELEMETRY_SCHEMA_VERSION = 1
TELEMETRY_ENV_VAR = "ACCELERATE_TELEMETRY"
TELEMETRY_DIR_ENV_VAR = "ACCELERATE_TELEMETRY_DIR"

_TRUE = {"1", "true", "yes", "y", "on"}


class _NullSpan:
    """No-op span handed out while telemetry is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Span:
    """Timed region: ``with log.span("name"): ...`` emits one ``span`` record
    with ``dur_s`` on exit. Extra attributes can be attached mid-flight via
    :meth:`set` (e.g. the compile/execute split measured inside the region)."""

    __slots__ = ("log", "name", "attrs", "t0")

    def __init__(self, log: "EventLog", name: str, attrs: dict):
        self.log = log
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> bool:
        self.log.emit("span", name=self.name, dur_s=round(time.monotonic() - self.t0, 6), **self.attrs)
        return False


class EventLog:
    """Buffered JSONL event writer for one process of one run."""

    def __init__(self, out_dir: str, run_id: Optional[str] = None, flush_every: int = 64):
        self.out_dir = out_dir
        self.run_id = run_id or _default_run_id()
        self.flush_every = max(1, int(flush_every))
        self.step: Optional[int] = None
        self.closed = False
        self.dropped_events = 0
        self._buffer: list[dict] = []
        self._file = None
        # the async data-pipeline producer emits from its own thread; buffer
        # append + drain must not interleave
        self._lock = threading.Lock()

    # ------------------------------------------------------------- identity --
    @staticmethod
    def _rank_world() -> "tuple[int, int]":
        from ..state import PartialState

        if PartialState._shared_state.get("_initialized"):
            state = PartialState()
            return state.process_index, state.num_processes
        return (
            int(os.environ.get("ACCELERATE_PROCESS_ID", 0)),
            int(os.environ.get("ACCELERATE_NUM_PROCESSES", 1)),
        )

    @property
    def path(self) -> str:
        rank, _ = self._rank_world()
        return os.path.join(self.out_dir, f"events-rank{rank}.jsonl")

    # -------------------------------------------------------------- recording --
    def emit(self, kind: str, **fields: Any) -> None:
        """Append one record. ``t`` is monotonic; ``step`` rides along when set."""
        if self.closed:
            return
        rec: dict = {"kind": kind, "t": round(time.monotonic(), 6)}
        if self.step is not None:
            rec["step"] = self.step
        rec.update(fields)
        with self._lock:
            self._buffer.append(rec)
            do_flush = len(self._buffer) >= self.flush_every
        if do_flush:
            self.flush()

    def counter(self, name: str, value, **attrs) -> None:
        self.emit("counter", name=name, value=value, **attrs)

    def gauge(self, name: str, value, **attrs) -> None:
        self.emit("gauge", name=name, value=value, **attrs)

    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def set_step(self, step: Optional[int]) -> None:
        self.step = step

    # -------------------------------------------------------------------- io --
    def _open(self) -> None:
        if self._file is not None:
            return
        os.makedirs(self.out_dir, exist_ok=True)
        rank, world = self._rank_world()
        self._file = open(self.path, "a")
        header = {
            "kind": "meta",
            "schema": TELEMETRY_SCHEMA_VERSION,
            "run_id": self.run_id,
            "process_index": rank,
            "num_processes": world,
            "pid": os.getpid(),
            "t": round(time.monotonic(), 6),
            "unix_time": time.time(),
        }
        self._file.write(json.dumps(header) + "\n")

    def flush(self) -> None:
        with self._lock:
            if not self._buffer:
                return
            pending, self._buffer = self._buffer, []
            try:
                self._open()
                self._file.write("".join(json.dumps(r, default=str) + "\n" for r in pending))
                self._file.flush()
            except (OSError, ValueError):
                # ValueError: write on a file another thread closed mid-race
                self.dropped_events += len(pending)

    def hard_flush(self) -> None:
        """Crash-handler flush: drain the buffer AND fsync so a dump written
        moments before the process dies is actually on disk. May run inside a
        signal handler that interrupted a frame already holding ``_lock``
        (``emit`` flushes every 64 events), so the acquire is bounded — the
        dying process must never deadlock on itself; worst case the buffered
        tail is dropped, never the dump."""
        if not self._lock.acquire(timeout=2.0):
            return
        try:
            if self._buffer:
                pending, self._buffer = self._buffer, []
                try:
                    self._open()
                    self._file.write(
                        "".join(json.dumps(r, default=str) + "\n" for r in pending)
                    )
                except (OSError, ValueError):
                    self.dropped_events += len(pending)
            if self._file is not None:
                try:
                    self._file.flush()
                    os.fsync(self._file.fileno())
                except (OSError, ValueError):
                    pass
        finally:
            self._lock.release()

    def close(self) -> None:
        if self.closed:
            return
        if self.dropped_events:
            with self._lock:
                self._buffer.append(
                    {"kind": "dropped", "t": round(time.monotonic(), 6), "count": self.dropped_events}
                )
        self.flush()
        self.closed = True
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


def _default_run_id() -> str:
    """``ACCELERATE_RUN_ID`` (launcher-provided, consistent across processes)
    → the live :class:`~accelerate_tpu.state.PartialState` run id → a fresh
    local ``run-<unix>-<pid>``."""
    env = os.environ.get("ACCELERATE_RUN_ID")
    if env:
        return env
    from ..state import PartialState

    if PartialState._shared_state.get("_initialized"):
        rid = getattr(PartialState(), "run_id", None)
        if rid:
            return rid
    return f"run-{int(time.time())}-{os.getpid()}"


# ---------------------------------------------------------------------------
# Module-level singleton + zero-overhead shims. The hot-path contract: every
# helper below costs exactly one attribute load + ``is None`` check when
# telemetry is disabled.

_ACTIVE: Optional[EventLog] = None
_ATEXIT_REGISTERED = False


def _close_active_at_exit() -> None:
    if _ACTIVE is not None:
        _ACTIVE.close()


def enabled_from_env() -> bool:
    """The kill switch: ``ACCELERATE_TELEMETRY`` truthy?"""
    return os.environ.get(TELEMETRY_ENV_VAR, "").strip().lower() in _TRUE


def enable(out_dir: Optional[str] = None, run_id: Optional[str] = None, flush_every: int = 64) -> EventLog:
    """Activate telemetry, writing to ``out_dir`` (defaults to
    ``$ACCELERATE_TELEMETRY_DIR`` or ``./telemetry``)."""
    global _ACTIVE, _ATEXIT_REGISTERED
    if _ACTIVE is not None:
        _ACTIVE.close()
    out_dir = out_dir or os.environ.get(TELEMETRY_DIR_ENV_VAR) or "telemetry"
    _ACTIVE = EventLog(out_dir, run_id=run_id, flush_every=flush_every)
    if not _ATEXIT_REGISTERED:
        atexit.register(_close_active_at_exit)
        _ATEXIT_REGISTERED = True
    return _ACTIVE


def disable() -> None:
    """Deactivate telemetry (flushes and closes the active log)."""
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.close()
        _ACTIVE = None


def maybe_enable_from_env(default_dir: Optional[str] = None) -> Optional[EventLog]:
    """Honor the env kill switch: enable iff ``ACCELERATE_TELEMETRY`` is truthy
    and telemetry is not already active. ``default_dir`` is used when
    ``ACCELERATE_TELEMETRY_DIR`` is unset (the Accelerator passes
    ``<project_dir>/telemetry``)."""
    if _ACTIVE is not None:
        return _ACTIVE
    if not enabled_from_env():
        return None
    return enable(os.environ.get(TELEMETRY_DIR_ENV_VAR) or default_dir)


def is_enabled() -> bool:
    return _ACTIVE is not None


def get_event_log() -> Optional[EventLog]:
    return _ACTIVE


def emit(kind: str, **fields: Any) -> None:
    if _ACTIVE is not None:
        _ACTIVE.emit(kind, **fields)


def counter(name: str, value, **attrs) -> None:
    if _ACTIVE is not None:
        _ACTIVE.counter(name, value, **attrs)


def gauge(name: str, value, **attrs) -> None:
    if _ACTIVE is not None:
        _ACTIVE.gauge(name, value, **attrs)


def span(name: str, **attrs):
    return _NULL_SPAN if _ACTIVE is None else _ACTIVE.span(name, **attrs)


def set_step(step: Optional[int]) -> None:
    if _ACTIVE is not None:
        _ACTIVE.set_step(step)


def flush() -> None:
    if _ACTIVE is not None:
        _ACTIVE.flush()


def hard_flush() -> None:
    """Crash-path flush+fsync of the active log (no-op when disabled)."""
    if _ACTIVE is not None:
        _ACTIVE.hard_flush()
