"""Optimizer wrapper over optax.

TPU-native counterpart of the reference's ``optimizer.py``
(``/root/reference/src/accelerate/optimizer.py`` — ``AcceleratedOptimizer:38``,
``step:148``, XLA lazy grad all-reduce ``:151-157``, scaler overflow-skip
``:163-180``, ``_switch_parameters:184``).

Design shift: a torch optimizer owns mutable param references; an optax
``GradientTransformation`` is a pure function over (grads, state, params). The
wrapper owns the *state* (sharded like the params — the GSPMD twin of FSDP2's
optimizer param-swap, reference ``utils/fsdp_utils.py:543``), exposes a torch-like
imperative surface (``step``/``zero_grad``/``state_dict``) for API parity, and is
consumed functionally by ``Accelerator``'s compiled train step. There is no grad
all-reduce here: gradients of a mean loss over a dp-sharded batch come out of
``jax.grad`` already reduced (compiler-inserted psum / reduce-scatter).

Gradient accumulation: ``accumulation_steps > 1`` wraps the transform in
``optax.MultiSteps`` — micro-step grads accumulate in sharded buffers and the
inner update runs only on boundary steps (reference ``_do_sync``/``no_sync``
semantics, ``accelerator.py:1227-1295``, without any python-side sync toggles).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

_device_copy_fn = None


def _device_copy(x):
    """Fresh device buffer with identical value/dtype/sharding — never
    concretizes to host (safe for multihost global arrays). One shared jitted
    function so repeated leaves hit the trace cache instead of recompiling."""
    global _device_copy_fn
    if _device_copy_fn is None:
        import jax
        import jax.numpy as jnp

        def _copy(a):
            # a real computation, so XLA returns a new buffer instead of
            # aliasing the input; dtype-exact (bool has no arithmetic `+ 0`)
            if a.dtype == jnp.bool_:
                return jnp.logical_and(a, True)
            return a + jnp.zeros((), a.dtype)

        _device_copy_fn = jax.jit(_copy)
    return _device_copy_fn(x)


class AcceleratedOptimizer:
    """Wraps an ``optax.GradientTransformation`` for mesh execution.

    Functional core: :meth:`init` / :meth:`update` (jit-safe). Imperative parity
    surface: :meth:`step`, :meth:`zero_grad`, :meth:`state_dict`.
    """

    def __init__(
        self,
        optimizer,  # optax.GradientTransformation
        accumulation_steps: int = 1,
        scheduler_fn: Optional[Callable] = None,
        wrap_accumulation: bool = True,
    ):
        import optax

        self.base_optimizer = optimizer
        self.accumulation_steps = accumulation_steps
        self.scheduler_fn = scheduler_fn
        if accumulation_steps > 1 and wrap_accumulation:
            self.optimizer = optax.MultiSteps(optimizer, every_k_schedule=accumulation_steps)
        else:
            # wrap_accumulation=False: the transform already handles boundaries
            # internally (fp8 partition nests MultiSteps on the param branch)
            self.optimizer = optimizer
        self.opt_state = None
        self._mesh = None
        self._param_specs = None
        self._plan = None  # ShardingPlan consumed by init (the single spec surface)
        self._fused_update = None  # fused ZeRO-1 update fn (parallel/weight_update.py)
        self._allow_fused_zero1 = True  # cleared to force the annotation path
        # fused-compatible inner transform for the BUCKETED update when
        # self.optimizer is label-routed over the model tree (fp8 partition):
        # the bucket plan carries meta leaves as passthrough slots, so the
        # bucketed tx is the plain inner optimizer (set by prepare_optimizer)
        self._fused_inner_tx = None
        self._fp16_scaler_config = None  # set by Accelerator.prepare_train_step (fp16)
        self._accelerate_step_called = False  # set by patch_optimizer_step wrappers
        self.accelerator_state = None  # set by Accelerator.prepare

    @property
    def fused_zero1(self) -> bool:
        """True when this optimizer's state is the bucketed, 1/N-per-replica
        fused ZeRO-1 layout (``parallel/weight_update.py``)."""
        return self._fused_update is not None

    # ------------------------------------------------------------- functional --
    def init(self, params, mesh=None, param_specs=None, zero1_axis=None, plan=None):
        """Initialize (and shard) optimizer state for ``params``.

        All spec decisions come from a ``parallel.sharding.ShardingPlan`` —
        passed by ``Accelerator.prepare`` or built here from the legacy
        (mesh, param_specs, zero1_axis) arguments. Under fused ZeRO-1 the
        state is BUCKETED (1/N per replica) and the compiled train step runs
        the fused reduce-scatter/update/all-gather; otherwise the state
        inherits param shardings (plus annotation-mode ZeRO-1 when asked)."""
        import jax
        import numpy as _np

        if plan is None and mesh is not None:
            from .parallel.sharding import make_sharding_plan

            plan = make_sharding_plan(
                params, mesh, param_specs=param_specs, zero1_axis=zero1_axis
            )
        self._plan = plan
        self._fused_update = None
        if plan is not None:
            self._mesh = plan.mesh
            self._param_specs = plan.param_specs
            fused = None
            if self._allow_fused_zero1:
                tx = self._fused_inner_tx if self._fused_inner_tx is not None else self.optimizer
                fused = plan.init_fused_optimizer_state(tx, params)
            elif plan.fused_zero1:
                # explicit opt-out: demote the plan so annotation-mode ZeRO-1
                # still shards the state below AND the per-step compiled-
                # collective accounting never reports the fused path's
                # (absent) traffic
                plan.zero1 = None
            if fused is not None:
                self.opt_state, self._fused_update = fused
                if getattr(self, "_fp16_scaler_config", None) is not None:
                    self._wrap_loss_scale_state()
                return self.opt_state
        self.opt_state = self.optimizer.init(params)
        # some optimizers (optax.contrib.schedule_free_*: z iterate) seed state
        # leaves AS the param buffers; a donating train step would then donate
        # the same buffer twice and XLA refuses. Copy aliased leaves once here.
        param_ids = {id(x) for x in jax.tree_util.tree_leaves(params)}

        def _unalias(x):
            if id(x) not in param_ids or not hasattr(x, "dtype"):
                return x
            if isinstance(x, _np.ndarray):
                return x.copy()
            return _device_copy(x)

        self.opt_state = jax.tree_util.tree_map(_unalias, self.opt_state)
        if plan is not None:
            self.opt_state = plan.place_opt_state(self.opt_state, params)
        if getattr(self, "_fp16_scaler_config", None) is not None:
            self._wrap_loss_scale_state()
        return self.opt_state

    def _wrap_loss_scale_state(self) -> None:
        """Extend opt_state to (inner, scale, growth_count) for fp16 dynamic loss
        scaling (set up by ``Accelerator.prepare_train_step``). Idempotent."""
        import jax.numpy as jnp

        cfg = self._fp16_scaler_config
        state = self.opt_state
        if (
            isinstance(state, tuple)
            and len(state) == 3
            and getattr(state[1], "ndim", None) == 0
            and getattr(state[2], "ndim", None) == 0
        ):
            return  # already wrapped
        self.opt_state = (state, jnp.float32(cfg.init_scale), jnp.int32(0))

    def update(self, grads, opt_state, params):
        """Pure optax update — safe to call inside jit."""
        return self.optimizer.update(grads, opt_state, params)

    # ------------------------------------------------------------- imperative --
    def step(self, grads, params):
        """Eager step: apply ``grads`` to ``params``, returning new params.

        The reference mutates wrapped torch params (``optimizer.py:148``); here the
        caller rebinds. Accumulation boundaries are handled inside MultiSteps.
        """
        import optax

        if self.opt_state is None:
            self.init(params)
        if self._fused_update is not None:
            # fused ZeRO-1 state is bucketed: route through the fused update
            # (eager shard_map — same math the compiled step runs)
            new_params, self.opt_state = self._fused_update(grads, self.opt_state, params)
            return new_params
        updates, self.opt_state = self.optimizer.update(grads, self.opt_state, params)
        return optax.apply_updates(params, updates)

    def zero_grad(self, set_to_none: bool = True) -> None:
        """No-op for parity: grads are values, not buffers (reference ``:127``)."""

    @property
    def step_count(self) -> int:
        """Number of *optimizer* (boundary) steps taken."""
        state = self.opt_state
        if state is None:
            return 0
        ms = _find_multisteps_state(state)
        if ms is not None:
            return int(ms.gradient_step)
        return int(_find_count(state) or 0)

    @property
    def is_accumulation_boundary(self) -> bool:
        if self.accumulation_steps <= 1:
            return True
        ms = _find_multisteps_state(self.opt_state) if self.opt_state is not None else None
        if ms is None:
            return True
        return int(ms.mini_step) == 0

    def state_dict(self) -> dict:
        import jax

        return {
            "opt_state": jax.tree_util.tree_map(np.asarray, self.opt_state),
            "accumulation_steps": self.accumulation_steps,
        }

    def load_state_dict(self, state_dict: dict) -> None:
        import jax

        loaded = state_dict["opt_state"]
        if self.opt_state is not None:
            # restore into existing (sharded) structure
            self.opt_state = jax.tree_util.tree_map(
                lambda cur, new: _placed_like(cur, new), self.opt_state, loaded
            )
        else:
            self.opt_state = loaded


def move_to_device(opt_state, device):
    """reference ``optimizer.py move_to_device``: place every array leaf of an
    optimizer state on ``device`` (a ``jax.Device`` or ``Sharding``).
    Delegates to the shared pytree placement helper."""
    from .utils.operations import send_to_device

    return send_to_device(opt_state, device)


def patch_optimizer_step(accelerated_optimizer: "AcceleratedOptimizer", method):
    """reference ``patch_optimizer_step:208``: wrap ``method`` so calling it
    marks ``_accelerate_step_called`` on the optimizer — how the reference's
    scaler path detects whether a step was actually taken vs overflow-skipped.
    Returns the wrapped method (the caller decides where to put it)."""

    def patched_step(*args, **kwargs):
        accelerated_optimizer._accelerate_step_called = True
        return method(*args, **kwargs)

    return patched_step


def _placed_like(current, new):
    import jax

    if isinstance(current, jax.Array):
        return jax.device_put(np.asarray(new), current.sharding)
    return new


def _find_multisteps_state(state):
    """Locate an ``optax.MultiSteps`` state node anywhere in the opt-state tree
    (it can be nested inside a multi_transform partition, e.g. fp8)."""
    if hasattr(state, "gradient_step") and hasattr(state, "mini_step"):
        return state
    if isinstance(state, dict):
        children = state.values()
    elif isinstance(state, (list, tuple)):
        children = state
    elif hasattr(state, "inner_states"):  # optax MultiTransformState
        children = state.inner_states.values()
    elif hasattr(state, "inner_state"):  # optax MaskedState
        children = (state.inner_state,)
    else:
        return None
    for child in children:
        found = _find_multisteps_state(child)
        if found is not None:
            return found
    return None


def _find_count(state):
    """Locate a step counter in an optax state tree (ScaleByAdamState.count etc.)."""
    import jax

    for leaf_path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        names = [getattr(p, "name", getattr(p, "key", "")) for p in leaf_path]
        if any(n == "count" for n in names):
            return leaf
    return None
