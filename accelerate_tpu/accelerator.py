"""The Accelerator: central orchestration facade.

TPU-native counterpart of the reference's ``accelerator.py``
(``/root/reference/src/accelerate/accelerator.py`` — class ``Accelerator:183``,
``prepare:1412``, ``backward:2770``, ``accumulate:1253``, ``clip_grad_norm_:2898``,
``gather_for_metrics:3020``, ``save_state:3529``/``load_state:3695``,
``autocast:4123``, ``profile:4148``, ``free_memory:3847``,
``set_trigger/check_trigger:2804/2830``, ``join_uneven_inputs:1298``).

Architecture shift (SURVEY.md §7): "prepare = wrap objects, comm = explicit
collectives" becomes "prepare = assign shardings, comm = compiler-inserted".
``prepare`` places params on the mesh per sharding rules (DP/FSDP/HSDP/TP fall out
of the specs), shards the optax state the same way, and reshards the dataloader.
The hot path is ONE jitted train step built by :meth:`prepare_train_step`:
gradients of a mean loss over the dp-sharded global batch emerge already reduced
(GSPMD psum / reduce-scatter), gradient accumulation is ``optax.MultiSteps``
inside the compiled step, and bf16 is a dtype policy — no autocast machinery, no
GradScaler for bf16, no ``mark_step``.
"""

from __future__ import annotations

import contextlib
import os
import time
from functools import partial
from typing import Any, Callable, Optional, Sequence

import numpy as np

from .data_loader import DataLoader, DataLoaderShard, prepare_data_loader, skip_first_batches
from .optimizer import AcceleratedOptimizer
from .parallelism_config import ParallelismConfig
from .parallel.sharding import ShardingRules, make_sharding_plan, shard_params
from .scheduler import AcceleratedScheduler
from .state import AcceleratorState, GradientState, PartialState
from .utils.dataclasses import (
    CheckpointConfig,
    DataLoaderConfiguration,
    GradScalerConfig,
    GradientAccumulationPlugin,
    JitConfig,
    PrecisionType,
    ProfileConfig,
    ProjectConfiguration,
    WatchdogConfig,
)
from .utils import operations as ops


# max cached compiled lomo steps (distinct loss_fns) per Accelerator
_LOMO_CACHE_SIZE = 8


class RemovableHandle:
    """Unregister token returned by the state-hook registrars (same contract as
    the torch handle the reference's ``register_*_state_pre_hook`` returns)."""

    _next_id = 0

    def __init__(self, registry: dict):
        self._registry = registry
        self.id = RemovableHandle._next_id
        RemovableHandle._next_id += 1

    def remove(self) -> None:
        self._registry.pop(self.id, None)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.remove()


def _is_param_pytree(obj) -> bool:
    """A dict/flax-style pytree whose leaves are all arrays → model params."""
    import jax

    if not isinstance(obj, dict):
        return False
    leaves = jax.tree_util.tree_leaves(obj)
    return len(leaves) > 0 and all(
        isinstance(x, (jax.Array, np.ndarray)) or np.isscalar(x) for x in leaves
    )


def _is_optax_transform(obj) -> bool:
    return hasattr(obj, "init") and hasattr(obj, "update") and not isinstance(obj, AcceleratedOptimizer)


def _is_dataloader(obj) -> bool:
    if isinstance(obj, (DataLoader, DataLoaderShard)):
        return True
    try:
        import torch.utils.data as tud

        if isinstance(obj, tud.DataLoader):
            return True
    except ImportError:
        pass
    return hasattr(obj, "__iter__") and hasattr(obj, "dataset")


def _is_torch_module(obj) -> bool:
    try:
        import torch.nn as nn

        return isinstance(obj, nn.Module)
    except ImportError:
        return False


def _is_torch_optimizer(obj) -> bool:
    try:
        import torch.optim as topt

        return isinstance(obj, topt.Optimizer)
    except ImportError:
        return False


def _is_torch_lr_scheduler(obj) -> bool:
    try:
        import torch.optim.lr_scheduler as tls

        return isinstance(obj, (tls.LRScheduler, tls.ReduceLROnPlateau))
    except (ImportError, AttributeError):
        return False


class StepProfiler:
    """Step-windowed ``jax.profiler`` driver (reference ``ProfileKwargs``
    schedule semantics, ``utils/dataclasses.py:484-599``): each cycle is
    ``wait`` untraced steps, ``warmup`` untraced steps (compile/cache settle),
    then ``active`` traced steps; ``repeat`` cycles (0 = until the context
    ends), all after ``skip_first`` initial steps. Call :meth:`step` once per
    training step."""

    def __init__(self, config: ProfileConfig, out_dir: str):
        self.config = config
        self.out_dir = out_dir
        self.step_num = 0  # completed work steps (= index of the UPCOMING one)
        self.cycle = -1
        self.tracing = False
        self.trace_dirs: list = []
        self._update()  # the very first work step may already be active

    def _position(self):
        """(cycle_index, step_within_cycle) of the UPCOMING work step after
        skip_first, or None (before skip_first / past the last repeat)."""
        cfg = self.config
        n = self.step_num - cfg.skip_first
        if n < 0:
            return None
        cycle_len = cfg.wait + cfg.warmup + cfg.active
        cycle, within = divmod(n, cycle_len)
        if cfg.repeat and cycle >= cfg.repeat:
            return None
        return cycle, within

    def _update(self) -> None:
        import jax

        cfg = self.config
        pos = self._position()
        should_trace = pos is not None and pos[1] >= cfg.wait + cfg.warmup
        # close the trace when leaving a window OR crossing into the next
        # cycle's window (back-to-back actives must produce per-cycle traces)
        if self.tracing and (not should_trace or pos[0] != self.cycle):
            jax.profiler.stop_trace()
            self.tracing = False
        if should_trace and not self.tracing:
            trace_dir = os.path.join(self.out_dir, f"cycle{pos[0]}")
            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir, create_perfetto_link=cfg.create_perfetto_link)
            self.trace_dirs.append(trace_dir)
            self.tracing = True
            self.cycle = pos[0]

    def step(self) -> None:
        """Mark the end of a work step; starts/stops traces at window boundaries."""
        self.step_num += 1
        self._update()

    def close(self) -> None:
        import jax

        if self.tracing:
            jax.profiler.stop_trace()
            self.tracing = False


class Accelerator:
    """Single facade for mesh setup, precision, prepare, train-step compilation,
    metrics gathering and checkpointing (reference ``accelerator.py:183``)."""

    def __init__(
        self,
        *,
        mixed_precision: Optional[str] = None,
        gradient_accumulation_steps: int = 1,
        gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None,
        parallelism_config: Optional[ParallelismConfig] = None,
        dataloader_config: Optional[DataLoaderConfiguration] = None,
        project_config: Optional[ProjectConfiguration] = None,
        project_dir: Optional[str] = None,
        jit_config: Optional[JitConfig] = None,
        grad_scaler_config: Optional[GradScalerConfig] = None,
        watchdog_config: Optional[WatchdogConfig] = None,
        checkpoint_config: Optional[CheckpointConfig] = None,
        shard_rules: Optional[ShardingRules] = None,
        rng_types: Optional[Sequence[str]] = None,
        rng_seed: Optional[int] = None,
        log_with: Optional[Any] = None,
        step_scheduler_with_optimizer: bool = True,
        cpu: bool = False,
        device_placement: bool = True,
        kwargs_handlers: Optional[Sequence[Any]] = None,
        fsdp_plugin: Optional[Any] = None,
        deepspeed_plugin: Optional[Any] = None,
        dynamo_plugin: Optional[Any] = None,
        megatron_lm_plugin: Optional[Any] = None,
    ):
        # Reference-compat plugins (accelerator.py:278 accepts both): each is a
        # sharding intent here — translate to ParallelismConfig unless the user
        # already gave one explicitly.
        if fsdp_plugin is not None and deepspeed_plugin is not None:
            raise ValueError("pass fsdp_plugin or deepspeed_plugin, not both")
        if deepspeed_plugin is None and fsdp_plugin is None:
            from .utils.environment import parse_flag_from_env

            if parse_flag_from_env("ACCELERATE_USE_DEEPSPEED"):
                # the launcher's --use_deepspeed env protocol (reference
                # utils/launch.py:557-577 → DeepSpeedPlugin env __post_init__)
                from .utils.dataclasses import DeepSpeedPlugin

                deepspeed_plugin = DeepSpeedPlugin.from_env()
        plugin = fsdp_plugin or deepspeed_plugin
        self.deepspeed_plugin = deepspeed_plugin  # reference exposes it too
        # MegatronLMPlugin shim (reference accelerator.py routes prepare through
        # the Megatron engine; here the plugin's degrees ARE the mesh config)
        self.megatron_lm_plugin = megatron_lm_plugin
        if megatron_lm_plugin is not None:
            if plugin is not None:
                raise ValueError(
                    "megatron_lm_plugin cannot be combined with fsdp_plugin/"
                    "deepspeed_plugin (the reference routes to ONE engine too)"
                )
            if parallelism_config is not None:
                raise ValueError(
                    "pass megatron_lm_plugin OR parallelism_config, not both — "
                    "the plugin's tp/pp/ep/sp degrees define the mesh"
                )
            parallelism_config = megatron_lm_plugin.to_parallelism_config()
            if (
                gradient_accumulation_steps == 1
                and megatron_lm_plugin.num_micro_batches > 1
            ):
                # Megatron's micro-batching is grad accumulation in mesh terms
                gradient_accumulation_steps = megatron_lm_plugin.num_micro_batches
        # TorchDynamoPlugin shim: the one actionable XLA knob is eager-vs-jit
        self.dynamo_plugin = dynamo_plugin
        if dynamo_plugin is not None:
            if jit_config is not None:
                raise ValueError("pass dynamo_plugin OR jit_config, not both")
            jit_config = dynamo_plugin.to_jit_config()
        plugin_mp = getattr(deepspeed_plugin, "mixed_precision", None)
        if plugin_mp is not None:
            # the ds config's bf16/fp16 section is the source of truth under
            # DeepSpeed. A CONSTRUCTOR value that disagrees is a hard config
            # mismatch (the reference's fill_match raises the same way). The
            # launcher env is NOT treated as explicit — launchers always set
            # ACCELERATE_MIXED_PRECISION, defaults included — so the config
            # simply wins over it, with a note when they disagree.
            if mixed_precision is not None and str(mixed_precision) != plugin_mp:
                raise ValueError(
                    f"mixed_precision={mixed_precision!r} disagrees with the ds "
                    f"config's {plugin_mp!r} section; align them (the reference "
                    "errors on this mismatch too)"
                )
            env_mp = os.environ.get("ACCELERATE_MIXED_PRECISION")
            if env_mp and env_mp != plugin_mp:
                import warnings

                warnings.warn(
                    f"launcher mixed precision {env_mp!r} differs from the ds "
                    f"config's {plugin_mp!r} section; the ds config wins"
                )
            mixed_precision = plugin_mp
        self._plugin_grad_clip = getattr(deepspeed_plugin, "gradient_clipping", None)
        if self._plugin_grad_clip is None:
            self._plugin_grad_clip = getattr(megatron_lm_plugin, "gradient_clipping", None)
        # ZeRO-Offload / FSDP cpu_offload intent → host-resident optimizer state
        _offload_dev = getattr(deepspeed_plugin, "offload_optimizer_device", None)
        if _offload_dev == "nvme":
            import warnings

            warnings.warn(
                "offload_optimizer_device='nvme' degrades to HOST RAM here "
                "(pinned_host memory kind) — there is no disk tier; make sure "
                "the optimizer state fits host memory"
            )
        self._offload_optimizer = bool(
            _offload_dev in ("cpu", "nvme") or getattr(fsdp_plugin, "cpu_offload", False)
        )
        # ZeRO-1: params replicated, optimizer state sharded across replicas
        self._zero1_axis = (
            "dp_replicate"
            if getattr(deepspeed_plugin, "zero_stage", None) == 1
            else None
        )
        if plugin is not None:
            if not hasattr(plugin, "to_parallelism_config"):
                raise TypeError(
                    f"{type(plugin).__name__} is not a FullyShardedDataParallelPlugin/"
                    "DeepSpeedPlugin (missing to_parallelism_config)"
                )
            if parallelism_config is None:
                # NO_SHARD/stage-0 translation counts devices — honor the cpu
                # flag FIRST or the count initializes the wrong backend (and
                # jax_platforms becomes immutable once a backend exists)
                from .utils.environment import parse_flag_from_env

                if cpu or parse_flag_from_env("ACCELERATE_USE_CPU"):
                    import jax

                    jax.config.update("jax_platforms", "cpu")
                parallelism_config = plugin.to_parallelism_config()
            if (
                deepspeed_plugin is not None
                and gradient_accumulation_steps == 1
                and getattr(plugin, "gradient_accumulation_steps", 1) > 1
            ):
                gradient_accumulation_steps = plugin.gradient_accumulation_steps
        if gradient_accumulation_plugin is None:
            env_steps = int(os.environ.get("ACCELERATE_GRADIENT_ACCUMULATION_STEPS", 1))
            steps = gradient_accumulation_steps if gradient_accumulation_steps != 1 else env_steps
            gradient_accumulation_plugin = GradientAccumulationPlugin(num_steps=steps)
        # kwargs_handlers routing (reference accelerator.py:414-460: one handler
        # per class, each steering one subsystem)
        self.ddp_handler = None
        self.autocast_handler = None
        self.profile_handler = None
        self.fp8_recipe_handler = None
        self.fp8_recipe = None
        init_pg_kwargs: dict[str, Any] = {}
        if kwargs_handlers:
            from .utils.dataclasses import (
                AutocastConfig,
                DistributedDataParallelKwargs,
                FP8RecipeKwargs,
                InitProcessGroupKwargs,
            )

            seen: set[type] = set()
            for handler in kwargs_handlers:
                if type(handler) in seen:
                    raise ValueError(f"duplicate kwargs handler of type {type(handler).__name__}")
                seen.add(type(handler))
                if isinstance(handler, InitProcessGroupKwargs):
                    init_pg_kwargs = {
                        k: v for k, v in handler.to_dict().items() if v is not None
                    }
                elif isinstance(handler, GradScalerConfig):
                    if grad_scaler_config is not None:
                        raise ValueError("grad_scaler_config given both directly and as a handler")
                    grad_scaler_config = handler
                elif isinstance(handler, CheckpointConfig):
                    if checkpoint_config is not None:
                        raise ValueError("checkpoint_config given both directly and as a handler")
                    checkpoint_config = handler
                elif isinstance(handler, AutocastConfig):
                    self.autocast_handler = handler
                elif isinstance(handler, DistributedDataParallelKwargs):
                    self.ddp_handler = handler
                elif isinstance(handler, ProfileConfig):
                    self.profile_handler = handler
                elif isinstance(handler, FP8RecipeKwargs):
                    # TE/AO/MSAMP recipe spellings all map onto the native
                    # delayed-scaling recipe (ops/fp8.py); the `seen` set keys
                    # on concrete type, so guard the base class explicitly —
                    # two different recipe subclasses are still a conflict
                    if self.fp8_recipe_handler is not None:
                        raise ValueError(
                            "multiple fp8 recipe handlers given "
                            f"({type(self.fp8_recipe_handler).__name__} and "
                            f"{type(handler).__name__}); pass exactly one"
                        )
                    self.fp8_recipe_handler = handler
                    self.fp8_recipe = handler.to_native()
                else:
                    raise ValueError(f"unsupported kwargs handler: {handler!r}")
        self.state = AcceleratorState(
            mixed_precision=mixed_precision, cpu=cpu, parallelism_config=parallelism_config,
            **init_pg_kwargs,
        )
        self.gradient_state = GradientState(gradient_accumulation_plugin)
        self.dataloader_config = dataloader_config or DataLoaderConfiguration()
        self.project_configuration = project_config or ProjectConfiguration(project_dir=project_dir)
        self.jit_config = jit_config or JitConfig()
        self.jit_config.apply()
        self.grad_scaler_config = grad_scaler_config or GradScalerConfig()
        self.checkpoint_config = checkpoint_config or CheckpointConfig()
        # background writer for save_state(blocking=False); built lazily so a
        # run that never saves async never starts a thread
        self._checkpoint_manager = None
        self.shard_rules = shard_rules
        # host-RNG streams synchronized across processes at each epoch start
        # (reference Accelerator rng_types, accelerator.py:278; default numpy —
        # our samplers draw from numpy)
        self.rng_types = list(rng_types) if rng_types is not None else ["numpy"]
        self.device_placement = device_placement
        self.step_scheduler_with_optimizer = step_scheduler_with_optimizer
        self._models: list = []
        self._optimizers: list[AcceleratedOptimizer] = []
        self._schedulers: list[AcceleratedScheduler] = []
        self._dataloaders: list[DataLoaderShard] = []
        self._custom_objects: list = []
        self._save_state_pre_hooks: dict[int, Callable] = {}
        self._load_state_pre_hooks: dict[int, Callable] = {}
        from collections import OrderedDict

        # small LRU keyed by the loss_fn object. Weak keying cannot work here
        # (the compiled step necessarily closes over loss_fn, so the value
        # would pin its own key); bounding the cache caps the damage of a
        # fresh-lambda-per-step caller at _LOMO_CACHE_SIZE live executables.
        self._lomo_steps: OrderedDict = OrderedDict()
        self._lomo_scale = float(self.grad_scaler_config.init_scale)
        self._lomo_scale_growth = 0
        self._autocast_enabled = True
        self._param_specs = None
        self._sharding_plan = None  # set by prepare_model (the single spec surface)
        self._accum_count = 0
        self.flag_tensor = None
        self.trackers: list = []
        self.log_with = log_with
        # Telemetry spine (telemetry/): honor the ACCELERATE_TELEMETRY kill
        # switch — when off, the StepTelemetry handle costs one flag check per
        # step and writes nothing.
        from . import telemetry as _telemetry

        _telemetry.maybe_enable_from_env(
            default_dir=os.path.join(self.project_dir, "telemetry") if self.project_dir else None
        )
        self._step_telemetry = _telemetry.StepTelemetry()
        self._compiled_counts: dict[str, int] = {}
        # Automatic profiler windows on the tracked step (telemetry/xplane.py):
        # armed by a ProfileConfig kwargs handler or the ACCELERATE_TRACE_*
        # env knobs; each closed window is parsed into a `trace` event
        # (top-k ops, compute/collective/idle split, comms-overlap ratio).
        self._trace_windows = None
        trace_cfg = self.profile_handler or ProfileConfig()
        if trace_cfg.windows_enabled:
            from .telemetry.xplane import TraceWindows

            trace_out = trace_cfg.output_trace_dir or os.path.join(
                self.project_dir or ".", "profile", "auto"
            )
            self._trace_windows = TraceWindows(
                trace_cfg, os.path.join(trace_out, f"rank{self.process_index}")
            )
        # Hang/crash forensics (telemetry/flight_recorder.py, telemetry/
        # watchdog.py): the ring buffer records regardless (pure memory); crash
        # handlers and the heartbeat thread arm only when asked — a default run
        # pays one env/flag check here and nothing per step.
        from .telemetry import flight_recorder as _flight
        from .telemetry import watchdog as _watchdog

        self.watchdog_config = watchdog_config or WatchdogConfig()
        flight_dir = self.watchdog_config.flight_dir
        if flight_dir is None:
            log = _telemetry.get_event_log()
            if log is not None:
                flight_dir = log.out_dir
            elif self.project_dir:
                flight_dir = os.path.join(self.project_dir, "telemetry")
        if (
            self.watchdog_config.enabled
            or _flight.enabled_from_env()
            or _telemetry.is_enabled()
        ):
            _flight.install(out_dir=flight_dir)
        self._watchdog_started = False
        if self.watchdog_config.enabled and not _watchdog.is_active():
            _watchdog.start(
                timeout=self.watchdog_config.timeout,
                interval=self.watchdog_config.interval,
                abort_on_stall=self.watchdog_config.abort_on_stall,
                out_dir=flight_dir,
            )
            self._watchdog_started = True
        # Chaos harness (resilience/chaos.py): a seeded fault schedule in
        # ACCELERATE_CHAOS_SCHEDULE arms deterministic SIGKILL/hang/straggler
        # injection for chaos tests; unset, this is one env lookup ever and a
        # None-check per injection site.
        from .resilience import chaos as _chaos

        _chaos.maybe_arm_from_env()
        # Training-side step-latency SLO (telemetry/slo.py):
        # ACCELERATE_SLO_STEP_LATENCY_S arms a burn-rate monitor over step
        # wall times — a sustained regression past the threshold emits one
        # ``slo_violation`` record per episode. Unset: one env lookup ever
        # and a None-check per step.
        from .telemetry import slo as _slo

        step_slo = _slo.step_latency_slo_from_env()
        self._step_slo_monitor = (
            _slo.SLOMonitor([step_slo]) if step_slo is not None else None
        )
        self._step_slo_last_eval = 0.0
        # Elastic cohort membership: under a supervised run (restart
        # generation set, or a roster dir published) announce ourselves so the
        # supervisor's roster reflects who actually came up.
        if os.environ.get("ACCELERATE_RESTART_GENERATION", "").strip():
            from .resilience import membership as _membership

            roster = os.environ.get("ACCELERATE_COHORT_DIR", "").strip()
            if not roster and flight_dir:
                roster = os.path.join(flight_dir, "cohort")
            if roster:
                try:
                    _membership.announce_membership(roster)
                except OSError:
                    pass  # announcement is advisory; training proceeds
        if rng_seed is not None:
            from .utils.random import set_seed

            set_seed(rng_seed)
        self.step = 0

    # ------------------------------------------------------------ properties --
    @property
    def partial_state(self) -> PartialState:
        return self.state._partial

    @property
    def mesh(self):
        return self.state.mesh

    @property
    def parallelism_config(self) -> ParallelismConfig:
        return self.state.parallelism_config

    @property
    def device(self):
        return self.partial_state.device

    @property
    def distributed_type(self):
        return self.partial_state.distributed_type

    @property
    def num_processes(self) -> int:
        return self.partial_state.num_processes

    @property
    def process_index(self) -> int:
        return self.partial_state.process_index

    @property
    def local_process_index(self) -> int:
        return self.partial_state.local_process_index

    @property
    def is_main_process(self) -> bool:
        return self.partial_state.is_main_process

    @property
    def is_local_main_process(self) -> bool:
        return self.partial_state.is_local_main_process

    @property
    def is_last_process(self) -> bool:
        return self.partial_state.is_last_process

    @property
    def use_distributed(self) -> bool:
        return self.partial_state.use_distributed

    @property
    def mixed_precision(self) -> str:
        return str(self.state.mixed_precision)

    @property
    def gradient_accumulation_steps(self) -> int:
        return self.gradient_state.num_steps

    @property
    def sync_gradients(self) -> bool:
        return self.gradient_state.sync_gradients

    @property
    def project_dir(self) -> Optional[str]:
        return self.project_configuration.project_dir

    @property
    def param_specs(self):
        """PartitionSpec tree assigned to the most recently prepared params."""
        return self._param_specs

    # --------------------------------------------------------------- prepare --
    def prepare(self, *args, shard_rules: Optional[ShardingRules] = None):
        """Type-dispatched preparation (reference ``prepare:1412`` /
        ``_prepare_one:1395``): params pytrees get shardings assigned and are
        placed on the mesh; optax transforms become :class:`AcceleratedOptimizer`
        with state sharded like the params; dataloaders are resharded."""
        _todo = object()
        results = [_todo] * len(args)
        params_seen = None
        bridged_module = None
        # models first regardless of argument order: optimizer preparation can
        # depend on the registered params (fp8 meta partitioning, state sharding)
        for i, obj in enumerate(args):
            if _is_torch_module(obj):
                prepared = self.prepare_torch_module(obj, shard_rules=shard_rules)
                bridged_module = prepared
                results[i] = prepared
            elif _is_param_pytree(obj):
                prepared = self.prepare_model(obj, shard_rules=shard_rules)
                params_seen = prepared
                results[i] = prepared
        from .utils.dataclasses import DummyOptim, DummyScheduler

        # reference DeepSpeed flow: placeholder optimizer/scheduler become real
        # at prepare time. When BOTH are present, the schedule is baked into
        # the optax optimizer as its learning_rate fn — the update really
        # follows warmup/decay, not just the reported get_last_lr()
        # ds-config-driven hyperparameters (reference: when the ds config
        # defines optimizer/scheduler sections, THEY are the source of truth
        # and the placeholders carry only what the config marks "auto")
        dsp = getattr(self, "deepspeed_plugin", None)
        for obj in args:
            if isinstance(obj, DummyOptim) and dsp is not None:
                for k, v in dsp.dummy_optim_kwargs().items():
                    if k in ("lr", "weight_decay"):
                        setattr(obj, k, v)
                    else:
                        obj.kwargs[k] = v
            if isinstance(obj, DummyScheduler) and dsp is not None:
                for k, v in dsp.dummy_scheduler_kwargs().items():
                    setattr(obj, k, v)
        dummy_scheds = [o for o in args if isinstance(o, DummyScheduler)]
        dummy_optims = [o for o in args if isinstance(o, DummyOptim)]
        schedule_fn = None
        if dummy_scheds:
            lead = dummy_scheds[0]
            if lead.optimizer is None and dummy_optims:
                # pair with the co-prepared placeholder so base_lr is ITS lr
                lead.optimizer = dummy_optims[0]
            if lead.lr_scheduler_callable is None:
                schedule_fn = self._dummy_schedule_fn(lead)
            if not dummy_optims:
                import warnings

                warnings.warn(
                    "DummyScheduler prepared without a DummyOptim in the SAME "
                    "prepare() call: the schedule cannot be baked into an "
                    "already-materialized optimizer — get_last_lr() will "
                    "report the schedule but updates keep the optimizer's "
                    "own learning rate. Prepare them together.",
                    stacklevel=2,
                )
        for i, obj in enumerate(args):
            if results[i] is not _todo:
                continue
            if _is_torch_optimizer(obj):
                results[i] = self.prepare_torch_optimizer(obj, module=bridged_module)
            elif isinstance(obj, DummyOptim):
                if dummy_scheds and dummy_scheds[0].lr_scheduler_callable is not None:
                    import warnings

                    warnings.warn(
                        "DummyScheduler.lr_scheduler_callable cannot modulate "
                        "an optax optimizer's learning rate; the DummyOptim "
                        "materializes at its constant lr",
                        stacklevel=2,
                    )
                results[i] = self.prepare_optimizer(obj.to_optax(learning_rate=schedule_fn))
            elif _is_dataloader(obj):
                results[i] = self.prepare_data_loader(obj)
            elif isinstance(obj, AcceleratedOptimizer) or _is_optax_transform(obj):
                results[i] = self.prepare_optimizer(obj)
            elif isinstance(obj, DummyScheduler):
                # DS schedulers advance once per OPTIMIZER step (no
                # num_processes scaling — the schedule is written in optimizer
                # steps, and the optax-side schedule counts the same way);
                # a callable takes the optimizer and returns a torch-style
                # scheduler object (reference contract), same stepping rule
                if obj.lr_scheduler_callable is not None:
                    underlying = obj.lr_scheduler_callable(obj.optimizer)
                elif obj is dummy_scheds[0] and schedule_fn is not None:
                    underlying = schedule_fn  # the already-built (baked) one
                else:
                    underlying = self._dummy_schedule_fn(obj)
                sched = AcceleratedScheduler(
                    underlying,
                    step_with_optimizer=self.step_scheduler_with_optimizer,
                    num_processes=1,
                )
                self._schedulers.append(sched)
                results[i] = sched
            elif isinstance(obj, AcceleratedScheduler) or _is_torch_lr_scheduler(obj):
                results[i] = self.prepare_scheduler(obj)
            else:
                results[i] = obj
        # late-bind optimizer state sharding to the prepared params — specs
        # (incl. fused ZeRO-1 bucketing) come from the ONE sharding plan
        if params_seen is not None:
            for opt in self._optimizers:
                if opt.opt_state is None:
                    opt.init(params_seen, plan=self._sharding_plan)
        return results[0] if len(results) == 1 else tuple(results)

    def prepare_model(self, params, shard_rules: Optional[ShardingRules] = None, specs=None):
        """Assign shardings + place params (reference ``prepare_model:1735``
        becomes a device_put; DDP/FSDP/TP wrapping collapses into the specs).

        All spec decisions flow through ONE :func:`make_sharding_plan` call —
        the plan is kept on the accelerator and later consumed by optimizer
        state init (incl. fused ZeRO-1), host offload and checkpoint restore."""
        rules = shard_rules or self.shard_rules
        plan = make_sharding_plan(
            params,
            self.mesh,
            self.parallelism_config,
            rules=rules,
            zero1_axis=self._zero1_axis,
            param_specs=specs,
        )
        if self.device_placement:
            params = plan.place_params(params)
        self._sharding_plan = plan
        self._param_specs = plan.param_specs
        self._models.append(params)
        return params

    def prepare_torch_module(self, module, shard_rules: Optional[ShardingRules] = None):
        """Bridge a ``torch.nn.Module`` onto the TPU-native core (the north-star
        interop path; reference ``prepare_model:1735``): params are DLPack-shared
        into a jax pytree, sharded on the mesh like any native model, and the
        module's math is fx-lowered to one jitted fused step on first call."""
        from .bridge import BridgedModule

        bridged = BridgedModule(module, accelerator=self)
        rules = shard_rules or self.shard_rules
        plan = make_sharding_plan(
            bridged.params, self.mesh, self.parallelism_config, rules=rules
        )
        if self.device_placement:
            from jax.sharding import PartitionSpec

            bridged.params = plan.place_params(bridged.params)
            bridged.buffers, _ = shard_params(  # buffers stay replicated
                bridged.buffers, self.mesh, {k: PartitionSpec() for k in bridged.buffers}
            )
        self._sharding_plan = plan
        self._param_specs = plan.param_specs
        self._models.append(bridged)
        return bridged

    def prepare_torch_optimizer(self, torch_optimizer, module=None):
        """Wrap a ``torch.optim.Optimizer`` as a :class:`BridgedOptimizer` over
        the bridged module's params (reference ``prepare_optimizer:2685``; the
        torch optimizer becomes the live hyperparameter source so torch LR
        schedulers keep working)."""
        from .bridge import BridgedModule, BridgedOptimizer

        if module is None:
            bridged = [m for m in self._models if isinstance(m, BridgedModule)]
            if not bridged:
                raise ValueError(
                    "prepare the torch nn.Module before (or together with) its optimizer"
                )
            module = bridged[-1]
        optimizer = BridgedOptimizer(torch_optimizer, module)
        self._optimizers.append(optimizer)
        return optimizer

    def backward(self, loss, **kwargs):
        """torch-parity ``accelerator.backward(loss)`` (reference ``:2770``).

        For bridged modules the forward already produced grads (one fused jitted
        value_and_grad); this moves them into the bridged optimizer's
        accumulator — several ``backward`` calls before ``optimizer.step()``
        average, which is exactly torch's gradient-accumulation semantics. For
        native functional loops use :meth:`prepare_train_step` /
        :meth:`gradient_fn` instead.
        """
        from .bridge import BridgedModule, BridgedOptimizer
        from .telemetry import events as _tel

        bridged = [m for m in self._models if isinstance(m, BridgedModule)]
        if not bridged:
            raise RuntimeError(
                "accelerator.backward() is the torch-interop path; in native JAX "
                "loops use prepare_train_step (grads are computed inside the "
                "compiled step) or gradient_fn for imperative grads"
            )
        with _tel.span("backward"):
            for model in bridged:
                grads = model.pop_pending_grads()
                if grads is None:
                    continue
                for opt in self._optimizers:
                    if isinstance(opt, BridgedOptimizer) and opt.module is model:
                        opt.accumulate_grads(grads)

    def prepare_optimizer(self, optimizer) -> AcceleratedOptimizer:
        if not isinstance(optimizer, AcceleratedOptimizer):
            if self._plugin_grad_clip is not None:
                # DeepSpeedPlugin.gradient_clipping carries over (the engine
                # clipped inside step; here clipping is an optax link ahead of
                # the user's transform)
                import optax

                optimizer = optax.chain(
                    optax.clip_by_global_norm(self._plugin_grad_clip), optimizer
                )
            # fp8 models carry delayed-scaling meta in the param tree; partition
            # the optimizer so meta leaves are replaced by their updated
            # histories instead of being "optimized" (reference: TE recipe wrap,
            # utils/transformer_engine.py apply_fp8_autowrap)
            wrap_accumulation = True
            fused_inner_tx = None
            if self.mixed_precision == PrecisionType.FP8 and self._models:
                from .ops.fp8 import has_fp8_meta, make_fp8_optimizer

                if has_fp8_meta(self._models[-1]):
                    # the fused ZeRO-1 path never sees the label-routed
                    # partition: the bucket plan carries meta leaves as
                    # passthrough slots (replace-with-cotangent applied by the
                    # fused update itself), so the BUCKETED transform is the
                    # plain inner optimizer — MultiSteps-wrapped to keep the
                    # same accumulation boundaries as the partition's default
                    # branch
                    inner_tx = optimizer
                    if self.gradient_accumulation_steps > 1:
                        import optax

                        inner_tx = optax.MultiSteps(
                            inner_tx,
                            every_k_schedule=self.gradient_accumulation_steps,
                        )
                    fused_inner_tx = inner_tx
                    # annotation/eager paths keep the partition: meta leaves
                    # replaced by their updated histories, accumulation INSIDE
                    # the partition so histories roll every micro-step (see
                    # make_fp8_optimizer)
                    optimizer = make_fp8_optimizer(
                        optimizer,
                        self._models[-1],
                        accumulation_steps=self.gradient_accumulation_steps,
                    )
                    wrap_accumulation = False
            optimizer = AcceleratedOptimizer(
                optimizer,
                accumulation_steps=self.gradient_accumulation_steps,
                wrap_accumulation=wrap_accumulation,
            )
            if fused_inner_tx is not None:
                optimizer._fused_inner_tx = fused_inner_tx
        optimizer.accelerator_state = self.state
        self._optimizers.append(optimizer)
        return optimizer

    @staticmethod
    def _dummy_schedule_fn(dummy):
        """Reference ``DummyScheduler`` flow (``utils/deepspeed.py``): linear
        warmup over ``warmup_num_steps`` then linear decay to 0 at
        ``total_num_steps`` (the DS ``WarmupDecayLR`` shape), around the
        paired optimizer's base learning rate. Returned as a pure
        ``step -> lr`` fn so it can serve BOTH as the optax learning_rate and
        as the AcceleratedScheduler's reporting schedule."""
        paired = getattr(dummy, "optimizer", None)
        base_lr = getattr(paired, "lr", None)
        if base_lr is None:
            base_lr = 1e-3
        total = dummy.total_num_steps
        # total known -> WarmupDecayLR (decay to 0 at total); total unknown ->
        # WarmupLR (hold base_lr after warmup) — matching the DS schedule the
        # config would have named
        warmup = dummy.warmup_num_steps if total is None else min(dummy.warmup_num_steps, total)

        def schedule_fn(step):
            import jax.numpy as jnp

            step = jnp.asarray(step, jnp.float32)
            warm = base_lr * (step + 1) / max(warmup, 1)
            if total is not None and total > warmup:
                frac = (step - warmup) / (total - warmup)
                after = base_lr * jnp.maximum(0.0, 1.0 - frac)
            else:
                after = jnp.asarray(base_lr, jnp.float32)
            return jnp.where(step < warmup, warm, after) if warmup else after

        return schedule_fn

    def prepare_scheduler(self, scheduler) -> AcceleratedScheduler:
        if not isinstance(scheduler, AcceleratedScheduler):
            scheduler = AcceleratedScheduler(
                scheduler,
                step_with_optimizer=self.step_scheduler_with_optimizer,
                split_batches=self.dataloader_config.split_batches,
            )
        self._schedulers.append(scheduler)
        return scheduler

    def prepare_data_loader(self, dataloader) -> DataLoaderShard:
        if isinstance(dataloader, DataLoaderShard):  # already prepared
            return dataloader
        cfg = self.dataloader_config
        if cfg.use_stateful_dataloader and not isinstance(dataloader, DataLoader) and not (
            hasattr(dataloader, "state_dict") and hasattr(dataloader, "load_state_dict")
        ):
            # reference DataLoaderAdapter:414-431: with torchdata>=0.8.0
            # installed, a PLAIN torch loader is rebuilt as a
            # StatefulDataLoader; the ImportError is reserved for torchdata
            # actually being absent. The native DataLoader already carries
            # state machinery, so the flag only gates plain torch loaders.
            from .data_loader import as_stateful_dataloader, stateful_dataloader_available

            rebuilt = as_stateful_dataloader(dataloader)
            if rebuilt is None:
                if stateful_dataloader_available():
                    # torchdata is fine — the LOADER is the problem; saying
                    # "install torchdata" would send the user the wrong way
                    raise TypeError(
                        "use_stateful_dataloader=True: "
                        f"{type(dataloader).__name__} cannot be rebuilt as a "
                        "torchdata StatefulDataLoader (only plain torch "
                        "DataLoaders are rebuildable). Pass a StatefulDataLoader "
                        "directly, or use the native DataLoader (stateful out "
                        "of the box)."
                    )
                raise ImportError(
                    "use_stateful_dataloader=True but this loader has no "
                    "state_dict/load_state_dict and torchdata>=0.8.0 is not "
                    "installed to rebuild it. Install torchdata>=0.8.0, or use "
                    "the native DataLoader (stateful out of the box)."
                )
            dataloader = rebuilt
        prepared = prepare_data_loader(
            dataloader,
            state=self.state,
            mesh=self.mesh,
            parallelism_config=self.parallelism_config,
            device_placement=self.device_placement,
            split_batches=cfg.split_batches,
            even_batches=cfg.even_batches,
            dispatch_batches=cfg.dispatch_batches,
            data_seed=cfg.data_seed,
            use_seedable_sampler=cfg.use_seedable_sampler,
            rng_types=self.rng_types if self.num_processes > 1 else None,
            prefetch_depth=cfg.prefetch_depth,
        )
        self._dataloaders.append(prepared)
        return prepared

    # ------------------------------------------------------------ train step --
    def _register_compiled(self, kind: str, fn):
        """Name + register a jitted function for telemetry recompile detection
        (a later jit-cache miss on it is a silent reshape-driven recompile).
        Registration pins the executable via the watcher, so it only happens
        while telemetry is enabled — disabled runs must not accumulate refs."""
        from .telemetry import events as _tel

        if not _tel.is_enabled():
            return fn
        n = self._compiled_counts.get(kind, 0)
        self._compiled_counts[kind] = n + 1
        self._step_telemetry.register_compiled(f"{kind}#{n}", fn)
        return fn

    def _resolve_optimizer(self, optimizer):
        if optimizer is None:
            if not self._optimizers:
                raise ValueError("prepare an optimizer first or pass one explicitly")
            optimizer = self._optimizers[-1]
        return optimizer

    def _build_train_step(
        self,
        loss_fn: Callable,
        optimizer: AcceleratedOptimizer,
        has_aux: bool,
        compute_grad_norm: bool,
    ) -> Callable:
        """The UNJITTED full step ``(params, opt_state, batch) -> (params,
        opt_state, metrics)``; shared by :meth:`prepare_train_step` (jit per
        call) and :meth:`prepare_train_loop` (scan over many steps)."""
        import jax
        import jax.numpy as jnp
        import optax

        policy = self.state.mixed_precision_policy
        if not self._autocast_enabled:
            # inside `autocast(AutocastKwargs(enabled=False))`: full precision
            from .utils.dataclasses import MixedPrecisionPolicy

            policy = MixedPrecisionPolicy.from_precision(PrecisionType.NO)
        fp16 = self.state.mixed_precision == PrecisionType.FP16
        scaler = self.grad_scaler_config
        # DDP comm-hook compat: bound the gradient signal to the compressed
        # wire dtype (the half of fp16/bf16 comm hooks that survives GSPMD —
        # see DistributedDataParallelKwargs)
        compress_dtype = (
            self.ddp_handler.gradient_compression_dtype()
            if getattr(self, "ddp_handler", None) is not None
            else None
        )

        def _scaled_loss(params, batch, loss_scale):
            compute_params = policy.cast_to_compute(params)
            # float batch leaves must match the compute dtype too: ops with
            # strict operand-dtype equality (lax.conv_general_dilated) would
            # otherwise fail on bf16-params × f32-activations
            batch = policy.cast_to_compute(batch)
            out = loss_fn(compute_params, batch)
            loss, aux = (out if has_aux else (out, None))
            loss = loss.astype(jnp.float32)
            return loss * loss_scale, (loss, aux)

        grad_fn = jax.grad(_scaled_loss, has_aux=True)

        def _base_step(params, opt_state, batch, loss_scale):
            grads, (loss, aux) = grad_fn(params, batch, loss_scale)
            if compress_dtype is not None:
                # compress while still loss-scaled (the reference's fp16 comm
                # hook compresses pre-unscale grads, so small signals ride the
                # scale above fp16's subnormal floor)
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(compress_dtype).astype(g.dtype), grads
                )
            grads = jax.tree_util.tree_map(lambda g: g / loss_scale, grads)
            grads = policy.cast_to_param(grads)  # accumulate/update in param dtype
            metrics = {"loss": loss}
            finite = None
            if fp16:
                finite = jnp.all(
                    jnp.asarray([jnp.all(jnp.isfinite(g)) for g in jax.tree_util.tree_leaves(grads)])
                )
                # skip the update on overflow (reference scaler overflow-skip
                # optimizer.py:163-180) by zeroing grads for this micro-step
                grads = jax.tree_util.tree_map(
                    lambda g: jnp.where(finite, g, jnp.zeros_like(g)), grads
                )
                metrics["grads_finite"] = finite
            if compute_grad_norm:
                metrics["grad_norm"] = optax.global_norm(grads)
            if optimizer._fused_update is not None:
                # fused ZeRO-1 (parallel/weight_update.py): bucketed
                # reduce-scatter → 1/N shard-local update → all-gather, all
                # inside this traced step
                new_params, new_opt_state = optimizer._fused_update(
                    grads, opt_state, params
                )
            else:
                updates, new_opt_state = optimizer.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
            if aux is not None:
                metrics["aux"] = aux
            return new_params, new_opt_state, metrics, finite

        if not fp16:

            def train_step(params, opt_state, batch):
                new_params, new_opt_state, metrics, _ = _base_step(
                    params, opt_state, batch, jnp.float32(1.0)
                )
                return new_params, new_opt_state, metrics

        else:
            # Dynamic loss scaling (reference GradScaler semantics,
            # utils/dataclasses.py:241): opt_state is extended to
            # (inner_state, scale, growth_count); backoff on overflow, grow after
            # growth_interval consecutive finite steps. If the optimizer is not
            # yet initialized, the wrap happens inside its init().
            optimizer._fp16_scaler_config = scaler
            if optimizer.opt_state is not None:
                optimizer._wrap_loss_scale_state()

            def train_step(params, opt_state, batch):
                inner_state, scale, growth_count = opt_state
                new_params, new_inner, metrics, finite = _base_step(
                    params, inner_state, batch, scale
                )
                new_scale = jnp.where(
                    finite,
                    jnp.where(
                        growth_count + 1 >= scaler.growth_interval,
                        scale * scaler.growth_factor,
                        scale,
                    ),
                    jnp.maximum(scale * scaler.backoff_factor, 1.0),
                )
                new_growth = jnp.where(
                    finite, (growth_count + 1) % scaler.growth_interval, 0
                ).astype(jnp.int32)
                metrics["loss_scale"] = new_scale
                return new_params, (new_inner, new_scale, new_growth), metrics

        return train_step

    def _track_step(self, step_fn, optimizer, kind: str = "train_step"):
        # The functional loop threads (params, opt_state) locally while
        # ``save_state`` reads ``optimizer.opt_state`` / ``self._models`` — and
        # donation deletes the stale buffers those references point at. Write the
        # fresh values back after every call so checkpointing always sees live
        # state (the reference's optimizer mutates in place; this is the
        # functional equivalent).
        # with several prepared models we cannot know which one this step trains,
        # so only track when unambiguous (callers with multiple models pass
        # params/opt_state to save_state explicitly)
        model_slot = 0 if len(self._models) == 1 else None
        from .resilience import chaos as _chaos
        from .telemetry import events as _tel
        from .telemetry import flight_recorder as _flight
        from .telemetry import perf as _perf
        from .telemetry import watchdog as _watchdog

        from . import compile_cache as _ccache

        step_telemetry = self._step_telemetry
        flight = _flight.get_recorder()
        trace_windows = self._trace_windows
        # XLA-reported cost of THIS wrapper's step fn (captured once, before
        # the first call — args are never donated-away yet at that point);
        # re-attached before every step so records from interleaved step fns
        # (train + a second loop) never carry each other's roofline numbers
        perf_cost: list = [None, False]  # [cost, capture_attempted]
        # Warm-restart probe state: on restart generations >= 1 (the elastic
        # supervisor respawned us) the persistent compile cache is probed once
        # before the first call — a hit runs the DESERIALIZED executable and
        # the restart never pays this function's XLA compile
        # [loaded executable | None, probe_attempted, cache key | None]
        cached_exec: list = [None, False, None]
        restart_generation = self.restart_generation

        slo_monitor = self._step_slo_monitor

        def step_and_track(params, opt_state, batch):
            # forensics: the flight ring always knows the current step, and an
            # active watchdog hears one beat per step (a rank whose beats stop
            # is stalled; its open phases name what it is blocked in)
            step_index = step_telemetry.step_index
            slo_t0 = time.monotonic() if slo_monitor is not None else 0.0
            flight.step = step_index
            _watchdog.beat("train_step", step=step_index)
            _chaos.maybe_inject("train_step", step=step_index)
            if trace_windows is not None:
                trace_windows.on_step_start(step_index)
            if not cached_exec[1]:
                cached_exec[1] = True
                if restart_generation >= 1 and _ccache.cache_enabled():
                    cached_exec[0], cached_exec[2] = _ccache.maybe_load_executable(
                        kind, step_fn, (params, opt_state, batch), mesh=self.mesh
                    )

            def run_step(p, o, b):
                if cached_exec[0] is None:
                    return step_fn(p, o, b)
                # AOT input checking rejects BEFORE execution, so a stale
                # cached executable falls back to the jit path (which then
                # compiles as a cold start would) without consuming donations
                out, usable = _ccache.call_with_fallback(
                    kind, cached_exec[0], step_fn, (p, o, b), key=cached_exec[2]
                )
                if not usable:
                    cached_exec[0] = None
                return out

            try:
                if _tel.is_enabled():
                    if not perf_cost[1] and _perf.capture_enabled():
                        perf_cost[1] = True
                        if cached_exec[0] is not None:
                            # warm restart: the cost analysis rides the loaded
                            # executable — no capture AOT compile either
                            perf_cost[0] = _perf.capture_from_executable(
                                kind, cached_exec[0]
                            )
                        else:
                            perf_cost[0] = _perf.capture_compiled(
                                kind, step_fn, (params, opt_state, batch),
                                mesh=self.mesh,
                            )
                    step_telemetry.set_step_cost(perf_cost[0])
                    with step_telemetry.step():
                        new_params, new_opt_state, metrics = run_step(params, opt_state, batch)
                else:
                    new_params, new_opt_state, metrics = run_step(params, opt_state, batch)
                    step_telemetry.step_index += 1
            finally:
                if trace_windows is not None:
                    trace_windows.on_step_end(step_index)
            if _tel.is_enabled():
                # fused ZeRO-1 collectives are compiled into the step — the
                # host never sees them, so account their payload from the
                # bucket plan (reduce-scatter + all-gather bytes per step)
                plan = getattr(optimizer, "_plan", None)
                compiled_comms = (
                    plan.zero1_collective_bytes() if plan is not None else None
                )
                if compiled_comms:
                    for op, nbytes in compiled_comms.items():
                        ops.record_compiled_collective(op, nbytes)
            if slo_monitor is not None:
                # step-latency SLO: observe every step, evaluate throttled
                # (evaluation walks the burn windows — once a second is the
                # right cadence, not once a step)
                wall = time.monotonic()
                slo_monitor.observe("step_latency", value=wall - slo_t0)
                if wall - self._step_slo_last_eval >= 1.0:
                    self._step_slo_last_eval = wall
                    slo_monitor.evaluate()
            optimizer.opt_state = new_opt_state
            if model_slot is not None:
                self._models[model_slot] = new_params
            return new_params, new_opt_state, metrics

        if hasattr(step_fn, "_cache_size"):
            # surface the jitted step's cache counter through the tracking
            # wrapper (the serving engine's jit_cache_sizes idiom) so callers
            # can assert frozen caches post-warmup
            step_and_track._cache_size = step_fn._cache_size
        return step_and_track

    def prepare_train_step(
        self,
        loss_fn: Callable,
        optimizer: Optional[AcceleratedOptimizer] = None,
        has_aux: bool = False,
        compute_grad_norm: bool = False,
        donate: Optional[bool] = None,
        offload_optimizer: Optional[bool] = None,
    ) -> Callable:
        """Compile the full training step (the reference's whole hot loop —
        forward, backward with overlapped comm, clip, optimizer, scheduler
        (``accelerator.py:2770``/``optimizer.py:148``) — as ONE jitted function).

        ``loss_fn(params, batch)`` returns a scalar loss (or ``(loss, aux)`` with
        ``has_aux=True``), computed on the global sharded batch. Returns
        ``step(params, opt_state, batch) -> (params, opt_state, metrics)``.

        Under gradient accumulation the same compiled function is called every
        micro-batch; ``optax.MultiSteps`` applies the inner update only on
        boundary steps (traced ``lax.cond`` — no python-side sync flags).

        ``offload_optimizer=True`` (defaulted on by
        ``DeepSpeedPlugin(offload_optimizer_device="cpu")`` or
        ``FullyShardedDataParallelPlugin(cpu_offload=True)``) keeps the
        optimizer state in host RAM (``pinned_host``) between steps — the
        ZeRO-Offload capability, XLA-native: H2D/D2H staging is inside the
        compiled step. Frees ~2× params of HBM for Adam-family optimizers at
        the cost of PCIe/DMA traffic per step. The live
        ``optimizer.opt_state`` is committed to host immediately. TPU only
        (the CPU emulation backend cannot compile memory-kind annotations;
        falls back with a warning).
        """
        import jax

        optimizer = self._resolve_optimizer(optimizer)
        train_step = self._build_train_step(loss_fn, optimizer, has_aux, compute_grad_norm)

        if offload_optimizer is None:
            offload_optimizer = self._offload_optimizer
        if offload_optimizer and self.jit_config.disable_jit:
            import warnings

            warnings.warn(
                "offload_optimizer requested but jit is disabled "
                "(jit_config.disable_jit) — memory-kind staging only exists "
                "inside compiled programs; keeping optimizer state in device memory"
            )
        if offload_optimizer and not self.jit_config.disable_jit:
            from .parallel.sharding import host_offload_supported, make_host_offloaded_step

            if optimizer.opt_state is None:
                raise ValueError(
                    "offload_optimizer needs the live optimizer state — call "
                    "prepare(params, optimizer) first"
                )
            if not host_offload_supported():
                import warnings

                warnings.warn(
                    "optimizer host-offload requested but this backend cannot "
                    "compile memory-kind annotations (CPU emulation); keeping "
                    "optimizer state in device memory"
                )
            else:
                donate = self.jit_config.donate_params if donate is None else donate
                step, host_state = make_host_offloaded_step(
                    train_step, optimizer.opt_state, donate=donate,
                    mesh=self.mesh, plan=self._sharding_plan,
                )
                optimizer.opt_state = host_state
                self._register_compiled("train_step_offload", step)
                return self._track_step(step, optimizer, kind="train_step_offload")

        if not self.jit_config.disable_jit:
            donate = self.jit_config.donate_params if donate is None else donate
            train_step = jax.jit(train_step, donate_argnums=(0, 1) if donate else ())
            self._register_compiled("train_step", train_step)

        return self._track_step(train_step, optimizer, kind="train_step")

    def prepare_train_loop(
        self,
        loss_fn: Callable,
        optimizer: Optional[AcceleratedOptimizer] = None,
        has_aux: bool = False,
        compute_grad_norm: bool = False,
        donate: Optional[bool] = None,
    ) -> Callable:
        """Compile a MULTI-step training loop: ``loop(params, opt_state,
        batches) -> (params, opt_state, metrics)`` where ``batches`` is a batch
        pytree with a leading ``[K, ...]`` step axis (see
        :func:`~accelerate_tpu.utils.operations.stack_batches`) and ``metrics``
        leaves are stacked ``[K]``.

        TPU-first redesign with no reference counterpart: the reference's hot
        loop re-enters Python every batch (``accelerator.py:2770`` backward →
        ``optimizer.py:148`` step), which on a remote-dispatched TPU runtime
        costs a host round-trip per step. Here the K steps run inside one
        ``lax.scan`` — one dispatch per K steps, so host/dispatch latency is
        amortized to nothing (measured: BERT-base step 45 ms/step dispatched
        per-step vs 36 ms/step inside the scanned loop on v5e).

        Semantically identical to calling the :meth:`prepare_train_step`
        function K times (same update math, incl. fp16 dynamic loss scaling and
        gradient accumulation via MultiSteps — K is micro-steps then).
        """
        import jax

        if self._offload_optimizer:
            import warnings

            warnings.warn(
                "optimizer host-offload is configured but not applied in the "
                "scanned train loop — state must stay in HBM across the K "
                "scanned steps; use prepare_train_step for per-step offload"
            )
        optimizer = self._resolve_optimizer(optimizer)
        train_step = self._build_train_step(loss_fn, optimizer, has_aux, compute_grad_norm)

        def train_loop(params, opt_state, batches):
            def body(carry, batch):
                p, s, _m = train_step(*carry, batch)
                return (p, s), _m

            (params, opt_state), metrics = jax.lax.scan(body, (params, opt_state), batches)
            return params, opt_state, metrics

        if not self.jit_config.disable_jit:
            donate = self.jit_config.donate_params if donate is None else donate
            train_loop = jax.jit(train_loop, donate_argnums=(0, 1) if donate else ())
            self._register_compiled("train_loop", train_loop)

        return self._track_step(train_loop, optimizer, kind="train_loop")

    def prepare_eval_step(self, eval_fn: Callable) -> Callable:
        """Compile an eval/forward step with the compute-dtype policy applied."""
        import jax

        policy = self.state.mixed_precision_policy

        def eval_step(params, batch):
            return eval_fn(policy.cast_to_compute(params), policy.cast_to_compute(batch))

        if self.jit_config.disable_jit:
            return eval_step
        return self._register_compiled("eval_step", jax.jit(eval_step))

    # ------------------------------------------- imperative parity surface ----
    def gradient_fn(self, loss_fn: Callable, has_aux: bool = False) -> Callable:
        """Eager ``(params, batch) -> (grads, loss[, aux])`` with the precision
        policy applied — the moral twin of ``accelerator.backward`` (reference
        ``accelerator.py:2770``) for imperative loops. Loss is divided by the
        accumulation step count exactly like the reference (``:2792``) when the
        optimizer is NOT a MultiSteps wrapper (MultiSteps averages internally)."""
        import jax

        policy = self.state.mixed_precision_policy

        def _loss(params, batch):
            out = loss_fn(policy.cast_to_compute(params), policy.cast_to_compute(batch))
            return out if not has_aux else out

        return jax.value_and_grad(_loss, has_aux=has_aux)

    @contextlib.contextmanager
    def accumulate(self, *models):
        """Context manager marking accumulation micro-steps (reference
        ``accumulate:1253`` + ``_do_sync:1227``). Under the compiled train step
        this is bookkeeping only (MultiSteps does the real work); it drives
        ``sync_gradients`` for schedulers and user code."""
        self._accum_count += 1
        end = self.gradient_state.end_of_dataloader and self.gradient_state.sync_with_dataloader
        sync = (
            self._accum_count % self.gradient_state.num_steps == 0
            or end
            or self.gradient_state.plugin.sync_each_batch
        )
        self.gradient_state._set_sync_gradients(sync)
        try:
            yield
        finally:
            if end:
                # re-align accumulation windows at epoch boundaries (reference
                # _do_sync resets self.step on end_of_dataloader, accelerator.py:1227)
                self._accum_count = 0

    @contextlib.contextmanager
    def no_sync(self, model=None):
        """Suppress sync flag (reference ``no_sync:1130``) — bookkeeping only."""
        prev = self.gradient_state.sync_gradients
        self.gradient_state._set_sync_gradients(False)
        try:
            yield
        finally:
            self.gradient_state._set_sync_gradients(prev)

    def clip_grad_norm_(self, grads, max_norm: float, norm_type: int = 2):
        """Eager global-norm clip returning (clipped_grads, total_norm)
        (reference ``clip_grad_norm_:2898`` returns the norm). In the compiled
        path put ``optax.clip_by_global_norm`` in the chain instead."""
        import jax
        import jax.numpy as jnp
        import optax

        if norm_type != 2:
            raise NotImplementedError("only the L2 global norm is supported on TPU")
        norm = optax.global_norm(grads)
        scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
        return jax.tree_util.tree_map(lambda g: g * scale, grads), norm

    def clip_grad_value_(self, grads, clip_value: float):
        import jax
        import jax.numpy as jnp

        return jax.tree_util.tree_map(lambda g: jnp.clip(g, -clip_value, clip_value), grads)

    # ------------------------------------------------------------- gathering --
    def gather(self, tree):
        return ops.gather(tree)

    def gather_for_metrics(self, data, use_gather_object: bool = False):
        """Gather eval outputs and drop wraparound duplicates of the final batch
        (reference ``gather_for_metrics:3020`` using ``GradientState.remainder``)."""
        if use_gather_object:
            return ops.gather_object(data)
        gathered = ops.gather(data)
        remainder = self.gradient_state.remainder
        if self.gradient_state.end_of_dataloader and remainder > 0:

            def _trim(x):
                return x[:remainder] if getattr(x, "ndim", 0) >= 1 else x

            gathered = ops.recursively_apply(_trim, gathered)
        return gathered

    def reduce(self, tree, reduction: str = "mean", scale: float = 1.0):
        return ops.reduce(tree, reduction=reduction, scale=scale)

    def pad_across_processes(self, tree, dim: int = 0, pad_index: int = 0, pad_first: bool = False):
        return ops.pad_across_processes(tree, dim=dim, pad_index=pad_index, pad_first=pad_first)

    def split_between_processes(self, inputs, apply_padding: bool = False):
        return self.partial_state.split_between_processes(inputs, apply_padding=apply_padding)

    # ------------------------------------------------------- process control --
    def wait_for_everyone(self):
        self.partial_state.wait_for_everyone()

    def print(self, *args, **kwargs):
        self.partial_state.print(*args, **kwargs)

    def on_main_process(self, function):
        return self.partial_state.on_main_process(function)

    def on_local_main_process(self, function):
        return self.partial_state.on_local_main_process(function)

    def on_process(self, function=None, process_index=None):
        return self.partial_state.on_process(function, process_index)

    @contextlib.contextmanager
    def main_process_first(self):
        with self.partial_state.main_process_first():
            yield

    @contextlib.contextmanager
    def local_main_process_first(self):
        with self.partial_state.local_main_process_first():
            yield

    # ------------------------------------------------------- long context ----
    def context_parallel_attention(self, strategy: Optional[str] = None):
        """attention_fn for the current mesh: ring/allgather over ``cp`` or
        Ulysses over ``sp``; plain attention otherwise. Pass it to the model's
        ``attention_fn`` hook (the functional twin of the reference's
        ``maybe_context_parallel`` ctx, ``accelerator.py:4056``)."""
        from .parallel.long_context import make_context_parallel_attention
        from .ops.attention import dot_product_attention

        pc = self.parallelism_config
        if pc.cp_enabled:
            strategy = strategy or pc.cp_rotate_method
            return make_context_parallel_attention(self.mesh, strategy=strategy)
        if pc.sp_enabled:
            return make_context_parallel_attention(self.mesh, strategy="ulysses")
        return lambda q, k, v, causal=True, scale=None: dot_product_attention(
            q, k, v, causal=causal, scale=scale
        )

    @contextlib.contextmanager
    def maybe_context_parallel(self, buffers=None, buffer_seq_dims=None, no_restore_buffers=None):
        """API-parity shim (reference ``maybe_context_parallel:4056-4120``): torch
        must shard buffers in-place per step; under GSPMD the dataloader already
        yields seq-sharded global arrays and the attention_fn does the rest.

        Buffer arguments are therefore IGNORED — warn so a ported reference
        script's author learns the actual CP hook (``get_attention_fn`` /
        ``seq_dim`` on ``prepare_data_loader``) instead of silently assuming
        per-step buffer sharding happened."""
        if buffers is not None or buffer_seq_dims is not None or no_restore_buffers is not None:
            import warnings

            warnings.warn(
                "maybe_context_parallel buffer arguments are ignored under SPMD: "
                "sequence sharding comes from the prepared dataloader (seq_dim) "
                "and the attention_fn from accelerator.get_attention_fn(); no "
                "per-step in-place buffer resharding exists or is needed",
                stacklevel=2,
            )
        yield

    @contextlib.contextmanager
    def join_uneven_inputs(self, joinables=None, even_batches=None):
        """Parity shim (reference ``join_uneven_inputs:1298``): with static shapes
        and even_batches wraparound there is nothing to join."""
        yield

    # ------------------------------------------------------------- triggers --
    def set_trigger(self):
        """Flag this process for a breakpoint visible to all (reference
        ``set_trigger:2804``)."""
        self.flag_tensor = True

    def check_trigger(self) -> bool:
        """True if any process called :meth:`set_trigger` (reference ``:2830``)."""
        flags = ops.gather_object(bool(self.flag_tensor))
        self.flag_tensor = False
        return any(flags)

    # ------------------------------------------------------------------ lomo --
    def lomo_backward(self, loss_fn: Callable, params, *args, learning_rate: float = 1e-3):
        """Fused backward + SGD update in one donated jit (reference
        ``lomo_backward:4265``, which routes backward through a LOMO optimizer's
        ``fused_backward`` so full gradients are never stored).

        The XLA-native form: ``jax.value_and_grad`` + the SGD update compiled as
        ONE step with the params buffer donated — the scheduler applies each
        layer's update as its gradient is produced, so the full gradient tree
        need not coexist with the params in HBM. Returns
        ``(loss, new_params)``; rebind params (functional update, no mutation).

        Define ``loss_fn`` ONCE outside the training loop and pass the batch
        through ``*args`` — a fresh lambda per step is a fresh compile per step
        (compiled steps are kept in a small LRU of ``_LOMO_CACHE_SIZE``
        entries, so fresh-lambda callers recompile but do not leak).

        Under ``mixed_precision="fp16"`` the loss is scaled by a dynamic loss
        scale held host-side on the Accelerator (``grad_scaler_config`` tunes
        it): overflowed steps are skipped (params returned unchanged) and the
        scale backs off, mirroring the prepared-step scaler — workable here
        because this eager-style API already syncs the loss to host each call.
        """
        import jax

        fp16 = self.state.mixed_precision == PrecisionType.FP16
        step = self._lomo_steps.get(loss_fn)
        if step is not None:
            self._lomo_steps.move_to_end(loss_fn)
        if step is None:
            import jax.numpy as jnp

            policy = self.state.mixed_precision_policy

            def _step(params, lr, loss_scale, *a):
                def _loss(p, *inner):
                    return loss_fn(policy.cast_to_compute(p), *inner).astype(jnp.float32) * loss_scale

                loss, grads = jax.value_and_grad(_loss)(params, *a)
                grads = jax.tree_util.tree_map(lambda g: g / loss_scale, grads)
                finite = jnp.all(jnp.asarray(
                    [jnp.all(jnp.isfinite(g)) for g in jax.tree_util.tree_leaves(grads)]
                ))
                if fp16:
                    grads = jax.tree_util.tree_map(
                        lambda g: jnp.where(finite, g, jnp.zeros_like(g)), grads
                    )
                new_params = jax.tree_util.tree_map(
                    lambda p, g: p - lr.astype(p.dtype) * g.astype(p.dtype), params, grads
                )
                return loss / loss_scale, new_params, finite

            step = jax.jit(_step, donate_argnums=(0,)) if not self.jit_config.disable_jit else _step
            self._lomo_steps[loss_fn] = step
            while len(self._lomo_steps) > _LOMO_CACHE_SIZE:
                self._lomo_steps.popitem(last=False)
        import jax.numpy as jnp

        scale = self._lomo_scale if fp16 else 1.0
        loss, new_params, finite = step(
            params, jnp.float32(learning_rate), jnp.float32(scale), *args
        )
        if fp16:
            # dynamic-scale bookkeeping (GradScaler semantics): backoff on
            # overflow, grow after growth_interval consecutive finite steps
            cfg = self.grad_scaler_config
            if bool(finite):
                self._lomo_scale_growth += 1
                if self._lomo_scale_growth >= cfg.growth_interval:
                    self._lomo_scale = scale * cfg.growth_factor
                    self._lomo_scale_growth = 0
            else:
                self._lomo_scale = max(1.0, scale * cfg.backoff_factor)
                self._lomo_scale_growth = 0
        return loss, new_params

    # ---------------------------------------------------------- persistence --
    def register_for_checkpointing(self, *objects):
        """Track custom stateful objects for save/load_state (reference ``:4019``).
        Objects must expose ``state_dict()``/``load_state_dict()``."""
        for obj in objects:
            if not (hasattr(obj, "state_dict") and hasattr(obj, "load_state_dict")):
                raise ValueError(f"{obj} lacks state_dict/load_state_dict")
            self._custom_objects.append(obj)

    def register_save_state_pre_hook(self, hook: Callable) -> "RemovableHandle":
        """Register ``hook(models, output_dir)`` to run at the top of
        :meth:`save_state` (reference ``register_save_state_pre_hook:3497``;
        its torch ``weights`` list collapses into the models/params list here).
        Returns a handle whose ``remove()`` unregisters."""
        handle = RemovableHandle(self._save_state_pre_hooks)
        self._save_state_pre_hooks[handle.id] = hook
        return handle

    def register_load_state_pre_hook(self, hook: Callable) -> "RemovableHandle":
        """Register ``hook(models, input_dir)`` to run at the top of
        :meth:`load_state` (reference ``register_load_state_pre_hook:3664``)."""
        handle = RemovableHandle(self._load_state_pre_hooks)
        self._load_state_pre_hooks[handle.id] = hook
        return handle

    def _ensure_checkpoint_manager(self):
        if self._checkpoint_manager is None:
            from .checkpoint_async import CheckpointManager

            self._checkpoint_manager = CheckpointManager(
                max_in_flight=self.checkpoint_config.max_in_flight
            )
        return self._checkpoint_manager

    def save_state(
        self,
        output_dir: Optional[str] = None,
        params=None,
        opt_state=None,
        blocking: Optional[bool] = None,
        **kwargs,
    ) -> str:
        """Save a resumable checkpoint (reference ``save_state:3529``).

        ``blocking=False`` (or ``CheckpointConfig(async_save=True)``) returns
        after the device→host **snapshot** — milliseconds — and a background
        writer serializes, fsyncs and atomically commits; the returned
        directory is guaranteed on disk only after :meth:`wait_for_checkpoint`
        (or the next back-pressured save / ``end_training``). Either way the
        save is crash-consistent: a kill at any point leaves the previous
        committed checkpoint loadable (see docs/checkpointing.md).
        """
        from .checkpointing import save_accelerator_state, snapshot_accelerator_state

        if blocking is None:
            blocking = not self.checkpoint_config.async_save
        kwargs.setdefault("save_on_each_node", self.checkpoint_config.save_on_each_node)
        if blocking:
            if self._checkpoint_manager is not None:
                # earlier async saves commit first: saves land in call order
                self._checkpoint_manager.drain()
            # pre-hooks fire inside save_accelerator_state, AFTER automatic
            # checkpoint naming resolves the real directory
            return save_accelerator_state(
                self, output_dir=output_dir, params=params, opt_state=opt_state, **kwargs
            )
        manager = self._ensure_checkpoint_manager()
        manager.check_error()  # surface a parked writer failure before blocking
        manager.reserve_slot()  # back-pressure: bounds extra host copies
        try:
            snap = snapshot_accelerator_state(
                self,
                output_dir=output_dir,
                params=params,
                opt_state=opt_state,
                blocking=False,
                active_staging=manager.active_staging(),
                **kwargs,
            )
            # submit inside the try: it re-raises parked writer errors BEFORE
            # enqueuing, and a leaked slot here would deadlock every later save
            return manager.submit(snap)
        except BaseException:
            manager.release_slot()
            raise

    def wait_for_checkpoint(self, timeout: Optional[float] = None) -> None:
        """Block until every in-flight async ``save_state`` has committed;
        re-raises the first background writer error. No-op when nothing is
        in flight."""
        if self._checkpoint_manager is not None:
            self._checkpoint_manager.drain(timeout=timeout)

    @property
    def resume_from_checkpoint(self) -> Optional[str]:
        """The checkpoint the launcher asked this incarnation to resume from:
        ``ACCELERATE_RESUME_FROM_CHECKPOINT`` — ``"latest"`` (set by the
        elastic supervisor and ``launch --max_restarts``) or an explicit
        directory. None when no resume was requested. Training scripts gate
        their ``load_state`` call on this::

            if accelerator.resume_from_checkpoint:
                params, opt_state = accelerator.load_state(
                    accelerator.resume_from_checkpoint, params=params,
                    opt_state=opt_state)
        """
        raw = os.environ.get("ACCELERATE_RESUME_FROM_CHECKPOINT", "").strip()
        return raw or None

    @property
    def restart_generation(self) -> int:
        """How many times the elastic supervisor has restarted this cohort
        (0 = first incarnation; see ``resilience/membership.py``)."""
        from .resilience.membership import current_generation

        return current_generation()

    def load_state(self, input_dir: Optional[str] = None, params=None, opt_state=None, **kwargs):
        """Restore a checkpoint (reference ``load_state:3617``).

        ``input_dir=None`` or ``"latest"`` picks the newest *committed*
        ``checkpoint_<i>`` under the project dir. Extra kwargs flow to
        ``checkpointing.load_accelerator_state`` — notably ``elastic=True``
        for a cross-topology resume (defaulted from
        ``ACCELERATE_ELASTIC_RESUME`` under a supervised elastic relaunch).
        """
        from .checkpointing import load_accelerator_state

        if input_dir == "latest":
            input_dir = None
        # an in-flight async save may be writing the very dir being loaded
        self.wait_for_checkpoint()
        return load_accelerator_state(
            self, input_dir=input_dir, params=params, opt_state=opt_state, **kwargs
        )

    def save_model(self, params, save_directory: str, max_shard_size: str = "10GB", safe_serialization: bool = True):
        from .checkpointing import save_model

        return save_model(params, save_directory, max_shard_size=max_shard_size, safe_serialization=safe_serialization)

    def get_state_dict(self, params, unwrap: bool = True):
        """Full host-side state dict: gather shards and convert to numpy
        (reference ``get_state_dict:3947`` — the ZeRO-3/FSDP gather collapses to a
        reshard-to-replicated)."""
        import jax

        gathered = ops.gather(params)
        return jax.tree_util.tree_map(np.asarray, gathered)

    def unwrap_model(self, model, keep_fp32_wrapper: bool = True):
        """Identity — params are never wrapped (reference ``unwrap_model:2876``)."""
        return model

    def skip_first_batches(self, dataloader, num_batches: int = 0):
        return skip_first_batches(dataloader, num_batches)

    def free_memory(self, *objects):
        """Release references + device buffers (reference ``free_memory:3847``)."""
        import gc
        import jax

        self._models.clear()
        self._optimizers.clear()
        self._schedulers.clear()
        self._dataloaders.clear()
        self._custom_objects.clear()
        gc.collect()
        try:
            jax.clear_caches()
        except Exception:
            pass
        return objects

    # -------------------------------------------------------------- contexts --
    @contextlib.contextmanager
    def autocast(self, autocast_handler=None):
        """Precision-policy override context (reference ``autocast:4123``).

        Precision here is a compile-time dtype policy, not a tape mode — so the
        context governs train steps *built* inside it: with
        ``AutocastKwargs(enabled=False)`` (passed here or via
        ``kwargs_handlers``), :meth:`prepare_train_step` calls made inside the
        context compile full-precision compute. Steps already compiled are
        unaffected (their policy is baked into the executable).
        """
        handler = autocast_handler or self.autocast_handler
        prev = self._autocast_enabled
        if handler is not None:
            self._autocast_enabled = bool(handler.enabled)
        try:
            yield
        finally:
            self._autocast_enabled = prev

    @contextlib.contextmanager
    def profile(self, profile_config: Optional[ProfileConfig] = None, trace_dir: Optional[str] = None):
        """``jax.profiler`` trace context (reference ``profile:4148`` exporting
        Chrome traces). Writes a TensorBoard/Perfetto trace to ``trace_dir`` or
        ``<project_dir>/profile``.

        Whole-context mode (default): the entire block is traced. Step-windowed
        mode (``ProfileConfig(active>0)``, mirroring the reference's
        ``ProfileKwargs`` schedule ``utils/dataclasses.py:484-599``): the
        yielded :class:`StepProfiler` traces only the active window of each
        ``skip_first → [wait → warmup → active] x repeat`` cycle — call
        ``prof.step()`` once per training step. Traces land in per-rank,
        per-cycle dirs ``<out>/rank<r>/cycle<c>``."""
        import jax

        cfg = profile_config or self.profile_handler or ProfileConfig()
        out = trace_dir or cfg.output_trace_dir or os.path.join(self.project_dir or ".", "profile")
        if cfg.schedule_enabled:
            prof = StepProfiler(cfg, os.path.join(out, f"rank{self.process_index}"))
            try:
                yield prof
            finally:
                prof.close()
            self.wait_for_everyone()
            return
        if self.is_main_process:
            os.makedirs(out, exist_ok=True)
        jax.profiler.start_trace(out, create_perfetto_link=cfg.create_perfetto_link)
        try:
            yield None
        finally:
            jax.profiler.stop_trace()
        self.wait_for_everyone()

    # --------------------------------------------------------------- logging --
    def init_trackers(self, project_name: str, config: Optional[dict] = None, init_kwargs: Optional[dict] = None):
        from .tracking import filter_trackers

        self.trackers = filter_trackers(
            self.log_with, project_name, self.project_configuration.logging_dir, config, init_kwargs or {}
        )

    def get_tracker(self, name: str, unwrap: bool = False):
        for tracker in self.trackers:
            if getattr(tracker, "name", None) == name:
                return tracker.tracker if unwrap else tracker
        raise ValueError(f"no tracker named {name!r} (have {[t.name for t in self.trackers]})")

    def log(self, values: dict, step: Optional[int] = None, log_kwargs: Optional[dict] = None):
        if self.is_main_process:
            for tracker in self.trackers:
                tracker.log(values, step=step, **((log_kwargs or {}).get(tracker.name, {})))

    def log_images(self, values: dict, step: Optional[int] = None, log_kwargs: Optional[dict] = None):
        """Log images on every tracker that supports them (reference
        ``tracking.py:272/364`` — trackers without image support warn+skip)."""
        if self.is_main_process:
            for tracker in self.trackers:
                tracker.log_images(values, step=step, **((log_kwargs or {}).get(tracker.name, {})))

    def log_table(
        self,
        table_name: str,
        columns: Optional[list] = None,
        data: Optional[list] = None,
        dataframe=None,
        step: Optional[int] = None,
        log_kwargs: Optional[dict] = None,
    ):
        """Log a table (columns+data or dataframe) on every tracker that
        supports tables (reference ``tracking.py:383``)."""
        if self.is_main_process:
            for tracker in self.trackers:
                tracker.log_table(
                    table_name, columns=columns, data=data, dataframe=dataframe,
                    step=step, **((log_kwargs or {}).get(tracker.name, {})),
                )

    def log_telemetry_summary(self, step: Optional[int] = None) -> dict:
        """Mirror the telemetry report aggregates (step percentiles, recompile
        totals, memory peaks, comms bytes) into the active trackers under a
        ``telemetry/`` prefix. No-op (empty dict) when telemetry is disabled."""
        from .telemetry import events as _tel
        from .telemetry.tracker_bridge import mirror_to_trackers

        if not _tel.is_enabled() or not self.is_main_process:
            return {}
        return mirror_to_trackers(self.trackers, step=step)

    def end_training(self):
        from .telemetry import events as _tel
        from .telemetry import watchdog as _watchdog

        # drain the async checkpoint writer BEFORE forensics teardown: a save
        # still committing must finish (and may beat the watchdog doing so),
        # and its errors must surface here rather than vanish with the daemon
        if self._checkpoint_manager is not None:
            self._checkpoint_manager.shutdown(drain=True)
            self._checkpoint_manager = None
        # a trace window open mid-run must be stopped (and parsed) before the
        # process exits, or the profiler session leaks into the next run
        if self._trace_windows is not None:
            self._trace_windows.close()
        if _tel.is_enabled() and self.trackers:
            self.log_telemetry_summary()
        # final goodput snapshot: whatever the live meter accumulated since
        # its last throttled emit must land in the event stream before exit
        if _tel.is_enabled():
            from .telemetry import goodput as _goodput

            _goodput.emit_now(final=True)
        # forensics teardown: training no longer beats, so the train-step
        # source must stop being watched (a finished run is not a stall) and a
        # watchdog we started is stopped with it
        _watchdog.unregister("train_step")
        if self._watchdog_started:
            _watchdog.stop()
            self._watchdog_started = False
        if self.is_main_process:
            for tracker in self.trackers:
                tracker.finish()
        self.wait_for_everyone()

    def __del__(self):
        # last-resort drain barrier: an interpreter exiting with an async save
        # still in flight must not tear the write mid-commit (daemon threads
        # die abruptly). end_training is the explicit spelling; this covers
        # scripts that never call it. Defensive: __del__ may run half-torn.
        try:
            manager = getattr(self, "_checkpoint_manager", None)
            if manager is not None:
                manager.shutdown(drain=True)
        except Exception:
            pass
