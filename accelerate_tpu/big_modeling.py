"""Big-model inference: zero-RAM init, layer→device dispatch, paged forward.

TPU-native counterpart of the reference's ``big_modeling.py``
(``/root/reference/src/accelerate/big_modeling.py`` — ``init_empty_weights:61``,
``cpu_offload:173``, ``disk_offload:263``, ``dispatch_model:309``,
``load_checkpoint_and_dispatch:512``).

Architecture shift: the reference mutates an ``nn.Module`` in place, attaching
``AlignDevicesHook``s that page weights per sub-forward. Here a model is
``(stage_fns, params)``; :func:`dispatch_params` produces a
:class:`DispatchedParams` store that materializes each stage's params on the
compute device on demand — HBM-resident stages are free, host/disk stages are
``device_put`` streams with one-stage-ahead prefetch
(:class:`~accelerate_tpu.hooks.PrefetchingLoader` semantics), which overlaps
PCIe/DMA with MXU compute instead of serializing them.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Mapping, Optional, Sequence, Union

import numpy as np

from .hooks import AlignDevicesHook, _default_device
from .utils.modeling import (
    abstract_params,
    clean_device_map,
    compute_module_sizes,
    find_tied_parameters,
    get_balanced_memory,
    get_max_memory,
    infer_auto_device_map,
    load_checkpoint_in_params,
    lookup_device,
    named_parameters,
    unflatten_parameters,
)
from .utils.offload import OffloadedWeightsLoader, offload_state_dict, save_offload_index, offload_weight

# Re-export: `init_empty_weights` is the reference's name for zero-RAM init; the
# native primitive is jax.eval_shape (reference big_modeling.py:61 monkeypatches
# nn.Module.register_parameter to the meta device instead).
init_empty_weights = abstract_params
init_on_device = abstract_params


class DispatchedParams(Mapping):
    """Per-stage param store honouring a device map (the functional twin of a
    hooked ``nn.Module`` after reference ``dispatch_model:309``).

    ``dp[stage]`` returns that stage's params ready for compute: already-placed
    HBM stages return their resident arrays, ``"cpu"``/``"disk"`` stages are
    paged in via ``device_put`` (and released after :meth:`release` /
    automatically when paged iteration advances).
    """

    def __init__(
        self,
        params: Mapping[str, Any],
        device_map: Mapping[str, Union[int, str]],
        offload_folder: Optional[str] = None,
        execution_device=None,
        offload_buffers: bool = False,
    ):
        import jax

        self.device_map = dict(device_map)
        self.execution_device = execution_device or _default_device()
        self.offload_folder = offload_folder
        self._jax = jax
        accel = [d for d in jax.local_devices() if d.platform != "cpu"] or jax.local_devices()
        self._accel = accel

        flat = named_parameters(params)
        self._resident: dict[str, Any] = {}  # HBM stages
        self._host: dict[str, Any] = {}  # cpu-offloaded (numpy / host commit)
        disk_state: dict[str, Any] = {}
        for path, leaf in flat.items():
            target = lookup_device(self.device_map, path)
            if target == "disk":
                disk_state[path] = leaf
            elif target == "cpu":
                self._host[path] = np.asarray(leaf) if leaf is not None else None
            else:
                if int(target) >= len(accel):
                    raise ValueError(
                        f"device_map places {path!r} on device {target} but only "
                        f"{len(accel)} local devices exist"
                    )
                dev = accel[int(target)]
                self._resident[path] = jax.device_put(leaf, dev) if leaf is not None else None
        if disk_state:
            if offload_folder is None:
                raise ValueError("device_map contains 'disk' but no offload_folder given")
            to_spill = {k: v for k, v in disk_state.items() if v is not None}
            if to_spill:
                offload_state_dict(offload_folder, to_spill)
            self._disk = OffloadedWeightsLoader(save_folder=offload_folder)
        else:
            self._disk = None
        self._stage_names = sorted(
            {path.split("/")[0] for path in flat}
        )
        self._paths_by_stage: dict[str, list[str]] = {}
        for path in flat:
            self._paths_by_stage.setdefault(path.split("/")[0], []).append(path)
        self._paged_cache: dict[str, Any] = {}
        # id(host array) → device array, so tied weights transfer once
        self._tied_map: dict[int, Any] = {}

    # ----------------------------------------------------------- mapping API --
    def __iter__(self):
        return iter(self._stage_names)

    def __len__(self):
        return len(self._stage_names)

    def __getitem__(self, stage: str):
        paths = self._paths_by_stage.get(stage)
        if paths is None:
            raise KeyError(stage)
        flat = {}
        for path in paths:
            flat[path[len(stage) + 1 :] if path != stage else stage] = self._leaf_on_device(path)
        if len(flat) == 1 and stage in flat:
            return flat[stage]
        return unflatten_parameters(flat)

    def _leaf_on_device(self, path: str):
        if path in self._resident:
            return self._resident[path]
        if path in self._paged_cache:
            return self._paged_cache[path]
        host = self._host.get(path)
        if host is None and self._disk is not None:
            host = self._disk[path]
        if host is None:
            return None
        # Tied-weight dedup: keyed by id(host), holding the host array in the
        # entry so its id stays valid for the cache's lifetime (a freed array's
        # id can be recycled by a later unrelated load).
        key = id(host)
        entry = self._tied_map.get(key)
        if entry is not None and entry[0] is host:
            placed = entry[1]
        else:
            placed = self._jax.device_put(host, self.execution_device)
            self._tied_map[key] = (host, placed)
        self._paged_cache[path] = placed
        return placed

    def prefetch(self, stage: str) -> None:
        """Start async H2D for a stage's offloaded params (device_put returns
        before the copy completes — call for stage i+1 while i computes)."""
        for path in self._paths_by_stage.get(stage, []):
            self._leaf_on_device(path)

    def release(self, stage: Optional[str] = None) -> None:
        """Drop paged-in copies (reference ``post_forward`` re-offload,
        ``hooks.py:377-407``)."""
        if stage is None:
            self._paged_cache.clear()
            self._tied_map.clear()
            return
        for path in self._paths_by_stage.get(stage, []):
            self._paged_cache.pop(path, None)
        self._tied_map.clear()

    def materialize(self) -> dict:
        """Full tree with every leaf on the execution device (small models /
        debugging)."""
        out = {}
        for stage in self._stage_names:
            out[stage] = self[stage]
        self.release()
        return out

    # ------------------------------------------------------------- execution --
    def run(self, stages: Sequence[tuple[str, Callable]], x, prefetch: bool = True):
        """Run ``x`` through ``[(stage_name, fn(params, x))…]`` with paged
        params and one-stage-ahead prefetch (the hot loop of reference §3.4)."""
        names = [n for n, _ in stages]
        for i, (name, fn) in enumerate(stages):
            if prefetch and i + 1 < len(stages):
                self.prefetch(names[i + 1])
            params = self[name]
            x = fn(params, x)
            self.release(name)
        return x


def attach_align_device_hook(params, execution_device=None, weights_map=None) -> AlignDevicesHook:
    """Build the paging hook for a params subtree (reference
    ``attach_align_device_hook:464``)."""
    return AlignDevicesHook(execution_device=execution_device, weights_map=weights_map)


def dispatch_params(
    params: Mapping[str, Any],
    device_map: Optional[Mapping[str, Union[int, str]]] = None,
    max_memory: Optional[dict] = None,
    no_split_module_patterns: Optional[list[str]] = None,
    offload_folder: Optional[str] = None,
    execution_device=None,
    dtype=None,
) -> DispatchedParams:
    """Place a param tree per a (possibly inferred) device map (reference
    ``dispatch_model:309``; ``device_map="auto"`` ≙ ``infer_auto_device_map``)."""
    if device_map is None or device_map == "auto":
        device_map = infer_auto_device_map(
            params, max_memory=max_memory, no_split_module_patterns=no_split_module_patterns, dtype=dtype
        )
    elif device_map == "balanced":
        balanced = get_balanced_memory(params, max_memory, no_split_module_patterns, dtype)
        device_map = infer_auto_device_map(
            params, max_memory=balanced, no_split_module_patterns=no_split_module_patterns, dtype=dtype
        )
    return DispatchedParams(
        params, device_map, offload_folder=offload_folder, execution_device=execution_device
    )


def cpu_offload(params, execution_device=None) -> DispatchedParams:
    """Everything on host, paged per stage (reference ``cpu_offload:173``)."""
    return DispatchedParams(params, {"": "cpu"}, execution_device=execution_device)


class UserCpuOffloadHook:
    """Manual paging control for one model in a multi-model pipeline (reference
    ``cpu_offload_with_hook:219`` returns this so e.g. a diffusion pipeline can
    keep only the active model in HBM). ``offload()`` commits the tree back to
    host RAM and frees the device buffers."""

    def __init__(self, host_tree, device=None):
        import jax

        self._jax = jax
        self._host = jax.tree_util.tree_map(np.asarray, host_tree)
        self._device = device or _default_device()
        self._on_device = None
        self.prev_hook: Optional["UserCpuOffloadHook"] = None

    @property
    def params(self):
        """The live tree: device-resident after :meth:`load`, host otherwise."""
        return self._on_device if self._on_device is not None else self._host

    def load(self):
        """Page onto the execution device (offloading the previous pipeline
        stage first, mirroring the reference's hook chaining)."""
        if self.prev_hook is not None:
            self.prev_hook.offload()
        if self._on_device is None:
            self._on_device = self._jax.tree_util.tree_map(
                lambda x: self._jax.device_put(x, self._device), self._host
            )
        return self._on_device

    def offload(self):
        """Commit back to host and drop device buffers."""
        if self._on_device is not None:
            self._host = self._jax.tree_util.tree_map(np.asarray, self._on_device)
            for leaf in self._jax.tree_util.tree_leaves(self._on_device):
                if hasattr(leaf, "delete"):
                    leaf.delete()
            self._on_device = None

    def remove(self):
        self.offload()


def cpu_offload_with_hook(
    params, execution_device=None, prev_module_hook: Optional[UserCpuOffloadHook] = None
):
    """Place ``params`` on device now and hand back a hook whose ``offload()``
    pages them off again (reference ``cpu_offload_with_hook:219``). Chaining
    ``prev_module_hook`` makes loading model N offload model N-1 — the pattern
    multi-model inference pipelines use to fit serially in HBM.

    Returns ``(device_params, hook)``.
    """
    hook = UserCpuOffloadHook(params, device=execution_device)
    hook.prev_hook = prev_module_hook
    return hook.load(), hook


def disk_offload(params, offload_dir: str, execution_device=None) -> DispatchedParams:
    """Everything spilled to disk memmaps (reference ``disk_offload:263``)."""
    os.makedirs(offload_dir, exist_ok=True)
    return DispatchedParams(
        params, {"": "disk"}, offload_folder=offload_dir, execution_device=execution_device
    )


def load_checkpoint_and_dispatch(
    abstract_tree,
    checkpoint: str,
    device_map: Optional[Union[str, Mapping[str, Any]]] = "auto",
    max_memory: Optional[dict] = None,
    no_split_module_patterns: Optional[list[str]] = None,
    offload_folder: Optional[str] = None,
    dtype=None,
) -> DispatchedParams:
    """Infer a map over the *abstract* tree, then stream the checkpoint straight
    to the mapped devices (reference ``load_checkpoint_and_dispatch:512`` —
    never materializes the full model in host RAM)."""
    if device_map in ("auto", "balanced", None):
        mem = (
            get_balanced_memory(abstract_tree, max_memory, no_split_module_patterns, dtype)
            if device_map == "balanced"
            else max_memory
        )
        device_map = infer_auto_device_map(
            abstract_tree, max_memory=mem, no_split_module_patterns=no_split_module_patterns, dtype=dtype
        )
    tree, _ = load_checkpoint_in_params(
        abstract_tree, checkpoint, device_map=device_map, offload_folder=offload_folder, dtype=dtype
    )
    # tensors already sit on their devices; DispatchedParams must not re-place
    # them — pass through resident leaves, page host/disk ones
    return DispatchedParams(tree, device_map, offload_folder=offload_folder)


# Reference name: a "model" here is its param tree, so dispatching a model is
# dispatching its params (reference ``dispatch_model:309``).
dispatch_model = dispatch_params


def attach_layerwise_casting_hooks(
    fn,
    storage_dtype,
    compute_dtype,
    stage_name: str = "",
):
    """reference ``attach_layerwise_casting_hooks big_modeling.py:653``: wrap a
    stage fn so its params live in ``storage_dtype`` (fp8/bf16) and upcast to
    ``compute_dtype`` only for the call — layerwise memory savings for
    inference. Returns ``(wrapped_fn, cast_params_fn)``: apply
    ``cast_params_fn`` once to your params to move storage to the narrow
    dtype."""
    from .hooks import LayerwiseCastingHook, add_hook_to_fn

    hook = LayerwiseCastingHook(storage_dtype, compute_dtype)
    return add_hook_to_fn(fn, hook, stage_name), (
        lambda params: hook.init_hook(stage_name, params)
    )
