"""HF-checkpoint → native-pytree weight converters.

The reference serves pretrained torch models directly; our native model
families (stacked-layer pytrees, ``models/{transformer,t5}.py``) need their
weights re-laid-out: torch ``[out, in]`` linears transpose to ``[in, out]``,
and per-layer tensors stack on a leading ``[L, ...]`` axis (the scan layout).
These converters accept an HF ``nn.Module``, a ``state_dict``-like mapping of
tensors/ndarrays, or a ``.safetensors`` path (streamed one tensor at a time —
no full-model torch materialization; the moral twin of the reference's
``load_checkpoint_in_model`` lazy loading, ``utils/modeling.py:1788``).

Architectural requirements (asserted where cheap): Llama expects the HF
``rotate_half`` RoPE convention (matches ``apply_rope``); T5 expects
``feed_forward_proj="relu"`` v1.0 blocks; BERT expects the classic
post-layer-norm encoder (``BertForSequenceClassification``).
"""

from __future__ import annotations

from typing import Mapping, Union

import numpy as np

import jax.numpy as jnp


def _as_numpy_getter(source):
    """Normalize (module | mapping | safetensors path) → (keys, getter, close).
    ``close()`` must be called when conversion is done (releases the
    safetensors file handle; no-op for in-memory sources)."""
    if isinstance(source, str):
        from safetensors import safe_open

        handle = safe_open(source, framework="numpy")
        return (
            list(handle.keys()),
            lambda k: handle.get_tensor(k),
            lambda: handle.__exit__(None, None, None),
        )
    if hasattr(source, "state_dict") and callable(source.state_dict):
        source = source.state_dict()
    if isinstance(source, Mapping):
        def get(k):
            v = source[k]
            if hasattr(v, "detach"):  # torch tensor
                from ..bridge.dlpack import torch_tensor_to_numpy

                return torch_tensor_to_numpy(v)
            return np.asarray(v)

        return list(source.keys()), get, lambda: None
    raise TypeError(f"unsupported weight source: {type(source)!r}")


def _stack_t(get, fmt: str, L: int):
    """Stack L per-layer torch ``[out, in]`` linears → ``[L, in, out]``."""
    return jnp.stack([jnp.asarray(get(fmt.format(i)).T) for i in range(L)])


def _stack_raw(get, fmt: str, L: int):
    """Stack L per-layer tensors unchanged → leading ``[L, ...]`` axis."""
    return jnp.stack([jnp.asarray(get(fmt.format(i))) for i in range(L)])


def _assert_not_dropping_head(keys, get, embedding, head_key: str, what: str):
    """Tied config + checkpoint carrying a DISTINCT head: refuse to silently
    discard the head weights (the reverse direction is handled by folding)."""
    if head_key not in keys:
        return
    head = np.asarray(get(head_key))
    emb = np.asarray(embedding)
    if head.shape == emb.shape and np.array_equal(head, emb):
        return  # materialized tied duplicate — nothing lost
    raise ValueError(
        f"checkpoint has a distinct {head_key} but the target {what} config is "
        "tied (tie embeddings=False to keep the checkpoint's head)"
    )


def llama_params_from_hf(source, config) -> dict:
    """HF ``LlamaForCausalLM`` weights → ``init_llama``-shaped pytree."""
    keys, get, close = _as_numpy_getter(source)
    try:
        return _llama_params(keys, get, config)
    finally:
        close()


def _llama_params(keys, get, config) -> dict:
    prefix = "model." if any(k.startswith("model.") for k in keys) else ""
    L = config.n_layers

    def stack_t(fmt):
        return _stack_t(get, fmt, L)

    def stack_raw(fmt):
        return _stack_raw(get, fmt, L)

    p = prefix
    params = {
        "embed_tokens": {"embedding": jnp.asarray(get(f"{p}embed_tokens.weight"))},
        "layers": {
            "attn_norm": {"scale": stack_raw(p + "layers.{}.input_layernorm.weight")},
            "wq": {"kernel": stack_t(p + "layers.{}.self_attn.q_proj.weight")},
            "wk": {"kernel": stack_t(p + "layers.{}.self_attn.k_proj.weight")},
            "wv": {"kernel": stack_t(p + "layers.{}.self_attn.v_proj.weight")},
            "wo": {"kernel": stack_t(p + "layers.{}.self_attn.o_proj.weight")},
            "mlp_norm": {"scale": stack_raw(p + "layers.{}.post_attention_layernorm.weight")},
            "w1": {"kernel": stack_t(p + "layers.{}.mlp.gate_proj.weight")},
            "w3": {"kernel": stack_t(p + "layers.{}.mlp.up_proj.weight")},
            "w2": {"kernel": stack_t(p + "layers.{}.mlp.down_proj.weight")},
        },
        "final_norm": {"scale": jnp.asarray(get(f"{p}norm.weight"))},
    }
    if not config.tie_embeddings:
        head_key = "lm_head.weight"
        if head_key in keys:
            params["lm_head"] = {"kernel": jnp.asarray(get(head_key).T)}
        else:  # HF tied checkpoint loaded into an untied config
            params["lm_head"] = {"kernel": params["embed_tokens"]["embedding"].T}
    else:
        _assert_not_dropping_head(
            keys, get, params["embed_tokens"]["embedding"], "lm_head.weight", "Llama"
        )
    return params


def bert_params_from_hf(source, config) -> dict:
    """HF ``BertForSequenceClassification`` weights → ``init_bert`` pytree."""
    keys, get, close = _as_numpy_getter(source)
    try:
        return _bert_params(keys, get, config)
    finally:
        close()


def _bert_params(keys, get, config) -> dict:
    prefix = "bert." if any(k.startswith("bert.") for k in keys) else ""
    L = config.n_layers
    p = prefix

    def stack_t(fmt):
        return _stack_t(get, fmt, L)

    def stack_raw(fmt):
        return _stack_raw(get, fmt, L)

    enc = p + "encoder.layer.{}."
    return {
        "embeddings": {
            "word": {"embedding": jnp.asarray(get(f"{p}embeddings.word_embeddings.weight"))},
            "position": {"embedding": jnp.asarray(get(f"{p}embeddings.position_embeddings.weight"))},
            "token_type": {"embedding": jnp.asarray(get(f"{p}embeddings.token_type_embeddings.weight"))},
            "norm": {"scale": jnp.asarray(get(f"{p}embeddings.LayerNorm.weight")),
                     "bias": jnp.asarray(get(f"{p}embeddings.LayerNorm.bias"))},
        },
        "layers": {
            "wq": {"kernel": stack_t(enc + "attention.self.query.weight"),
                   "bias": stack_raw(enc + "attention.self.query.bias")},
            "wk": {"kernel": stack_t(enc + "attention.self.key.weight"),
                   "bias": stack_raw(enc + "attention.self.key.bias")},
            "wv": {"kernel": stack_t(enc + "attention.self.value.weight"),
                   "bias": stack_raw(enc + "attention.self.value.bias")},
            "wo": {"kernel": stack_t(enc + "attention.output.dense.weight"),
                   "bias": stack_raw(enc + "attention.output.dense.bias")},
            "attn_norm": {"scale": stack_raw(enc + "attention.output.LayerNorm.weight"),
                          "bias": stack_raw(enc + "attention.output.LayerNorm.bias")},
            "fc1": {"kernel": stack_t(enc + "intermediate.dense.weight"),
                    "bias": stack_raw(enc + "intermediate.dense.bias")},
            "fc2": {"kernel": stack_t(enc + "output.dense.weight"),
                    "bias": stack_raw(enc + "output.dense.bias")},
            "mlp_norm": {"scale": stack_raw(enc + "output.LayerNorm.weight"),
                         "bias": stack_raw(enc + "output.LayerNorm.bias")},
        },
        "pooler": {"kernel": jnp.asarray(get(f"{p}pooler.dense.weight").T),
                   "bias": jnp.asarray(get(f"{p}pooler.dense.bias"))},
        "classifier": {"kernel": jnp.asarray(get("classifier.weight").T),
                       "bias": jnp.asarray(get("classifier.bias"))},
    }


def t5_params_from_hf(source, config) -> dict:
    """HF ``T5ForConditionalGeneration`` weights → ``init_t5`` pytree."""
    keys, get, close = _as_numpy_getter(source)
    try:
        return _t5_params(keys, get, config)
    finally:
        close()


def _t5_params(keys, get, config) -> dict:
    L = config.n_layers

    def stack_t(fmt):
        return _stack_t(get, fmt, L)

    def stack_raw(fmt):
        return _stack_raw(get, fmt, L)

    def attn_block(stem, hf_attn):
        return {
            "wq": {"kernel": stack_t(f"{stem}.{hf_attn}.q.weight")},
            "wk": {"kernel": stack_t(f"{stem}.{hf_attn}.k.weight")},
            "wv": {"kernel": stack_t(f"{stem}.{hf_attn}.v.weight")},
            "wo": {"kernel": stack_t(f"{stem}.{hf_attn}.o.weight")},
        }

    params = {
        "shared_embedding": {"embedding": jnp.asarray(get("shared.weight"))},
        "encoder": {
            "rel_pos": {"embedding": jnp.asarray(get(
                "encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"
            ))},
            "layers": {
                "attn_norm": {"scale": stack_raw("encoder.block.{}.layer.0.layer_norm.weight")},
                "attn": attn_block("encoder.block.{}.layer.0", "SelfAttention"),
                "mlp_norm": {"scale": stack_raw("encoder.block.{}.layer.1.layer_norm.weight")},
                "wi": {"kernel": stack_t("encoder.block.{}.layer.1.DenseReluDense.wi.weight")},
                "wo": {"kernel": stack_t("encoder.block.{}.layer.1.DenseReluDense.wo.weight")},
            },
            "final_norm": {"scale": jnp.asarray(get("encoder.final_layer_norm.weight"))},
        },
        "decoder": {
            "rel_pos": {"embedding": jnp.asarray(get(
                "decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"
            ))},
            "layers": {
                "self_norm": {"scale": stack_raw("decoder.block.{}.layer.0.layer_norm.weight")},
                "self_attn": attn_block("decoder.block.{}.layer.0", "SelfAttention"),
                "cross_norm": {"scale": stack_raw("decoder.block.{}.layer.1.layer_norm.weight")},
                "cross_attn": attn_block("decoder.block.{}.layer.1", "EncDecAttention"),
                "mlp_norm": {"scale": stack_raw("decoder.block.{}.layer.2.layer_norm.weight")},
                "wi": {"kernel": stack_t("decoder.block.{}.layer.2.DenseReluDense.wi.weight")},
                "wo": {"kernel": stack_t("decoder.block.{}.layer.2.DenseReluDense.wo.weight")},
            },
            "final_norm": {"scale": jnp.asarray(get("decoder.final_layer_norm.weight"))},
        },
    }
    if not config.tie_word_embeddings:
        # tied HF checkpoints into an untied config: HF's tied forward rescales
        # hidden states by d^-0.5 before the shared projection; our untied
        # forward does not, so the rescale folds into the kernel. A tied
        # checkpoint shows up either as a MISSING lm_head tensor (safetensors
        # drops shared storage) or as a byte-identical duplicate of shared
        # (state_dict materializes both names).
        shared = np.asarray(params["shared_embedding"]["embedding"])
        if "lm_head.weight" in keys:
            head = np.asarray(get("lm_head.weight"))
            kernel = jnp.asarray(head.T)
            if head.shape == shared.shape and np.array_equal(head, shared):
                kernel = kernel * (config.dim ** -0.5)
            params["lm_head"] = {"kernel": kernel}
        else:
            params["lm_head"] = {"kernel": jnp.asarray(shared.T) * (config.dim ** -0.5)}
    else:
        _assert_not_dropping_head(
            keys, get, params["shared_embedding"]["embedding"], "lm_head.weight", "T5"
        )
    return params
