"""T5-style encoder-decoder family, pure-JAX, TPU-first.

Widens the model-family acceptance surface to seq2seq: the reference's
big-model-inference table includes T0pp-11B (a T5 derivative,
``/root/reference/benchmarks/big_model_inference/README.md:27-37``) and its
``transformers`` integration serves encoder-decoder models throughout.

Same design rules as ``models/transformer.py``: params are nested dicts,
per-layer tensors are STACKED on a leading axis and iterated with ``lax.scan``
(O(1)-in-depth compile, one FSDP spec per stack), attention routes through
``ops.attention``. T5 specifics kept TPU-friendly:

- relative-position bias: T5 shares one bucketed embedding table (held by
  layer 0 in the torch layout); here it is a single table OUTSIDE the layer
  stack, and the [H, Sq, Sk] bias is computed ONCE per forward and closed over
  by the scanned layer body — no per-layer gather, no ragged shapes.
- T5LayerNorm ≡ RMSNorm (no mean subtraction, no bias) — ``rms_norm`` reused.
- encoder-decoder attention: the decoder's cross-attention keys/values are
  computed from the encoder output once per forward (and once per GENERATION,
  see ``t5_greedy_generate`` — the cross KV is position-independent so the
  decode loop only grows the self-attention cache).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import _dense_init, rms_norm


@dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    dim: int = 512
    n_layers: int = 6  # per stack (encoder and decoder)
    n_heads: int = 8
    ffn_dim: int = 2048
    head_dim: int = 64
    rel_pos_buckets: int = 32
    rel_pos_max_distance: int = 128
    norm_eps: float = 1e-6
    # T5 v1.0 ties lm_head to the shared embedding with a d^-0.5 rescale of
    # the final hidden states (HF `tie_word_embeddings` semantics); False
    # gives a v1.1-style separate head
    tie_word_embeddings: bool = True
    unroll_layers: bool = True

    @classmethod
    def small(cls) -> "T5Config":
        return cls()

    @classmethod
    def tiny(cls) -> "T5Config":
        return cls(vocab_size=512, dim=64, n_layers=2, n_heads=4, ffn_dim=128,
                   head_dim=16, rel_pos_buckets=8, rel_pos_max_distance=32)


def _relative_position_bucket(rel_pos, bidirectional: bool, num_buckets: int,
                              max_distance: int):
    """T5's log-bucketed relative positions (torch reference semantics)."""
    ret = 0
    n = -rel_pos
    if bidirectional:
        num_buckets //= 2
        ret += (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    # max(n,1) guards the log only in the discarded (is_small) branch — the
    # kept branch always has n >= max_exact >= 1, so bucket math is exact
    val_if_large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


def relative_position_bias(table: jax.Array, sq: int, sk: int, *,
                           bidirectional: bool, config: T5Config,
                           q_offset: int = 0) -> jax.Array:
    """[1, H, sq, sk] additive attention bias from the shared bucket table
    ([buckets, H]). ``q_offset`` positions the query block for cached decode."""
    ctx = jnp.arange(sq)[:, None] + q_offset
    mem = jnp.arange(sk)[None, :]
    buckets = _relative_position_bucket(
        mem - ctx, bidirectional, config.rel_pos_buckets, config.rel_pos_max_distance
    )
    return jnp.transpose(table[buckets], (2, 0, 1))[None]  # [1, H, sq, sk]


def init_t5(config: T5Config, key) -> dict:
    keys = jax.random.split(key, 16)
    L, D, F = config.n_layers, config.dim, config.ffn_dim
    H = config.n_heads * config.head_dim

    def stack(k, a, b):
        ks = jax.random.split(k, L)
        return jnp.stack([_dense_init(ks[i], a, b, scale=(a ** -0.5)) for i in range(L)])

    def block(k):
        ks = jax.random.split(k, 4)
        return {
            "wq": {"kernel": stack(ks[0], D, H)},
            "wk": {"kernel": stack(ks[1], D, H)},
            "wv": {"kernel": stack(ks[2], D, H)},
            "wo": {"kernel": stack(ks[3], H, D)},
        }

    return {
        "shared_embedding": {"embedding": _dense_init(keys[0], config.vocab_size, D, 1.0)},
        "encoder": {
            "rel_pos": {"embedding": _dense_init(keys[1], config.rel_pos_buckets,
                                                 config.n_heads, 1.0)},
            "layers": {
                "attn_norm": {"scale": jnp.ones((L, D))},
                "attn": block(keys[2]),
                "mlp_norm": {"scale": jnp.ones((L, D))},
                "wi": {"kernel": stack(keys[3], D, F)},
                "wo": {"kernel": stack(keys[4], F, D)},
            },
            "final_norm": {"scale": jnp.ones(D)},
        },
        "decoder": {
            "rel_pos": {"embedding": _dense_init(keys[5], config.rel_pos_buckets,
                                                 config.n_heads, 1.0)},
            "layers": {
                "self_norm": {"scale": jnp.ones((L, D))},
                "self_attn": block(keys[6]),
                "cross_norm": {"scale": jnp.ones((L, D))},
                "cross_attn": block(keys[7]),
                "mlp_norm": {"scale": jnp.ones((L, D))},
                "wi": {"kernel": stack(keys[8], D, F)},
                "wo": {"kernel": stack(keys[9], F, D)},
            },
            "final_norm": {"scale": jnp.ones(D)},
        },
        **(
            {}
            if config.tie_word_embeddings
            else {"lm_head": {"kernel": _dense_init(keys[10], D, config.vocab_size, D ** -0.5)}}
        ),
    }


def _heads(x, B, S, config):
    return x.reshape(B, S, config.n_heads, config.head_dim)


def _attn(q, k, v, bias, mask):
    """Bias-additive attention (T5 has no 1/sqrt(d) scaling — folded into init).
    ``bias`` [1,H,Sq,Sk]; ``mask`` [B,1,1,Sk] boolean keep-mask or None."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    logits = logits + bias.astype(jnp.float32)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e9)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def t5_encode(params, input_ids, config: T5Config, enc_mask=None) -> jax.Array:
    """Encoder stack → [B, S, D] hidden states."""
    B, S = input_ids.shape
    enc = params["encoder"]
    h = params["shared_embedding"]["embedding"][input_ids]
    bias = relative_position_bias(enc["rel_pos"]["embedding"], S, S,
                                  bidirectional=True, config=config)
    keep = None if enc_mask is None else (enc_mask[:, None, None, :] > 0)

    def layer(h, lp):
        x = rms_norm(h, lp["attn_norm"]["scale"], config.norm_eps)
        a = lp["attn"]
        q = _heads(x @ a["wq"]["kernel"], B, S, config)
        k = _heads(x @ a["wk"]["kernel"], B, S, config)
        v = _heads(x @ a["wv"]["kernel"], B, S, config)
        h = h + _attn(q, k, v, bias, keep).reshape(B, S, -1) @ a["wo"]["kernel"]
        x = rms_norm(h, lp["mlp_norm"]["scale"], config.norm_eps)
        h = h + jax.nn.relu(x @ lp["wi"]["kernel"]) @ lp["wo"]["kernel"]
        return h, None

    h, _ = jax.lax.scan(layer, h, enc["layers"], unroll=config.unroll_layers)
    return rms_norm(h, enc["final_norm"]["scale"], config.norm_eps)


def t5_decode(params, decoder_ids, enc_out, config: T5Config,
              enc_mask=None) -> jax.Array:
    """Decoder stack over full target sequence → logits [B, St, vocab]."""
    B, St = decoder_ids.shape
    Sk = enc_out.shape[1]
    dec = params["decoder"]
    h = params["shared_embedding"]["embedding"][decoder_ids]
    self_bias = relative_position_bias(dec["rel_pos"]["embedding"], St, St,
                                       bidirectional=False, config=config)
    causal = jnp.tril(jnp.ones((St, St), bool))[None, None]
    self_keep = causal
    cross_keep = None if enc_mask is None else (enc_mask[:, None, None, :] > 0)
    zero_bias = jnp.zeros((1, config.n_heads, St, Sk), jnp.float32)

    def layer(h, lp):
        x = rms_norm(h, lp["self_norm"]["scale"], config.norm_eps)
        a = lp["self_attn"]
        q = _heads(x @ a["wq"]["kernel"], B, St, config)
        k = _heads(x @ a["wk"]["kernel"], B, St, config)
        v = _heads(x @ a["wv"]["kernel"], B, St, config)
        h = h + _attn(q, k, v, self_bias, self_keep).reshape(B, St, -1) @ a["wo"]["kernel"]
        x = rms_norm(h, lp["cross_norm"]["scale"], config.norm_eps)
        c = lp["cross_attn"]
        q = _heads(x @ c["wq"]["kernel"], B, St, config)
        k = _heads(enc_out @ c["wk"]["kernel"], B, Sk, config)
        v = _heads(enc_out @ c["wv"]["kernel"], B, Sk, config)
        h = h + _attn(q, k, v, zero_bias, cross_keep).reshape(B, St, -1) @ c["wo"]["kernel"]
        x = rms_norm(h, lp["mlp_norm"]["scale"], config.norm_eps)
        h = h + jax.nn.relu(x @ lp["wi"]["kernel"]) @ lp["wo"]["kernel"]
        return h, None

    h, _ = jax.lax.scan(layer, h, dec["layers"], unroll=config.unroll_layers)
    h = rms_norm(h, dec["final_norm"]["scale"], config.norm_eps)
    if config.tie_word_embeddings:
        # HF tie_word_embeddings: rescale hidden by d^-0.5, project on the
        # shared embedding
        return (h * (config.dim ** -0.5)) @ params["shared_embedding"]["embedding"].T
    return h @ params["lm_head"]["kernel"]


def t5_forward(params, batch: dict, config: T5Config) -> jax.Array:
    """batch: input_ids [B,Se], decoder_input_ids [B,St], optional
    attention_mask [B,Se]. Returns logits [B, St, vocab]."""
    enc_mask = batch.get("attention_mask")
    enc_out = t5_encode(params, batch["input_ids"], config, enc_mask)
    return t5_decode(params, batch["decoder_input_ids"], enc_out, config, enc_mask)


def t5_loss(params, batch: dict, config: T5Config) -> jax.Array:
    """Seq2seq cross entropy; ``labels`` [B,St], -100 = ignored (HF parity)."""
    logits = t5_forward(params, batch, config)
    labels = batch["labels"]
    valid = (labels != -100).astype(jnp.float32)
    safe = jnp.where(labels == -100, 0, labels)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1.0)


import functools


@functools.lru_cache(maxsize=32)
def _t5_decode_loop(config: T5Config, max_new_tokens: int,
                    decoder_start_token_id: int, eos_token_id: Optional[int],
                    with_mask: bool):
    """Jitted greedy decode loop, cached on the STATIC values so repeated
    ``t5_greedy_generate`` calls (the normal inference loop) reuse one compiled
    executable per (config, length, token-id, mask-ness) combination instead of
    recompiling a fresh closure each call."""
    import jax

    @jax.jit
    def decode(params, enc_out, enc_mask):
        B = enc_out.shape[0]
        total = 1 + max_new_tokens
        ids0 = jnp.full((B, total), decoder_start_token_id, jnp.int32)
        mask = enc_mask if with_mask else None

        def body(carry, i):
            ids, finished = carry
            logits = t5_decode(params, ids, enc_out, config, mask)
            # gather step i's logits ([B, vocab]) without dynamic shapes
            step_logits = jax.lax.dynamic_slice_in_dim(logits, i, 1, axis=1)[:, 0]
            nxt = jnp.argmax(step_logits, axis=-1).astype(jnp.int32)
            if eos_token_id is not None:
                nxt = jnp.where(finished, eos_token_id, nxt)
                finished = jnp.logical_or(finished, nxt == eos_token_id)
            ids = jax.lax.dynamic_update_slice_in_dim(ids, nxt[:, None], i + 1, axis=1)
            return (ids, finished), None

        (ids, _), _ = jax.lax.scan(
            body, (ids0, jnp.zeros((B,), bool)), jnp.arange(max_new_tokens)
        )
        return ids

    return decode


def t5_greedy_generate(params, input_ids, config: T5Config,
                       max_new_tokens: int = 32,
                       decoder_start_token_id: int = 0,
                       eos_token_id: Optional[int] = None,
                       enc_mask=None) -> jax.Array:
    """Greedy seq2seq decode. The encoder runs ONCE; the decode loop re-runs
    the (short) target prefix per step inside one ``lax.scan`` — full-forward
    semantics with zero host round-trips, exact under causal masking. Returns
    decoder ids [B, 1 + max_new_tokens] (leading start token)."""
    input_ids = jnp.asarray(input_ids)
    enc_out = t5_encode(params, input_ids, config, enc_mask)
    decode = _t5_decode_loop(
        config, max_new_tokens, decoder_start_token_id, eos_token_id,
        enc_mask is not None,
    )
    # a dummy mask arg keeps the jit signature fixed when no mask is used
    mask_arg = enc_mask if enc_mask is not None else jnp.ones(input_ids.shape, jnp.int32)
    return decode(params, enc_out, mask_arg)


def t5_shard_rules():
    """TP rules for the stacked layout (dim 0 = layer stack)."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharding import ShardingRules

    return ShardingRules(
        [
            (r"(attn|self_attn|cross_attn)/(wq|wk|wv)/kernel", P(None, None, "tp")),
            (r"(attn|self_attn|cross_attn)/wo/kernel", P(None, "tp", None)),
            (r"layers/wi/kernel", P(None, None, "tp")),
            (r"layers/wo/kernel", P(None, "tp", None)),
            (r"shared_embedding/embedding", P("tp", None)),
            (r"lm_head/kernel", P(None, "tp")),
            (r"(norm|rel_pos)", P()),
        ]
    )
