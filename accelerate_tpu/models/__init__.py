from .transformer import (
    BertConfig,
    LlamaConfig,
    bert_forward,
    bert_loss,
    bert_shard_rules,
    draft_config,
    draft_params,
    init_bert,
    init_llama,
    llama_forward,
    llama_loss,
    llama_shard_rules,
)
from .resnet import (
    ResNetConfig,
    init_resnet,
    resnet_forward,
    resnet_loss,
    resnet_shard_rules,
)
from .convert import (
    bert_params_from_hf,
    llama_params_from_hf,
    t5_params_from_hf,
)
from .t5 import (
    T5Config,
    init_t5,
    t5_decode,
    t5_encode,
    t5_forward,
    t5_greedy_generate,
    t5_loss,
    t5_shard_rules,
)
