"""ResNet family, pure-JAX, TPU-first (the reference's CV acceptance workload:
``examples/cv_example.py`` / ``complete_cv_example.py`` fine-tune ResNet-50).

Functional pytree params like the transformer family. Normalization is
GroupNorm(32) rather than BatchNorm: identical FLOP/memory shape on the MXU,
but stateless — no running-stats side channel to thread through the functional
train step (torch-interop BatchNorm models still work through the bridge's
``batch_norm2d`` handler). NHWC layout throughout — the TPU-native choice
(XLA's conv tiling prefers channels-last; NCHW is a torch artifact).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class ResNetConfig:
    block_sizes: tuple = (3, 4, 6, 3)  # ResNet-50
    width: int = 64
    num_classes: int = 1000
    groups: int = 32  # GroupNorm groups

    @classmethod
    def resnet50(cls, num_classes: int = 1000) -> "ResNetConfig":
        return cls(num_classes=num_classes)

    @classmethod
    def resnet18_ish(cls, num_classes: int = 10) -> "ResNetConfig":
        # basic-depth stand-in at bottleneck structure (2,2,2,2) for small runs
        return cls(block_sizes=(2, 2, 2, 2), num_classes=num_classes)

    @classmethod
    def tiny(cls, num_classes: int = 4) -> "ResNetConfig":
        return cls(block_sizes=(1, 1), width=16, num_classes=num_classes, groups=4)


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return (jax.random.normal(key, (kh, kw, cin, cout)) * np.sqrt(2.0 / fan_in)).astype(
        jnp.float32
    )


def _norm_params(c):
    return {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}


def init_resnet(config: ResNetConfig, key) -> dict:
    keys = iter(jax.random.split(key, 4 + sum(config.block_sizes) * 4 + len(config.block_sizes)))
    w = config.width
    params: dict = {
        "stem": {"conv": {"kernel": _conv_init(next(keys), 7, 7, 3, w)}, "norm": _norm_params(w)}
    }
    cin = w
    for stage_idx, n_blocks in enumerate(config.block_sizes):
        cmid = w * (2**stage_idx)
        cout = cmid * 4
        stage = []
        for block_idx in range(n_blocks):
            block = {
                "conv1": {"kernel": _conv_init(next(keys), 1, 1, cin, cmid)},
                "norm1": _norm_params(cmid),
                "conv2": {"kernel": _conv_init(next(keys), 3, 3, cmid, cmid)},
                "norm2": _norm_params(cmid),
                "conv3": {"kernel": _conv_init(next(keys), 1, 1, cmid, cout)},
                "norm3": _norm_params(cout),
            }
            if block_idx == 0 and cin != cout:
                block["downsample"] = {
                    "conv": {"kernel": _conv_init(next(keys), 1, 1, cin, cout)},
                    "norm": _norm_params(cout),
                }
            stage.append(block)
            cin = cout
        params[f"stage_{stage_idx}"] = stage
    params["fc"] = {
        "kernel": (jax.random.normal(next(keys), (cin, config.num_classes)) * 0.01).astype(
            jnp.float32
        ),
        "bias": jnp.zeros((config.num_classes,)),
    }
    return params


def _conv(x, kernel, stride=1):
    return jax.lax.conv_general_dilated(
        x, kernel, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _group_norm(x, p, groups):
    c = x.shape[-1]
    g = min(groups, c)
    while c % g:
        g -= 1
    xf = x.astype(jnp.float32).reshape(*x.shape[:-1], g, c // g)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = xf.var(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * jax.lax.rsqrt(var + 1e-5)
    return (xf.reshape(x.shape).astype(x.dtype)) * p["scale"] + p["bias"]


def resnet_forward(params: dict, x: jax.Array, config: ResNetConfig) -> jax.Array:
    """x: [B, H, W, 3] → logits [B, num_classes]."""
    h = _conv(x, params["stem"]["conv"]["kernel"], stride=2)
    h = jax.nn.relu(_group_norm(h, params["stem"]["norm"], config.groups))
    h = jax.lax.reduce_window(
        h, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    for stage_idx in range(len(config.block_sizes)):
        for block_idx, block in enumerate(params[f"stage_{stage_idx}"]):
            stride = 2 if (stage_idx > 0 and block_idx == 0) else 1
            shortcut = h
            out = jax.nn.relu(_group_norm(_conv(h, block["conv1"]["kernel"]), block["norm1"], config.groups))
            out = jax.nn.relu(
                _group_norm(_conv(out, block["conv2"]["kernel"], stride=stride), block["norm2"], config.groups)
            )
            out = _group_norm(_conv(out, block["conv3"]["kernel"]), block["norm3"], config.groups)
            if "downsample" in block:
                shortcut = _group_norm(
                    _conv(h, block["downsample"]["conv"]["kernel"], stride=stride),
                    block["downsample"]["norm"],
                    config.groups,
                )
            elif stride != 1:  # pragma: no cover - first block always downsamples
                shortcut = shortcut[:, ::stride, ::stride]
            h = jax.nn.relu(out + shortcut)
    h = h.mean(axis=(1, 2))
    return h @ params["fc"]["kernel"] + params["fc"]["bias"]


def resnet_loss(params: dict, batch: dict, config: ResNetConfig) -> jax.Array:
    logits = resnet_forward(params, batch["pixels"], config)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, batch["labels"][:, None], axis=-1))


def resnet_shard_rules():
    """FSDP/TP sharding rules: conv kernels shard the output-channel dim."""
    from ..parallel.sharding import ShardingRules

    return ShardingRules(
        rules=[
            (r".*conv.*/kernel", (None, None, None, "tp")),
            (r".*fc/kernel", (None, "tp")),
        ]
    )
