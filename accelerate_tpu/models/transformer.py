"""Transformer model family, pure-JAX, TPU-first.

These are the acceptance workloads for the framework (reference examples:
``examples/nlp_example.py`` BERT-base MRPC — the north star —,
``examples/cv_example.py``, LM fine-tunes in ``benchmarks/fsdp2``; SURVEY.md §2.5).
They are intentionally *plain pytrees + pure functions*, not a module framework:

- params are nested dicts → sharding rules are path regexes, checkpoints are
  flat path→array maps, and every parallelism axis composes;
- per-layer params are **stacked on a leading axis and iterated with
  ``lax.scan``** → compile time is O(1) in depth and FSDP sharding of the stack
  is one spec (a deliberate TPU-first departure from the reference's per-module
  python structure);
- attention routes through ``ops.attention`` so CP/SP/flash kernels swap in
  without touching model code.

``LlamaModel`` (decoder, RoPE/RMSNorm/SwiGLU/GQA) is the flagship;
``BertClassifier`` (encoder + pooled classification head) is the MRPC
north-star workload.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.attention import dot_product_attention
from ..ops.fp8 import META_KEY, fp8_dot, init_fp8_meta


# ---------------------------------------------------------------------------
# init helpers


def _dense_init(key, in_dim, out_dim, scale=None):
    scale = scale if scale is not None else 1.0 / np.sqrt(in_dim)
    return (jax.random.normal(key, (in_dim, out_dim)) * scale).astype(jnp.float32)


def _proj(entry: dict, x: jax.Array) -> jax.Array:
    """``x @ entry["kernel"]``, through :func:`ops.fp8.fp8_dot` when the entry
    carries fp8 meta (``dtype_recipe="fp8"`` threads the delayed-scaling state
    into the param tree at init; its cotangent is the updated meta — see
    ``ops/fp8.py``)."""
    if META_KEY in entry:  # dict-key membership: static at trace time  # jaxlint: disable=R1
        return fp8_dot(x, entry["kernel"], entry[META_KEY])
    return x @ entry["kernel"]


def _stacked_fp8_meta(n_layers: int):
    """Per-layer fp8 meta stacked on the layer axis, so it rides the same
    ``lax.scan`` as the stacked projection kernels (the test_fp8
    meta-under-scan pattern)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[init_fp8_meta() for _ in range(n_layers)]
    )


def _check_dtype_recipe(recipe):
    if recipe not in (None, "fp8"):
        raise ValueError(f"dtype_recipe must be None or 'fp8', got {recipe!r}")


def rms_norm(x, scale, eps=1e-6):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps).astype(x.dtype)) * scale


def layer_norm(x, scale, bias, eps=1e-6):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (out.astype(x.dtype) * scale) + bias


# ---------------------------------------------------------------------------
# RoPE


def rope_frequencies(head_dim: int, max_seq: int, theta: float = 10000.0):
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2) / head_dim))
    t = np.arange(max_seq)
    freqs = np.outer(t, inv)
    return np.cos(freqs).astype(np.float32), np.sin(freqs).astype(np.float32)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array, positions=None) -> jax.Array:
    """x: [B, S, H, D]; cos/sin: [max_seq, D/2]."""
    seq = x.shape[1]
    if positions is None:
        cos_s = cos[:seq][None, :, None, :]
        sin_s = sin[:seq][None, :, None, :]
    else:
        cos_s = cos[positions][:, :, None, :]
        sin_s = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    cos_s = cos_s.astype(x.dtype)
    sin_s = sin_s.astype(x.dtype)
    return jnp.concatenate([x1 * cos_s - x2 * sin_s, x2 * cos_s + x1 * sin_s], axis=-1)


# ---------------------------------------------------------------------------
# Llama-style decoder (flagship)


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 2048
    n_layers: int = 16
    n_heads: int = 16
    n_kv_heads: int = 8
    ffn_dim: Optional[int] = None  # default 8/3 * dim rounded to 256
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    tie_embeddings: bool = False
    # moe_experts > 0 swaps the dense SwiGLU FFN for a top-k expert-parallel
    # MoE (parallel/moe.py) in every layer; experts shard over the ``ep`` axis
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # True → layer loop fully unrolled (scan(..., unroll)): XLA fuses across
    # layer boundaries and skips the stacked-residual dynamic-slices; measured
    # 1.5× fwd+bwd on v5e for BERT-base. False → O(1)-in-depth compile time.
    unroll_layers: bool = True
    # default attention implementation for forwards that don't pass one
    # explicitly: "auto" | "xla" | "flash" | "fused" (ops.attention impls)
    attn_impl: str = "auto"
    # None → matmuls in the param dtype; "fp8" → QKV/O and MLP projections run
    # through ops.fp8.fp8_dot (delayed scaling, e4m3 fwd / e5m2 bwd) with the
    # per-site amax histories living IN the param tree (embeddings and the lm
    # head stay high-precision — the standard first/last-layer exclusion)
    dtype_recipe: Optional[str] = None

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def hidden_dim(self) -> int:
        if self.ffn_dim is not None:
            return self.ffn_dim
        return int(np.ceil(self.dim * 8 / 3 / 256) * 256)

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        return cls(vocab_size=512, dim=128, n_layers=2, n_heads=4, n_kv_heads=2, max_seq_len=256)


def init_llama(config: LlamaConfig, key) -> dict:
    """Stacked-layer param pytree: every per-layer tensor has leading dim L.
    ``dtype_recipe="fp8"`` adds a stacked ``fp8_meta`` subtree to every
    projection entry (QKV/O + SwiGLU) — state the forward reads and whose
    gradient-side cotangent is the rolled amax histories."""
    _check_dtype_recipe(config.dtype_recipe)
    if config.dtype_recipe == "fp8" and config.moe_experts > 0:
        raise ValueError("dtype_recipe='fp8' does not support MoE layers yet")
    keys = jax.random.split(key, 9)
    L, D, H = config.n_layers, config.dim, config.hidden_dim
    Dq = config.n_heads * config.head_dim
    Dkv = config.n_kv_heads * config.head_dim

    def stack(k, in_dim, out_dim):
        ks = jax.random.split(k, L)
        return jnp.stack([_dense_init(ks[i], in_dim, out_dim) for i in range(L)])

    if config.moe_experts > 0:
        from ..parallel.moe import init_moe_ffn

        moe_keys = jax.random.split(keys[5], L)
        per_layer = [
            init_moe_ffn(moe_keys[i], D, H, config.moe_experts) for i in range(L)
        ]
        ffn = {"moe": jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_layer)}
    else:
        ffn = {
            "w1": {"kernel": stack(keys[5], D, H)},
            "w3": {"kernel": stack(keys[6], D, H)},
            "w2": {"kernel": stack(keys[7], H, D)},
        }
    params = {
        "embed_tokens": {"embedding": _dense_init(keys[0], config.vocab_size, D, scale=0.02)},
        "layers": {
            "attn_norm": {"scale": jnp.ones((L, D))},
            "wq": {"kernel": stack(keys[1], D, Dq)},
            "wk": {"kernel": stack(keys[2], D, Dkv)},
            "wv": {"kernel": stack(keys[3], D, Dkv)},
            "wo": {"kernel": stack(keys[4], Dq, D)},
            "mlp_norm": {"scale": jnp.ones((L, D))},
            **ffn,
        },
        "final_norm": {"scale": jnp.ones(D)},
    }
    if config.dtype_recipe == "fp8":
        for name in ("wq", "wk", "wv", "wo", "w1", "w3", "w2"):
            params["layers"][name][META_KEY] = _stacked_fp8_meta(L)
    if not config.tie_embeddings:
        params["lm_head"] = {"kernel": _dense_init(keys[8], D, config.vocab_size, scale=0.02)}
    return params


def _activation_spec(mesh, *logical):
    """PartitionSpec from logical dim names, dropping axes absent from the mesh.
    ``logical`` entries: None, an axis name, or a tuple of axis names."""
    from jax.sharding import PartitionSpec

    def _present(axis):
        if axis is None:
            return None
        if isinstance(axis, (tuple, list)):
            kept = tuple(a for a in axis if mesh.shape.get(a, 1) > 1)
            return kept if kept else None
        return axis if mesh.shape.get(axis, 1) > 1 else None

    return PartitionSpec(*(_present(ax) for ax in logical))


def _constrain(x, mesh, *logical):
    """Explicit activation sharding (maxtext-style): without these annotations
    GSPMD may pick conflicting intermediate shardings around the embedding
    gather / layer scan and fall back to replicate-then-reshard ("involuntary
    full rematerialization")."""
    if mesh is None:
        return x
    from jax.sharding import NamedSharding

    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, _activation_spec(mesh, *logical))
    )


def _remat_policy(remat: bool | str):
    """Map the ``remat`` knob to a ``jax.checkpoint`` policy (None = save
    nothing, i.e. full recompute). ``"offload_dots"`` saves the
    weight-stationary matmul outputs to HOST memory instead of HBM
    (activation offloading — compose with optimizer host offload to fit the
    largest models). Unlike top-level program I/O placement, offload
    annotations inside remat are compiler hints that every backend accepts
    (the CPU mesh runs them too); only on TPU do they actually move bytes to
    host RAM."""
    if remat is True or remat == "nothing":
        return None
    if remat == "offload_dots":
        return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host"
        )
    policies = {
        "dots": jax.checkpoint_policies.checkpoint_dots,
        "dots_no_batch": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    }
    try:
        return policies[remat]
    except KeyError:
        raise ValueError(
            f"remat must be bool, 'nothing', 'dots', 'dots_no_batch' or "
            f"'offload_dots'; got {remat!r}"
        ) from None


def llama_ffn(layer_params: dict, x: jax.Array, config: LlamaConfig, mesh=None,
              capacity_factor: Optional[float] = None):
    """The per-layer FFN block — dense SwiGLU or expert-parallel MoE — shared
    by the training forward and the cached decode path (generation.py) so the
    two cannot drift. Returns ``(y, aux)``; ``capacity_factor`` overrides the
    config's (the decode path floors it for drop-free per-step routing)."""
    if config.moe_experts > 0:
        from ..parallel.moe import moe_ffn

        return moe_ffn(
            layer_params["moe"], x,
            top_k=config.moe_top_k,
            capacity_factor=(
                config.moe_capacity_factor if capacity_factor is None else capacity_factor
            ),
            mesh=mesh,  # ep-axis dispatch/expert activation constraints
        )
    gate = jax.nn.silu(_proj(layer_params["w1"], x))
    up = _proj(layer_params["w3"], x)
    return _proj(layer_params["w2"], gate * up), jnp.float32(0.0)


def llama_forward(
    params: dict,
    input_ids: jax.Array,  # [B, S]
    config: LlamaConfig,
    attention_impl: Optional[str] = None,  # default: config.attn_impl
    attention_fn=None,
    remat: bool | str = False,
    mesh=None,
    with_aux: bool = False,
    segment_ids=None,  # [B, S] int — packed sequences (0 = padding)
    positions=None,  # [B, S] int — rope positions (default: per-segment index)
) -> jax.Array:
    """Return logits [B, S, vocab] (``with_aux=True`` → (logits, aux) where aux
    is the mean MoE load-balance loss, 0.0 for dense configs). ``attention_fn``
    overrides the attention op (ring attention for CP plugs in here); ``mesh``
    enables explicit activation sharding constraints (batch over dp axes, seq
    over cp).

    ``remat``: ``False`` (save all), ``True`` (recompute all — min memory), or
    a policy name trading memory for recompute FLOPs (the knob behind the
    reference's FSDP ``activation_checkpointing``): ``"dots"`` saves matmul
    outputs, ``"dots_no_batch"`` saves only weight-stationary matmuls (the
    usual transformer sweet spot), ``"offload_dots"`` saves them to host RAM
    instead of HBM (activation offloading), ``"nothing"`` ≡ ``True``.

    ``segment_ids`` enables PACKED sequences (``utils/packing.py``): tokens
    attend only within their segment (still causally), rope positions restart
    per segment, and id 0 marks padding. Not combinable with ``attention_fn``
    (the CP/SP rings don't carry segment info)."""
    if attention_impl is None:
        attention_impl = config.attn_impl
    cos, sin = rope_frequencies(config.head_dim, config.max_seq_len, config.rope_theta)
    cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    if segment_ids is not None:
        if attention_fn is not None:
            raise ValueError("segment_ids (packing) cannot combine with attention_fn (CP/SP)")
        if positions is None:
            # per-segment position: index minus the running segment-start index
            # (roll-based start detection keeps the sequence extent unchanged)
            seq_idx = jnp.arange(segment_ids.shape[1])[None, :]
            is_start = jnp.roll(segment_ids, 1, axis=1) != segment_ids
            is_start = is_start.at[:, 0].set(True)
            positions = seq_idx - jax.lax.cummax(jnp.where(is_start, seq_idx, 0), axis=1)
    _batch_axes = ("dp_replicate", "dp_shard")
    # FSDP shards the table's embedding dim at rest; gather it for compute
    # (classic FSDP all-gather-on-use) or the lookup output inherits a D-dim
    # sharding that conflicts with the (batch, seq) activation layout and
    # GSPMD falls back to full rematerialization
    table = _constrain(params["embed_tokens"]["embedding"], mesh, "tp", None)
    h = table[input_ids]
    h = _constrain(h, mesh, _batch_axes, "cp", None)
    B, S, D = h.shape

    def layer(h, layer_params):
        x = rms_norm(h, layer_params["attn_norm"]["scale"], config.norm_eps)
        q = _proj(layer_params["wq"], x).reshape(B, S, config.n_heads, config.head_dim)
        k = _proj(layer_params["wk"], x).reshape(B, S, config.n_kv_heads, config.head_dim)
        v = _proj(layer_params["wv"], x).reshape(B, S, config.n_kv_heads, config.head_dim)
        q = apply_rope(q, cos, sin, positions=positions)
        k = apply_rope(k, cos, sin, positions=positions)
        if attention_fn is not None:
            attn = attention_fn(q, k, v, causal=True)
        else:
            attn = dot_product_attention(
                q, k, v, causal=True, segment_ids=segment_ids, impl=attention_impl
            )
        h = h + _proj(layer_params["wo"], attn.reshape(B, S, -1))
        h = _constrain(h, mesh, _batch_axes, "cp", None)
        x = rms_norm(h, layer_params["mlp_norm"]["scale"], config.norm_eps)
        y, aux = llama_ffn(layer_params, x, config, mesh=mesh)
        h = h + y
        h = _constrain(h, mesh, _batch_axes, "cp", None)
        return h, aux

    if remat:
        layer = jax.checkpoint(layer, policy=_remat_policy(remat))
    h, aux_per_layer = jax.lax.scan(layer, h, params["layers"], unroll=config.unroll_layers)
    h = rms_norm(h, params["final_norm"]["scale"], config.norm_eps)
    if config.tie_embeddings:
        logits = h @ params["embed_tokens"]["embedding"].T
    else:
        logits = h @ params["lm_head"]["kernel"]
    logits = _constrain(logits, mesh, _batch_axes, "cp", "tp")
    if with_aux:
        return logits, jnp.mean(aux_per_layer)
    return logits


def llama_loss(params: dict, batch: dict, config: LlamaConfig, **fwd_kwargs) -> jax.Array:
    """Next-token cross entropy. ``batch``: input_ids [B, S] (labels shifted
    internally), optional loss_mask [B, S].

    The forward runs on the FULL sequence and targets come from a
    shape-preserving ``roll`` (a cheap ppermute along cp on the ICI) with the
    final position masked out — a ``[:, :-1]``/``[:, 1:]`` slice pair would
    change the sequence extent and force GSPMD to replicate-then-reshard every
    activation crossing the shift ("involuntary full rematerialization")."""
    ids = batch["input_ids"]
    seq_len = ids.shape[1]
    # packing: segment ids may arrive in the batch OR as a forward kwarg —
    # both must engage the boundary/padding loss masking below
    segment_ids = batch.get("segment_ids")
    if segment_ids is None:
        segment_ids = fwd_kwargs.get("segment_ids")
    elif "segment_ids" not in fwd_kwargs:
        fwd_kwargs = {**fwd_kwargs, "segment_ids": segment_ids}
    if config.moe_experts > 0:
        logits, moe_aux = llama_forward(params, ids, config, with_aux=True, **fwd_kwargs)
    else:
        logits, moe_aux = llama_forward(params, ids, config, **fwd_kwargs), 0.0
    targets = jnp.roll(ids, shift=-1, axis=1)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]  # [B, S]
    # position S-1 has no next token; its rolled target is position 0 — mask it
    valid = jnp.broadcast_to(
        (jnp.arange(seq_len) < seq_len - 1).astype(jnp.float32)[None, :], nll.shape
    )
    if segment_ids is not None:
        # packed: a position's target must be the NEXT token of the SAME
        # segment — segment boundaries and padding (id 0) don't contribute
        same_seg = jnp.roll(segment_ids, shift=-1, axis=1) == segment_ids
        valid = valid * same_seg.astype(jnp.float32) * (segment_ids > 0).astype(jnp.float32)
    mask = batch.get("loss_mask")
    if mask is not None:
        valid = valid * jnp.roll(mask, shift=-1, axis=1).astype(jnp.float32)
    nll_mean = jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1.0)
    return nll_mean + config.moe_aux_weight * moe_aux


def llama_shard_rules():
    """TP rules for the stacked-layer layout: dim 0 is the layer-stack axis, so TP
    shards dim 1 (in) / dim 2 (out). Embeddings/head are 2-D."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharding import ShardingRules

    return ShardingRules(
        [
            # fp8 scaling metadata: tiny f32 history buffers, always replicated
            (r"fp8_meta", P()),
            (r"layers/(wq|wk|wv|w1|w3)/kernel", P(None, None, "tp")),  # column-parallel
            (r"layers/(wo|w2)/kernel", P(None, "tp", None)),  # row-parallel
            # MoE: leading dims are [layer, expert]; experts over ep, the
            # expert matmul dims over tp like their dense counterparts
            (r"layers/moe/router/kernel", P()),
            (r"layers/moe/wi/kernel", P(None, "ep", None, "tp")),
            (r"layers/moe/wo/kernel", P(None, "ep", "tp", None)),
            (r"embed_tokens/embedding", P("tp", None)),  # vocab-parallel
            (r"lm_head/kernel", P(None, "tp")),
            (r"norm", P()),
        ]
    )


# ---------------------------------------------------------------------------
# Self-draft construction (speculative decoding)


def draft_config(config: LlamaConfig, n_layers: int) -> LlamaConfig:
    """Config for a truncated-layer self-draft: the verifier's config with
    only its first ``n_layers`` decoder layers (``serving/engine.py``'s
    speculative-decoding draft). Everything else — vocab, dims, heads, rope —
    is inherited, so the draft reads/writes the SAME paged KV layout as the
    verifier's first ``n_layers`` layers."""
    if not (0 < n_layers <= config.n_layers):
        raise ValueError(
            f"draft_layers must be in 1..{config.n_layers}, got {n_layers}"
        )
    return replace(config, n_layers=n_layers)


def draft_params(params: dict, n_layers: int) -> dict:
    """Truncated-layer self-draft params: slice the stacked-layer pytree to
    the first ``n_layers`` layers and SHARE embeddings / final norm / lm head
    with the verifier (no copy — the stacked-layer layout makes the slice a
    view-cheap ``x[:n]`` per leaf). Because draft layer i *is* verifier layer
    i, KV the verifier's prefill/verify steps land in the paged pool is
    byte-valid for the draft — the draft needs no pool, no prefill, and no
    extra memory of its own."""
    out = {k: v for k, v in params.items() if k != "layers"}
    out["layers"] = jax.tree_util.tree_map(lambda x: x[:n_layers], params["layers"])
    return out


# ---------------------------------------------------------------------------
# BERT-style encoder + classifier (north-star MRPC workload)


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_dim: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    num_labels: int = 2
    norm_eps: float = 1e-12
    # see LlamaConfig.unroll_layers — same measured win applies here
    unroll_layers: bool = True
    # see LlamaConfig.attn_impl — the config-level attention knob
    attn_impl: str = "auto"
    # see LlamaConfig.dtype_recipe — None (native) or "fp8" (delayed-scaling
    # projections + MLP matmuls through ops.fp8.fp8_dot)
    dtype_recipe: Optional[str] = None

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @classmethod
    def base(cls) -> "BertConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "BertConfig":
        return cls(vocab_size=1024, dim=128, n_layers=2, n_heads=4, ffn_dim=256, max_seq_len=128)


def init_bert(config: BertConfig, key) -> dict:
    _check_dtype_recipe(config.dtype_recipe)
    keys = jax.random.split(key, 12)
    L, D, F = config.n_layers, config.dim, config.ffn_dim

    def stack(k, a, b):
        ks = jax.random.split(k, L)
        return jnp.stack([_dense_init(ks[i], a, b, scale=0.02) for i in range(L)])

    params = {
        "embeddings": {
            "word": {"embedding": _dense_init(keys[0], config.vocab_size, D, 0.02)},
            "position": {"embedding": _dense_init(keys[1], config.max_seq_len, D, 0.02)},
            "token_type": {"embedding": _dense_init(keys[2], config.type_vocab_size, D, 0.02)},
            "norm": {"scale": jnp.ones(D), "bias": jnp.zeros(D)},
        },
        "layers": {
            "wq": {"kernel": stack(keys[3], D, D), "bias": jnp.zeros((L, D))},
            "wk": {"kernel": stack(keys[4], D, D), "bias": jnp.zeros((L, D))},
            "wv": {"kernel": stack(keys[5], D, D), "bias": jnp.zeros((L, D))},
            "wo": {"kernel": stack(keys[6], D, D), "bias": jnp.zeros((L, D))},
            "attn_norm": {"scale": jnp.ones((L, D)), "bias": jnp.zeros((L, D))},
            "fc1": {"kernel": stack(keys[7], D, F), "bias": jnp.zeros((L, F))},
            "fc2": {"kernel": stack(keys[8], F, D), "bias": jnp.zeros((L, D))},
            "mlp_norm": {"scale": jnp.ones((L, D)), "bias": jnp.zeros((L, D))},
        },
        "pooler": {"kernel": _dense_init(keys[9], D, D, 0.02), "bias": jnp.zeros(D)},
        "classifier": {"kernel": _dense_init(keys[10], D, config.num_labels, 0.02), "bias": jnp.zeros(config.num_labels)},
    }
    if config.dtype_recipe == "fp8":
        # per-layer delayed-scaling state for every projection that routes
        # through fp8_dot in bert_forward (pooler/classifier stay native —
        # first/last-matmul exclusion, same as llama's embed/lm_head)
        for name in ("wq", "wk", "wv", "wo", "fc1", "fc2"):
            params["layers"][name][META_KEY] = _stacked_fp8_meta(L)
    return params


def bert_forward(
    params: dict, batch: dict, config: BertConfig, attention_impl: Optional[str] = None
) -> jax.Array:
    """Return classification logits [B, num_labels]. batch: input_ids,
    attention_mask, token_type_ids (all [B, S]). ``attention_impl`` defaults
    to ``config.attn_impl`` (the config-level knob)."""
    if attention_impl is None:
        attention_impl = config.attn_impl
    ids = batch["input_ids"]
    B, S = ids.shape
    emb = params["embeddings"]
    h = (
        emb["word"]["embedding"][ids]
        + emb["position"]["embedding"][jnp.arange(S)][None]
        + emb["token_type"]["embedding"][batch.get("token_type_ids", jnp.zeros_like(ids))]
    )
    h = layer_norm(h, emb["norm"]["scale"], emb["norm"]["bias"], config.norm_eps)
    # padding expressed as segment ids (pad=0, real=1) so the Pallas flash
    # kernel stays engaged under masking (round-2 verdict: the einsum fallback
    # with an explicit [B,1,S,S] mask was the top unplugged perf lever)
    attn_mask = batch.get("attention_mask")
    seg_ids = attn_mask.astype(jnp.int32) if attn_mask is not None else None

    def layer(h, lp):
        # bias adds stay outside _proj — fp8_dot quantizes the matmul only
        q = (_proj(lp["wq"], h) + lp["wq"]["bias"]).reshape(B, S, config.n_heads, config.head_dim)
        k = (_proj(lp["wk"], h) + lp["wk"]["bias"]).reshape(B, S, config.n_heads, config.head_dim)
        v = (_proj(lp["wv"], h) + lp["wv"]["bias"]).reshape(B, S, config.n_heads, config.head_dim)
        attn = dot_product_attention(q, k, v, segment_ids=seg_ids, impl=attention_impl).reshape(B, S, -1)
        h = layer_norm(
            h + _proj(lp["wo"], attn) + lp["wo"]["bias"],
            lp["attn_norm"]["scale"],
            lp["attn_norm"]["bias"],
            config.norm_eps,
        )
        x = jax.nn.gelu(_proj(lp["fc1"], h) + lp["fc1"]["bias"])
        h = layer_norm(
            h + _proj(lp["fc2"], x) + lp["fc2"]["bias"],
            lp["mlp_norm"]["scale"],
            lp["mlp_norm"]["bias"],
            config.norm_eps,
        )
        return h, None

    h, _ = jax.lax.scan(layer, h, params["layers"], unroll=config.unroll_layers)
    pooled = jnp.tanh(h[:, 0] @ params["pooler"]["kernel"] + params["pooler"]["bias"])
    return pooled @ params["classifier"]["kernel"] + params["classifier"]["bias"]


def bert_loss(params: dict, batch: dict, config: BertConfig, **kwargs) -> jax.Array:
    logits = bert_forward(params, batch, config, **kwargs)
    labels = batch["labels"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


def bert_shard_rules():
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharding import ShardingRules

    return ShardingRules(
        [
            # fp8 scaling metadata: tiny f32 history buffers, always replicated
            (r"fp8_meta", P()),
            (r"layers/(wq|wk|wv|fc1)/kernel", P(None, None, "tp")),
            (r"layers/(wo|fc2)/kernel", P(None, "tp", None)),
            (r"embeddings/word/embedding", P("tp", None)),
            (r"(norm|bias|pooler|classifier)", P()),
        ]
    )
