"""Fused short-sequence attention: a Pallas TPU kernel for the S ≲ 512 regime.

Why this exists: the stock flash kernel
(``jax.experimental.pallas.ops.tpu.flash_attention``) streams KV through VMEM
with online softmax — the right shape for long sequences, but at BERT-class
lengths (S=128–256) its multi-kernel pipeline loses to XLA's einsum by ~2×
(measured on v5e). The einsum path in turn pays HBM round-trips for the
[B,H,S,S] f32 score tensor (50 MB/layer at B=64) plus layout shuffles.

At short S the whole per-program score block FITS in VMEM, so this kernel
fuses QKᵀ → mask → softmax → PV into ONE pass over a (batch-block × all
heads) tile: scores never touch HBM in either direction, matmuls run in the
input dtype (bf16 full MXU rate, f32 accumulate), and the grid is just
B/block_b steps so Mosaic's per-step pipeline overhead is amortized. The
backward is a second single-pass kernel (recompute scores from the saved
logsumexp, then dq/dk/dv — the flash recompute trick with no blocking).

Measured reality check (v5e, fwd+bwd, H=12, D=64): this kernel beats the
stock flash kernel at short S but XLA's fused einsum still edges it out
(~0.8× at S=128, ~1.0× at S=256) — XLA fuses the mask/softmax into the
matmul epilogue extremely well at these sizes. It therefore ships as the
explicit ``impl="fused"`` option rather than the "auto" default: useful when
the surrounding graph is fusion-hostile, and as the in-tree template for
bespoke attention variants (the bwd shows the full recompute-from-lse
pattern in ~40 lines, vs ~600 for the blocked streaming kernel).

Reference surface: flash/SDPA CUDA kernels reached through transformers
(SURVEY.md §2.3); layout/semantics match ``ops.attention.dot_product_attention``
(BSHD public API, GQA via in-kernel kv broadcast, segment-id masking, causal).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# batched matmul helpers over a single flattened (bb·H) batch dim — Mosaic's
# tpu.matmul supports at most ONE batch dimension
_BATCH = ((0,), (0,))


def _dot_nt(a, b):  # [G, M, K] × [G, N, K] → [G, M, N]
    return jax.lax.dot_general(a, b, (((2,), (2,)), _BATCH), preferred_element_type=jnp.float32)


def _dot_nn(a, b):  # [G, M, K] × [G, K, N] → [G, M, N]
    return jax.lax.dot_general(a, b, (((2,), (1,)), _BATCH), preferred_element_type=jnp.float32)


def _dot_tn(a, b):  # [G, K, M] × [G, K, N] → [G, M, N]
    return jax.lax.dot_general(a, b, (((1,), (1,)), _BATCH), preferred_element_type=jnp.float32)


def _flat_heads(ref, rep):
    """[bb, Hkv, S, D] block → [bb·Hkv·rep, S, D] with GQA head broadcast
    (leading-dim reshapes/broadcasts are layout-free in Mosaic)."""
    x = ref[...]
    bb, hkv, s, d = x.shape
    if rep > 1:
        x = jnp.broadcast_to(x[:, :, None], (bb, hkv, rep, s, d))
    return x.reshape(bb * hkv * rep, s, d)


def _seg_mask(seg_ref, h, sq, skv):
    """[bb, 1, S] seg block → [bb·H, Sq, Skv] bool allow-mask."""
    seg = seg_ref[:, 0, :]
    bb = seg.shape[0]
    m = seg[:, :, None] == seg[:, None, :]
    return jnp.broadcast_to(m[:, None], (bb, h, sq, skv)).reshape(bb * h, sq, skv)


def _masked_scores(q, k, seg_ref, scale, causal, h, use_seg):
    """q,k [G,S,D] → masked [G,Sq,Skv] f32 scores (G = bb·H)."""
    s = _dot_nt(q, k) * scale
    if use_seg:
        s = jnp.where(_seg_mask(seg_ref, h, s.shape[1], s.shape[2]), s, NEG_INF)
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(rows >= cols, s, NEG_INF)
    return s


def _fwd_kernel(q_ref, k_ref, v_ref, seg_ref, o_ref, lse_ref,
                *, scale, causal, rep, use_seg):
    # blocks (BHSD): q/o [bb, H, S, D]; k/v [bb, Hkv, S, D]; seg [bb, 1, S];
    # lse [bb, H, 1, S]
    bb, h, sq, d = q_ref.shape
    q = q_ref[...].reshape(bb * h, sq, d)
    k = _flat_heads(k_ref, rep)
    v = _flat_heads(v_ref, rep)
    s = _masked_scores(q, k, seg_ref, scale, causal, h, use_seg)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = _dot_nn(p.astype(v.dtype), v) / l
    o_ref[...] = o.reshape(bb, h, sq, d).astype(o_ref.dtype)
    lse_ref[:, :, 0, :] = (m[..., 0] + jnp.log(l[..., 0])).reshape(bb, h, sq)


def _bwd_kernel(q_ref, k_ref, v_ref, seg_ref, lse_ref, o_ref, do_ref,
                dq_ref, dk_ref, dv_ref, *, scale, causal, rep, use_seg):
    bb, h, sq, d = q_ref.shape
    q = q_ref[...].reshape(bb * h, sq, d)
    k = _flat_heads(k_ref, rep)
    v = _flat_heads(v_ref, rep)
    o = o_ref[...].reshape(bb * h, sq, d).astype(jnp.float32)
    do = do_ref[...].reshape(bb * h, sq, d)
    lse = lse_ref[:, :, 0, :].reshape(bb * h, sq)

    s = _masked_scores(q, k, seg_ref, scale, causal, h, use_seg)
    p = jnp.exp(s - lse[:, :, None])  # [G, Sq, Skv] f32
    pc = p.astype(q.dtype)

    # dv = pᵀ do ; dp = do vᵀ ; ds = p (dp − ⟨do,o⟩) ; dq = ds k ; dk = dsᵀ q
    dv = _dot_tn(pc, do)                      # [G, Skv, D]
    dp = _dot_nt(do, v)                       # [G, Sq, Skv]
    delta = jnp.sum(do.astype(jnp.float32) * o, axis=-1, keepdims=True)
    ds = (p * (dp - delta)).astype(q.dtype)
    dq = _dot_nn(ds, k) * scale               # [G, Sq, D]
    dk = _dot_tn(ds, q) * scale               # [G, Skv, D]
    dq_ref[...] = dq.reshape(bb, h, sq, d).astype(dq_ref.dtype)
    dk_ref[...] = dk.reshape(bb, h, sq, d).astype(dk_ref.dtype)
    dv_ref[...] = dv.reshape(bb, h, sq, d).astype(dv_ref.dtype)


def _block_b(batch: int, h: int, s: int, n_score_bufs: int) -> int:
    """Largest batch block whose f32 score buffers stay within ~4 MB of VMEM
    (leaves room for the q/k/v/o tiles and Mosaic's double buffering)."""
    budget = max(1, (4 * 1024 * 1024) // (h * s * s * 4 * n_score_bufs))
    for bb in (8, 4, 2, 1):
        if bb <= budget and batch % bb == 0:
            return bb
    return 1


def _specs(H, Hkv, S, D, bb):
    from jax.experimental import pallas as pl

    q_spec = pl.BlockSpec((bb, H, S, D), lambda b: (b, 0, 0, 0))
    kv_spec = pl.BlockSpec((bb, Hkv, S, D), lambda b: (b, 0, 0, 0))
    seg_spec = pl.BlockSpec((bb, 1, S), lambda b: (b, 0, 0))
    lse_spec = pl.BlockSpec((bb, H, 1, S), lambda b: (b, 0, 0, 0))
    return q_spec, kv_spec, seg_spec, lse_spec


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _fused_attention(q, k, v, segment_ids, scale, causal):
    out, _ = _fused_fwd(q, k, v, segment_ids, scale, causal)
    return out


def _fused_fwd(q, k, v, segment_ids, scale, causal):
    from jax.experimental import pallas as pl

    B, H, S, D = q.shape
    Hkv = k.shape[1]
    bb = _block_b(B, H, S, n_score_bufs=2)
    use_seg = segment_ids is not None
    seg = (segment_ids if use_seg else jnp.zeros((B, S), jnp.int32))
    seg = seg.astype(jnp.int32).reshape(B, 1, S)
    q_spec, kv_spec, seg_spec, lse_spec = _specs(H, Hkv, S, D, bb)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          rep=H // Hkv, use_seg=use_seg),
        grid=(B // bb,),
        in_specs=[q_spec, kv_spec, kv_spec, seg_spec],
        out_specs=[q_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B, H, 1, S), jnp.float32),
        ],
    )(q, k, v, seg)
    return out, (q, k, v, seg, use_seg, lse, out)


def _fused_bwd(scale, causal, res, do):
    from jax.experimental import pallas as pl

    q, k, v, seg, use_seg, lse, out = res
    B, H, S, D = q.shape
    Hkv = k.shape[1]
    bb = _block_b(B, H, S, n_score_bufs=3)
    q_spec, kv_spec, seg_spec, lse_spec = _specs(H, Hkv, S, D, bb)

    # dk/dv come out per q-head ([B,H,S,D]); GQA folds them below
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale, causal=causal,
                          rep=H // Hkv, use_seg=use_seg),
        grid=(B // bb,),
        in_specs=[q_spec, kv_spec, kv_spec, seg_spec, lse_spec, q_spec, q_spec],
        out_specs=[q_spec, q_spec, q_spec],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B, H, S, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, S, D), v.dtype),
        ],
    )(q, k, v, seg, lse, out, do)
    if Hkv != H:
        rep = H // Hkv
        dk = dk.reshape(B, Hkv, rep, S, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, rep, S, D).sum(axis=2)
    return dq, dk, dv, None


_fused_attention.defvjp(_fused_fwd, _fused_bwd)


def fused_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,  # [B, S] int; padding = 0
) -> jax.Array:
    """Single-pass fused attention for short sequences (BSHD in/out).

    Falls back to the XLA einsum path off-TPU so call sites stay portable."""
    if jax.default_backend() != "tpu":
        from .attention import _xla_attention, segment_mask

        mask = segment_mask(segment_ids) if segment_ids is not None else None
        return _xla_attention(q, k, v, causal=causal, mask=mask, scale=scale)
    scale = 1.0 / math.sqrt(q.shape[-1]) if scale is None else scale
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))  # BSHD → BHSD
    out = _fused_attention(qt, kt, vt, segment_ids, scale, causal)
    return out.transpose(0, 2, 1, 3)


def fused_supported(q, k) -> bool:
    """Shapes the single-tile kernel handles: one (batch row × all heads) score
    block must fit VMEM, and q-heads must divide by kv-heads for the GQA
    broadcast."""
    B, Sq, H, D = q.shape
    Skv, Hkv = k.shape[1], k.shape[2]
    if Sq != Skv or Sq % 128 != 0 or Sq > 1024:
        return False
    if D % 64 != 0 or D > 256:
        return False
    if H % Hkv != 0:
        return False
    # one batch row's score block (f32, ×3 buffers in bwd) must fit the budget
    return H * Sq * Sq * 4 * 3 <= 8 * 1024 * 1024
