from .attention import dot_product_attention, make_padding_mask
