from .attention import dot_product_attention, make_padding_mask, segment_mask
from .flash_attention import (
    flash_attention,
    flash_kernel_mode,
    paged_attention_decode,
    paged_attention_prefill,
)
from .fused_attention import fused_attention
