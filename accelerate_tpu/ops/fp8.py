"""FP8 training: delayed-scaling fp8 matmul (e4m3 forward / e5m2 backward).

Reference surface: the fp8 recipe stack — ``utils/transformer_engine.py``
(``apply_fp8_autowrap:186``), ``utils/ao.py`` (``convert_model_to_fp8_ao``),
``AORecipeKwargs``/``TERecipeKwargs`` (``utils/dataclasses.py:311/359``) —
all thin shims over CUDA engines.

TPU redesign: XLA lowers ``dot_general`` on ``float8_e4m3fn``/``float8_e5m2``
operands natively, so the whole recipe is expressible in-graph:

- **Delayed scaling** (TE semantics): each tensor role (input / weight / grad)
  keeps an amax history; the quantization scale for step N comes from the
  history of steps < N, so quantize-and-dot needs no extra pass over the data.
- **State threading** (the functional twist): the backward pass is where grad
  amax is observed, but a ``custom_vjp`` can't side-effect. Following the
  established JAX fp8 pattern, the meta (scales/histories) is passed as a
  *differentiable input* whose "cotangent" IS the updated meta; an optax
  partition (:func:`make_fp8_optimizer`) applies ``new - old`` as the update
  for meta leaves, so the standard ``params = params + updates`` step installs
  the fresh histories while real params get the real optimizer.

Use :func:`fp8_dense_init` / :func:`fp8_dense_apply` for a drop-in linear, or
:func:`fp8_dot` directly inside a model.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp

E4M3_MAX = 448.0
E5M2_MAX = 57344.0

META_KEY = "fp8_meta"  # param-tree key marking fp8 state leaves


@dataclass(frozen=True)
class FP8Recipe:
    """Twin of ``TERecipeKwargs`` (``utils/dataclasses.py:359``)."""

    margin: int = 0
    amax_history_len: int = 16
    amax_compute_algo: str = "max"  # "max" | "most_recent"
    # HYBRID: e4m3 for fwd tensors (x, w), e5m2 for grads — the TE default
    fp8_format: str = "HYBRID"

    def __post_init__(self):
        if self.amax_compute_algo not in ("max", "most_recent"):
            raise ValueError(f"unknown amax_compute_algo {self.amax_compute_algo!r}")
        if self.fp8_format not in ("HYBRID", "E4M3"):
            raise ValueError(f"unknown fp8_format {self.fp8_format!r}")

    @property
    def grad_dtype(self):
        return jnp.float8_e5m2 if self.fp8_format == "HYBRID" else jnp.float8_e4m3fn

    @property
    def grad_max(self) -> float:
        return E5M2_MAX if self.fp8_format == "HYBRID" else E4M3_MAX


def init_fp8_meta(recipe: FP8Recipe = FP8Recipe()) -> dict:
    """Fresh per-dot-site meta: one amax history per tensor role."""
    h = recipe.amax_history_len
    return {
        "x_hist": jnp.zeros((h,), jnp.float32),
        "w_hist": jnp.zeros((h,), jnp.float32),
        "g_hist": jnp.zeros((h,), jnp.float32),
    }


def _scale_from_history(hist, fp8_max: float, recipe: FP8Recipe):
    amax = jnp.max(hist) if recipe.amax_compute_algo == "max" else hist[0]  # static recipe field  # jaxlint: disable=R1
    safe = jnp.where(amax > 0, amax, fp8_max)
    return (fp8_max / safe) * (2.0 ** -recipe.margin)


def _quantize(x, scale, fp8_max: float, dtype):
    scaled = x.astype(jnp.float32) * scale
    return jnp.clip(scaled, -fp8_max, fp8_max).astype(dtype)


def _push(hist, amax):
    return jnp.concatenate([amax[None].astype(jnp.float32), hist[:-1]])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fp8_dot(x, w, meta, recipe: FP8Recipe = FP8Recipe()):
    """``x @ w`` computed in fp8 with delayed scaling.

    x: (..., k); w: (k, n); meta: :func:`init_fp8_meta` leaves. Differentiate
    through (x, w, meta) — meta's cotangent is its UPDATED value (see module
    docstring); train with :func:`make_fp8_optimizer` so it lands in params.
    """
    out, _ = _fp8_dot_fwd(x, w, meta, recipe)
    return out


def _fp8_dot_fwd(x, w, meta, recipe: FP8Recipe):
    sx = _scale_from_history(meta["x_hist"], E4M3_MAX, recipe)
    sw = _scale_from_history(meta["w_hist"], E4M3_MAX, recipe)
    qx = _quantize(x, sx, E4M3_MAX, jnp.float8_e4m3fn)
    qw = _quantize(w, sw, E4M3_MAX, jnp.float8_e4m3fn)
    x2 = qx.reshape(-1, x.shape[-1])
    out = jax.lax.dot_general(
        x2, qw, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) / (sx * sw)
    out = out.reshape(*x.shape[:-1], w.shape[-1]).astype(x.dtype)
    # zero-size sentinels carry the primal dtypes through the residual pytree
    # (raw dtypes aren't valid jax types)
    res = (qx, qw, sx, sw, meta, jnp.max(jnp.abs(x)), jnp.max(jnp.abs(w)),
           jnp.zeros((0,), x.dtype), jnp.zeros((0,), w.dtype))
    return out, res


def _fp8_dot_bwd(recipe: FP8Recipe, res, g):
    qx, qw, sx, sw, meta, amax_x, amax_w, x_sent, w_sent = res
    x_dtype, w_dtype = x_sent.dtype, w_sent.dtype
    sg = _scale_from_history(meta["g_hist"], recipe.grad_max, recipe)
    qg = _quantize(g, sg, recipe.grad_max, recipe.grad_dtype)
    g2 = qg.reshape(-1, qg.shape[-1])
    x2 = qx.reshape(-1, qx.shape[-1])
    # dx = g @ w.T ; dw = x.T @ g — both in fp8 with f32 accumulation
    dx = jax.lax.dot_general(
        g2, qw, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) / (sg * sw)
    dw = jax.lax.dot_general(
        x2, g2, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) / (sx * sg)
    dx = dx.reshape(qx.shape).astype(x_dtype)
    dw = dw.astype(w_dtype)
    # meta cotangent = UPDATED meta (histories rolled with this step's amax)
    dmeta = {
        "x_hist": _push(meta["x_hist"], amax_x),
        "w_hist": _push(meta["w_hist"], amax_w),
        "g_hist": _push(meta["g_hist"], jnp.max(jnp.abs(g))),
    }
    return dx, dw, dmeta


fp8_dot.defvjp(_fp8_dot_fwd, _fp8_dot_bwd)


# ------------------------------------------------------------ dense helper --
def fp8_dense_init(key, in_dim: int, out_dim: int,
                   recipe: FP8Recipe = FP8Recipe(), scale: Optional[float] = None) -> dict:
    """Params for a drop-in fp8 linear: {"kernel", "bias", META_KEY}."""
    scale = scale if scale is not None else 1.0 / np.sqrt(in_dim)
    return {
        "kernel": jax.random.normal(key, (in_dim, out_dim)) * scale,
        "bias": jnp.zeros((out_dim,)),
        META_KEY: init_fp8_meta(recipe),
    }


def fp8_dense_apply(params: dict, x, recipe: FP8Recipe = FP8Recipe()):
    out = fp8_dot(x, params["kernel"], params[META_KEY], recipe)
    if "bias" in params:
        out = out + params["bias"]
    return out


# ----------------------------------------------------- optimizer partition --
def fp8_param_labels(params):
    """Label tree for ``optax.multi_transform``: "fp8_meta" under any META_KEY
    subtree, "default" elsewhere."""
    def walk(node, in_meta):
        if isinstance(node, dict):
            return {k: walk(v, in_meta or k == META_KEY) for k, v in node.items()}
        return "fp8_meta" if in_meta else "default"

    return walk(params, False)


def _meta_replace_transform():
    """Updates for meta leaves = (new - old), so apply_updates installs the
    fresh histories delivered as cotangents."""
    import optax

    def init(params):
        return optax.EmptyState()

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fp8 meta update needs params")
        updates = jax.tree_util.tree_map(lambda new, old: new - old, grads, params)
        return updates, state

    return optax.GradientTransformation(init, update)


def make_fp8_optimizer(inner, params, accumulation_steps: int = 1):
    """Partition the optimizer: real params get ``inner``, fp8 meta leaves get
    replace-with-cotangent (see module docstring). ``params`` fixes the tree
    structure for labeling.

    Gradient accumulation must wrap ONLY the real-param branch: amax histories
    are observations, not gradients — averaging/delaying them across micro-steps
    (MultiSteps around the whole partition) would smear the delayed-scaling
    statistics. With ``accumulation_steps > 1`` the inner transform is wrapped
    in ``optax.MultiSteps`` *inside* the partition, so meta leaves roll every
    micro-step while params update on boundaries only.
    """
    import optax

    labels = fp8_param_labels(params)
    if accumulation_steps > 1:
        inner = optax.MultiSteps(inner, every_k_schedule=accumulation_steps)
    return optax.multi_transform(
        {"default": inner, "fp8_meta": _meta_replace_transform()}, labels
    )


def has_fp8_meta(params) -> bool:
    found = []

    def walk(node):
        if isinstance(node, dict):
            for k, v in node.items():
                if k == META_KEY:
                    found.append(True)
                else:
                    walk(v)

    walk(params)
    return bool(found)


def self_check(n_devices: int = 8, steps: int = 3) -> dict:
    """fp8 train step end to end through fused ZeRO-1 on ``n_devices``
    virtual CPU devices: the fused path must stay ENGAGED with the meta
    leaves riding as passthrough slots (not demote to annotation mode), the
    bucketed optimizer state must shard 1/N per replica, losses must match
    the replicated stage-0 baseline, and the compiled step must not grow its
    jit cache after warmup. Run in a FRESH process (sets XLA_FLAGS before
    jax loads); ``make doctor`` invokes it via a subprocess."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from .. import Accelerator, DeepSpeedPlugin
    from ..state import AcceleratorState, GradientState, PartialState

    def loss_fn(p, b):
        h = jax.nn.relu(fp8_dense_apply(p["l1"], b["x"]))
        return jnp.mean((fp8_dense_apply(p["l2"], h) - b["y"]) ** 2)

    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 16)).astype(np.float32)
    batch = {
        "x": jnp.asarray(X),
        "y": jnp.asarray((X @ rng.normal(size=(16, 1))).astype(np.float32)),
    }

    def run(stage):
        for st in (AcceleratorState, GradientState, PartialState):
            st._reset_state()
        acc = Accelerator(
            cpu=True, mixed_precision="fp8",
            deepspeed_plugin=DeepSpeedPlugin(zero_stage=stage), rng_seed=0,
        )
        keys = jax.random.split(jax.random.PRNGKey(0), 2)
        init = {"l1": fp8_dense_init(keys[0], 16, 32),
                "l2": fp8_dense_init(keys[1], 32, 1)}
        params, opt = acc.prepare(init, optax.adam(1e-2))
        step = acc.prepare_train_step(loss_fn, opt)
        s, losses, cache_after_warm = opt.opt_state, [], None
        for i in range(steps):
            params, s, m = step(params, s, batch)
            losses.append(float(m["loss"]))
            if i == 0 and hasattr(step, "_cache_size"):
                cache_after_warm = int(step._cache_size())
        cache_end = int(step._cache_size()) if hasattr(step, "_cache_size") else None
        return acc, opt, params, losses, cache_after_warm, cache_end

    acc, opt, params, fused_losses, warm, end = run(stage=1)
    plan = acc._sharding_plan
    shard_fraction = None
    for leaf in jax.tree_util.tree_leaves(opt.opt_state):
        if (hasattr(leaf, "addressable_shards") and getattr(leaf, "ndim", 0) == 1
                and any(ax is not None for ax in tuple(leaf.sharding.spec))):
            shard = next(iter(leaf.addressable_shards))
            shard_fraction = shard.data.size / leaf.size
            break
    meta_rolled = float(jnp.max(params["l1"][META_KEY]["x_hist"])) > 0
    _, opt0, _, base_losses, _, _ = run(stage=0)
    parity = max(
        abs(a - b) / max(abs(a), 1e-12) for a, b in zip(fused_losses, base_losses)
    )
    return {
        "n_devices": n_devices,
        "fused_engaged": bool(opt.fused_zero1),
        "baseline_fused": bool(opt0.fused_zero1),  # stage 0: must be False
        "plan_fused": bool(plan.fused_zero1),
        "plan_collective_bytes": plan.zero1_collective_bytes(),
        "passthrough_leaves": len(plan.zero1.passthrough_indices),
        "opt_state_shard_fraction": shard_fraction,
        "loss_parity_max_rel_delta": parity,
        "meta_histories_rolled": meta_rolled,
        "jit_cache_after_warmup": warm,
        "jit_cache_at_end": end,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(self_check()))
