"""Weight quantization: blockwise int8 and NF4, TPU-first.

Reference surface: ``utils/bnb.py`` (``load_and_quantize_model:44``,
``replace_with_bnb_layers:276``) + ``BnbQuantizationConfig``
(``utils/dataclasses.py:3025``), which delegate to bitsandbytes CUDA kernels.

TPU redesign: no custom kernels needed for the memory win — weights live in
HBM as int8 codes (or packed uint8 nibble pairs for NF4) with per-block
scales, and dequantization is expressed as plain XLA ops so the compiler fuses
it into the consuming matmul: HBM traffic is halved/quartered while the MXU
still sees bf16 operands. A :class:`QuantizedArray` is a pytree node with
``__jax_array__``, so model forwards written against plain arrays
(``x @ p["wq"]["kernel"]``) consume quantized params unchanged. For
activation×weight int8 (both operands int8, int32 accumulation — the MXU's
native low-precision mode) use :func:`int8_dynamic_matmul`.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

# NF4 codebook (QLoRA): 16 quantiles of N(0,1) normalized to [-1, 1].
NF4_CODE = np.asarray(
    [
        -1.0, -0.6961928009986877, -0.5250730514526367, -0.39491748809814453,
        -0.28444138169288635, -0.18477343022823334, -0.09105003625154495, 0.0,
        0.07958029955625534, 0.16093020141124725, 0.24611230194568634,
        0.33791524171829224, 0.44070982933044434, 0.5626170039176941,
        0.7229568362236023, 1.0,
    ],
    dtype=np.float32,
)


@dataclass
class QuantizationConfig:
    """Twin of the reference's ``BnbQuantizationConfig``
    (``utils/dataclasses.py:3025``): what to quantize and how."""

    load_in_8bit: bool = False
    load_in_4bit: bool = False
    quant_type: str = "nf4"  # for 4-bit: "nf4" | "fp4"-style linear
    block_size: int = 64
    compute_dtype: Any = jnp.bfloat16
    # leaves are skipped when their path contains any of these substrings
    # (reference skip_modules defaults to lm_head)
    skip_modules: Sequence[str] = field(default_factory=lambda: ("lm_head", "embed"))
    # only quantize matrices at least this big (small norms/bias stay fp)
    min_size: int = 4096

    def __post_init__(self):
        if self.load_in_8bit and self.load_in_4bit:
            raise ValueError("pick one of load_in_8bit / load_in_4bit")
        if not (self.load_in_8bit or self.load_in_4bit):
            raise ValueError("enable load_in_8bit or load_in_4bit")
        if self.load_in_4bit and self.quant_type not in ("nf4", "fp4"):
            raise ValueError(f"unknown 4-bit quant_type {self.quant_type!r}")

    @property
    def bits(self) -> int:
        return 8 if self.load_in_8bit else 4


# ----------------------------------------------------------------- int8 -----
def _lead(shape) -> int:
    """Every ndim ≥ 2 leaf keeps its leading axis through quantization: codes
    and scales get shape (d0, ...), so stacked-per-layer leaves — (L, D, D')
    kernels AND (L, D) vectors — remain sliceable by ``lax.scan`` and shardable
    along dim 0. 1D leaves use flat blocks."""
    return shape[0] if len(shape) >= 2 else 1


def quantize_blockwise_int8(arr, block_size: int = 64):
    """Absmax int8 per contiguous block of the (per-row) flattened array →
    (codes, scales); scale = absmax/127, codes = round(x/scale) ∈ [-127, 127].

    Layout: 1D input → flat 1D codes; ndim ≥ 2 → codes shaped (d0, -1) with
    blocks contained in each leading-axis slice (this diverges from bnb's flat
    stream on purpose: the leading axis stays real, so stacked-per-layer
    leaves slice under ``lax.scan`` and shard along dim 0; the cost is
    per-slice padding when the slice size is not a block multiple).
    """
    arr = jnp.asarray(arr)
    lead = _lead(arr.shape)
    flat = arr.reshape(lead, -1)
    pad = (-flat.shape[1]) % block_size
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    blocks = flat.reshape(lead, -1, block_size).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(blocks), axis=2, keepdims=True)
    scales = (absmax / 127.0).astype(jnp.float32)
    codes = jnp.round(blocks / jnp.where(scales > 0, scales, 1.0))
    codes = jnp.clip(codes, -127, 127).astype(jnp.int8)
    if arr.ndim < 2:
        return codes.reshape(-1), scales.reshape(-1)
    return codes.reshape(lead, -1), scales.reshape(lead, -1)


def dequantize_blockwise_int8(codes, scales, shape, dtype=jnp.bfloat16, block_size: int = 64):
    lead = _lead(shape)
    blocks = codes.reshape(lead, -1, block_size).astype(jnp.float32)
    out = blocks * scales.reshape(lead, -1, 1)
    per_slice = int(np.prod(shape)) // lead
    return out.reshape(lead, -1)[:, :per_slice].reshape(shape).astype(dtype)


# ------------------------------------------------------------------ 4-bit ----
def _codebook(quant_type: str):
    if quant_type == "nf4":
        return jnp.asarray(NF4_CODE)
    # "fp4"-style: 16 evenly spaced levels in [-1, 1]
    return jnp.linspace(-1.0, 1.0, 16, dtype=jnp.float32)


def quantize_blockwise_4bit(arr, block_size: int = 64, quant_type: str = "nf4"):
    """Codebook 4-bit quantization, two codes packed per uint8 → (packed, scales).
    ndim ≥ 3 keeps the leading axis (see :func:`_lead`)."""
    code = _codebook(quant_type)
    arr = jnp.asarray(arr)
    lead = _lead(arr.shape)
    flat = arr.reshape(lead, -1)
    pad = (-flat.shape[1]) % block_size
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    blocks = flat.reshape(lead, -1, block_size).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(blocks), axis=2, keepdims=True)
    scales = jnp.where(absmax > 0, absmax, 1.0).astype(jnp.float32)
    normed = blocks / scales
    # nearest codebook entry
    idx = jnp.argmin(jnp.abs(normed[..., None] - code[None, None, None, :]), axis=-1)
    idx = idx.reshape(lead, -1).astype(jnp.uint8)
    packed = (idx[:, 0::2] << 4) | idx[:, 1::2]
    if arr.ndim < 2:
        return packed.reshape(-1), scales.reshape(-1)
    return packed, scales.reshape(lead, -1)


def dequantize_blockwise_4bit(packed, scales, shape, dtype=jnp.bfloat16,
                              block_size: int = 64, quant_type: str = "nf4"):
    code = _codebook(quant_type)
    lead = _lead(shape)
    packed = packed.reshape(lead, -1)
    hi = (packed >> 4).astype(jnp.int32)
    lo = (packed & 0xF).astype(jnp.int32)
    idx = jnp.stack([hi, lo], axis=2).reshape(lead, -1)
    vals = code[idx].reshape(lead, -1, block_size) * scales.reshape(lead, -1, 1)
    per_slice = int(np.prod(shape)) // lead
    return vals.reshape(lead, -1)[:, :per_slice].reshape(shape).astype(dtype)


# --------------------------------------------------------- QuantizedArray ---
@jax.tree_util.register_pytree_node_class
class QuantizedArray:
    """Quantized weight leaf: int8/packed-uint8 codes + per-block scales.

    A pytree node (codes/scales are the traced children → they stay quantized
    in HBM across jit boundaries) implementing ``__jax_array__``, so any jnp
    op consuming it triggers an on-the-fly dequant that XLA fuses into the
    consumer. ``shape``/``dtype``/``ndim`` mimic the dense array.
    """

    def __init__(self, codes, scales, shape, dtype, bits: int, block_size: int,
                 quant_type: str = "nf4"):
        self.codes = codes
        self.scales = scales
        self.shape = tuple(shape)
        self.dtype = dtype
        self.bits = bits
        self.block_size = block_size
        self.quant_type = quant_type

    # pytree protocol
    def tree_flatten(self):
        return (self.codes, self.scales), (self.shape, self.dtype, self.bits,
                                           self.block_size, self.quant_type)

    @classmethod
    def tree_unflatten(cls, aux, children):
        codes, scales = children
        shape, dtype, bits, block_size, quant_type = aux
        return cls(codes, scales, shape, dtype, bits, block_size, quant_type)

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape))

    @property
    def nbytes_quantized(self) -> int:
        return int(self.codes.size * self.codes.dtype.itemsize
                   + self.scales.size * self.scales.dtype.itemsize)

    def _sliced_shape(self):
        """None for intact leaves; the per-layer shape when ``lax.scan`` has
        sliced the children along the stacked axis (children lose dim 0, the
        static aux shape can't follow). Detected structurally: ndim ≥ 2 leaves
        store 2D codes, so 1D codes mean one slice — works for any stack
        length including L=1."""
        if len(self.shape) >= 2 and self.codes.ndim == 1:
            return self.shape[1:]
        return None

    def dequantize(self, dtype=None):
        dtype = dtype or self.dtype
        if self.quant_type == "int8_kblock":
            return _dequantize_kblock(self, dtype)
        shape = self.shape
        sliced = self._sliced_shape()
        if sliced is not None:
            # one layer's flat block stream — dequantize flat, then reshape
            # (going through shape=sliced directly would recompute a bogus
            # lead from sliced[0])
            n = int(np.prod(sliced))
            shape = (n,)
        if self.bits == 8:
            out = dequantize_blockwise_int8(self.codes, self.scales, shape,
                                            dtype, self.block_size)
        else:
            out = dequantize_blockwise_4bit(self.codes, self.scales, shape,
                                            dtype, self.block_size, self.quant_type)
        return out.reshape(sliced) if sliced is not None else out

    # any jnp consumer sees the dense (dequantized) array; under jit the
    # dequant fuses into the consuming op
    def __jax_array__(self):
        return self.dequantize()

    def astype(self, dtype):
        return self.dequantize(dtype)

    def __matmul__(self, other):
        return self.dequantize() @ other

    def __rmatmul__(self, other):
        return other @ self.dequantize()

    def __repr__(self):
        return (f"QuantizedArray(shape={self.shape}, bits={self.bits}, "
                f"type={self.quant_type if self.bits == 4 else 'int8'}, "
                f"block={self.block_size})")


def quantize(arr, config: QuantizationConfig) -> QuantizedArray:
    arr = jnp.asarray(arr)
    if config.load_in_8bit:
        codes, scales = quantize_blockwise_int8(arr, config.block_size)
        return QuantizedArray(codes, scales, arr.shape, config.compute_dtype, 8,
                              config.block_size)
    packed, scales = quantize_blockwise_4bit(arr, config.block_size, config.quant_type)
    return QuantizedArray(packed, scales, arr.shape, config.compute_dtype, 4,
                          config.block_size, config.quant_type)


def quantize_params(params, config: QuantizationConfig):
    """Quantize every large floating matrix leaf; small/skipped leaves pass
    through (reference ``replace_with_bnb_layers`` replaces nn.Linear modules;
    our params are pytrees so the unit is the leaf).
    """
    counter = [0]

    def _path_str(path) -> str:
        parts = []
        for k in path:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            else:
                parts.append(str(k))
        return "/".join(parts)

    def _maybe_quantize(path, leaf):
        # inspect WITHOUT converting: offloaded host leaves must not be
        # device_put just to be skipped, and disk-offloaded leaves are None
        if leaf is None:
            return None
        dtype = getattr(leaf, "dtype", None)
        ndim = getattr(leaf, "ndim", 0)
        size = int(getattr(leaf, "size", 0))
        skip = any(s in _path_str(path) for s in config.skip_modules)
        is_float = dtype is not None and jnp.issubdtype(dtype, jnp.floating)
        if skip or not is_float or ndim < 2 or size < config.min_size:
            return leaf
        counter[0] += 1
        return quantize(jnp.asarray(leaf), config)

    # tree_map preserves the container types (lists/tuples/dicts) exactly —
    # the result must stay structure-compatible with optimizer/sharding trees
    out = jax.tree_util.tree_map_with_path(
        _maybe_quantize, params, is_leaf=lambda x: x is None
    )
    if counter[0] == 0:
        raise ValueError("nothing was quantized — check skip_modules/min_size")
    return out


def dequantize_params(params, dtype=None):
    """Materialize every QuantizedArray leaf back to dense."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.dequantize(dtype) if isinstance(leaf, QuantizedArray) else leaf,
        params,
        is_leaf=lambda x: isinstance(x, QuantizedArray),
    )


def quantized_byte_size(params) -> int:
    """Total bytes with quantized leaves at their stored size."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedArray)
    ):
        if isinstance(leaf, QuantizedArray):
            total += leaf.nbytes_quantized
        else:
            arr = np.asarray(leaf) if not hasattr(leaf, "nbytes") else leaf
            total += int(arr.nbytes)
    return total


# ----------------------------------------------------- int8 MXU matmul ------
def quantize_int8_matmul_weight(w, block_size: int = 128) -> QuantizedArray:
    """Quantize a 2D (k, n) weight in k-blocked layout for int8×int8 matmuls:
    one scale per (k-block, column), so the contraction can run in int8 with
    exact int32 accumulation and a cheap per-block rescale.

    This differs from the flat storage layout (bnb parity) where blocks run
    along the last axis and cross the contraction dimension.
    """
    w = jnp.asarray(w)
    if w.ndim != 2:
        raise ValueError("k-blocked int8 layout is for 2D weights")
    k, n = w.shape
    pad = (-k) % block_size
    if pad:
        w = jnp.pad(w, ((0, pad), (0, 0)))
    nblk = w.shape[0] // block_size
    blocks = w.reshape(nblk, block_size, n).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)  # (nblk, 1, n)
    scales = (absmax / 127.0).astype(jnp.float32)
    codes = jnp.clip(jnp.round(blocks / jnp.where(scales > 0, scales, 1.0)),
                     -127, 127).astype(jnp.int8)
    return QuantizedArray(codes, scales.reshape(nblk, n), (k, n), jnp.bfloat16, 8,
                          block_size, quant_type="int8_kblock")


def _dequantize_kblock(q: QuantizedArray, dtype):
    k, n = q.shape
    vals = q.codes.astype(jnp.float32) * q.scales[:, None, :]
    return vals.reshape(-1, n)[:k].reshape(k, n).astype(dtype)


def int8_dynamic_matmul(x, w_q: QuantizedArray, preferred_dtype=jnp.bfloat16):
    """Activation-dynamic int8×int8 matmul with exact int32 accumulation.

    ``x`` is absmax-quantized per row at trace time; both operands hit the MXU
    as int8 (its double-throughput mode); the int32 block-partials are rescaled
    by ``x_scale ⊗ w_scale``. Needs a k-blocked weight
    (:func:`quantize_int8_matmul_weight`); anything else falls back to the
    fused dequant-matmul.
    """
    if getattr(w_q, "quant_type", None) != "int8_kblock":
        return jnp.asarray(x) @ w_q.dequantize()
    k, n = w_q.shape
    x = jnp.asarray(x)
    x2 = x.reshape(-1, x.shape[-1]).astype(jnp.float32)
    pad = (-k) % w_q.block_size
    if pad:
        x2 = jnp.pad(x2, ((0, 0), (0, pad)))
    x_absmax = jnp.max(jnp.abs(x2), axis=1, keepdims=True)
    x_scale = jnp.where(x_absmax > 0, x_absmax / 127.0, 1.0)
    x_q = jnp.clip(jnp.round(x2 / x_scale), -127, 127).astype(jnp.int8)
    nblk = w_q.codes.shape[0]
    xb = x_q.reshape(x_q.shape[0], nblk, w_q.block_size)
    acc = jnp.einsum(
        "rbk,bkn->brn", xb, w_q.codes, preferred_element_type=jnp.int32
    )  # (nblk, rows, n) int32 — exact
    out = jnp.sum(acc.astype(jnp.float32) * w_q.scales[:, None, :], axis=0) * x_scale
    return out.reshape(*x.shape[:-1], n).astype(preferred_dtype)
