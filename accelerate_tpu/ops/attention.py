"""Attention ops with pluggable implementations.

The compute core every model routes through — and the swap point for
long-context parallelism (ring attention over ``cp``, Ulysses over ``sp``) and
Pallas flash kernels. The reference reaches flash/SDPA kernels through
transformers (SURVEY.md §2.3); here the kernel boundary is explicit.

Layouts: ``q,k,v: [batch, seq, heads, head_dim]`` (BSHD). GQA supported via
``num_kv_heads <= num_heads`` with head repetition folded into the einsum.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp


def segment_mask(segment_ids: jax.Array) -> jax.Array:
    """[B, S] ids → [B, 1, Sq, Skv] bool allow-mask: attend iff same id.

    The single definition of segment semantics — the xla path and the off-TPU
    kernel fallbacks all build their masks here so the three impls cannot
    drift."""
    return segment_ids[:, None, :, None] == segment_ids[:, None, None, :]


def _repeat_kv(hidden: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, Hkv, D] -> [B, S, Hkv*n_rep, D] (GQA broadcast)."""
    if n_rep == 1:
        return hidden
    b, s, h, d = hidden.shape
    return jnp.broadcast_to(hidden[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,  # [B, 1|H, Sq, Skv] additive or bool
    segment_ids: Optional[jax.Array] = None,  # [B, S] int; padding = 0
    scale: Optional[float] = None,
    impl: str = "auto",
) -> jax.Array:
    """Standard softmax attention, BSHD layout.

    ``impl``:

    - "xla" — einsum, fused by XLA on the MXU. Fastest at short S (the whole
      score tensor is small enough that XLA's fusions win — measured on v5e).
    - "flash" — the streaming Pallas flash kernel; wins once S ≳ 512.
    - "fused" — our single-pass Pallas kernel (``ops.fused_attention``): whole
      score block in VMEM, one kernel for fwd and one for bwd. Within ~20% of
      xla at S=128–256; available for fusion-hostile surrounding graphs.
    - "auto" — picks by measured crossover: flash for S ≥ 512, else xla.

    Masking comes in two forms:

    - ``segment_ids`` — per-token ids for self-attention; position *i* attends
      *j* iff ``segment_ids[b, i] == segment_ids[b, j]``. Encode padding as id
      0 and real tokens as id 1 (or document ids for packed sequences). All
      impls support this form — padded models (BERT + attention_mask) keep
      kernel paths available.
    - ``mask`` — arbitrary [B, 1|H, Sq, Skv] bool/additive mask; forces the
      XLA einsum path (kernels cannot consult a full score-shaped mask).
    """
    if impl == "auto":
        impl = "flash" if mask is None and _flash_supported(q, k) else "xla"
    if impl in ("flash", "fused"):
        if mask is not None:
            raise ValueError(
                f"impl={impl!r} does not support an arbitrary mask (causal and "
                "segment_ids only); use impl='xla', or express padding/packing "
                "as segment_ids"
            )
        if impl == "fused":
            from .fused_attention import fused_attention, fused_supported

            # off-TPU the wrapper falls back to the einsum path, any shape
            if jax.default_backend() == "tpu" and not fused_supported(q, k):
                raise ValueError(
                    f"impl='fused' does not support shapes q={q.shape} k={k.shape} "
                    "(needs Sq == Skv, S a multiple of 128 and ≤ 1024, D a "
                    "multiple of 64 and ≤ 256, q-heads divisible by kv-heads, "
                    "and the per-row score block within VMEM); use impl='xla'"
                )
            return fused_attention(q, k, v, causal=causal, scale=scale, segment_ids=segment_ids)
        from .flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, scale=scale, segment_ids=segment_ids)
    if segment_ids is not None:
        seg_mask = segment_mask(segment_ids)
        if mask is None:
            mask = seg_mask
        elif mask.dtype == bool:
            mask = jnp.logical_and(mask, seg_mask)
        else:  # additive mask: fold the segment constraint in as -inf
            mask = mask + jnp.where(seg_mask, 0.0, jnp.finfo(jnp.float32).min)
    return _xla_attention(q, k, v, causal=causal, mask=mask, scale=scale)


def _flash_supported(q, k) -> bool:
    try:
        if jax.default_backend() != "tpu":
            return False
    except Exception:
        return False
    # flash kernel wants seq multiples of its block size…
    if not (q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0 and q.shape[-1] in (64, 128, 256)):
        return False
    # …and only wins once the [S,S] score matrix stops fitting comfortably:
    # measured on v5e (fwd+bwd, H=12, D=64): S=128 xla is 2.2× faster, S=512
    # break-even, S=2048 flash 1.7× faster. Streaming KV through VMEM only
    # pays past the crossover.
    return k.shape[1] >= 512


def _xla_attention(q, k, v, *, causal, mask, scale):
    *_, sq, hq, d = q.shape
    skv = k.shape[1]
    hkv = k.shape[2]
    if hq != hkv:
        rep = hq // hkv
        k = _repeat_kv(k, rep)
        v = _repeat_kv(v, rep)
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    # compute logits in f32 for stability, inputs may be bf16
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        causal_mask = jnp.tril(jnp.ones((sq, skv), dtype=bool), k=skv - sq)
        logits = jnp.where(causal_mask[None, None], logits, jnp.finfo(jnp.float32).min)
    if mask is not None:
        if mask.dtype == bool:
            logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def make_padding_mask(attention_mask: jax.Array, sq: int) -> jax.Array:
    """[B, Skv] 1/0 padding mask -> [B, 1, Sq, Skv] bool mask."""
    return jnp.broadcast_to(
        attention_mask[:, None, None, :].astype(bool),
        (attention_mask.shape[0], 1, sq, attention_mask.shape[1]),
    )
