"""Attention ops with pluggable implementations.

The compute core every model routes through — and the swap point for
long-context parallelism (ring attention over ``cp``, Ulysses over ``sp``) and
Pallas flash kernels. The reference reaches flash/SDPA kernels through
transformers (SURVEY.md §2.3); here the kernel boundary is explicit.

Layouts: ``q,k,v: [batch, seq, heads, head_dim]`` (BSHD). GQA supported via
``num_kv_heads <= num_heads`` with head repetition folded into the einsum.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp


def segment_mask(segment_ids: jax.Array) -> jax.Array:
    """[B, S] ids → [B, 1, Sq, Skv] bool allow-mask: attend iff same id.

    The single definition of segment semantics — the xla path and the off-TPU
    kernel fallbacks all build their masks here so the three impls cannot
    drift."""
    return segment_ids[:, None, :, None] == segment_ids[:, None, None, :]


def _repeat_kv(hidden: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, Hkv, D] -> [B, S, Hkv*n_rep, D] (GQA broadcast)."""
    if n_rep == 1:
        return hidden
    b, s, h, d = hidden.shape
    return jnp.broadcast_to(hidden[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,  # [B, 1|H, Sq, Skv] additive or bool
    segment_ids: Optional[jax.Array] = None,  # [B, S] int; padding = 0
    scale: Optional[float] = None,
    window: Optional[int] = None,  # sliding window: attend iff 0 <= i-j < window
    impl: str = "auto",
) -> jax.Array:
    """Standard softmax attention, BSHD layout.

    ``impl``:

    - "xla" — einsum, fused by XLA on the MXU. Fastest at short S (the whole
      score tensor is small enough that XLA's fusions win — measured on v5e).
    - "flash" — the in-tree blocked streaming kernel (``ops.flash_attention``):
      online softmax, in-kernel GQA, block-sparse causal/window/segment
      skipping. Wins past the measured crossover (see ``ATTN_CROSSOVER_S``).
    - "fused" — our single-pass Pallas kernel (``ops.fused_attention``): whole
      score block in VMEM, one kernel for fwd and one for bwd. Within ~20% of
      xla at S=128–256; available for fusion-hostile surrounding graphs.
    - "auto" — picks flash vs xla from the measured crossover table
      (``ATTN_CROSSOVER_S``, derived from ``benchmarks/attention/run.py``),
      keyed by dtype and mask sparsity.

    Masking comes in three forms:

    - ``segment_ids`` — per-token ids for self-attention; position *i* attends
      *j* iff ``segment_ids[b, i] == segment_ids[b, j]``. Encode padding as id
      0 and real tokens as id 1 (or document ids for packed sequences). All
      impls support this form — padded models (BERT + attention_mask) keep
      kernel paths available.
    - ``window`` — causal sliding-window band (attend iff ``0 <= i-j <
      window``; requires ``causal=True``). Supported by the xla and flash
      paths; the flash kernel skips out-of-band blocks entirely.
    - ``mask`` — arbitrary [B, 1|H, Sq, Skv] bool/additive mask; forces the
      XLA einsum path (kernels cannot consult a full score-shaped mask).
    """
    if window is not None and not causal:
        raise ValueError(
            "window requires causal=True (the sliding window is a causal band)"
        )
    if impl == "auto":
        impl = (
            "flash"
            if mask is None and _flash_supported(q, k, causal=causal, window=window)
            else "xla"
        )
    if impl in ("flash", "fused"):
        if mask is not None:
            raise ValueError(
                f"impl={impl!r} does not support an arbitrary mask (causal and "
                "segment_ids only); use impl='xla', or express padding/packing "
                "as segment_ids"
            )
        if impl == "fused":
            if window is not None:
                raise ValueError(
                    "impl='fused' does not support window (the short-S single-"
                    "pass kernel has no band masking); use impl='flash' or 'xla'"
                )
            from .fused_attention import fused_attention, fused_supported

            # off-TPU the wrapper falls back to the einsum path, any shape
            if jax.default_backend() == "tpu" and not fused_supported(q, k):
                raise ValueError(
                    f"impl='fused' does not support shapes q={q.shape} k={k.shape} "
                    "(needs Sq == Skv, S a multiple of 128 and ≤ 1024, D a "
                    "multiple of 64 and ≤ 256, q-heads divisible by kv-heads, "
                    "and the per-row score block within VMEM); use impl='xla'"
                )
            return fused_attention(q, k, v, causal=causal, scale=scale, segment_ids=segment_ids)
        from .flash_attention import flash_attention

        return flash_attention(
            q, k, v, causal=causal, scale=scale, segment_ids=segment_ids, window=window
        )
    if segment_ids is not None:
        seg_mask = segment_mask(segment_ids)
        if mask is None:
            mask = seg_mask
        elif mask.dtype == bool:
            mask = jnp.logical_and(mask, seg_mask)
        else:  # additive mask: fold the segment constraint in as -inf
            mask = mask + jnp.where(seg_mask, 0.0, jnp.finfo(jnp.float32).min)
    return _xla_attention(q, k, v, causal=causal, mask=mask, scale=scale, window=window)


# Measured flash-vs-xla crossover (fwd+bwd step time, v5e, B=8, H=12, D=64;
# benchmarks/attention/run.py is the generating grid): the einsum path wins
# below the listed S, the streaming kernel at/after it. Sparser masks move
# the crossover EARLIER — the block-skip lattice drops whole tiles, so the
# kernel's streamed work shrinks while the einsum path still materializes
# (and masks) every score. f32 crosses earlier than bf16 because the f32
# score tensor doubles the einsum path's HBM traffic but the kernel's VMEM
# accumulators are f32 either way.
ATTN_CROSSOVER_S = {
    ("bf16", "dense"): 512,
    ("bf16", "causal"): 384,
    ("bf16", "window"): 256,
    ("f32", "dense"): 384,
    ("f32", "causal"): 256,
    ("f32", "window"): 256,
}


def _flash_supported(q, k, *, causal: bool = False, window: Optional[int] = None) -> bool:
    try:
        if jax.default_backend() != "tpu":
            return False
    except Exception:
        return False
    # flash kernel wants seq multiples of its block size…
    if not (q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0 and q.shape[-1] in (64, 128, 256)):
        return False
    # …and only wins past the measured crossover for this dtype × sparsity
    sparsity = "window" if window is not None else ("causal" if causal else "dense")
    dkey = "bf16" if q.dtype == jnp.bfloat16 else "f32"
    return k.shape[1] >= ATTN_CROSSOVER_S[(dkey, sparsity)]


def _xla_attention(q, k, v, *, causal, mask, scale, window=None):
    *_, sq, hq, d = q.shape
    skv = k.shape[1]
    hkv = k.shape[2]
    if hq != hkv:
        rep = hq // hkv
        k = _repeat_kv(k, rep)
        v = _repeat_kv(v, rep)
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    # compute logits in f32 for stability, inputs may be bf16
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        causal_mask = jnp.tril(jnp.ones((sq, skv), dtype=bool), k=skv - sq)
        logits = jnp.where(causal_mask[None, None], logits, jnp.finfo(jnp.float32).min)
    if window is not None:
        # query i sits at absolute position i + (skv - sq); band: 0 <= i-j < w
        qpos = jnp.arange(sq)[:, None] + (skv - sq)
        kpos = jnp.arange(skv)[None, :]
        band = qpos - kpos < window
        logits = jnp.where(band[None, None], logits, jnp.finfo(jnp.float32).min)
    if mask is not None:
        if mask.dtype == bool:
            logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def make_padding_mask(attention_mask: jax.Array, sq: int) -> jax.Array:
    """[B, Skv] 1/0 padding mask -> [B, 1, Sq, Skv] bool mask."""
    return jnp.broadcast_to(
        attention_mask[:, None, None, :].astype(bool),
        (attention_mask.shape[0], 1, sq, attention_mask.shape[1]),
    )
