"""Attention ops with pluggable implementations.

The compute core every model routes through — and the swap point for
long-context parallelism (ring attention over ``cp``, Ulysses over ``sp``) and
Pallas flash kernels. The reference reaches flash/SDPA kernels through
transformers (SURVEY.md §2.3); here the kernel boundary is explicit.

Layouts: ``q,k,v: [batch, seq, heads, head_dim]`` (BSHD). GQA supported via
``num_kv_heads <= num_heads`` with head repetition folded into the einsum.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp


def _repeat_kv(hidden: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, Hkv, D] -> [B, S, Hkv*n_rep, D] (GQA broadcast)."""
    if n_rep == 1:
        return hidden
    b, s, h, d = hidden.shape
    return jnp.broadcast_to(hidden[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,  # [B, 1|H, Sq, Skv] additive or bool
    scale: Optional[float] = None,
    impl: str = "auto",
) -> jax.Array:
    """Standard softmax attention, BSHD layout.

    ``impl``: "xla" (einsum, fused by XLA on the MXU), "flash" (Pallas kernel,
    TPU), "auto" (flash on TPU when shapes allow, else xla).
    """
    if impl == "auto":
        # the flash kernel has no arbitrary-mask support (causal only)
        impl = "flash" if mask is None and _flash_supported(q, k) else "xla"
    if impl == "flash":
        if mask is not None:
            raise ValueError(
                "impl='flash' does not support an explicit mask (causal only); "
                "use impl='xla' for padding masks"
            )
        from .flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, scale=scale)
    return _xla_attention(q, k, v, causal=causal, mask=mask, scale=scale)


def _flash_supported(q, k) -> bool:
    try:
        if jax.default_backend() != "tpu":
            return False
    except Exception:
        return False
    # flash kernel wants seq multiples of its block size
    return q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0 and q.shape[-1] in (64, 128, 256)


def _xla_attention(q, k, v, *, causal, mask, scale):
    *_, sq, hq, d = q.shape
    skv = k.shape[1]
    hkv = k.shape[2]
    if hq != hkv:
        rep = hq // hkv
        k = _repeat_kv(k, rep)
        v = _repeat_kv(v, rep)
    scale = 1.0 / math.sqrt(d) if scale is None else scale
    # compute logits in f32 for stability, inputs may be bf16
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        causal_mask = jnp.tril(jnp.ones((sq, skv), dtype=bool), k=skv - sq)
        logits = jnp.where(causal_mask[None, None], logits, jnp.finfo(jnp.float32).min)
    if mask is not None:
        if mask.dtype == bool:
            logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def make_padding_mask(attention_mask: jax.Array, sq: int) -> jax.Array:
    """[B, Skv] 1/0 padding mask -> [B, 1, Sq, Skv] bool mask."""
    return jnp.broadcast_to(
        attention_mask[:, None, None, :].astype(bool),
        (attention_mask.shape[0], 1, sq, attention_mask.shape[1]),
    )
