"""Flash attention for TPU: in-tree blocked streaming Pallas kernel.

The reference reaches flash/SDPA CUDA kernels through transformers + torch
(SURVEY.md §2.3 "flash attention / SDPA kernels"). Earlier rounds wrapped the
stock JAX kernel (``jax.experimental.pallas.ops.tpu.flash_attention``); that
wrapper materialized repeated KV in HBM for GQA, supported no sliding-window
or block-sparse masking, and had no interpret mode, so tier-1 never exercised
its dataflow. This module replaces it with an in-tree blocked online-softmax
kernel (fwd + custom_vjp bwd with recompute-from-logsumexp, the pattern
``ops/fused_attention.py`` demonstrates at short S):

- grid ``(B·H, q_blocks, kv_blocks)`` with the kv axis innermost; f32 online
  softmax carried in VMEM scratch across kv steps;
- **in-kernel GQA**: the k/v BlockSpec index maps address the kv-head pool
  directly (``g → b·Hkv + h // groups``), so repeated KV never exists in HBM;
- a **block-sparse mask lattice**: causal, sliding-window and segment/packing
  masks are collapsed into a per-``(q_block, kv_block)`` skip map built at
  trace time (scalar-prefetch, like the paged kernels' block tables). The kv
  index map *clamps* skipped steps onto the previous active block — a repeated
  block index elides the DMA — and ``pl.when`` skips their compute, so fully
  masked blocks are never streamed: long-context cost scales with the lattice
  density, not S².

Dispatch follows the same env contract as the paged serving kernels
(:func:`flash_kernel_mode`, ``ACCELERATE_FLASH_KERNEL``): the kill switch is
the einsum reference (byte-identical to ``impl="xla"``), and interpret mode
drives the exact kernel dataflow through CPU tier-1.
"""

from __future__ import annotations

import math
import os
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


def flash_kernel_mode() -> str:
    """Dispatch mode for :func:`flash_attention`, read once per trace (step
    functions bake it in at compile time — flipping the env var mid-run does
    not retrace warm jit entries):

    - ``"on"`` (default): the in-tree Pallas kernel when the backend is TPU,
      einsum reference everywhere else;
    - ``"off"`` (``ACCELERATE_FLASH_KERNEL=0``): einsum reference always —
      the kill switch, byte-identical to ``impl="xla"``;
    - ``"interpret"`` (``ACCELERATE_FLASH_KERNEL=interpret``): the Pallas
      kernel in interpreter mode on ANY backend — how CPU CI drives the
      kernel's exact dataflow (including the backward) in tier-1."""
    raw = os.environ.get("ACCELERATE_FLASH_KERNEL", "1").strip().lower()
    if raw in ("0", "off", "false"):
        return "off"
    if raw == "interpret":
        return "interpret"
    return "on"


class _FlashConfig(NamedTuple):
    """Static kernel configuration (hashable: rides custom_vjp nondiff)."""

    scale: float
    causal: bool
    window: Optional[int]
    block_q: int
    block_kv: int
    h: int
    hkv: int
    use_seg: bool
    interpret: bool

    @property
    def groups(self) -> int:
        return self.h // self.hkv


def _block_lattice(seg: jax.Array, cfg: _FlashConfig):
    """Per-``(q_block, kv_block)`` active map → (ids, counts) in both
    orientations.

    ``ids[b, qi, :counts[b, qi]]`` lists the kv blocks q block ``qi`` must
    stream, in ascending order; the transposed pair drives the dk/dv kernel.
    Causal and sliding-window activity are pure block-coordinate bands;
    segment activity is an interval-overlap test on per-block id min/max —
    exact for contiguous packing, never-false-negative in general (a q and kv
    block sharing id ``x`` both bracket ``x``). The diagonal block is active
    under every mask (every token attends itself), so counts ≥ 1 and the
    clamped index maps below always have a real block to land on."""
    B, S = seg.shape
    nq, nkv = S // cfg.block_q, S // cfg.block_kv
    qlo = jnp.arange(nq, dtype=jnp.int32) * cfg.block_q
    qhi = qlo + cfg.block_q - 1
    klo = jnp.arange(nkv, dtype=jnp.int32) * cfg.block_kv
    khi = klo + cfg.block_kv - 1
    active = jnp.ones((B, nq, nkv), bool)
    if cfg.causal:
        active &= klo[None, None, :] <= qhi[None, :, None]
    if cfg.window is not None:
        active &= qlo[None, :, None] - khi[None, None, :] < cfg.window
    if cfg.use_seg:
        sq = seg.reshape(B, nq, cfg.block_q)
        skv = seg.reshape(B, nkv, cfg.block_kv)
        qmin, qmax = sq.min(-1), sq.max(-1)
        kmin, kmax = skv.min(-1), skv.max(-1)
        active &= (qmin[:, :, None] <= kmax[:, None, :]) & (
            kmin[:, None, :] <= qmax[:, :, None]
        )

    def order(act):
        # actives first, each side in ascending block order, no stable-sort
        # dependence: inactive keys are offset past every active key
        n = act.shape[-1]
        pos = jnp.arange(n, dtype=jnp.int32)
        key = jnp.where(act, 0, n).astype(jnp.int32) + pos
        return jnp.argsort(key, axis=-1).astype(jnp.int32)

    activeT = active.transpose(0, 2, 1)
    return (
        order(active),
        active.sum(-1).astype(jnp.int32),
        order(activeT),
        activeT.sum(-1).astype(jnp.int32),
    )


def _allow_mask(cfg: _FlashConfig, shape, qi, blk, segq, segkv):
    """Element mask for one (q_block, kv_block) score tile, or None (dense)."""
    preds = []
    if cfg.causal or cfg.window is not None:
        qpos = qi * cfg.block_q + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
        kpos = blk * cfg.block_kv + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        if cfg.causal:
            preds.append(kpos <= qpos)
        if cfg.window is not None:
            preds.append(qpos - kpos < cfg.window)
    if cfg.use_seg:
        preds.append(segq[:, None] == segkv[None, :])
    if not preds:
        return None
    allow = preds[0]
    for p in preds[1:]:
        allow = jnp.logical_and(allow, p)
    return allow


def _dot_nt2(a, b):  # [M, K] × [N, K] → [M, N], f32 accumulate
    return jax.lax.dot_general(
        a, b, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot_nn2(a, b):  # [M, K] × [K, N] → [M, N], f32 accumulate
    return jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot_tn2(a, b):  # [K, M] × [K, N] → [M, N], f32 accumulate
    return jax.lax.dot_general(
        a, b, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _flash_fwd_kernel(
    ids_ref,     # [B, nq, nkv] int32 scalar-prefetch: active kv blocks per q block
    counts_ref,  # [B, nq]      int32 scalar-prefetch: how many are active
    q_ref,       # [1, bq, D]       this (head, q-block) tile
    k_ref,       # [1, bkv, D]      the kv block the clamped index map selected
    v_ref,       # [1, bkv, D]
    segq_ref,    # [1, bq] int32
    segkv_ref,   # [1, bkv] int32
    o_ref,       # [1, bq, D]
    lse_ref,     # [1, bq] f32
    acc_ref,     # VMEM [bq, D] f32   online-softmax accumulators,
    m_ref,       # VMEM [bq, 1] f32   carried across the kv grid steps
    l_ref,       # VMEM [bq, 1] f32
    *,
    cfg: _FlashConfig,
):
    """One (head, q_block, kv_step) grid step of blocked streaming flash.

    The kv axis is innermost; ``t`` walks this q block's *active-block list*
    (``ids[b, qi, t]``), not the raw kv range. Steps past ``counts[b, qi]``
    repeat the last active block (the index map clamps, so the DMA is elided)
    and skip their compute via ``pl.when`` — that is the whole block-sparsity
    mechanism. Within an active block, causal/window/segment masking is
    recomputed per element from positions and the streamed segment-id tiles;
    masked lanes go to ``-inf`` and the running max's shift is clamped so a
    fully masked prefix never turns into NaN (same trick as the paged
    kernels)."""
    from jax.experimental import pallas as pl  # deferred with pallas_call's

    g, qi, t = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    b = g // cfg.h
    count = counts_ref[b, qi]

    @pl.when(t == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(t < count)
    def _step():
        blk = ids_ref[b, qi, t]
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = _dot_nt2(q, k) * cfg.scale  # [bq, bkv] f32
        allow = _allow_mask(cfg, s.shape, qi, blk, segq_ref[0], segkv_ref[0])
        if allow is not None:
            s = jnp.where(allow, s, -jnp.inf)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # a fully-masked prefix keeps m at -inf: exp(-inf - -inf) would be
        # NaN, so clamp the shift (everything is 0-weighted anyway)
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        alpha = jnp.exp(m_prev - shift)
        p = jnp.exp(s - shift)  # [bq, bkv] f32, masked -> 0
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + _dot_nn2(p.astype(v.dtype), v)
        m_ref[...] = m_new

    @pl.when(t == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:, 0] + jnp.log(l_ref[:, 0])


def _flash_dq_kernel(
    ids_ref, counts_ref,
    q_ref,      # [1, bq, D]
    k_ref,      # [1, bkv, D]
    v_ref,      # [1, bkv, D]
    segq_ref, segkv_ref,
    lse_ref,    # [1, bq] f32
    delta_ref,  # [1, bq] f32: sum(do * o) per row, precomputed
    do_ref,     # [1, bq, D]
    dq_ref,     # [1, bq, D]
    dq_acc_ref,  # VMEM [bq, D] f32
    *,
    cfg: _FlashConfig,
):
    """dq kernel: same grid and lattice walk as the forward, recomputing
    probabilities from the saved logsumexp (``p = exp(s - lse)``) instead of
    re-running the online softmax — the fused_attention recompute pattern,
    blocked."""
    from jax.experimental import pallas as pl

    g, qi, t = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    b = g // cfg.h
    count = counts_ref[b, qi]

    @pl.when(t == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    @pl.when(t < count)
    def _step():
        blk = ids_ref[b, qi, t]
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = _dot_nt2(q, k) * cfg.scale
        allow = _allow_mask(cfg, s.shape, qi, blk, segq_ref[0], segkv_ref[0])
        if allow is not None:
            s = jnp.where(allow, s, -jnp.inf)
        p = jnp.exp(s - lse_ref[0][:, None])  # [bq, bkv] f32, masked -> 0
        dp = _dot_nt2(do, v)                  # [bq, bkv] f32
        ds = p * (dp - delta_ref[0][:, None])
        dq_acc_ref[...] += _dot_nn2(ds.astype(k.dtype), k) * cfg.scale

    @pl.when(t == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _flash_dkdv_kernel(
    idsT_ref,     # [B, nkv, nq] int32: active q blocks per kv block
    countsT_ref,  # [B, nkv]     int32
    q_ref,        # [1, bq, D]   q block of group member r = t % groups
    do_ref,       # [1, bq, D]
    k_ref,        # [1, bkv, D]  this kv head's block
    v_ref,        # [1, bkv, D]
    segq_ref, segkv_ref,
    lse_ref,      # [1, bq] f32
    delta_ref,    # [1, bq] f32
    dk_ref,       # [1, bkv, D]
    dv_ref,       # [1, bkv, D]
    dk_acc_ref,   # VMEM [bkv, D] f32
    dv_acc_ref,   # VMEM [bkv, D] f32
    *,
    cfg: _FlashConfig,
):
    """dk/dv kernel: grid ``(B·Hkv, kv_blocks, q_steps·groups)`` — one program
    per *kv head*, streaming every (active q block × GQA group member) pair
    through the transposed lattice and accumulating the group-summed dk/dv in
    VMEM. The GQA reduction happens here, in-kernel: per-q-head dk/dv and
    repeated KV never exist in HBM."""
    from jax.experimental import pallas as pl

    a, j, t = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    b = a // cfg.hkv
    qidx = t // cfg.groups
    count = countsT_ref[b, j]

    @pl.when(t == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    @pl.when(qidx < count)
    def _step():
        qb = idsT_ref[b, j, qidx]
        q = q_ref[0]
        do = do_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = _dot_nt2(q, k) * cfg.scale  # [bq, bkv] f32
        allow = _allow_mask(cfg, s.shape, qb, j, segq_ref[0], segkv_ref[0])
        if allow is not None:
            s = jnp.where(allow, s, -jnp.inf)
        p = jnp.exp(s - lse_ref[0][:, None])  # [bq, bkv] f32
        dv_acc_ref[...] += _dot_tn2(p.astype(do.dtype), do)   # pᵀ do
        dp = _dot_nt2(do, v)
        ds = p * (dp - delta_ref[0][:, None])
        dk_acc_ref[...] += _dot_tn2(ds.astype(q.dtype), q) * cfg.scale

    @pl.when(t == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _clamped_block(ids, counts, b, qi, t):
    """Index-map helper: step t of q block qi, clamped onto the last active
    block once t runs past the active count — the repeated block index is what
    lets Mosaic elide the DMA for skipped steps."""
    return ids[b, qi, jnp.minimum(t, jnp.maximum(counts[b, qi] - 1, 0))]


def _flash_pallas_call(kernel, cfg, grid, in_specs, out_specs, out_shape, scratch):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # lattice ids + counts
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=cfg.interpret
    )


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flash_call(q3, k3, v3, seg, cfg):
    out, _ = _flash_call_fwd(q3, k3, v3, seg, cfg)
    return out


def _flash_call_fwd(q3, k3, v3, seg, cfg):
    """q3 [B·H, S, D]; k3/v3 [B·Hkv, S, D]; seg [B, S] int32."""
    from jax.experimental import pallas as pl

    BH, S, D = q3.shape
    H, Hkv, groups = cfg.h, cfg.hkv, cfg.groups
    bq, bkv = cfg.block_q, cfg.block_kv
    nq, nkv = S // bq, S // bkv
    ids, counts, _, _ = _block_lattice(seg, cfg)

    def kv_batch(g):
        return (g // H) * Hkv + (g % H) // groups

    in_specs = [
        pl.BlockSpec((1, bq, D), lambda g, qi, t, ids, cnt: (g, qi, 0)),
        pl.BlockSpec(
            (1, bkv, D),
            lambda g, qi, t, ids, cnt: (
                kv_batch(g), _clamped_block(ids, cnt, g // H, qi, t), 0),
        ),
        pl.BlockSpec(
            (1, bkv, D),
            lambda g, qi, t, ids, cnt: (
                kv_batch(g), _clamped_block(ids, cnt, g // H, qi, t), 0),
        ),
        pl.BlockSpec((1, bq), lambda g, qi, t, ids, cnt: (g // H, qi)),
        pl.BlockSpec(
            (1, bkv),
            lambda g, qi, t, ids, cnt: (
                g // H, _clamped_block(ids, cnt, g // H, qi, t)),
        ),
    ]
    out_specs = [
        pl.BlockSpec((1, bq, D), lambda g, qi, t, ids, cnt: (g, qi, 0)),
        pl.BlockSpec((1, bq), lambda g, qi, t, ids, cnt: (g, qi)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
        jax.ShapeDtypeStruct((BH, S), jnp.float32),
    ]
    from jax.experimental.pallas import tpu as pltpu

    scratch = [
        pltpu.VMEM((bq, D), jnp.float32),
        pltpu.VMEM((bq, 1), jnp.float32),
        pltpu.VMEM((bq, 1), jnp.float32),
    ]
    out, lse = _flash_pallas_call(
        partial(_flash_fwd_kernel, cfg=cfg),
        cfg, (BH, nq, nkv), in_specs, out_specs, out_shape, scratch,
    )(ids, counts, q3, k3, v3, seg, seg)
    return out, (q3, k3, v3, seg, lse, out)


def _flash_call_bwd(cfg, res, do):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    q3, k3, v3, seg, lse, out = res
    BH, S, D = q3.shape
    H, Hkv, groups = cfg.h, cfg.hkv, cfg.groups
    bq, bkv = cfg.block_q, cfg.block_kv
    nq, nkv = S // bq, S // bkv
    B = BH // H
    ids, counts, idsT, countsT = _block_lattice(seg, cfg)
    # delta = Σ_d do·o per row: elementwise, O(S·D) — no score-shaped tensor
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    def kv_batch(g):
        return (g // H) * Hkv + (g % H) // groups

    q_spec = pl.BlockSpec((1, bq, D), lambda g, qi, t, ids, cnt: (g, qi, 0))
    kv_spec = pl.BlockSpec(
        (1, bkv, D),
        lambda g, qi, t, ids, cnt: (
            kv_batch(g), _clamped_block(ids, cnt, g // H, qi, t), 0),
    )
    row_spec = pl.BlockSpec((1, bq), lambda g, qi, t, ids, cnt: (g, qi))
    dq = _flash_pallas_call(
        partial(_flash_dq_kernel, cfg=cfg),
        cfg,
        (BH, nq, nkv),
        [
            q_spec,
            kv_spec,
            kv_spec,
            pl.BlockSpec((1, bq), lambda g, qi, t, ids, cnt: (g // H, qi)),
            pl.BlockSpec(
                (1, bkv),
                lambda g, qi, t, ids, cnt: (
                    g // H, _clamped_block(ids, cnt, g // H, qi, t)),
            ),
            row_spec,
            row_spec,
            q_spec,
        ],
        [q_spec],
        [jax.ShapeDtypeStruct((BH, S, D), q3.dtype)],
        [pltpu.VMEM((bq, D), jnp.float32)],
    )(ids, counts, q3, k3, v3, seg, seg, lse, delta, do)[0]

    # transposed walk: per kv head, stream (active q block × group member)
    # pairs; t enumerates them with the member index fastest
    def q_batch(a, t):
        return (a // Hkv) * H + (a % Hkv) * groups + t % groups

    qT_spec = pl.BlockSpec(
        (1, bq, D),
        lambda a, j, t, ids, cnt: (
            q_batch(a, t),
            _clamped_block(ids, cnt, a // Hkv, j, t // groups),
            0,
        ),
    )
    rowT_spec = pl.BlockSpec(
        (1, bq),
        lambda a, j, t, ids, cnt: (
            q_batch(a, t),
            _clamped_block(ids, cnt, a // Hkv, j, t // groups),
        ),
    )
    kvT_spec = pl.BlockSpec((1, bkv, D), lambda a, j, t, ids, cnt: (a, j, 0))
    dk, dv = _flash_pallas_call(
        partial(_flash_dkdv_kernel, cfg=cfg),
        cfg,
        (B * Hkv, nkv, nq * groups),
        [
            qT_spec,
            qT_spec,
            kvT_spec,
            kvT_spec,
            pl.BlockSpec(
                (1, bq),
                lambda a, j, t, ids, cnt: (
                    a // Hkv,
                    _clamped_block(ids, cnt, a // Hkv, j, t // groups),
                ),
            ),
            pl.BlockSpec((1, bkv), lambda a, j, t, ids, cnt: (a // Hkv, j)),
            rowT_spec,
            rowT_spec,
        ],
        [kvT_spec, kvT_spec],
        [
            jax.ShapeDtypeStruct((B * Hkv, S, D), k3.dtype),
            jax.ShapeDtypeStruct((B * Hkv, S, D), v3.dtype),
        ],
        [pltpu.VMEM((bkv, D), jnp.float32), pltpu.VMEM((bkv, D), jnp.float32)],
    )(idsT, countsT, q3, do, k3, v3, seg, seg, lse, delta)
    return dq, dk, dv, None


_flash_call.defvjp(_flash_call_fwd, _flash_call_bwd)


def _reference_attention(q, k, v, *, causal, scale, segment_ids, window):
    """The einsum reference: the ``"off"`` kill switch and the off-TPU path.
    Byte-identical to ``dot_product_attention(..., impl="xla")`` — both call
    :func:`ops.attention._xla_attention` with the same mask construction."""
    from .attention import _xla_attention, segment_mask

    mask = segment_mask(segment_ids) if segment_ids is not None else None
    return _xla_attention(q, k, v, causal=causal, mask=mask, scale=scale, window=window)


def flash_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,  # [B, S] int; padding = 0
    window: Optional[int] = None,  # sliding window: attend iff 0 <= i-j < window
    block_q: int = 128,
    block_kv: int = 128,
) -> jax.Array:
    """Blocked streaming flash attention (BSHD in/out), fwd + bwd.

    ``segment_ids`` gates attention to same-id pairs — the kernel-native form
    of padding/packing masks; ``window`` adds a causal sliding-window band
    (requires ``causal=True``). Both feed the block-skip lattice, so fully
    masked (q_block, kv_block) tiles cost nothing. Dispatch is governed by
    :func:`flash_kernel_mode`; shapes the blocked kernel cannot tile
    (cross-attention, S not a multiple of the block size) fall back to the
    einsum reference."""
    if window is not None:
        if not causal:
            raise ValueError(
                "window requires causal=True (the sliding window is a causal band)"
            )
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    B, Sq, H, D = q.shape
    Skv, Hkv = k.shape[1], k.shape[2]
    if H % Hkv:
        raise ValueError(f"q heads {H} not a multiple of kv heads {Hkv}")
    sm_scale = 1.0 / math.sqrt(D) if scale is None else float(scale)

    mode = flash_kernel_mode()
    use_kernel = mode == "interpret" or (mode == "on" and jax.default_backend() == "tpu")
    bq, bkv = min(block_q, Sq), min(block_kv, Skv)
    tileable = Sq == Skv and Sq % bq == 0 and Skv % bkv == 0
    if not (use_kernel and tileable):
        return _reference_attention(
            q, k, v, causal=causal, scale=scale, segment_ids=segment_ids, window=window
        )

    cfg = _FlashConfig(
        scale=sm_scale,
        causal=causal,
        window=window,
        block_q=bq,
        block_kv=bkv,
        h=H,
        hkv=Hkv,
        use_seg=segment_ids is not None,
        interpret=mode == "interpret",
    )
    seg = (
        segment_ids.astype(jnp.int32)
        if segment_ids is not None
        else jnp.zeros((B, Sq), jnp.int32)
    )
    # BSHD → flat [B·H, S, D]; layout-only, no repeated KV
    q3 = q.transpose(0, 2, 1, 3).reshape(B * H, Sq, D)
    k3 = k.transpose(0, 2, 1, 3).reshape(B * Hkv, Skv, D)
    v3 = v.transpose(0, 2, 1, 3).reshape(B * Hkv, Skv, D)
    out = _flash_call(q3, k3, v3, seg, cfg)
    return out.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)


def paged_kernel_mode() -> str:
    """Dispatch mode for :func:`paged_attention`, read once per trace (the
    engine's step functions bake it in at compile time — flipping the env var
    mid-run does not retrace warm jit entries):

    - ``"on"`` (default): Pallas decode kernel when the backend is TPU,
      gather reference everywhere else;
    - ``"off"`` (``ACCELERATE_PAGED_KERNEL=0``): gather reference always —
      the kill switch, byte-identical to the pre-kernel engine;
    - ``"interpret"`` (``ACCELERATE_PAGED_KERNEL=interpret``): the Pallas
      kernel in interpreter mode on ANY backend — how CPU CI drives the
      kernel's exact dataflow through the full engine."""
    raw = os.environ.get("ACCELERATE_PAGED_KERNEL", "1").strip().lower()
    if raw in ("0", "off", "false"):
        return "off"
    if raw == "interpret":
        return "interpret"
    return "on"


def _paged_decode_kernel(
    tables_ref,  # [B, W] int32 scalar-prefetch (drives the k/v index maps)
    lens_ref,    # [B]    int32 scalar-prefetch: per-row live kv length
    q_ref,       # [1, H, D]            this row's query
    k_ref,       # [1, block_size, Hkv, D]  the block the index map selected
    v_ref,       # [1, block_size, Hkv, D]
    o_ref,       # [1, H, D]
    acc_ref,     # VMEM [H, D] f32      online-softmax accumulators,
    m_ref,       # VMEM [H, 1] f32      carried across the W grid steps
    l_ref,       # VMEM [H, 1] f32
    *,
    block_size: int,
    groups: int,
    scale: float,
):
    """One (row, logical-block) grid step of paged flash decode.

    The grid is ``(B, W)`` with the block axis innermost; the BlockSpec index
    maps already DMA'd physical block ``tables[b, w]`` of each pool into VMEM
    — the kernel never sees the pool, only one streamed block — so the body
    is plain online softmax: rescale the running (max, sum, acc) by the new
    block's contribution and normalize on the last block. Padded table
    entries point at the null block and their positions exceed the row's
    live length, so the same position mask that makes the gather reference
    exact silences them here. All math is f32 on the VPU: decode attention
    is bandwidth-bound (one query row per block), so streaming, not the MXU,
    is what this kernel buys."""
    from jax.experimental import pallas as pl  # deferred with pallas_call's

    w = pl.program_id(1)

    @pl.when(w == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32) * scale           # [H, D]
    k = k_ref[0].astype(jnp.float32)                   # [bs, Hkv, D]
    v = v_ref[0].astype(jnp.float32)
    if groups > 1:  # GQA: every q head in a group reads its kv head's block
        bs, hkv, d = k.shape
        k = jnp.broadcast_to(k[:, :, None, :], (bs, hkv, groups, d)).reshape(bs, -1, d)
        v = jnp.broadcast_to(v[:, :, None, :], (bs, hkv, groups, d)).reshape(bs, -1, d)
    # s[h, j] = q[h] . k[j, h] — broadcast-multiply-reduce on the VPU (one
    # query row per head: an MXU matmul would be all padding)
    s = jnp.sum(q[:, None, :] * k.transpose(1, 0, 2), axis=-1)  # [H, bs]
    pos = w * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(pos < lens_ref[pl.program_id(0)], s, -jnp.inf)

    m_prev, l_prev = m_ref[...], l_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))  # [H, 1]
    # a fully-masked prefix of blocks keeps m at -inf: exp(-inf - -inf) would
    # be NaN, so clamp the shift (everything is 0-weighted anyway)
    shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.exp(m_prev - shift)                    # [H, 1]
    p = jnp.exp(s - shift)                             # [H, bs], masked -> 0
    l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jnp.sum(
        p[:, :, None] * v.transpose(1, 0, 2), axis=1
    )
    m_ref[...] = m_new
    l_ref[...] = l_new

    @pl.when(w == pl.num_programs(1) - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def paged_attention_decode(
    q, k_pool, v_pool, block_tables, kv_lens, scale=None, *, interpret=False
):
    """Pallas paged flash-attention decode: q ``[B, 1, H, D]`` against
    per-layer pools ``[num_blocks, block_size, Hkv, D]`` through
    ``block_tables [B, W]``, with ragged per-row live lengths ``kv_lens
    [B]``. Walks each row's block table and streams the referenced KV blocks
    through VMEM with online softmax — the gathered ``[B, W*block_size]``
    cache the XLA reference materializes per layer never exists.
    ``interpret=True`` runs the identical kernel through the Pallas
    interpreter (the CPU parity path in tier-1 CI)."""
    from jax.experimental import pallas as pl_  # deferred: CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    if S != 1:
        raise ValueError(f"decode kernel wants S=1 queries, got S={S}")
    num_blocks, block_size, Hkv, _ = k_pool.shape
    W = block_tables.shape[1]
    if H % Hkv:
        raise ValueError(f"q heads {H} not a multiple of kv heads {Hkv}")
    sm_scale = (1.0 / math.sqrt(D)) if scale is None else float(scale)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # block tables + lengths
        grid=(B, W),
        in_specs=[
            pl_.BlockSpec((1, H, D), lambda b, w, tables, lens: (b, 0, 0)),
            pl_.BlockSpec(
                (1, block_size, Hkv, D),
                lambda b, w, tables, lens: (tables[b, w], 0, 0, 0),
            ),
            pl_.BlockSpec(
                (1, block_size, Hkv, D),
                lambda b, w, tables, lens: (tables[b, w], 0, 0, 0),
            ),
        ],
        out_specs=pl_.BlockSpec((1, H, D), lambda b, w, tables, lens: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, D), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
        ],
    )
    kernel = partial(
        _paged_decode_kernel,
        block_size=block_size,
        groups=H // Hkv,
        scale=sm_scale,
    )
    out = pl_.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        jnp.asarray(kv_lens, jnp.int32).reshape(B),
        q[:, 0],
        k_pool,
        v_pool,
    )
    return out[:, None]  # [B, 1, H, D], the caller's BSHD contract


def _paged_prefill_kernel(
    tables_ref,  # [B, W] int32 scalar-prefetch (drives the k/v index maps)
    qpos_ref,    # [B, S] int32 scalar-prefetch: absolute position of each query
    q_ref,       # [1, S, H, D]             this row's chunk of queries
    k_ref,       # [1, block_size, Hkv, D]  the block the index map selected
    v_ref,       # [1, block_size, Hkv, D]
    o_ref,       # [1, S, H, D]
    acc_ref,     # VMEM [H, S, D] f32   online-softmax accumulators,
    m_ref,       # VMEM [H, S, 1] f32   carried across the W grid steps
    l_ref,       # VMEM [H, S, 1] f32
    *,
    block_size: int,
    groups: int,
    scale: float,
):
    """One (row, logical-block) grid step of paged chunked-prefill attention.

    Same shape of walk as :func:`_paged_decode_kernel` — grid ``(B, W)``,
    block axis innermost, BlockSpec index maps DMA physical block
    ``tables[b, w]`` into VMEM — but with ``S > 1`` queries per row, so the
    score/PV contractions are real ``[H, S, d] x [H, d, bs]`` matmuls on the
    MXU (``dot_general`` batched over heads) instead of the decode kernel's
    VPU broadcast-reduce. Causality inside the chunk and raggedness against
    previously-landed KV collapse into ONE predicate: the engine scatter-
    writes the chunk's own KV into the pool *before* attention, so every KV
    position — old blocks and the chunk's own tokens alike — is live in the
    walked blocks, and masking ``kv_pos <= q_position`` per query reproduces
    the gather reference exactly (null-padded table entries sit at positions
    past every query and are silenced by the same predicate)."""
    from jax.experimental import pallas as pl  # deferred with pallas_call's

    b, w = pl.program_id(0), pl.program_id(1)

    @pl.when(w == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32) * scale           # [S, H, D]
    k = k_ref[0].astype(jnp.float32)                   # [bs, Hkv, D]
    v = v_ref[0].astype(jnp.float32)
    if groups > 1:  # GQA: every q head in a group reads its kv head's block
        bs, hkv, d = k.shape
        k = jnp.broadcast_to(k[:, :, None, :], (bs, hkv, groups, d)).reshape(bs, -1, d)
        v = jnp.broadcast_to(v[:, :, None, :], (bs, hkv, groups, d)).reshape(bs, -1, d)
    qh = q.transpose(1, 0, 2)                          # [H, S, D]
    kh = k.transpose(1, 0, 2)                          # [H, bs, D]
    vh = v.transpose(1, 0, 2)                          # [H, bs, D]
    # s[h, i, j] = q[i, h] . k[j, h] — an MXU matmul batched over heads (the
    # chunk gives the systolic array S real rows, unlike decode's single one)
    s = jax.lax.dot_general(
        qh, kh, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
    )                                                  # [H, S, bs]
    pos = w * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    s = jnp.where(pos <= qpos_ref[b][None, :, None], s, -jnp.inf)

    m_prev, l_prev = m_ref[...], l_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))  # [H, S, 1]
    # a fully-masked prefix of blocks keeps m at -inf: exp(-inf - -inf) would
    # be NaN, so clamp the shift (everything is 0-weighted anyway)
    shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.exp(m_prev - shift)                    # [H, S, 1]
    p = jnp.exp(s - shift)                             # [H, S, bs], masked -> 0
    l_new = l_prev * alpha + jnp.sum(p, axis=2, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p, vh, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
    )
    m_ref[...] = m_new
    l_ref[...] = l_new

    @pl.when(w == pl.num_programs(1) - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] / l_ref[...]).transpose(1, 0, 2).astype(o_ref.dtype)


def paged_attention_prefill(
    q, k_pool, v_pool, block_tables, q_positions, scale=None, *, interpret=False
):
    """Pallas paged chunked-prefill attention: q ``[B, S, H, D]`` (``S > 1``)
    against per-layer pools ``[num_blocks, block_size, Hkv, D]`` through
    ``block_tables [B, W]``, with per-query absolute positions
    ``q_positions [B, S]``. The engine has already scatter-written the
    chunk's own KV into the pool, so one walk over each row's block table
    covers both the previously-landed KV and the in-chunk causal part; the
    per-query position mask is what makes the online softmax match the
    gather reference's causal masking bit for bit. The gathered
    ``[B, W*block_size]`` cache the XLA reference materializes per layer
    never exists. ``interpret=True`` runs the identical kernel through the
    Pallas interpreter (the CPU parity path in tier-1 CI)."""
    from jax.experimental import pallas as pl_  # deferred: CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    if S < 2:
        raise ValueError(f"prefill kernel wants S>1 queries, got S={S}")
    num_blocks, block_size, Hkv, _ = k_pool.shape
    W = block_tables.shape[1]
    if H % Hkv:
        raise ValueError(f"q heads {H} not a multiple of kv heads {Hkv}")
    sm_scale = (1.0 / math.sqrt(D)) if scale is None else float(scale)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # block tables + per-query positions
        grid=(B, W),
        in_specs=[
            pl_.BlockSpec((1, S, H, D), lambda b, w, tables, qpos: (b, 0, 0, 0)),
            pl_.BlockSpec(
                (1, block_size, Hkv, D),
                lambda b, w, tables, qpos: (tables[b, w], 0, 0, 0),
            ),
            pl_.BlockSpec(
                (1, block_size, Hkv, D),
                lambda b, w, tables, qpos: (tables[b, w], 0, 0, 0),
            ),
        ],
        out_specs=pl_.BlockSpec((1, S, H, D), lambda b, w, tables, qpos: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, S, D), jnp.float32),
            pltpu.VMEM((H, S, 1), jnp.float32),
            pltpu.VMEM((H, S, 1), jnp.float32),
        ],
    )
    kernel = partial(
        _paged_prefill_kernel,
        block_size=block_size,
        groups=H // Hkv,
        scale=sm_scale,
    )
    return pl_.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, S, H, D), q.dtype),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        jnp.asarray(q_positions, jnp.int32).reshape(B, S),
        q,
        k_pool,
        v_pool,
    )


def paged_attention(q, k_pool, v_pool, block_tables, q_positions, scale=None):
    """Paged attention for the serving engine (kernel dispatch point).

    q ``[B, S, H, D]``; per-layer pools ``[num_blocks, block_size, Hkv, D]``;
    ``block_tables [B, W]`` (physical block ids, null-padded); ``q_positions
    [B, S]``. On the TPU backend BOTH serving shapes dispatch to Pallas
    paged kernels: single-token decode (``S == 1``) to
    :func:`paged_attention_decode` and chunked prefill / multi-token verify
    (``S > 1``) to :func:`paged_attention_prefill` — block-table walk + VMEM
    block streaming + online softmax, no materialized gathered KV per layer.
    Everywhere else — non-TPU backends and the ``ACCELERATE_PAGED_KERNEL=0``
    kill switch — runs the XLA reference path (``serving.kv_pager.
    paged_attention``: gather blocks by table, shared masked-attention core
    — bitwise-identical to contiguous decode), exactly like
    :func:`flash_attention`'s pallas-vs-xla split.
    ``ACCELERATE_PAGED_KERNEL=interpret`` forces the kernels (interpreter
    mode) on any backend so CPU CI can drive the kernel dataflow through
    the full engine."""
    mode = paged_kernel_mode()
    if mode != "off":
        interpret = mode == "interpret"
        if interpret or jax.default_backend() == "tpu":
            if q.shape[1] == 1:
                return paged_attention_decode(
                    q, k_pool, v_pool, block_tables, q_positions[:, 0] + 1,
                    scale, interpret=interpret,
                )
            return paged_attention_prefill(
                q, k_pool, v_pool, block_tables, q_positions,
                scale, interpret=interpret,
            )
    from ..serving.kv_pager import paged_attention as _xla_paged

    return _xla_paged(q, k_pool, v_pool, block_tables, q_positions, scale)
