"""Flash attention for TPU: Pallas-kernel path with XLA fallback.

The reference reaches flash/SDPA CUDA kernels through transformers + torch
(SURVEY.md §2.3 "flash attention / SDPA kernels"); the TPU-native equivalent is
the Pallas flash kernel that ships with JAX
(``jax.experimental.pallas.ops.tpu.flash_attention``) — blocked online-softmax
attention that streams KV through VMEM instead of materializing the [S, S]
score matrix in HBM. We wrap it behind the framework's BSHD layout and GQA
conventions so models/CP kernels can swap implementations freely.
"""

from __future__ import annotations

import math
import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


def flash_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,  # [B, S] int; padding = 0
    block_q: int = 512,
    block_kv: int = 512,
) -> jax.Array:
    """Pallas flash attention (TPU), BSHD in/out. Falls back to the XLA einsum
    path off-TPU or for unsupported shapes.

    ``segment_ids`` gates attention to same-id pairs — the kernel-native form
    of padding/packing masks (``pallas...flash_attention`` ``SegmentIds``), so
    masked models need not fall back to the einsum path (round-2 verdict: the
    headline bench ran with the flash kernel idle because of this)."""
    if jax.default_backend() != "tpu":
        from .attention import _xla_attention, segment_mask

        mask = segment_mask(segment_ids) if segment_ids is not None else None
        return _xla_attention(q, k, v, causal=causal, mask=mask, scale=scale)

    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        SegmentIds,
        flash_attention as pallas_flash,
    )

    orig_dtype = q.dtype
    hq, hkv = q.shape[2], k.shape[2]
    if hq != hkv:
        from .attention import _repeat_kv

        k = _repeat_kv(k, hq // hkv)
        v = _repeat_kv(v, hq // hkv)
    # BSHD -> BHSD
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    sm_scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    sq, skv = qt.shape[2], kt.shape[2]
    block_sizes = BlockSizes(
        block_q=min(block_q, sq),
        block_k_major=min(block_kv, skv),
        block_k=min(block_kv, skv),
        block_b=1,
        block_q_major_dkv=min(block_q, sq),
        block_k_major_dkv=min(block_kv, skv),
        block_k_dkv=min(block_kv, skv),
        block_q_dkv=min(block_q, sq),
        block_k_major_dq=min(block_kv, skv),
        block_k_dq=min(block_kv, skv),
        block_q_dq=min(block_q, sq),
    )
    seg = None
    if segment_ids is not None:
        seg = SegmentIds(q=segment_ids.astype(jnp.int32), kv=segment_ids.astype(jnp.int32))
    out = pallas_flash(
        qt, kt, vt, segment_ids=seg, causal=causal, sm_scale=sm_scale, block_sizes=block_sizes
    )
    return out.transpose(0, 2, 1, 3).astype(orig_dtype)


def paged_kernel_mode() -> str:
    """Dispatch mode for :func:`paged_attention`, read once per trace (the
    engine's step functions bake it in at compile time — flipping the env var
    mid-run does not retrace warm jit entries):

    - ``"on"`` (default): Pallas decode kernel when the backend is TPU,
      gather reference everywhere else;
    - ``"off"`` (``ACCELERATE_PAGED_KERNEL=0``): gather reference always —
      the kill switch, byte-identical to the pre-kernel engine;
    - ``"interpret"`` (``ACCELERATE_PAGED_KERNEL=interpret``): the Pallas
      kernel in interpreter mode on ANY backend — how CPU CI drives the
      kernel's exact dataflow through the full engine."""
    raw = os.environ.get("ACCELERATE_PAGED_KERNEL", "1").strip().lower()
    if raw in ("0", "off", "false"):
        return "off"
    if raw == "interpret":
        return "interpret"
    return "on"


def _paged_decode_kernel(
    tables_ref,  # [B, W] int32 scalar-prefetch (drives the k/v index maps)
    lens_ref,    # [B]    int32 scalar-prefetch: per-row live kv length
    q_ref,       # [1, H, D]            this row's query
    k_ref,       # [1, block_size, Hkv, D]  the block the index map selected
    v_ref,       # [1, block_size, Hkv, D]
    o_ref,       # [1, H, D]
    acc_ref,     # VMEM [H, D] f32      online-softmax accumulators,
    m_ref,       # VMEM [H, 1] f32      carried across the W grid steps
    l_ref,       # VMEM [H, 1] f32
    *,
    block_size: int,
    groups: int,
    scale: float,
):
    """One (row, logical-block) grid step of paged flash decode.

    The grid is ``(B, W)`` with the block axis innermost; the BlockSpec index
    maps already DMA'd physical block ``tables[b, w]`` of each pool into VMEM
    — the kernel never sees the pool, only one streamed block — so the body
    is plain online softmax: rescale the running (max, sum, acc) by the new
    block's contribution and normalize on the last block. Padded table
    entries point at the null block and their positions exceed the row's
    live length, so the same position mask that makes the gather reference
    exact silences them here. All math is f32 on the VPU: decode attention
    is bandwidth-bound (one query row per block), so streaming, not the MXU,
    is what this kernel buys."""
    from jax.experimental import pallas as pl  # deferred with pallas_call's

    w = pl.program_id(1)

    @pl.when(w == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32) * scale           # [H, D]
    k = k_ref[0].astype(jnp.float32)                   # [bs, Hkv, D]
    v = v_ref[0].astype(jnp.float32)
    if groups > 1:  # GQA: every q head in a group reads its kv head's block
        bs, hkv, d = k.shape
        k = jnp.broadcast_to(k[:, :, None, :], (bs, hkv, groups, d)).reshape(bs, -1, d)
        v = jnp.broadcast_to(v[:, :, None, :], (bs, hkv, groups, d)).reshape(bs, -1, d)
    # s[h, j] = q[h] . k[j, h] — broadcast-multiply-reduce on the VPU (one
    # query row per head: an MXU matmul would be all padding)
    s = jnp.sum(q[:, None, :] * k.transpose(1, 0, 2), axis=-1)  # [H, bs]
    pos = w * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(pos < lens_ref[pl.program_id(0)], s, -jnp.inf)

    m_prev, l_prev = m_ref[...], l_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))  # [H, 1]
    # a fully-masked prefix of blocks keeps m at -inf: exp(-inf - -inf) would
    # be NaN, so clamp the shift (everything is 0-weighted anyway)
    shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.exp(m_prev - shift)                    # [H, 1]
    p = jnp.exp(s - shift)                             # [H, bs], masked -> 0
    l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jnp.sum(
        p[:, :, None] * v.transpose(1, 0, 2), axis=1
    )
    m_ref[...] = m_new
    l_ref[...] = l_new

    @pl.when(w == pl.num_programs(1) - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def paged_attention_decode(
    q, k_pool, v_pool, block_tables, kv_lens, scale=None, *, interpret=False
):
    """Pallas paged flash-attention decode: q ``[B, 1, H, D]`` against
    per-layer pools ``[num_blocks, block_size, Hkv, D]`` through
    ``block_tables [B, W]``, with ragged per-row live lengths ``kv_lens
    [B]``. Walks each row's block table and streams the referenced KV blocks
    through VMEM with online softmax — the gathered ``[B, W*block_size]``
    cache the XLA reference materializes per layer never exists.
    ``interpret=True`` runs the identical kernel through the Pallas
    interpreter (the CPU parity path in tier-1 CI)."""
    from jax.experimental import pallas as pl_  # deferred: CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    if S != 1:
        raise ValueError(f"decode kernel wants S=1 queries, got S={S}")
    num_blocks, block_size, Hkv, _ = k_pool.shape
    W = block_tables.shape[1]
    if H % Hkv:
        raise ValueError(f"q heads {H} not a multiple of kv heads {Hkv}")
    sm_scale = (1.0 / math.sqrt(D)) if scale is None else float(scale)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # block tables + lengths
        grid=(B, W),
        in_specs=[
            pl_.BlockSpec((1, H, D), lambda b, w, tables, lens: (b, 0, 0)),
            pl_.BlockSpec(
                (1, block_size, Hkv, D),
                lambda b, w, tables, lens: (tables[b, w], 0, 0, 0),
            ),
            pl_.BlockSpec(
                (1, block_size, Hkv, D),
                lambda b, w, tables, lens: (tables[b, w], 0, 0, 0),
            ),
        ],
        out_specs=pl_.BlockSpec((1, H, D), lambda b, w, tables, lens: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, D), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
        ],
    )
    kernel = partial(
        _paged_decode_kernel,
        block_size=block_size,
        groups=H // Hkv,
        scale=sm_scale,
    )
    out = pl_.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        jnp.asarray(kv_lens, jnp.int32).reshape(B),
        q[:, 0],
        k_pool,
        v_pool,
    )
    return out[:, None]  # [B, 1, H, D], the caller's BSHD contract


def _paged_prefill_kernel(
    tables_ref,  # [B, W] int32 scalar-prefetch (drives the k/v index maps)
    qpos_ref,    # [B, S] int32 scalar-prefetch: absolute position of each query
    q_ref,       # [1, S, H, D]             this row's chunk of queries
    k_ref,       # [1, block_size, Hkv, D]  the block the index map selected
    v_ref,       # [1, block_size, Hkv, D]
    o_ref,       # [1, S, H, D]
    acc_ref,     # VMEM [H, S, D] f32   online-softmax accumulators,
    m_ref,       # VMEM [H, S, 1] f32   carried across the W grid steps
    l_ref,       # VMEM [H, S, 1] f32
    *,
    block_size: int,
    groups: int,
    scale: float,
):
    """One (row, logical-block) grid step of paged chunked-prefill attention.

    Same shape of walk as :func:`_paged_decode_kernel` — grid ``(B, W)``,
    block axis innermost, BlockSpec index maps DMA physical block
    ``tables[b, w]`` into VMEM — but with ``S > 1`` queries per row, so the
    score/PV contractions are real ``[H, S, d] x [H, d, bs]`` matmuls on the
    MXU (``dot_general`` batched over heads) instead of the decode kernel's
    VPU broadcast-reduce. Causality inside the chunk and raggedness against
    previously-landed KV collapse into ONE predicate: the engine scatter-
    writes the chunk's own KV into the pool *before* attention, so every KV
    position — old blocks and the chunk's own tokens alike — is live in the
    walked blocks, and masking ``kv_pos <= q_position`` per query reproduces
    the gather reference exactly (null-padded table entries sit at positions
    past every query and are silenced by the same predicate)."""
    from jax.experimental import pallas as pl  # deferred with pallas_call's

    b, w = pl.program_id(0), pl.program_id(1)

    @pl.when(w == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32) * scale           # [S, H, D]
    k = k_ref[0].astype(jnp.float32)                   # [bs, Hkv, D]
    v = v_ref[0].astype(jnp.float32)
    if groups > 1:  # GQA: every q head in a group reads its kv head's block
        bs, hkv, d = k.shape
        k = jnp.broadcast_to(k[:, :, None, :], (bs, hkv, groups, d)).reshape(bs, -1, d)
        v = jnp.broadcast_to(v[:, :, None, :], (bs, hkv, groups, d)).reshape(bs, -1, d)
    qh = q.transpose(1, 0, 2)                          # [H, S, D]
    kh = k.transpose(1, 0, 2)                          # [H, bs, D]
    vh = v.transpose(1, 0, 2)                          # [H, bs, D]
    # s[h, i, j] = q[i, h] . k[j, h] — an MXU matmul batched over heads (the
    # chunk gives the systolic array S real rows, unlike decode's single one)
    s = jax.lax.dot_general(
        qh, kh, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
    )                                                  # [H, S, bs]
    pos = w * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    s = jnp.where(pos <= qpos_ref[b][None, :, None], s, -jnp.inf)

    m_prev, l_prev = m_ref[...], l_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))  # [H, S, 1]
    # a fully-masked prefix of blocks keeps m at -inf: exp(-inf - -inf) would
    # be NaN, so clamp the shift (everything is 0-weighted anyway)
    shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.exp(m_prev - shift)                    # [H, S, 1]
    p = jnp.exp(s - shift)                             # [H, S, bs], masked -> 0
    l_new = l_prev * alpha + jnp.sum(p, axis=2, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p, vh, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
    )
    m_ref[...] = m_new
    l_ref[...] = l_new

    @pl.when(w == pl.num_programs(1) - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] / l_ref[...]).transpose(1, 0, 2).astype(o_ref.dtype)


def paged_attention_prefill(
    q, k_pool, v_pool, block_tables, q_positions, scale=None, *, interpret=False
):
    """Pallas paged chunked-prefill attention: q ``[B, S, H, D]`` (``S > 1``)
    against per-layer pools ``[num_blocks, block_size, Hkv, D]`` through
    ``block_tables [B, W]``, with per-query absolute positions
    ``q_positions [B, S]``. The engine has already scatter-written the
    chunk's own KV into the pool, so one walk over each row's block table
    covers both the previously-landed KV and the in-chunk causal part; the
    per-query position mask is what makes the online softmax match the
    gather reference's causal masking bit for bit. The gathered
    ``[B, W*block_size]`` cache the XLA reference materializes per layer
    never exists. ``interpret=True`` runs the identical kernel through the
    Pallas interpreter (the CPU parity path in tier-1 CI)."""
    from jax.experimental import pallas as pl_  # deferred: CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    if S < 2:
        raise ValueError(f"prefill kernel wants S>1 queries, got S={S}")
    num_blocks, block_size, Hkv, _ = k_pool.shape
    W = block_tables.shape[1]
    if H % Hkv:
        raise ValueError(f"q heads {H} not a multiple of kv heads {Hkv}")
    sm_scale = (1.0 / math.sqrt(D)) if scale is None else float(scale)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # block tables + per-query positions
        grid=(B, W),
        in_specs=[
            pl_.BlockSpec((1, S, H, D), lambda b, w, tables, qpos: (b, 0, 0, 0)),
            pl_.BlockSpec(
                (1, block_size, Hkv, D),
                lambda b, w, tables, qpos: (tables[b, w], 0, 0, 0),
            ),
            pl_.BlockSpec(
                (1, block_size, Hkv, D),
                lambda b, w, tables, qpos: (tables[b, w], 0, 0, 0),
            ),
        ],
        out_specs=pl_.BlockSpec((1, S, H, D), lambda b, w, tables, qpos: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, S, D), jnp.float32),
            pltpu.VMEM((H, S, 1), jnp.float32),
            pltpu.VMEM((H, S, 1), jnp.float32),
        ],
    )
    kernel = partial(
        _paged_prefill_kernel,
        block_size=block_size,
        groups=H // Hkv,
        scale=sm_scale,
    )
    return pl_.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, S, H, D), q.dtype),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        jnp.asarray(q_positions, jnp.int32).reshape(B, S),
        q,
        k_pool,
        v_pool,
    )


def paged_attention(q, k_pool, v_pool, block_tables, q_positions, scale=None):
    """Paged attention for the serving engine (kernel dispatch point).

    q ``[B, S, H, D]``; per-layer pools ``[num_blocks, block_size, Hkv, D]``;
    ``block_tables [B, W]`` (physical block ids, null-padded); ``q_positions
    [B, S]``. On the TPU backend BOTH serving shapes dispatch to Pallas
    paged kernels: single-token decode (``S == 1``) to
    :func:`paged_attention_decode` and chunked prefill / multi-token verify
    (``S > 1``) to :func:`paged_attention_prefill` — block-table walk + VMEM
    block streaming + online softmax, no materialized gathered KV per layer.
    Everywhere else — non-TPU backends and the ``ACCELERATE_PAGED_KERNEL=0``
    kill switch — runs the XLA reference path (``serving.kv_pager.
    paged_attention``: gather blocks by table, shared masked-attention core
    — bitwise-identical to contiguous decode), exactly like
    :func:`flash_attention`'s pallas-vs-xla split.
    ``ACCELERATE_PAGED_KERNEL=interpret`` forces the kernels (interpreter
    mode) on any backend so CPU CI can drive the kernel dataflow through
    the full engine."""
    mode = paged_kernel_mode()
    if mode != "off":
        interpret = mode == "interpret"
        if interpret or jax.default_backend() == "tpu":
            if q.shape[1] == 1:
                return paged_attention_decode(
                    q, k_pool, v_pool, block_tables, q_positions[:, 0] + 1,
                    scale, interpret=interpret,
                )
            return paged_attention_prefill(
                q, k_pool, v_pool, block_tables, q_positions,
                scale, interpret=interpret,
            )
    from ..serving.kv_pager import paged_attention as _xla_paged

    return _xla_paged(q, k_pool, v_pool, block_tables, q_positions, scale)
