"""Flash attention for TPU: Pallas-kernel path with XLA fallback.

The reference reaches flash/SDPA CUDA kernels through transformers + torch
(SURVEY.md §2.3 "flash attention / SDPA kernels"); the TPU-native equivalent is
the Pallas flash kernel that ships with JAX
(``jax.experimental.pallas.ops.tpu.flash_attention``) — blocked online-softmax
attention that streams KV through VMEM instead of materializing the [S, S]
score matrix in HBM. We wrap it behind the framework's BSHD layout and GQA
conventions so models/CP kernels can swap implementations freely.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp


def flash_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,  # [B, S] int; padding = 0
    block_q: int = 512,
    block_kv: int = 512,
) -> jax.Array:
    """Pallas flash attention (TPU), BSHD in/out. Falls back to the XLA einsum
    path off-TPU or for unsupported shapes.

    ``segment_ids`` gates attention to same-id pairs — the kernel-native form
    of padding/packing masks (``pallas...flash_attention`` ``SegmentIds``), so
    masked models need not fall back to the einsum path (round-2 verdict: the
    headline bench ran with the flash kernel idle because of this)."""
    if jax.default_backend() != "tpu":
        from .attention import _xla_attention, segment_mask

        mask = segment_mask(segment_ids) if segment_ids is not None else None
        return _xla_attention(q, k, v, causal=causal, mask=mask, scale=scale)

    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        SegmentIds,
        flash_attention as pallas_flash,
    )

    orig_dtype = q.dtype
    hq, hkv = q.shape[2], k.shape[2]
    if hq != hkv:
        from .attention import _repeat_kv

        k = _repeat_kv(k, hq // hkv)
        v = _repeat_kv(v, hq // hkv)
    # BSHD -> BHSD
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    sm_scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    sq, skv = qt.shape[2], kt.shape[2]
    block_sizes = BlockSizes(
        block_q=min(block_q, sq),
        block_k_major=min(block_kv, skv),
        block_k=min(block_kv, skv),
        block_b=1,
        block_q_major_dkv=min(block_q, sq),
        block_k_major_dkv=min(block_kv, skv),
        block_k_dkv=min(block_kv, skv),
        block_q_dkv=min(block_q, sq),
        block_k_major_dq=min(block_kv, skv),
        block_k_dq=min(block_kv, skv),
        block_q_dq=min(block_q, sq),
    )
    seg = None
    if segment_ids is not None:
        seg = SegmentIds(q=segment_ids.astype(jnp.int32), kv=segment_ids.astype(jnp.int32))
    out = pallas_flash(
        qt, kt, vt, segment_ids=seg, causal=causal, sm_scale=sm_scale, block_sizes=block_sizes
    )
    return out.transpose(0, 2, 1, 3).astype(orig_dtype)


def paged_attention(q, k_pool, v_pool, block_tables, q_positions, scale=None):
    """Paged decode attention for the serving engine (kernel dispatch point).

    q ``[B, S, H, D]``; per-layer pools ``[num_blocks, block_size, Hkv, D]``;
    ``block_tables [B, W]`` (physical block ids, null-padded); ``q_positions
    [B, S]``. Today every backend runs the XLA reference path
    (``serving.kv_pager.paged_attention``: gather blocks by table, shared
    masked-attention core — bitwise-identical to contiguous decode); a
    Pallas paged-attention kernel that streams blocks through VMEM without
    materializing the gathered cache (vLLM-style PagedAttention) is the TPU
    upgrade and slots in HERE without touching engine callers, exactly like
    :func:`flash_attention`'s pallas-vs-xla split."""
    from ..serving.kv_pager import paged_attention as _xla_paged

    return _xla_paged(q, k_pool, v_pool, block_tables, q_positions, scale)
